"""SLO-aware prefetch planner: warm the shared cache ahead of the
cursor, as deep as the latency promise requires.

The serve regime's read problem is not "is the next range cached" but
"will the next unit make its deadline" — on a remote source the
answer is dominated by origin round trips, and the cure the reader
already owns is :meth:`~tpuparquet.io.reader.FileReader.
prefetch_chunks`: coalesced, parallel, populates the disk tier, and
skips anything already cached (``contains`` — which, over a
:class:`~tpuparquet.io.rangecache.SharedDiskRangeCache`, sees every
OTHER server process's publishes too).  This module decides *when*
and *how far ahead* to call it:

* **Depth** comes from the SLO signals, clamped to
  ``TPQ_PREFETCH_DEPTH`` (the max lookahead, 0 disables): when the
  latency digest's p99 for ``(label, "unit")`` is comfortably inside
  the job's ``unit_deadline`` (≤ 25% of it), one unit of lookahead is
  plenty and the byte budget stays unspent; as the p99 climbs toward
  the deadline the window deepens proportionally; and when the SLO
  burn rate (``obs/slo.py`` over the time-series ring) says the error
  budget is being spent at ≥ 1×, the planner goes to max depth —
  origin latency must be fully hidden *before* units start missing
  deadlines.  Without a deadline or digest data it stays at max depth
  (prefetch is cheap insurance; ``contains`` dedup keeps it honest).
* **Bytes** are bounded by ``TPQ_PREFETCH_BYTES_MB`` of
  prefetched-but-unconsumed row-group bytes (meta
  ``total_byte_size``), so a deep window over fat row groups cannot
  blow the cache budget; the unit right after the cursor is always
  allowed through, or fat units would never prefetch.
* **Threads** are whatever the reader's own planner gets: the worker
  thread binds :func:`~tpuparquet.serve.arbiter.tenant_scope`, so
  ``prefetch_ranges`` sizes its pool from the tenant's arbiter share.

Counter exactness: the worker thread runs every fetch under a
``worker_stats`` collector; :meth:`PrefetchPlanner.close` (called on
the job driver's thread, inside the job's ``collect_stats`` scope)
merges them — so ``remote_ranges_fetched`` / ``remote_bytes`` /
``cache_*_disk`` from prefetch land on the job's tenant exactly once,
and fleet-wide sums stay conservation-exact.

Lock discipline: the planner condition variable is a LEAF — window
bookkeeping only; every fetch, digest read, and ring read happens
outside it.
"""

from __future__ import annotations

import os
import threading

from . import arbiter as _arbiter

__all__ = ["PrefetchPlanner", "prefetch_depth_default",
           "prefetch_bytes_default"]

#: units between SLO-signal refreshes (digest fold + ring read are
#: not per-unit cheap; the signals move slower than this anyway)
_REFRESH_UNITS = 16


def prefetch_depth_default() -> int:
    """``TPQ_PREFETCH_DEPTH`` — max units of lookahead the planner
    may warm (default 2; ``0`` disables serve-side prefetch)."""
    v = os.environ.get("TPQ_PREFETCH_DEPTH")
    if v is None or v == "":
        return 2
    return max(0, int(v))


def prefetch_bytes_default() -> int:
    """``TPQ_PREFETCH_BYTES_MB`` in bytes — cap on
    prefetched-but-unconsumed row-group bytes (default 64 MiB)."""
    v = os.environ.get("TPQ_PREFETCH_BYTES_MB")
    if v is None or v == "":
        return 64 * (1 << 20)
    return max(0, int(float(v) * (1 << 20)))


def _unit_est_bytes(readers, unit) -> int:
    """Window-budget sizing for one ``(file, row_group)`` unit from
    footer meta — compressed row-group bytes, 0 when unknowable."""
    fi, rgi = unit
    r = readers[fi] if fi < len(readers) else None
    if r is None:
        return 0
    try:
        return max(0, int(r.meta.row_groups[rgi].total_byte_size))
    except (AttributeError, IndexError, TypeError, ValueError):
        return 0


class PrefetchPlanner:
    """One per running job: a worker thread that keeps the next
    ``depth(t)`` units' chunk ranges warm in the (shared) disk tier.

    Driver contract: :meth:`start` once, :meth:`note_progress(k)`
    after each completed unit, :meth:`close` on the driver thread
    inside the job's stats scope (merges the worker's counters and
    joins the thread).  All methods are cheap; the fetching happens on
    the planner's own thread."""

    def __init__(self, readers, units, label: str, *,
                 start: int = 0,
                 unit_deadline: float | None = None,
                 max_depth: int | None = None,
                 byte_cap: int | None = None):
        self._readers = readers
        self._units = units
        self._label = label
        self._unit_deadline = unit_deadline
        self._max_depth = (max_depth if max_depth is not None
                           else prefetch_depth_default())
        self._byte_cap = (byte_cap if byte_cap is not None
                          else prefetch_bytes_default())
        self._cv = threading.Condition()
        self._cursor = start - 1   # last unit the driver consumed
        self._next = start         # next unit index to prefetch
        self._ahead: list = []     # (unit_idx, est_bytes) in window
        self._stop = False
        self._depth = 1            # deepened by the SLO signals
        self._since_refresh = _REFRESH_UNITS  # refresh on first use
        self._workers: list = []   # worker collectors, merged at close
        self._thread: threading.Thread | None = None

    # -- driver side ------------------------------------------------------

    def start(self) -> "PrefetchPlanner":
        if self._max_depth <= 0 or not self._units:
            return self  # disabled: every method stays a no-op
        self._thread = threading.Thread(
            target=self._run, name=f"tpq-prefetch:{self._label}",
            daemon=True)
        self._thread.start()
        return self

    def note_progress(self, k: int) -> None:
        """Unit ``k`` was consumed: slide the window."""
        if self._thread is None:
            return
        with self._cv:
            if k > self._cursor:
                self._cursor = k
                self._ahead = [(u, b) for u, b in self._ahead if u > k]
            self._cv.notify_all()

    def close(self) -> None:
        """Stop + join, then fold the worker's counters into the
        CALLING thread's collector — call on the driver thread, inside
        the job's stats scope, after the scan loop ends."""
        if self._thread is None:
            return
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(30.0)
        self._thread = None
        from ..stats import current_stats, merge_worker_stats

        st = current_stats()
        for ws in self._workers:
            merge_worker_stats(st, ws, failed=False)
        self._workers = []

    # -- SLO signals ------------------------------------------------------

    def _target_depth(self) -> int:
        """Lookahead for the current window — see module docstring."""
        depth = self._max_depth
        p99_s = self._digest_p99_s()
        if self._unit_deadline and p99_s is not None:
            pressure = p99_s / self._unit_deadline
            if pressure <= 0.25:
                depth = 1
            else:
                depth = max(1, min(self._max_depth,
                                   round(self._max_depth
                                         * min(pressure, 1.0))))
        burn = self._fast_burn()
        if burn is not None and burn >= 1.0:
            depth = self._max_depth
        return depth

    def _digest_p99_s(self) -> float | None:
        from ..obs import digest as _digest

        reg = _digest.digests()
        if reg is None:
            return None
        g = reg.snapshot().get((self._label, "unit"))
        if g is None or not g.n:
            return None
        return g.quantile(0.99) / 1e6  # digests observe microseconds

    def _fast_burn(self) -> float | None:
        """Fast-window burn rate for this label from the time-series
        ring + SLO objectives; None when either is unarmed."""
        from ..obs import slo as _slo
        from ..obs import timeseries as _timeseries

        ring = _timeseries.ring()
        if ring is None:
            return None
        try:
            objectives = [o for o in _slo.load_objectives()
                          if o["label"] == self._label]
            if not objectives:
                return None
            frames = _timeseries.load_ring(ring.dir)
            if not frames:
                return None
            report = _slo.evaluate(frames, objectives)
        except (OSError, ValueError, KeyError):
            return None
        for row in report["objectives"]:
            burn = (row.get("burn") or {}).get("fast")
            if burn is not None:
                return burn
        return None

    # -- the worker -------------------------------------------------------

    def _pick(self):
        """Next unit to warm, or None to wait.  Called under the cv;
        byte-cap and depth decisions use the last refreshed signals."""
        if self._next >= len(self._units):
            return None
        if self._next > self._cursor + self._depth:
            return None
        ahead_bytes = sum(b for _u, b in self._ahead)
        est = _unit_est_bytes(self._readers, self._units[self._next])
        if ahead_bytes > 0 and ahead_bytes + est > self._byte_cap:
            return None  # window full by bytes; first unit always goes
        k = self._next
        self._next += 1
        self._ahead.append((k, est))
        return k

    def _run(self) -> None:
        from ..stats import worker_stats

        with worker_stats() as ws, _arbiter.tenant_scope(self._label):
            # one collector for the thread's whole life; close()
            # merges it after the join, so there is no concurrent
            # access — the worker_stats exactness discipline
            self._workers.append(ws)
            while True:
                with self._cv:
                    k = self._pick()
                    while k is None and not self._stop:
                        self._cv.wait(0.05)
                        k = self._pick()
                    if self._stop:
                        return
                if self._since_refresh >= _REFRESH_UNITS:
                    self._since_refresh = 0
                    depth = self._target_depth()  # outside the cv
                    with self._cv:
                        self._depth = depth
                self._since_refresh += 1
                fi, rgi = self._units[k]
                reader = (self._readers[fi]
                          if fi < len(self._readers) else None)
                if reader is None:
                    continue
                try:
                    reader.prefetch_chunks(rgi)
                except Exception:  # noqa: BLE001 — advisory path
                    # prefetch must never fail the scan: the per-unit
                    # decode re-reads with the full resilience policy
                    # and surfaces real errors with coordinates
                    pass
