"""The multi-tenant scan server: bounded per-tenant queues over one
shared arbiter, with graceful drain.

One :class:`ScanServer` owns (or adopts) a
:class:`~tpuparquet.serve.arbiter.ResourceArbiter`, activates it
process-wide, and multiplexes concurrent tenant scans onto the
library's shared substrate — the plan cache, the arena pool, the
watchdog, the metrics registry and per-label ledgers/digests.  Each
tenant gets a FIFO queue bounded by admission control; a round-robin
scheduler starts at most ONE scan per tenant at a time (the
*arbiter* shares cores between tenants; serializing a tenant's own
jobs keeps its queue estimate honest), and every scan runs in
quarantine mode under :func:`~tpuparquet.serve.arbiter.tenant_scope`
with a durable cursor in the server's state directory.

**Graceful drain** (``SIGTERM`` via
:meth:`ScanServer.install_signal_handlers`, or :meth:`ScanServer.
shutdown`): admissions start rejecting with a retryable
``"draining"`` :class:`~tpuparquet.serve.arbiter.AdmissionRejected`;
every in-flight scan is asked to stop cooperatively
(:meth:`~tpuparquet.shard.scan.DurableScanMixin.request_stop` — it
finishes its current unit, flushes the durable cursor, and marks its
progress ``stopped``); queued-but-unstarted jobs are handed back as
``drained``; telemetry is flushed.  A successor server that
resubmits the same ``(tenant, job_id)`` jobs resumes every cursor —
with a keyed sink (the ``tests/checkpoint_child.py`` discipline) the
union of results is duplicate-free and bit-exact.

Lock discipline: the server condition variable is a LEAF like the
arbiter lock — queue bookkeeping only, never held across admission,
scan driving, arbiter rebalance, or telemetry calls.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from collections import deque

from . import arbiter as _arbiter
from .arbiter import AdmissionRejected, ResourceArbiter

__all__ = ["ScanJob", "ScanServer", "state_dir_default"]


def state_dir_default() -> str | None:
    """Durable-cursor directory from ``TPQ_SERVE_STATE_DIR`` (None =
    no checkpointing: jobs are not resumable across a restart)."""
    return os.environ.get("TPQ_SERVE_STATE_DIR") or None


class ScanJob:
    """One admitted scan request.

    ``wait(timeout)`` blocks until the job reaches a terminal state:
    ``done`` (all units decoded), ``drained`` (checkpointed mid-scan
    by a drain — resubmit on the successor to continue), or
    ``failed`` (:attr:`error` holds the exception).  Without a
    ``sink``, decoded units land in :attr:`outputs` keyed by unit
    index; with one, ``sink(unit_index, out)`` is called from the
    driver thread as each unit decodes (keyed atomic writes there
    make a crash-safe consumer — see ``tests/serve_child.py``)."""

    def __init__(self, tenant: str, job_id: str, sources, columns,
                 options: dict, sink):
        self.tenant = tenant
        self.job_id = job_id
        self.sources = sources
        self.columns = columns
        self.options = options
        self.sink = sink
        self.outputs: dict = {} if sink is None else None
        self.state = "queued"
        self.error: BaseException | None = None
        self.units_done = 0
        self.units_total: int | None = None
        self.units_quarantined = 0
        self.quarantine = None     # QuarantineReport after the run
        self.stats = None          # exact DecodeStats for this job
        self.est_bytes = 0
        self.scan = None           # live ShardedScan while running
        self._event = threading.Event()

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)

    @property
    def terminal(self) -> bool:
        return self.state in ("done", "drained", "failed")

    def _finish(self, state: str) -> None:
        self.state = state
        self._event.set()

    def as_dict(self) -> dict:
        return {
            "tenant": self.tenant, "job_id": self.job_id,
            "state": self.state,
            "units_done": self.units_done,
            "units_total": self.units_total,
            "error": (f"{type(self.error).__name__}: {self.error}"
                      if self.error is not None else None),
        }


class ScanServer:
    """Long-lived multi-tenant scan host (see module docstring).

    ``plan_cache_mb``: arm the shared plan cache at this budget for
    the server's lifetime (concurrent tenants re-planning the same
    files is the serve-shaped hit pattern); None leaves the
    ``TPQ_PLAN_CACHE_MB`` env setting alone.  The arena-pool free-
    list retention is raised to the worker budget while the server
    runs and trimmed back on shutdown."""

    def __init__(self, *, arbiter: ResourceArbiter | None = None,
                 state_dir: str | None = None,
                 queue_bound: int | None = None,
                 rebalance_interval: float | None = None,
                 plan_cache_mb: float | None = None):
        self._arb = arbiter if arbiter is not None else ResourceArbiter()
        _arbiter.activate(self._arb)
        self.state_dir = (state_dir if state_dir is not None
                          else state_dir_default())
        if self.state_dir:
            os.makedirs(self.state_dir, exist_ok=True)
        self._queue_bound = (queue_bound if queue_bound is not None
                             else _arbiter.queue_bound_default())
        self._reb_interval = (
            rebalance_interval if rebalance_interval is not None
            else _arbiter.rebalance_interval_default())
        self._cv = threading.Condition()
        self._queues: dict[str, deque[ScanJob]] = {}
        self._running: dict[str, ScanJob] = {}
        self._rr: list[str] = []      # round-robin tenant order
        self._rr_pos = 0
        self._finished: list[ScanJob] = []
        self._drivers: list[threading.Thread] = []
        self._draining = False
        self._closed = False
        self._drain_event = threading.Event()
        from ..kernels import arena as _arena
        from ..kernels import plancache as _plancache

        self._plancache_token = (
            _plancache.set_plan_cache_budget(plan_cache_mb)
            if plan_cache_mb is not None else None)
        self._arena_keep_prev = _arena.set_arena_retention(
            self._arb.total_workers)
        self._scheduler = threading.Thread(
            target=self._schedule_loop, name="tpq-serve-sched",
            daemon=True)
        self._scheduler.start()

    # -- tenants ---------------------------------------------------------

    def add_tenant(self, label: str, *, weight: float = 1.0,
                   byte_budget: int | None = None,
                   latency_target_ms: float | None = None,
                   error_rate_target: float | None = None) -> None:
        """Register a tenant with the arbiter and give it a queue."""
        self._arb.register(
            label, weight=weight, byte_budget=byte_budget,
            latency_target_ms=latency_target_ms,
            error_rate_target=error_rate_target)
        with self._cv:
            if label not in self._queues:
                self._queues[label] = deque()
                self._rr.append(label)

    # -- submission ------------------------------------------------------

    @staticmethod
    def _estimate_bytes(sources) -> int:
        """Admission-control sizing: local file sizes where knowable,
        0 for remote/opened sources (their budget charge lands when a
        cheap remote HEAD estimate exists; unknown must not reject)."""
        total = 0
        for s in sources if isinstance(sources, (list, tuple)) else [sources]:
            if isinstance(s, (str, os.PathLike)):
                try:
                    total += os.path.getsize(s)
                except OSError:
                    pass
        return total

    def _cursor_path(self, job: ScanJob) -> str | None:
        if not self.state_dir:
            return None
        from ..obs.progress import label_slug

        name = f"{label_slug(job.tenant)}__{label_slug(job.job_id)}.cursor"
        return os.path.join(self.state_dir, name)

    def submit(self, tenant: str, sources, *columns: str,
               job_id: str | None = None,
               unit_deadline: float | None = None,
               scan_deadline: float | None = None,
               retries: int | None = 0,
               checkpoint_every: int | None = None,
               filter=None, sink=None,
               est_bytes: int | None = None) -> ScanJob:
        """Admit and enqueue one scan for ``tenant``.

        Raises :class:`AdmissionRejected` (retryable) when draining,
        when the tenant's bounded queue is full, or when its byte /
        deadline budget cannot take the job — the request never
        hangs.  ``job_id`` keys the durable cursor: resubmitting the
        same id on a successor server resumes the checkpoint.
        ``est_bytes`` overrides the local-stat sizing when the caller
        already knows the read size (dataset manifests record it)."""
        if self._draining or self._closed:
            raise AdmissionRejected(
                f"server is draining; resubmit tenant {tenant!r} "
                f"work to the successor", tenant=tenant,
                reason="draining", retry_after_s=5.0)
        est = est_bytes if est_bytes is not None \
            else self._estimate_bytes(sources)
        with self._cv:
            q = self._queues.get(tenant)
            depth = (len(q) if q is not None else 0) \
                + (1 if tenant in self._running else 0)
        # admission outside the server lock: the arbiter lock is its
        # own leaf and the two must never nest
        self._arb.admit(tenant, est_bytes=est, deadline_s=scan_deadline,
                        queue_depth=depth, queue_bound=self._queue_bound)
        if job_id is None:
            job_id = f"job{int(time.monotonic() * 1e6):x}"
        job = ScanJob(tenant, job_id, sources, columns, {
            "unit_deadline": unit_deadline,
            "scan_deadline": scan_deadline,
            "retries": retries,
            "checkpoint_every": checkpoint_every,
            "filter": filter,
        }, sink)
        job.est_bytes = est
        enqueued = False
        with self._cv:
            q = self._queues.get(tenant)
            if q is not None and not self._draining \
                    and len(q) < self._queue_bound:
                q.append(job)
                enqueued = True
                self._cv.notify_all()
        if not enqueued:
            self._arb.retract(tenant, est)
            raise AdmissionRejected(
                f"tenant {tenant!r} queue filled while admitting; "
                f"retry", tenant=tenant, reason="queue_full",
                retry_after_s=1.0)
        return job

    def submit_dataset(self, tenant: str, root, *columns: str,
                       filter=None, **kw) -> ScanJob:
        """Admit a partitioned-dataset scan (``tpuparquet/dataset/``).

        The file list comes from the newest valid manifest;
        partition-key conjuncts of ``filter`` prune files *before*
        admission, and the byte-budget charge is the manifest's
        recorded sizes for the surviving files (exact even for
        remote ``emu://`` members, which local stat cannot size).
        The residual predicate and every :meth:`submit` option pass
        through; the job runs as an ordinary sharded scan over the
        surviving members."""
        from ..dataset import manifest as mf
        from ..dataset.scan import (partition_matches,
                                    split_partition_filter)

        body, _version, findings = mf.resolve_manifest(root)
        if body is None:
            raise FileNotFoundError(
                f"{root!r} has no valid manifest snapshot"
                + (f" ({len(findings)} rejected)" if findings else ""))
        part_pred, residual = split_partition_filter(
            filter, body["partition_keys"])
        sources, est = [], 0
        for e in body["files"]:
            if partition_matches(part_pred, e["partition"]):
                sources.append(e.get("uri")
                               or mf.file_uri(root, e["path"]))
                est += int(e.get("bytes") or 0)
        return self.submit(tenant, sources, *columns, filter=residual,
                           est_bytes=est, **kw)

    # -- scheduling ------------------------------------------------------

    def _pick_locked(self) -> ScanJob | None:
        """Round-robin: next tenant with queued work and no running
        job.  Called under the cv."""
        n = len(self._rr)
        for i in range(n):
            label = self._rr[(self._rr_pos + i) % n]
            if label in self._running:
                continue
            q = self._queues.get(label)
            if q:
                self._rr_pos = (self._rr_pos + i + 1) % n
                job = q.popleft()
                self._running[label] = job
                return job
        return None

    def _schedule_loop(self) -> None:
        last_reb = time.monotonic()
        while True:
            job = None
            with self._cv:
                if self._closed:
                    return
                job = self._pick_locked()
                if job is None:
                    self._cv.wait(timeout=self._reb_interval)
            if self._closed:
                return
            if job is not None:
                if self._draining:
                    # admitted before the drain began but never
                    # started: hand it back untouched for the
                    # successor (its cursor, if any, is intact)
                    with self._cv:
                        self._running.pop(job.tenant, None)
                        self._cv.notify_all()
                    job._finish("drained")
                    continue
                t = threading.Thread(
                    target=self._drive_job, args=(job,),
                    name=f"tpq-serve:{job.tenant}", daemon=True)
                with self._cv:
                    self._drivers = [d for d in self._drivers
                                     if d.is_alive()]
                    self._drivers.append(t)
                t.start()
            now = time.monotonic()
            if now - last_reb >= self._reb_interval:
                # outside every server lock: rebalance reads the obs
                # registries and takes the arbiter leaf lock
                self._arb.rebalance()
                last_reb = now

    # -- the per-job driver ----------------------------------------------

    @staticmethod
    def _maybe_prefetcher(scan, label: str, opts: dict):
        """Arm the SLO-aware prefetch planner for this job when it
        can pay off: lookahead enabled, a disk tier to warm, and at
        least one remote source to warm it from (a local mmap scan
        gets nothing from prefetch).  Returns a started
        :class:`~tpuparquet.serve.prefetch.PrefetchPlanner` or None."""
        from ..io.rangecache import disk_cache
        from .prefetch import PrefetchPlanner, prefetch_depth_default

        if prefetch_depth_default() <= 0:
            return None
        if disk_cache() is None:
            return None
        if not any(r is not None and getattr(r, "_source", None)
                   is not None for r in scan.readers):
            return None
        start, _total = scan._progress()
        return PrefetchPlanner(
            scan.readers, scan.units, label, start=start,
            unit_deadline=opts.get("unit_deadline")).start()

    def _drive_job(self, job: ScanJob) -> None:
        from ..shard.scan import ShardedScan
        from ..stats import collect_stats

        label = job.tenant
        t0 = time.monotonic()
        scan = None
        opts = job.options
        try:
            with _arbiter.tenant_scope(label):
                scan = ShardedScan(
                    job.sources, *job.columns, on_error="quarantine",
                    retries=opts.get("retries"),
                    unit_deadline=opts.get("unit_deadline"),
                    scan_deadline=opts.get("scan_deadline"),
                    filter=opts.get("filter"),
                    resume_from=self._cursor_path(job),
                    checkpoint_every=opts.get("checkpoint_every"),
                    progress_label=label)
                job.scan = scan
                job.units_total = len(scan.units)
                job.state = "running"
                if self._draining:
                    scan.request_stop()  # raced the drain broadcast
                with collect_stats() as st:
                    planner = self._maybe_prefetcher(scan, label, opts)
                    try:
                        for k, out in scan.run_iter():
                            if job.sink is not None:
                                job.sink(k, out)
                            else:
                                job.outputs[k] = out
                            job.units_done += 1
                            if planner is not None:
                                planner.note_progress(k)
                    finally:
                        if planner is not None:
                            planner.close()
                job.stats = st
                job.quarantine = scan.quarantine
                # the scan's own tally is authoritative: it counts
                # quarantined units too, which never reach the sink
                job.units_done = scan.progress.units_done
                job.units_quarantined = scan.progress.units_quarantined
                final = "drained" if scan.stopped else "done"
        except BaseException as e:  # noqa: BLE001 — reported on the job
            job.error = e
            if scan is not None:
                job.quarantine = scan.quarantine
                job.units_done = scan.progress.units_done
                job.units_quarantined = scan.progress.units_quarantined
            final = "failed"
        finally:
            if scan is not None:
                scan.close()
            job.scan = None
        self._arb.note_job_done(label, time.monotonic() - t0,
                                ok=final == "done")
        # refund the in-flight byte charge: the admission-time bytes
        # are no longer outstanding, so a previously shed job can now
        # clear the byte-budget check on its retry
        self._arb.release(label, job.est_bytes)
        with self._cv:
            self._running.pop(label, None)
            self._finished.append(job)
            self._cv.notify_all()
        job._finish(final)

    # -- waiting ---------------------------------------------------------

    def wait_idle(self, timeout: float | None = None) -> bool:
        """Block until no job is queued or running (or ``timeout``
        elapses); True when idle."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with self._cv:
            while self._running or any(self._queues.values()):
                rem = None
                if deadline is not None:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        return False
                self._cv.wait(timeout=rem if rem is not None else 1.0)
            return True

    # -- drain / shutdown ------------------------------------------------

    def request_drain(self) -> None:
        """Begin a graceful drain; safe to call from a signal handler
        (sets flags and events only — no locks)."""
        self._draining = True
        for job in list(self._running.values()):
            scan = job.scan
            if scan is not None:
                scan.request_stop()
        self._drain_event.set()

    def drain(self, timeout: float | None = None) -> bool:
        """Stop admissions, checkpoint every in-flight scan, hand
        queued jobs back as ``drained``, flush telemetry.  True when
        everything reached a terminal state in time."""
        self.request_drain()
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        drained_q: list[ScanJob] = []
        ok = True
        with self._cv:
            for q in self._queues.values():
                while q:
                    drained_q.append(q.popleft())
            self._cv.notify_all()
        for job in drained_q:
            job._finish("drained")
        with self._cv:
            while self._running:
                rem = None
                if deadline is not None:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        ok = False
                        break
                self._cv.wait(timeout=rem if rem is not None else 1.0)
        self._flush_telemetry()
        return ok

    def _flush_telemetry(self) -> None:
        """Best-effort scan-end style flush: a final registry export
        (when the exporter is armed) and a drain tick on the
        time-series ring — post-mortems and progress files were
        already written by the scans themselves."""
        from ..obs import live as _live
        from ..obs import timeseries as _timeseries

        try:
            _live.export_now()
        except OSError:
            pass
        _timeseries.tick("serve_drain")

    def shutdown(self, *, drain: bool = True,
                 timeout: float | None = None) -> bool:
        """Drain (optionally), stop the scheduler, release the shared
        resources and deactivate the arbiter.  Idempotent."""
        ok = True
        if drain and not self._closed:
            ok = self.drain(timeout=timeout)
        self._draining = True
        with self._cv:
            self._closed = True
            drivers = list(self._drivers)
            self._cv.notify_all()
        self._scheduler.join(timeout=5.0)
        # jobs reach their terminal state moments BEFORE the driver
        # thread finishes unwinding; exiting the process through that
        # window tears down native state under a live thread — join
        # the (daemon) drivers so a clean shutdown never races it
        for d in drivers:
            d.join(timeout=5.0)
        from ..kernels import arena as _arena
        from ..kernels import plancache as _plancache

        _arena.set_arena_retention(self._arena_keep_prev)
        _arena.trim_arena_pool(0)
        if self._plancache_token is not None:
            _plancache.set_plan_cache_budget(self._plancache_token)
        _arbiter.deactivate(self._arb)
        return ok

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown()

    # -- signals / status ------------------------------------------------

    def install_signal_handlers(self, signals=(signal.SIGTERM,)) -> None:
        """Route ``SIGTERM`` (by default) to :meth:`request_drain`;
        pair with :meth:`serve_forever`.  Main thread only (a CPython
        restriction on ``signal.signal``)."""

        def _handler(signum, frame):
            self.request_drain()

        for s in signals:
            signal.signal(s, _handler)

    def serve_forever(self, poll_s: float = 0.2) -> None:
        """Block until a drain is requested (signal or another
        thread), then finish the drain and return."""
        while not self._drain_event.wait(timeout=poll_s):
            pass
        self.drain()

    @property
    def draining(self) -> bool:
        return self._draining

    def status(self) -> dict:
        """The ``parquet-tool tenants`` document: per-tenant arbiter
        accounting + queue/running/finished state."""
        with self._cv:
            queued = {lb: [j.as_dict() for j in q]
                      for lb, q in self._queues.items()}
            running = {lb: j.as_dict()
                       for lb, j in self._running.items()}
            finished = [j.as_dict() for j in self._finished]
        tenants = self._arb.tenants_state()
        for lb, row in tenants.items():
            row["queued"] = queued.get(lb, [])
            row["running"] = running.get(lb)
        return {
            "total_workers": self._arb.total_workers,
            "shares": self._arb.shares(),
            "draining": self._draining,
            "state_dir": self.state_dir,
            "tenants": tenants,
            "finished": finished,
        }

    def write_status(self, path: str) -> None:
        """Atomic JSON status export for out-of-process viewers."""
        import json

        from ..obs.live import atomic_write_text

        atomic_write_text(path, json.dumps(self.status(), indent=2,
                                           sort_keys=True) + "\n")
