"""Partitioned datasets: atomic multi-file writes, manifest-resolved
scans, compaction.

* :class:`DatasetWriter` — hive-partitioned writer whose commit is a
  CRC-framed, atomically-renamed manifest journal: a SIGKILL at any
  byte leaves the previous snapshot or a resumable journal, never a
  torn dataset (``dataset/writer.py``).
* :class:`DatasetScan` — reads only through the newest valid manifest,
  with partition-value pruning composed in front of the per-file
  stats/bloom/page-index layers (``dataset/scan.py``).
* :func:`compact_dataset` — small-file merge, re-sorted by a filter
  column, committed through the same protocol (``dataset/compact.py``).
* :func:`sweep_orphans` — quarantines (never silently deletes)
  staging leftovers from crashed writes (``dataset/manifest.py``).
"""

from .compact import compact_dataset, gc_unreferenced  # noqa: F401
from .manifest import (  # noqa: F401
    resolve_manifest,
    sweep_orphans,
)
from .scan import (  # noqa: F401
    DatasetScan,
    partition_matches,
    split_partition_filter,
)
from .writer import DatasetWriter  # noqa: F401

__all__ = [
    "DatasetWriter",
    "DatasetScan",
    "compact_dataset",
    "gc_unreferenced",
    "resolve_manifest",
    "sweep_orphans",
    "split_partition_filter",
    "partition_matches",
]
