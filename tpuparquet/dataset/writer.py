"""DatasetWriter: hive-partitioned multi-file writes with atomic
dataset commit.

Rows arrive as row-aligned column arrays (partition columns included);
the writer routes them by partition value into per-partition streaming
:class:`~tpuparquet.io.writer.FileWriter` files under ``_tmp/``, rolls
a partition to a fresh file when it crosses the
``TPQ_DATASET_TARGET_MB`` size target, and publishes everything in
:meth:`commit` through the manifest-journal protocol
(``dataset/manifest.py``):

1. each open file is *staged*: footer + fsync, then renamed (within
   ``_tmp/``) to its content-addressed name ``part-<sha1>.parquet`` —
   a staged name asserts complete, durable content;
2. the **journal** (``_commit.json``) is atomically written, recording
   every staged file and its final partition path;
3. each staged file is renamed into its ``key=value`` directory
   (fault site ``dataset.file.promote``), idempotently — a file whose
   final path already exists was promoted by a previous attempt;
4. the new **manifest snapshot** is atomically written (previous
   snapshot's files + the new ones) — this rename is the commit point;
5. the journal is cleared and old snapshots pruned.

SIGKILL before step 2 leaves the previous snapshot plus orphaned
staging files (swept to quarantine, or reused bit-exact by a re-run —
content addressing makes re-staging idempotent); SIGKILL after step 2
leaves a journal from which ``DatasetWriter(root, ...,
resume_from=root)`` finishes the commit duplicate-free without the
caller re-supplying data.  Readers resolve only through manifests, so
no intermediate state is ever visible.

Concurrency: one :func:`~tpuparquet.io.writer._write_threads` budget
is SPLIT across the partitions flushed by one ``write_columns`` call —
``k`` partition files encode concurrently on an outer pool while each
inner ``FileWriter`` gets ``encode_threads = max(1, W // k)`` — so a
partitioned write never oversubscribes the box the way ``k``
independent writers each sizing to ``W`` would.
"""

from __future__ import annotations

import hashlib
import os

import numpy as np

from ..faults import fault_point
from ..format.dsl import SchemaDefinition, parse_schema_definition
from ..io.writer import FileWriter, _write_threads
from . import manifest as mf

__all__ = ["DatasetWriter", "target_bytes_default"]


def target_bytes_default() -> int:
    """``TPQ_DATASET_TARGET_MB`` — rolling file-size target per
    partition file (default 64 MiB; a partition crossing it rolls to
    a fresh content-addressed file at the next write boundary)."""
    try:
        v = float(os.environ.get("TPQ_DATASET_TARGET_MB", ""))
    except ValueError:
        return 64 * 1024 * 1024
    return max(int(v * 1024 * 1024), 1)


class _HashingTee:
    """File-object facade that mirrors every write into an incremental
    SHA-1 — the content address is known the moment the stream closes,
    without re-reading the staged bytes."""

    __slots__ = ("_fh", "sha1")

    def __init__(self, fh):
        self._fh = fh
        self.sha1 = hashlib.sha1()

    def write(self, data):
        self.sha1.update(data)
        return self._fh.write(data)

    def flush(self):
        self._fh.flush()

    def fileno(self):
        return self._fh.fileno()


class _OpenPart:
    """One in-flight partition file: its FileWriter, hashing tee, and
    routing metadata.  The raw handle lives in the owning writer's
    ``_handles`` registry (directory-scoped ownership: the writer's
    close/abort release every member)."""

    __slots__ = ("key", "partition", "partial", "tee", "writer", "rows")

    def __init__(self, key, partition, partial, tee, writer):
        self.key = key
        self.partition = partition
        self.partial = partial
        self.tee = tee
        self.writer = writer
        self.rows = 0


def _part_sort_key(key: tuple):
    """Deterministic partition ordering for mixed-type keys (None
    sorts first within a column)."""
    return tuple((v is None, str(v)) for v in key)


class DatasetWriter:
    """Write a hive-partitioned dataset with atomic snapshot commits.

    ``schema`` is the FULL row schema (a DSL string or
    :class:`SchemaDefinition`) including the ``partition_by`` columns;
    data files are written WITHOUT the partition columns (hive style —
    their values live in the directory names and the manifest).
    Partition columns must be top-level primitive leaves; v1 restricts
    the data columns to flat (non-repeated) leaves.

    ``resume_from`` (normally the dataset root itself) picks up a
    crashed commit: a pending journal's files are folded into this
    writer's commit, and re-supplied data dedups against already
    staged/promoted content by content address — the resumed dataset
    is bit-exact with an uninterrupted write.

    Use as a context manager: a clean exit commits, an exception
    aborts (partials removed, staged files left for the orphan sweep).
    """

    def __init__(self, root, schema, partition_by, *,
                 target_mb=None, resume_from=None, manifest_keep=None,
                 step_hook=None, **writer_options):
        scheme, root_path = mf.split_root(root)
        self.root = root
        self.root_path = root_path
        if isinstance(partition_by, str):
            partition_by = (partition_by,)
        self.partition_by = tuple(partition_by)
        if isinstance(schema, str):
            schema = parse_schema_definition(schema)
        if not isinstance(schema, SchemaDefinition):
            raise TypeError(
                "schema must be a DSL string or SchemaDefinition, "
                f"not {type(schema).__name__}")
        self.schema = schema
        self._data_schema = self._split_schema(schema)
        self._target = int(target_mb * 1024 * 1024) \
            if target_mb is not None else target_bytes_default()
        self._keep = manifest_keep
        self._step_hook = step_hook
        self._writer_options = dict(writer_options)
        self._parts: dict = {}
        self._handles: dict = {}
        self._staged: list = []
        self._seq = 0
        self._closed = False
        os.makedirs(os.path.join(root_path, mf.TMP_DIR), exist_ok=True)
        self._journal = None
        if resume_from:
            if isinstance(resume_from, str):
                _, resume_path = mf.split_root(resume_from)
                if os.path.abspath(resume_path) != \
                        os.path.abspath(root_path):
                    raise ValueError(
                        f"resume_from={resume_from!r} does not name "
                        f"this dataset root {root!r}")
            self._journal = mf.load_journal(root_path)

    # -- schema routing ---------------------------------------------------

    def _split_schema(self, sd: SchemaDefinition) -> SchemaDefinition:
        """The data-file schema: the full schema minus the partition
        columns (which must be top-level primitive leaves)."""
        import copy

        names = {c.name for c in sd.root.children}
        for k in self.partition_by:
            if k not in names:
                raise ValueError(
                    f"partition column {k!r} is not a top-level "
                    f"schema field")
        keep = []
        for c in sd.root.children:
            if c.name in self.partition_by:
                if c.children:
                    raise ValueError(
                        f"partition column {c.name!r} must be a "
                        f"primitive leaf, not a group")
                continue
            keep.append(copy.deepcopy(c))
        if not keep:
            raise ValueError(
                "schema has no data columns besides the partition "
                "keys")
        root = copy.deepcopy(sd.root)
        root.children = keep
        out = SchemaDefinition(root)
        out.validate()
        return out

    # -- writing ----------------------------------------------------------

    def write_columns(self, columns: dict, *, masks=None) -> None:
        """Route one batch of rows to their partition files.

        ``columns`` maps column name -> ROW-ALIGNED values (numpy
        array, or list for binary/string columns; partition columns
        included and required non-null unless a None value routes the
        row to the hive null partition).  ``masks`` maps data-column
        name -> row-aligned bool validity (values at null rows are
        ignored).  Each call appends one row group per touched
        partition file.
        """
        if self._closed:
            raise ValueError("dataset writer is closed")
        masks = masks or {}
        for k in self.partition_by:
            if k not in columns:
                raise ValueError(f"missing partition column {k!r}")
            if k in masks:
                raise ValueError(
                    f"partition column {k!r} cannot carry a mask; "
                    f"use None values for the hive null partition")
        data_names = [c.name for c in self._data_schema.root.children]
        for name in columns:
            if name not in data_names and \
                    name not in self.partition_by:
                raise ValueError(f"unknown column {name!r}")
        n_rows = None
        for name, vals in columns.items():
            n = len(vals)
            if n_rows is None:
                n_rows = n
            elif n != n_rows:
                raise ValueError(
                    f"column {name!r} has {n} rows, expected {n_rows}")
        if not n_rows:
            return
        groups = self._group_rows(columns, n_rows)
        self._flush_groups(groups, columns, masks)

    def _group_rows(self, columns, n_rows):
        """partition-value tuple -> row-index array, in deterministic
        partition order."""
        cols = []
        for k in self.partition_by:
            vals = columns[k]
            cols.append([None if v is None else
                         (v.item() if isinstance(v, np.generic) else
                          (v.decode("utf-8") if isinstance(v, bytes)
                           else v))
                         for v in (vals.tolist()
                                   if isinstance(vals, np.ndarray)
                                   else list(vals))])
        buckets: dict = {}
        for i in range(n_rows):
            key = tuple(c[i] for c in cols)
            buckets.setdefault(key, []).append(i)
        return [(key, np.asarray(buckets[key], dtype=np.int64))
                for key in sorted(buckets, key=_part_sort_key)]

    def _slice(self, vals, mask, idx):
        """Row-aligned (vals, mask) -> FileWriter's (dense non-null
        values, mask) for the selected rows."""
        if isinstance(vals, np.ndarray):
            sub = vals[idx]
        else:
            lst = list(vals)
            sub = [lst[i] for i in idx]
        if mask is None:
            return sub, None
        m = np.asarray(mask, dtype=bool)[idx]
        if isinstance(sub, np.ndarray):
            return sub[m], m
        return [v for v, keep in zip(sub, m) if keep], m

    def _open_part(self, key, partition) -> _OpenPart:
        self._seq += 1
        partial = os.path.join(
            self.root_path, mf.TMP_DIR,
            f".partial.{os.getpid()}.{self._seq}")
        # the raw handle is owned by the writer-level registry: close()
        # and abort() release every member, so a failed flush cannot
        # strand fds on abandoned _OpenParts
        self._handles[key] = open(partial, "wb")
        tee = _HashingTee(self._handles[key])
        fw = FileWriter(tee, self._data_schema, **self._writer_options)
        part = _OpenPart(key, partition, partial, tee, fw)
        self._parts[key] = part
        return part

    def _flush_groups(self, groups, columns, masks) -> None:
        budget = _write_threads()
        share = max(1, budget // max(len(groups), 1))
        jobs = []
        for key, idx in groups:
            part = self._parts.get(key)
            if part is None:
                partition = dict(zip(self.partition_by, key))
                part = self._open_part(key, partition)
            part.writer.encode_threads = share
            cols = {}
            mks = {}
            for c in self._data_schema.root.children:
                name = c.name
                if name not in columns:
                    continue
                vals, m = self._slice(columns[name],
                                      masks.get(name), idx)
                cols[name] = vals
                if m is not None:
                    mks[name] = m
            jobs.append((part, cols, mks, len(idx)))

        def flush(part, cols, mks, n):
            part.writer.write_columns(cols, masks=mks or None)
            part.rows += n

        if len(jobs) > 1 and budget > 1:
            # outer pool over partitions: workers adopt the caller's
            # trace context and collect stats per-thread, merged into
            # the ambient collector (same discipline as the per-column
            # pool in io/writer.py)
            from concurrent.futures import ThreadPoolExecutor

            from ..obs import trace as _trace
            from ..stats import current_stats, worker_stats

            _tctx = _trace.current_ctx()
            _sink = current_stats()

            def run(job):
                with _trace.adopt(_tctx), worker_stats() as ws:
                    flush(*job)
                return ws

            with ThreadPoolExecutor(
                    max_workers=min(len(jobs), budget)) as ex:
                for ws in ex.map(run, jobs):
                    if _sink is not None:
                        _sink.merge_from(ws)
        else:
            for job in jobs:
                flush(*job)
        # roll AFTER the parallel flush (deterministic: depends only
        # on the bytes written, never on thread timing)
        for key, _ in groups:
            part = self._parts.get(key)
            if part is not None and \
                    part.writer.current_file_size() >= self._target:
                self._stage_part(key)

    def write_partition(self, partition: dict, columns: dict, *,
                        masks=None, source_bytes=None) -> None:
        """Write row-aligned DATA columns (no partition columns)
        straight into one partition — the compaction path.  Rows are
        chunked so the rolling size target still applies, with the
        per-row byte estimate taken from ``source_bytes`` (the size of
        the files being rewritten) when given."""
        if self._closed:
            raise ValueError("dataset writer is closed")
        if set(partition) != set(self.partition_by):
            raise ValueError(
                f"partition {sorted(partition)} does not match "
                f"partition_by {sorted(self.partition_by)}")
        masks = masks or {}
        n_rows = None
        for name, vals in columns.items():
            if n_rows is None:
                n_rows = len(vals)
            elif len(vals) != n_rows:
                raise ValueError(
                    f"column {name!r} has {len(vals)} rows, "
                    f"expected {n_rows}")
        if not n_rows:
            return
        est_row = max(int(source_bytes / n_rows), 1) \
            if source_bytes else 64
        chunk = max(self._target // (4 * est_row), 1)
        key = tuple(partition[k] for k in self.partition_by)
        idx_all = np.arange(n_rows, dtype=np.int64)
        for lo in range(0, n_rows, chunk):
            idx = idx_all[lo:lo + chunk]
            part = self._parts.get(key)
            if part is None:
                part = self._open_part(key, dict(partition))
            part.writer.encode_threads = None
            cols, mks = {}, {}
            for name, vals in columns.items():
                v, m = self._slice(vals, masks.get(name), idx)
                cols[name] = v
                if m is not None:
                    mks[name] = m
            part.writer.write_columns(cols, masks=mks or None)
            part.rows += len(idx)
            if part.writer.current_file_size() >= self._target:
                self._stage_part(key)

    # -- staging / commit protocol ----------------------------------------

    def _step(self, *label) -> None:
        """Commit-protocol step boundary: the kill-sweep harness hooks
        here to SIGKILL the writer between any two protocol actions."""
        if self._step_hook is not None:
            self._step_hook(label)

    def _stage_part(self, key) -> dict:
        """Finalize one partition file into its content-addressed
        staging name.  After this returns, ``_tmp/part-<sha1>.parquet``
        is complete and durable (a ``.partial.*`` name never is)."""
        part = self._parts.pop(key)
        fh = self._handles[key]
        self._step("stage", part.partial)
        part.writer.close()  # footer
        fh.flush()
        os.fsync(fh.fileno())
        size = fh.tell()
        fh.close()
        del self._handles[key]
        digest = part.tee.sha1.hexdigest()[:16]
        name = f"part-{digest}.parquet"
        staged = os.path.join(self.root_path, mf.TMP_DIR, name)
        if os.path.exists(staged):
            # identical content already staged (a resumed re-run):
            # reuse it, drop the duplicate partial
            os.unlink(part.partial)
        else:
            os.replace(part.partial, staged)
            self._fsync_dir(os.path.dirname(staged))
        pdir = mf.partition_dir(self.partition_by, part.partition)
        rel = f"{pdir}/{name}" if pdir else name
        entry = {"tmp": name, "path": rel,
                 "partition": part.partition,
                 "rows": part.rows, "bytes": size, "sha1": digest}
        self._staged.append(entry)
        return entry

    def _fsync_dir(self, d: str) -> None:
        try:
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass

    def _promote(self, entry: dict, tmp_refs: dict) -> None:
        """Move one staged file to its final partition path —
        idempotent, so a resumed commit re-runs it safely."""
        rel = entry["path"]
        final = os.path.join(self.root_path, rel)
        fault_point("dataset.file.promote", file=rel)
        # count down even when skipping: a resumed commit must still
        # consume the staged copy on its LAST reference, or the
        # leftover would read as a spurious orphan
        tmp_refs[entry["tmp"]] -= 1
        tmp = os.path.join(self.root_path, mf.TMP_DIR, entry["tmp"])
        if os.path.exists(final):
            # promoted by a previous attempt; a resume that re-staged
            # the same content leaves a duplicate of the PUBLISHED
            # file (same content address) — consume it
            if tmp_refs[entry["tmp"]] <= 0 and os.path.exists(tmp):
                os.unlink(tmp)
            return
        os.makedirs(os.path.dirname(final) or self.root_path,
                    exist_ok=True)
        if tmp_refs[entry["tmp"]] > 0:
            # identical content published under several partition
            # paths: keep the staged copy for the remaining entries
            os.link(tmp, final)
        else:
            os.replace(tmp, final)
        self._fsync_dir(os.path.dirname(final))

    def commit(self, *, remove_paths=()):
        """Run the commit protocol; returns the new manifest version
        (or the current one when there is nothing to publish).  Safe
        to call on a resumed writer with no new data — it finishes
        whatever the journal recorded.  ``remove_paths`` drops base
        files from the new snapshot (compaction: the merged-away
        originals stay on disk, still referenced by older snapshots,
        until snapshot pruning + GC collects them)."""
        if self._closed:
            raise ValueError("dataset writer is closed")
        for key in sorted(self._parts, key=_part_sort_key):
            self._stage_part(key)
        new_files = {e["path"]: e for e in self._staged}
        base_body, base_ver, _ = mf.resolve_manifest(self.root)
        base_ver = base_ver or 0
        version = base_ver + 1
        if self._journal is not None:
            if base_ver >= self._journal["version"]:
                # the crashed run already published its manifest; run
                # its cleanup step, then fall through to commit any
                # NEW data at the next version
                self._step("clean")
                mf.clear_journal(self.root_path)
                mf.prune_manifests(self.root_path, self._keep)
                self._journal = None
                if not new_files:
                    self._staged = []
                    return base_ver
            else:
                for e in self._journal["files"]:
                    new_files.setdefault(e["path"], dict(e))
                version = self._journal["version"]
                # a journaled compaction's drop-list must survive the
                # crash, or a resume would republish the merged-away
                # originals next to their replacements
                remove_paths = set(remove_paths) | \
                    set(self._journal.get("remove_paths") or [])
        if not new_files:
            return base_ver if base_ver else None
        entries = [new_files[p] for p in sorted(new_files)]
        self._step("journal")
        mf.write_journal(self.root_path, {
            "version": version, "base_version": base_ver,
            "partition_keys": list(self.partition_by),
            "files": entries,
            "remove_paths": sorted(remove_paths)})
        tmp_refs: dict = {}
        for e in entries:
            tmp_refs[e["tmp"]] = tmp_refs.get(e["tmp"], 0) + 1
        for e in entries:
            self._step("promote", e["path"])
            self._promote(e, tmp_refs)
        base_files = list(base_body["files"]) if base_body else []
        removed = set(remove_paths)
        published = {p: {k: v for k, v in e.items() if k != "tmp"}
                     for p, e in new_files.items()}
        for e in base_files:
            if e["path"] not in removed:
                published.setdefault(e["path"], dict(e))
        self._step("manifest")
        mf.write_manifest(self.root_path, {
            "version": version,
            "partition_keys": list(self.partition_by),
            "schema": str(self.schema),
            "files": [published[p] for p in sorted(published)]})
        self._step("clean")
        mf.clear_journal(self.root_path)
        mf.prune_manifests(self.root_path, self._keep)
        self._journal = None
        self._staged = []
        return version

    # -- lifecycle --------------------------------------------------------

    def close(self):
        """Commit pending data, then release every partition handle."""
        if self._closed:
            return
        try:
            self.commit()
        finally:
            self._release()

    def abort(self):
        """Discard without committing: every open partial is removed;
        already-staged content is LEFT under ``_tmp/`` for the orphan
        sweep (never silently deleted — a deliberate abort may still
        be the only copy of expensive data)."""
        if self._closed:
            return
        partials = [p.partial for p in self._parts.values()]
        self._release()
        for p in partials:
            try:
                os.unlink(p)
            except OSError:
                pass

    def _release(self):
        self._closed = True
        for fh in self._handles.values():
            try:
                fh.close()
            except OSError:
                pass
        self._handles.clear()
        self._parts.clear()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        else:
            self.abort()
