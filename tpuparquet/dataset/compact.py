"""Dataset compaction: small-file merge through the atomic commit.

``compact_dataset`` reads every data file of every partition back
(bit-exact CPU decode), optionally re-sorts each partition by a
filter column (so the per-page min/max stats written by
``TPQ_PAGE_ROWS`` chunking become tight and page pruning fires), and
rewrites each partition as rolling ``TPQ_DATASET_TARGET_MB``-sized
files — published through the SAME manifest-journal protocol as any
other write.  The new snapshot drops the compacted-away files, so a
compaction that dies at any byte is invisible (the prior snapshot
still lists the old files, which are untouched until the new manifest
is the newest valid one).

After the commit, snapshots beyond ``TPQ_DATASET_MANIFEST_KEEP`` are
pruned and data files no RETAINED snapshot (nor a pending journal)
references are garbage-collected — explicit, committed-state GC, not
an orphan sweep (orphans under ``_tmp/`` are quarantined, never
deleted; see ``manifest.sweep_orphans``).
"""

from __future__ import annotations

import os

import numpy as np

from ..cpu.plain import ByteArrayColumn
from ..format.schema import Schema
from ..io.reader import FileReader
from . import manifest as mf
from .writer import DatasetWriter

__all__ = ["compact_dataset", "gc_unreferenced"]


def _row_aligned(cd, max_def):
    """ChunkData (dense non-null values + def levels) -> row-aligned
    ``(values, mask)`` in the shape :meth:`DatasetWriter
    .write_columns` routing expects."""
    n = len(cd.def_levels)
    if max_def == 0:
        vals = cd.values
        if isinstance(vals, ByteArrayColumn):
            return vals.to_list(), None
        return np.asarray(vals), None
    mask = np.asarray(cd.def_levels) == max_def
    if isinstance(cd.values, ByteArrayColumn):
        dense = cd.values.to_list()
        out = [b""] * n
        j = 0
        for i in range(n):
            if mask[i]:
                out[i] = dense[j]
                j += 1
        return out, mask
    vals = np.asarray(cd.values)
    out = np.zeros(n, dtype=vals.dtype)
    out[mask] = vals
    return out, mask


def _concat(parts, masks):
    """Concatenate per-file row-aligned (values, mask) pairs."""
    if all(isinstance(p, np.ndarray) for p in parts):
        vals = np.concatenate(parts) if parts else np.array([])
    else:
        vals = []
        for p in parts:
            vals.extend(p.tolist() if isinstance(p, np.ndarray)
                        else list(p))
    if all(m is None for m in masks):
        return vals, None
    out = np.concatenate([
        m if m is not None else np.ones(len(p), dtype=bool)
        for p, m in zip(parts, masks)])
    return vals, out


def _sort_order(vals, mask):
    """Stable ascending order with nulls last."""
    n = len(vals)
    null = np.zeros(n, dtype=bool) if mask is None else ~np.asarray(
        mask, dtype=bool)
    if isinstance(vals, np.ndarray) and vals.dtype != object:
        key = vals.copy()
        # neutralize null slots so they cannot perturb the sort
        if n and null.any():
            key[null] = key[~null][0] if (~null).any() else key[0]
        return np.lexsort((np.arange(n), key, null))
    keyed = [(bool(null[i]), vals[i] if not null[i] else b"", i)
             for i in range(n)]
    keyed.sort(key=lambda t: (t[0], t[1]))
    return np.asarray([t[2] for t in keyed], dtype=np.int64)


def gc_unreferenced(root_path: str) -> list:
    """Delete data files referenced by NO retained snapshot and no
    pending journal (committed-state GC after manifest pruning).
    Returns the deleted relative paths."""
    referenced = set()
    for v in mf.list_manifest_versions(root_path):
        try:
            body = mf.load_envelope(
                os.path.join(root_path, mf.manifest_name(v)),
                mf.MANIFEST_FORMAT, display=mf.manifest_name(v))
        except Exception:
            continue  # a corrupt snapshot pins nothing
        for e in body.get("files", []):
            referenced.add(e["path"])
    try:
        journal = mf.load_journal(root_path)
    except Exception:
        journal = None
    if journal is not None:
        for e in journal["files"]:
            referenced.add(e["path"])
    removed = []
    for dirpath, dirnames, filenames in os.walk(root_path):
        dirnames[:] = [d for d in dirnames
                       if not d.startswith(("_", "."))]
        for name in filenames:
            if name.startswith(("_", ".")) or \
                    not name.endswith(".parquet"):
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root_path).replace(os.sep, "/")
            if rel not in referenced:
                os.unlink(full)
                removed.append(rel)
    # drop now-empty partition directories (bottom-up)
    for dirpath, dirnames, filenames in sorted(
            os.walk(root_path), key=lambda t: -len(t[0])):
        if dirpath == root_path or \
                os.path.basename(dirpath).startswith(("_", ".")):
            continue
        try:
            os.rmdir(dirpath)
        except OSError:
            pass
    return removed


def compact_dataset(root, *, sort_by=None, target_mb=None,
                    manifest_keep=None, step_hook=None,
                    **writer_options):
    """Merge each partition's files into rolling target-sized files,
    optionally re-sorted by ``sort_by``; commit atomically; GC.

    Returns a report dict: new manifest ``version``, ``files_before``
    / ``files_after``, ``rows``, ``gc`` (deleted paths)."""
    _, root_path = mf.split_root(root)
    body, version, _ = mf.resolve_manifest(root)
    if body is None:
        raise FileNotFoundError(
            f"{root!r} has no valid manifest snapshot to compact")
    dsl = body.get("schema")
    if not dsl:
        raise ValueError(
            f"{root!r} manifest records no schema (imported hive "
            f"dataset?) — compaction needs it to rewrite files")
    keys = body["partition_keys"]
    writer = DatasetWriter(root, dsl, keys, target_mb=target_mb,
                           manifest_keep=manifest_keep,
                           step_hook=step_hook, **writer_options)
    data_schema = Schema.from_definition(writer._data_schema)
    leaves = data_schema.leaves
    for leaf in leaves:
        if leaf.max_rep_level > 0 or leaf.parent is not data_schema.root:
            raise NotImplementedError(
                f"compaction supports flat top-level columns only "
                f"(column {leaf.flat_name!r})")
    if sort_by is not None and \
            sort_by not in {lf.flat_name for lf in leaves}:
        raise ValueError(f"sort_by names no data column {sort_by!r}")

    by_part: dict = {}
    for e in body["files"]:
        key = tuple(e["partition"][k] for k in keys)
        by_part.setdefault(key, []).append(e)

    total_rows = 0
    old_paths = [e["path"] for e in body["files"]]
    for key in sorted(by_part, key=lambda t: tuple(
            (v is None, str(v)) for v in t)):
        entries = by_part[key]
        cols: dict = {lf.flat_name: [] for lf in leaves}
        msks: dict = {lf.flat_name: [] for lf in leaves}
        part_rows = 0
        part_bytes = 0
        for e in entries:
            full = os.path.join(root_path, e["path"])
            part_bytes += os.path.getsize(full)
            with FileReader(full) as r:
                for rg in range(r.row_group_count()):
                    arrays = r.read_row_group_arrays(rg)
                    n = None
                    for lf in leaves:
                        cd = arrays[lf.flat_name]
                        vals, m = _row_aligned(cd, lf.max_def_level)
                        cols[lf.flat_name].append(vals)
                        msks[lf.flat_name].append(m)
                        n = len(cd.def_levels)
                    part_rows += n or 0
        merged: dict = {}
        mmask: dict = {}
        for name in cols:
            merged[name], mmask[name] = _concat(cols[name], msks[name])
        if sort_by is not None and part_rows:
            order = _sort_order(merged[sort_by], mmask.get(sort_by))
            for name in merged:
                v = merged[name]
                merged[name] = v[order] if isinstance(v, np.ndarray) \
                    else [v[i] for i in order]
                if mmask[name] is not None:
                    mmask[name] = np.asarray(mmask[name])[order]
        partition = dict(zip(keys, key))
        writer.write_partition(partition, merged,
                               masks={k: v for k, v in mmask.items()
                                      if v is not None},
                               source_bytes=part_bytes)
        total_rows += part_rows

    new_version = writer.commit(remove_paths=old_paths)
    writer._release()
    gc = gc_unreferenced(root_path)
    after, _, _ = mf.resolve_manifest(root)
    return {"version": new_version,
            "files_before": len(old_paths),
            "files_after": len(after["files"]) if after else 0,
            "rows": total_rows,
            "gc": gc}
