"""Dataset manifest + commit journal: the atomic-snapshot substrate.

A partitioned dataset directory is resolved ONLY through its newest
valid manifest — never by listing data files.  That single rule is
what makes multi-file writes transactional: data files land under
``_tmp/`` with content-addressed names, a write-ahead **journal**
(``_commit.json``) records exactly which staged files the commit
intends to publish, the files are renamed into their partition
directories, and a new immutable **manifest snapshot**
(``_manifest-<version>.json``) is promoted last.  Every one of those
artifacts is published with the same discipline as
``shard.scan.save_cursor_file``: a versioned JSON envelope carrying a
CRC32 over the canonical body, written tmp-in-same-dir + flush +
fsync + ``os.replace`` + directory fsync.  A SIGKILL at ANY byte
therefore leaves either the previous snapshot (commit invisible) or a
complete journal (commit resumable) — never a torn dataset.

Layout of a dataset root::

    _manifest-00000001.json   immutable snapshots (newest valid wins;
    _manifest-00000002.json   a corrupt newest degrades to the one
    ...                       before it, with a quarantine finding)
    _commit.json              write-ahead journal of an in-flight commit
    _tmp/                     content-addressed staging (part-<sha1>.parquet)
    _quarantine/              swept orphans (never deleted silently)
    key=value/.../part-<sha1>.parquet   published data files (hive dirs)

Fault sites (``faults.SITES``): ``dataset.manifest.write`` before the
envelope write, ``dataset.manifest.load`` on the blob read (supports
``corrupt``/``truncate`` byte kinds — the CRC must catch them).
"""

from __future__ import annotations

import json
import os
import re
import urllib.parse
import zlib

from ..errors import CorruptManifestError
from ..faults import fault_point, filter_bytes, retry_transient
from ..format.validate import Finding

__all__ = [
    "MANIFEST_FORMAT",
    "JOURNAL_FORMAT",
    "ENVELOPE_VERSION",
    "JOURNAL_NAME",
    "TMP_DIR",
    "QUARANTINE_DIR",
    "HIVE_NULL",
    "split_root",
    "manifest_name",
    "list_manifest_versions",
    "atomic_write_envelope",
    "load_envelope",
    "validate_manifest_body",
    "resolve_manifest",
    "load_journal",
    "write_journal",
    "clear_journal",
    "write_manifest",
    "prune_manifests",
    "hive_token",
    "parse_hive_token",
    "partition_dir",
    "discover_hive",
    "sweep_orphans",
]

MANIFEST_FORMAT = "tpq-dataset-manifest"
JOURNAL_FORMAT = "tpq-dataset-commit"
ENVELOPE_VERSION = 1

JOURNAL_NAME = "_commit.json"
TMP_DIR = "_tmp"
QUARANTINE_DIR = "_quarantine"

#: hive's conventional token for a null partition value (what pyarrow
#: and hive itself write, so interop round-trips)
HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"

_MANIFEST_RE = re.compile(r"^_manifest-(\d{8})\.json$")


def split_root(root: str) -> tuple:
    """``"emu:///d/ds"`` -> ``("emu", "/d/ds")``; bare paths ->
    ``(None, path)``.  Both known schemes are backed by local
    directories, so the path half always supports listing/writing."""
    from ..io.source import parse_source_uri

    parsed = parse_source_uri(root) if isinstance(root, str) else None
    if parsed is None:
        return None, root
    return parsed


def file_uri(root: str, relpath: str) -> str:
    """The source string for a manifest entry: scheme-prefixed when
    the dataset root was, else a bare path (which keeps every
    path-keyed artifact identical to a plain local scan)."""
    scheme, path = split_root(root)
    full = os.path.join(path, relpath)
    return f"{scheme}://{full}" if scheme else full


def manifest_name(version: int) -> str:
    return f"_manifest-{int(version):08d}.json"


def list_manifest_versions(root_path: str) -> list:
    """Snapshot versions present in the root, ascending."""
    out = []
    for name in os.listdir(root_path):
        m = _MANIFEST_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    out.sort()
    return out


def _canonical(obj) -> bytes:
    """Canonical JSON bytes for CRC framing (same form as the durable
    scan cursor: sorted, separator-pinned)."""
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode()


def atomic_write_envelope(path: str, fmt: str, body: dict) -> None:
    """Publish a manifest/journal body durably and atomically: CRC'd
    versioned envelope, tmp-in-same-dir + flush + fsync +
    ``os.replace`` + directory fsync (the ``save_cursor_file``
    discipline) — a SIGKILL at any byte leaves the previous complete
    artifact or the new complete artifact, never a torn one."""
    fault_point("dataset.manifest.write", file=path)
    doc = {"format": fmt,
           "file_version": ENVELOPE_VERSION,
           "crc32": zlib.crc32(_canonical(body)),
           "body": body}
    d = os.path.dirname(os.path.abspath(path)) or "."
    tmp = os.path.join(
        d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def _read_blob(src) -> bytes:
    """Whole-file read through the byte-range source layer when the
    source is scheme-prefixed (a dataset can live on ``emu://``),
    plain ``open`` otherwise."""
    from ..io.source import open_byte_source

    bs = open_byte_source(src) if isinstance(src, str) else None
    if bs is not None:
        try:
            return bs.get_range(0, bs.size())
        finally:
            bs.close()
    with open(src, "rb") as f:
        return f.read()


def load_envelope(src, fmt: str, *, display=None) -> dict:
    """Read back an :func:`atomic_write_envelope` artifact, validating
    format, version, and the CRC32 over the canonical body.  Raises
    :class:`~tpuparquet.errors.CorruptManifestError` on anything that
    is not a complete, untampered artifact (atomic writes mean a torn
    file here is damage, not a crash artifact)."""
    name = display if display is not None else src
    fault_point("dataset.manifest.load", file=name)
    blob = filter_bytes("dataset.manifest.load", _read_blob(src),
                        file=name)
    try:
        doc = json.loads(blob.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CorruptManifestError(
            f"{name!r} is not valid JSON: {e}", file=name) from e
    if not isinstance(doc, dict) or doc.get("format") != fmt:
        raise CorruptManifestError(
            f"{name!r} is not a {fmt} artifact", file=name)
    if doc.get("file_version") != ENVELOPE_VERSION:
        raise CorruptManifestError(
            f"unknown {fmt} file_version "
            f"{doc.get('file_version')!r} in {name!r}", file=name)
    body = doc.get("body")
    if zlib.crc32(_canonical(body)) != doc.get("crc32"):
        raise CorruptManifestError(
            f"{name!r} failed its integrity checksum", file=name)
    return body


def validate_manifest_body(body, *, name="manifest") -> None:
    """Structural validation of a manifest/journal body: the reader
    must never act on a snapshot whose entries could walk outside the
    dataset root or whose accounting fields are unusable."""
    def bad(msg):
        raise CorruptManifestError(f"{name}: {msg}", file=name)

    if not isinstance(body, dict):
        bad("body is not an object")
    if not isinstance(body.get("version"), int) or body["version"] < 0:
        bad(f"bad version {body.get('version')!r}")
    keys = body.get("partition_keys")
    if not isinstance(keys, list) or \
            not all(isinstance(k, str) for k in keys):
        bad("partition_keys is not a list of strings")
    files = body.get("files")
    if not isinstance(files, list):
        bad("files is not a list")
    seen = set()
    for e in files:
        if not isinstance(e, dict):
            bad("file entry is not an object")
        p = e.get("path")
        if not isinstance(p, str) or not p or os.path.isabs(p) \
                or ".." in p.split("/"):
            bad(f"file path {p!r} escapes the dataset root")
        if p in seen:
            bad(f"duplicate file path {p!r}")
        seen.add(p)
        part = e.get("partition")
        if not isinstance(part, dict) or set(part) != set(keys):
            bad(f"file {p!r} partition keys do not match "
                f"{keys!r}")
        for field in ("rows", "bytes"):
            v = e.get(field)
            if v is not None and (not isinstance(v, int) or v < 0):
                bad(f"file {p!r} has bad {field} {v!r}")


def resolve_manifest(root: str, *, quarantine=None):
    """Resolve the dataset to its newest VALID manifest snapshot.

    Returns ``(body, version, findings)``.  A newest snapshot that
    fails its CRC/validation degrades to the one before it — the
    failure is recorded as an error :class:`Finding` (and a
    file-granularity entry in ``quarantine`` when one is passed),
    never silently skipped.  ``(None, None, findings)`` when no valid
    snapshot exists."""
    scheme, root_path = split_root(root)
    findings = []
    for version in reversed(list_manifest_versions(root_path)):
        rel = manifest_name(version)
        src = file_uri(root, rel)
        try:
            body = retry_transient(
                lambda s=src, r=rel: load_envelope(
                    s, MANIFEST_FORMAT, display=r))
            validate_manifest_body(body, name=rel)
            if body["version"] != version:
                raise CorruptManifestError(
                    f"{rel}: body version {body['version']} does not "
                    f"match its filename", file=rel)
        except (CorruptManifestError, OSError) as e:
            findings.append(Finding(
                "error", "dataset.manifest",
                f"snapshot {rel} rejected ({type(e).__name__}: {e}); "
                f"degrading to the previous snapshot"))
            if quarantine is not None:
                quarantine.add_file(file=rel, error=e)
            continue
        return body, version, findings
    return None, None, findings


def journal_path(root_path: str) -> str:
    return os.path.join(root_path, JOURNAL_NAME)


def load_journal(root_path: str):
    """The in-flight commit journal, or None when no commit is
    pending.  A journal that fails its framing raises — it is damage,
    not a crash artifact (the envelope write is atomic)."""
    p = journal_path(root_path)
    if not os.path.exists(p):
        return None
    body = load_envelope(p, JOURNAL_FORMAT, display=JOURNAL_NAME)
    validate_manifest_body(body, name=JOURNAL_NAME)
    return body


def write_journal(root_path: str, body: dict) -> None:
    atomic_write_envelope(journal_path(root_path), JOURNAL_FORMAT, body)


def clear_journal(root_path: str) -> None:
    try:
        os.unlink(journal_path(root_path))
    except FileNotFoundError:
        pass


def write_manifest(root_path: str, body: dict) -> str:
    p = os.path.join(root_path, manifest_name(body["version"]))
    atomic_write_envelope(p, MANIFEST_FORMAT, body)
    return p


def manifest_keep_default() -> int:
    """``TPQ_DATASET_MANIFEST_KEEP`` — how many manifest snapshots to
    retain after a commit (default 3; older time-travel/degrade
    targets are pruned, and compaction GC may then delete data files
    no retained snapshot references)."""
    try:
        v = int(os.environ.get("TPQ_DATASET_MANIFEST_KEEP", ""))
    except ValueError:
        return 3
    return max(v, 1)


def prune_manifests(root_path: str, keep: int | None = None) -> list:
    """Drop all but the newest ``keep`` snapshots; returns the pruned
    versions.  Old snapshots are superseded committed state (every
    retained reader resolves newest-first), so removal is safe."""
    if keep is None:
        keep = manifest_keep_default()
    versions = list_manifest_versions(root_path)
    pruned = versions[:-keep] if keep < len(versions) else []
    for v in pruned:
        try:
            os.unlink(os.path.join(root_path, manifest_name(v)))
        except FileNotFoundError:
            pass
    return pruned


# ----------------------------------------------------------------------
# Hive path tokens
# ----------------------------------------------------------------------

def hive_token(value) -> str:
    """One ``key=value`` path token's value half: hive-escaped so
    pyarrow's ``dataset(..., partitioning="hive")`` parses it back."""
    if value is None:
        return HIVE_NULL
    if isinstance(value, bytes):
        value = value.decode("utf-8")
    return urllib.parse.quote(str(value), safe="")


def parse_hive_token(token: str):
    """Invert :func:`hive_token` (best effort on types: int, then
    float, else string — the manifest, not the path, is authoritative
    for our own readers)."""
    if token == HIVE_NULL:
        return None
    s = urllib.parse.unquote(token)
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        return s


def partition_dir(partition_keys, partition: dict) -> str:
    """``key=value/...`` relative directory for one partition ('' for
    an unpartitioned dataset)."""
    return "/".join(f"{k}={hive_token(partition[k])}"
                    for k in partition_keys)


def discover_hive(root_path: str):
    """Manifest-less fallback: synthesize a version-0 manifest body by
    walking ``key=value`` directories (interop with datasets written
    by pyarrow/hive, which have no tpq manifest).  Returns None when
    the directory holds no parquet files."""
    files = []
    keys = None
    for dirpath, dirnames, filenames in os.walk(root_path):
        dirnames[:] = sorted(
            d for d in dirnames if not d.startswith(("_", ".")))
        rel = os.path.relpath(dirpath, root_path)
        comps = [] if rel == "." else rel.split(os.sep)
        part = {}
        ok = True
        for c in comps:
            if "=" not in c:
                ok = False
                break
            k, _, v = c.partition("=")
            part[k] = parse_hive_token(v)
        if not ok:
            continue
        for name in sorted(filenames):
            if name.startswith(("_", ".")) or \
                    not name.endswith(".parquet"):
                continue
            if keys is None:
                keys = list(part)
            if set(part) != set(keys):
                raise CorruptManifestError(
                    f"inconsistent partition depth under {root_path!r}:"
                    f" {sorted(part)} vs {sorted(keys)}",
                    file=root_path)
            p = os.path.join(*comps, name) if comps else name
            files.append({
                "path": p.replace(os.sep, "/"),
                "partition": dict(part),
                "rows": None,
                "bytes": os.path.getsize(os.path.join(dirpath, name)),
            })
    if not files:
        return None
    return {"version": 0, "partition_keys": keys or [],
            "files": files}


# ----------------------------------------------------------------------
# Orphan sweep
# ----------------------------------------------------------------------

def sweep_orphans(root: str, *, quarantine=None) -> list:
    """Move staging files and stale journals that no live commit
    references into ``_quarantine/`` — NEVER delete them silently
    (they are the only copy of data from a crashed write; the finding
    tells the operator to resume or discard deliberately).

    A staged file is an orphan when it is referenced by neither the
    pending journal nor the newest valid manifest.  Counts
    ``DecodeStats.dataset_orphans_swept``; each sweep records a
    file-granularity quarantine entry when a report is passed.
    Returns the swept relative paths."""
    from ..stats import current_stats

    _, root_path = split_root(root)
    tmp_dir = os.path.join(root_path, TMP_DIR)
    if not os.path.isdir(tmp_dir):
        return []
    referenced = set()
    swept = []
    qdir = os.path.join(root_path, QUARANTINE_DIR)
    try:
        journal = load_journal(root_path)
    except CorruptManifestError as e:
        # a journal that fails its framing is damage: sweep it too,
        # so a later writer does not trip over it
        journal = None
        os.makedirs(qdir, exist_ok=True)
        os.replace(journal_path(root_path),
                   os.path.join(qdir, JOURNAL_NAME))
        swept.append(JOURNAL_NAME)
        if quarantine is not None:
            quarantine.add_file(
                file=JOURNAL_NAME, error=e,
                swept_to=f"{QUARANTINE_DIR}/{JOURNAL_NAME}")
    if journal is not None:
        for e in journal["files"]:
            if e.get("tmp"):
                referenced.add(e["tmp"])
    for name in sorted(os.listdir(tmp_dir)):
        if name in referenced:
            continue
        src = os.path.join(tmp_dir, name)
        if not os.path.isfile(src):
            continue
        os.makedirs(qdir, exist_ok=True)
        os.replace(src, os.path.join(qdir, name))
        swept.append(f"{TMP_DIR}/{name}")
        if quarantine is not None:
            quarantine.add_file(
                file=f"{TMP_DIR}/{name}",
                error=CorruptManifestError(
                    "orphaned staging file from a crashed write "
                    "(no journal or manifest references it); moved "
                    "to _quarantine/", file=f"{TMP_DIR}/{name}"),
                swept_to=f"{QUARANTINE_DIR}/{name}")
    st = current_stats()
    if st is not None and swept:
        st.dataset_orphans_swept += len(swept)
    return swept
