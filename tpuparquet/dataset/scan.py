"""DatasetScan: manifest-resolved scans with partition-value pruning.

The read-side composition order is the whole point: partition-value
pruning runs against the MANIFEST (no file ever opened), in front of
the per-file stats/bloom/page-index pruning layers, which run in
front of exact predicate evaluation — each layer only sees what the
previous one could not eliminate.  Partition predicates are exact at
file granularity (every row of a file shares its partition values),
so a conjunct that references only partition keys is fully consumed
by pruning and never re-evaluated row-wise.

Everything below the manifest is a plain
:class:`~tpuparquet.shard.scan.ShardedScan` over the surviving files
(sources ride the round-18 ``ByteRangeSource`` layer, so one dataset
can span ``file://`` and ``emu://``), with the dataset's
manifest/sweep findings merged into the same
:class:`~tpuparquet.faults.QuarantineReport` the file-level salvage
ladder reports through.
"""

from __future__ import annotations

import os

from ..errors import CorruptManifestError
from ..faults import QuarantineReport
from ..filter import And, Cmp, In, IsNull, Or, parse_filter
from ..stats import current_stats
from . import manifest as mf

__all__ = ["DatasetScan", "split_partition_filter",
           "partition_matches"]

_CMP = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def split_partition_filter(filter, keys):
    """Split a predicate into ``(partition_pred, residual)``.

    Top-level conjuncts referencing only partition ``keys`` go to the
    partition side (evaluated exactly, per file, against the
    manifest); conjuncts referencing only data columns go to the
    residual (the per-file pruning + exact layers).  A conjunct mixing
    both (an OR across the boundary) cannot be decided at either
    granularity alone and is rejected."""
    if filter is None:
        return None, None
    if isinstance(filter, str):
        filter = parse_filter(filter)
    keys = set(keys)
    part_side, data_side = [], []
    conjuncts = filter.parts if isinstance(filter, And) else [filter]
    for c in conjuncts:
        cols = c.columns()
        if cols <= keys:
            part_side.append(c)
        elif cols & keys:
            raise ValueError(
                f"predicate {c.describe()} mixes partition keys and "
                f"data columns in one disjunct — split it into "
                f"AND-able conjuncts")
        else:
            data_side.append(c)

    def fold(parts):
        if not parts:
            return None
        return parts[0] if len(parts) == 1 else And(parts)

    return fold(part_side), fold(data_side)


def partition_matches(pred, partition: dict) -> bool:
    """Exact evaluation of a partition-only predicate against one
    file's partition values (comparisons never match null, same as
    the row-level filter semantics)."""
    if pred is None:
        return True
    if isinstance(pred, And):
        return all(partition_matches(p, partition) for p in pred.parts)
    if isinstance(pred, Or):
        return any(partition_matches(p, partition) for p in pred.parts)
    v = partition.get(pred.column)
    if isinstance(pred, IsNull):
        return (v is not None) if pred.invert else (v is None)
    if v is None:
        return False
    if isinstance(pred, Cmp):
        try:
            return bool(_CMP[pred.op](v, pred.value))
        except TypeError:
            return pred.op == "!="  # cross-type: never equal
    if isinstance(pred, In):
        return v in pred.values
    raise TypeError(
        f"unsupported partition predicate {type(pred).__name__}")


class DatasetScan:
    """Scan a partitioned dataset through its newest valid manifest.

    ``root`` may be a bare path or a ``file://``/``emu://`` URI; a
    root with no tpq manifest falls back to hive directory discovery
    (interop: datasets written by pyarrow).  ``filter`` conjuncts on
    partition keys prune files against the manifest
    (``DecodeStats.dataset_files_pruned``); the rest flows to the
    inner :class:`ShardedScan` untouched — every per-file keyword
    (``on_error``, ``salvage``, ``resume_from``, ``mesh``, ...)
    passes through.

    ``sweep_orphans=True`` additionally quarantines staging orphans
    from crashed writes before scanning (findings ride
    :attr:`quarantine`; nothing is silently deleted).
    """

    def __init__(self, root, *columns, filter=None,
                 sweep_orphans: bool = False, **scan_kwargs):
        self.root = root
        self._pre_quarantine = QuarantineReport()
        if sweep_orphans:
            mf.sweep_orphans(root, quarantine=self._pre_quarantine)
        body, version, findings = mf.resolve_manifest(
            root, quarantine=self._pre_quarantine)
        if body is None:
            _, root_path = mf.split_root(root)
            if findings:
                raise CorruptManifestError(
                    f"no valid manifest snapshot in {root!r} "
                    f"({len(findings)} rejected)", file=root)
            if os.path.exists(os.path.join(root_path,
                                           mf.JOURNAL_NAME)):
                # a first commit died mid-protocol: half-promoted
                # files must NOT leak through hive discovery — the
                # snapshot-or-nothing contract says "nothing"
                raise FileNotFoundError(
                    f"{root!r} has a pending commit journal and no "
                    f"published snapshot — resume the write with "
                    f"DatasetWriter(resume_from=...) to finish it")
            body = mf.discover_hive(root_path)
            if body is None:
                raise FileNotFoundError(
                    f"{root!r} holds neither a manifest nor hive "
                    f"partition directories")
            version = 0
        self.manifest = body
        self.version = version
        self.findings = findings
        keys = body["partition_keys"]
        for c in columns:
            if c in keys:
                raise ValueError(
                    f"column {c!r} is a partition key: hive data "
                    f"files do not store it — read it from "
                    f".files() / .partitions instead")
        part_pred, residual = split_partition_filter(filter, keys)
        survivors, pruned = [], 0
        for e in body["files"]:
            if partition_matches(part_pred, e["partition"]):
                survivors.append(e)
            else:
                pruned += 1
        self.files_pruned = pruned
        st = current_stats()
        if st is not None and pruned:
            st.dataset_files_pruned += pruned
        self._entries = survivors
        self.sources = [e.get("uri") or mf.file_uri(root, e["path"])
                        for e in survivors]
        #: source string -> partition-value dict (what a consumer
        #: joins back to reconstruct partition columns)
        self.partitions = {s: dict(e["partition"])
                           for s, e in zip(self.sources, survivors)}
        from ..shard.scan import ShardedScan

        self._scan = ShardedScan(self.sources, *columns,
                                 filter=residual, **scan_kwargs)

    # -- delegation -------------------------------------------------------

    def files(self):
        """The surviving ``(source, partition_dict, rows, bytes)``
        entries, in manifest order."""
        return [(s, dict(e["partition"]), e.get("rows"),
                 e.get("bytes"))
                for s, e in zip(self.sources, self._entries)]

    @property
    def units(self):
        return self._scan.units

    @property
    def readers(self):
        return self._scan.readers

    @property
    def quarantine(self) -> QuarantineReport:
        """Manifest/sweep findings + the inner scan's report, one
        report (dataset failures and file failures flow to the same
        place)."""
        out = QuarantineReport(self._pre_quarantine.as_dicts())
        out.merge_unique(self._scan.quarantine.as_dicts())
        return out

    def run_iter(self):
        yield from self._scan.run_iter()

    def run(self):
        return self._scan.run()

    def run_with_stats(self, events: bool = False):
        """:meth:`run` under a fresh collector (the dataset-level
        prune verdicts are folded in, so the counters a caller sees
        are complete for this run)."""
        from ..stats import collect_stats

        with collect_stats(events=events) as st:
            if self.files_pruned:
                st.dataset_files_pruned += self.files_pruned
            results = self._scan.run()
        return results, st

    def state(self) -> dict:
        return self._scan.state()

    def request_stop(self) -> None:
        self._scan.request_stop()

    def gather_column(self, results, path, **kw):
        return self._scan.gather_column(results, path, **kw)

    def gather_byte_column(self, results, path, **kw):
        return self._scan.gather_byte_column(results, path, **kw)

    def close(self):
        self._scan.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
