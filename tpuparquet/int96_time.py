"""INT96 timestamp conversion (legacy Impala/Hive encoding).

Parity with ``Int96ToTime``/``TimeToInt96``
(``/root/reference/int96_time.go:29-46``): 12 little-endian bytes =
uint64 nanoseconds within the day followed by uint32 Julian day number.
"""

from __future__ import annotations

import datetime

__all__ = ["int96_to_datetime", "datetime_to_int96"]

_JULIAN_UNIX_EPOCH = 2_440_588  # Julian day of 1970-01-01
_NS_PER_DAY = 86_400 * 1_000_000_000


def int96_to_datetime(b: bytes) -> datetime.datetime:
    """12-byte INT96 -> naive UTC datetime (microsecond resolution)."""
    if len(b) != 12:
        raise ValueError(f"INT96 must be 12 bytes, got {len(b)}")
    nanos = int.from_bytes(b[:8], "little")
    jd = int.from_bytes(b[8:12], "little")
    days = jd - _JULIAN_UNIX_EPOCH
    epoch = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
    dt = epoch + datetime.timedelta(days=days, microseconds=nanos // 1000)
    return dt.replace(tzinfo=None)


def datetime_to_int96(dt: datetime.datetime) -> bytes:
    """Naive-UTC (or aware) datetime -> 12-byte INT96."""
    if dt.tzinfo is not None:
        dt = dt.astimezone(datetime.timezone.utc).replace(tzinfo=None)
    epoch = datetime.datetime(1970, 1, 1)
    delta = dt - epoch
    jd = delta.days + _JULIAN_UNIX_EPOCH
    nanos = (delta.seconds * 1_000_000 + delta.microseconds) * 1000
    return nanos.to_bytes(8, "little") + jd.to_bytes(4, "little")
