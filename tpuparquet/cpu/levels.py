"""Definition/repetition level codecs + null-mask derivation (NumPy).

Levels are hybrid-RLE-encoded: V1 data pages carry a 4-byte length prefix
per level stream (``helpers.go:260-271`` / ``page_v1.go:27-55``); V2 pages
store the streams raw with their byte lengths in the page header
(``page_v2.go:73-129``, ``helpers.go:272-282``).  A column with
``max_level == 0`` has no stream at all — every level is 0
(``constDecoder``, ``helpers.go:208``).

``decode_levels`` also returns what the fused TPU kernel produces: the
non-null count (values with ``def == max_def`` are present —
``decodePackedArray``, ``helpers.go:131-147``).
"""

from __future__ import annotations

import numpy as np

from .bitpack import unpack_msb
from .hybrid import (
    as_uint32,
    decode_hybrid,
    decode_hybrid_prefixed,
    encode_hybrid,
    encode_hybrid_prefixed,
    expand_scan,
    scan_hybrid,
    slice_prefixed,
)

__all__ = [
    "bit_width",
    "decode_levels_v1",
    "decode_levels_raw",
    "decode_levels_bitpacked",
    "encode_levels_v1",
    "encode_levels_v2",
    "null_mask",
]


def bit_width(max_level: int) -> int:
    """Bits needed for levels 0..max_level (``bits.Len16`` equivalent)."""
    return int(max_level).bit_length()


def decode_levels_v1(data, count: int, max_level: int, pos: int = 0):
    """Length-prefixed RLE level stream; returns (levels, end_pos)."""
    if max_level == 0:
        return np.zeros(count, dtype=np.int32), pos
    stream, end = slice_prefixed(data, pos)
    return _expand_checked(stream, count, max_level), end


def decode_levels_raw(data, count: int, max_level: int):
    """Unprefixed RLE level stream (V2 pages; byte length known from the
    page header, so ``data`` is exactly the stream)."""
    if max_level == 0:
        return np.zeros(count, dtype=np.int32)
    return _expand_checked(data, count, max_level)


def _scan_max(sc, width: int):
    """Max level over a run table's CONSUMED values without a full
    expand: RLE run values are read straight off the table, bit-packed
    segments get one native C pass over their consumed lanes
    (``tpq_bp_stats``).  Returns None when the native scanner is
    unavailable — the caller then validates on the expanded array (the
    pre-round-6 full pass)."""
    ends, is_rle, value, bp_start, bp_bytes, n_bp = sc[:6]
    mx = 0
    if is_rle.any():
        mx = int(value[is_rle].max())
    bp = ~is_rle
    if bp.any() and n_bp:
        from ..native import hybrid_native

        nat = hybrid_native()
        if nat is None or getattr(nat, "_bp_stats_fn", None) is None:
            return None
        lens = np.diff(ends, prepend=np.int32(0))
        bp_mx, _ = nat.bp_stats(bp_bytes, width, bp_start[bp], lens[bp], 0)
        if bp_mx is not None:
            mx = max(mx, bp_mx)
    return mx


def _expand_checked(data, count: int, max_level: int) -> np.ndarray:
    """One-scan level decode: run-table max validation (O(runs), native
    bp pass) + vectorized expand, and a zero-copy int32 view of the
    expanded uint32 instead of the old full-array ``astype`` — the
    rep/def streams of a nested 50M-value chunk paid two extra full
    passes here."""
    width = bit_width(max_level)
    sc = scan_hybrid(data, count, width)
    mx = _scan_max(sc, width)
    if mx is not None and mx > max_level:
        raise ValueError(
            f"level value {mx} exceeds max level {max_level}")
    vals = expand_scan(*sc[:6], count, width)
    out = (vals.view(np.int32) if vals.dtype == np.uint32
           else vals.astype(np.int32))
    if mx is None:
        return _check(out, max_level)
    return out


def decode_levels_bitpacked(data, count: int, max_level: int):
    """Deprecated BIT_PACKED (MSB-first) level encoding."""
    if max_level == 0:
        return np.zeros(count, dtype=np.int32)
    return _check(unpack_msb(data, count, bit_width(max_level)), max_level)


def _check(vals, max_level: int) -> np.ndarray:
    out = vals.astype(np.int32)
    if out.size and out.max() > max_level:
        raise ValueError(
            f"level value {int(out.max())} exceeds max level {max_level}"
        )
    return out


def encode_levels_v1(levels, max_level: int) -> bytes:
    if max_level == 0:
        return b""
    return encode_hybrid_prefixed(as_uint32(levels), bit_width(max_level))


def encode_levels_v2(levels, max_level: int) -> bytes:
    if max_level == 0:
        return b""
    return encode_hybrid(as_uint32(levels), bit_width(max_level))


def null_mask(def_levels: np.ndarray, max_def: int) -> np.ndarray:
    """True where a value is present (non-null) at this leaf."""
    if max_def == 0:
        return np.ones(len(def_levels), dtype=bool)
    return np.asarray(def_levels) == max_def
