"""BYTE_STREAM_SPLIT codec (NumPy): scatter value bytes into K streams.

In the Encoding enum (``parquet.thrift:468``) but unimplemented by the
reference; trivial as a transpose here, and it measurably improves the
compressibility of float columns."""

from __future__ import annotations

import numpy as np

__all__ = ["encode_byte_stream_split", "decode_byte_stream_split"]


def encode_byte_stream_split(values: np.ndarray) -> bytes:
    v = np.ascontiguousarray(values)
    k = v.dtype.itemsize
    return v.view(np.uint8).reshape(-1, k).T.tobytes()


def decode_byte_stream_split(data, count: int, dtype) -> np.ndarray:
    dt = np.dtype(dtype)
    k = dt.itemsize
    need = count * k
    if len(data) < need:
        raise ValueError("BYTE_STREAM_SPLIT: input too short")
    streams = np.frombuffer(data, dtype=np.uint8, count=need).reshape(k, count)
    return np.ascontiguousarray(streams.T).reshape(-1).view(dt)
