"""RLE_DICTIONARY index codec + dictionary build/gather (NumPy).

Wire format (``/root/reference/type_dict.go:22-59,161-196``): a data page of
dictionary-encoded values is one byte of index bit-width followed by an
unprefixed hybrid RLE/bit-packed stream of dictionary indices.  Dictionary
*pages* hold the distinct values PLAIN-encoded (handled by the page layer).

The write-side dictionary is built with ``np.unique`` in one shot at flush
time instead of the reference's per-value interning hash map
(``type_dict.go:93-143``) — same result, vectorized.
"""

from __future__ import annotations

import numpy as np

from .hybrid import decode_hybrid, encode_hybrid
from .plain import ByteArrayColumn

__all__ = [
    "decode_dict_indices",
    "encode_dict_indices",
    "gather",
    "build_dictionary",
]


def decode_dict_indices(data, count: int) -> np.ndarray:
    """Decode (bit_width byte + hybrid stream) to int32 indices."""
    if count == 0:
        return np.empty(0, dtype=np.int32)
    if len(data) < 1:
        raise ValueError("empty dictionary-index stream")
    width = data[0]
    if width > 32:
        raise ValueError(f"dictionary index bit width {width} > 32")
    if width == 0:
        return np.zeros(count, dtype=np.int32)
    return decode_hybrid(data, count, width, pos=1).astype(np.int32)


def encode_dict_indices(indices, dict_size: int) -> bytes:
    """Encode int indices as (bit_width byte + hybrid stream)."""
    width = max(int(dict_size - 1).bit_length(), 1) if dict_size > 1 else 1
    return bytes([width]) + encode_hybrid(
        np.asarray(indices, dtype=np.uint32), width
    )


def gather(dictionary, indices: np.ndarray):
    """Materialize values from dictionary + indices.

    ndarray dictionaries gather with fancy indexing; ByteArrayColumn
    dictionaries gather into a new offsets+data pair (the same shape the
    Pallas dict-gather kernel produces on device)."""
    idx = np.asarray(indices)
    if isinstance(dictionary, ByteArrayColumn):
        if idx.size and (idx.min() < 0 or idx.max() >= len(dictionary)):
            raise ValueError("dictionary index out of range")
        lens = dictionary.lengths()[idx]
        offsets = np.zeros(idx.size + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        src_off = np.asarray(dictionary.offsets, dtype=np.int64)
        data = np.asarray(dictionary.data)
        # vectorized byte gather: out byte b of value i comes from
        # src_off[idx[i]] + (b - offsets[i]) — fancy indexing instead of
        # a per-value Python loop (2.7 -> ~9 M values/s on strings).
        # Value-aligned slabs bound the int64 position temporaries to
        # ~3x slab size instead of ~24x the whole output.
        total = int(offsets[-1])
        out = np.empty(total, dtype=np.uint8)
        shift = src_off[idx] - offsets[:-1]
        slab = 4 << 20
        va = 0
        while va < idx.size:
            vb = (int(np.searchsorted(offsets, offsets[va] + slab,
                                      side="left"))
                  if total - int(offsets[va]) > slab else idx.size)
            vb = max(vb, va + 1)
            lo, hi = int(offsets[va]), int(offsets[vb])
            pos = (np.arange(lo, hi, dtype=np.int64)
                   + np.repeat(shift[va:vb], lens[va:vb]))
            out[lo:hi] = data[pos]
            va = vb
        return ByteArrayColumn(offsets, out)
    arr = np.asarray(dictionary)
    if idx.size and (idx.min() < 0 or idx.max() >= len(arr)):
        raise ValueError("dictionary index out of range")
    return arr[idx]


def build_dictionary(values):
    """Return (dictionary, indices) preserving first-occurrence order.

    First-occurrence order matches what an interning writer produces, so
    files we write look like the reference's (and parquet-mr's) output.
    """
    if isinstance(values, (list, tuple)):
        # np.asarray on a list of bytes coerces to a fixed 'S' dtype that
        # strips trailing NULs — go through ByteArrayColumn instead.
        values = ByteArrayColumn.from_list(values)
    if isinstance(values, ByteArrayColumn):
        vals = values.to_list()
        seen: dict = {}
        indices = np.empty(len(vals), dtype=np.int32)
        for i, v in enumerate(vals):
            j = seen.get(v)
            if j is None:
                j = len(seen)
                seen[v] = j
            indices[i] = j
        return ByteArrayColumn.from_list(list(seen)), indices
    arr = np.asarray(values)
    if arr.ndim == 2:  # FIXED_LEN_BYTE_ARRAY / INT96 rows
        uniq, first_idx, inv = np.unique(
            arr, axis=0, return_index=True, return_inverse=True
        )
    else:
        uniq, first_idx, inv = np.unique(
            arr, return_index=True, return_inverse=True
        )
    # np.unique sorts; remap to first-occurrence order.
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty_like(order)
    rank[order] = np.arange(order.size)
    return uniq[order], rank[inv].astype(np.int32)
