"""RLE_DICTIONARY index codec + dictionary build/gather (NumPy).

Wire format (``/root/reference/type_dict.go:22-59,161-196``): a data page of
dictionary-encoded values is one byte of index bit-width followed by an
unprefixed hybrid RLE/bit-packed stream of dictionary indices.  Dictionary
*pages* hold the distinct values PLAIN-encoded (handled by the page layer).

The write-side dictionary is built with ``np.unique`` in one shot at flush
time instead of the reference's per-value interning hash map
(``type_dict.go:93-143``) — same result, vectorized.
"""

from __future__ import annotations

import numpy as np

from .hybrid import as_uint32, decode_hybrid, encode_hybrid
from .plain import ByteArrayColumn

__all__ = [
    "decode_dict_indices",
    "encode_dict_indices",
    "gather",
    "build_dictionary",
]


def decode_dict_indices(data, count: int) -> np.ndarray:
    """Decode (bit_width byte + hybrid stream) to int32 indices."""
    if count == 0:
        return np.empty(0, dtype=np.int32)
    if len(data) < 1:
        raise ValueError("empty dictionary-index stream")
    width = data[0]
    if width > 32:
        raise ValueError(f"dictionary index bit width {width} > 32")
    if width == 0:
        return np.zeros(count, dtype=np.int32)
    return decode_hybrid(data, count, width, pos=1).astype(np.int32)


def encode_dict_indices(indices, dict_size: int) -> bytes:
    """Encode int indices as (bit_width byte + hybrid stream)."""
    width = max(int(dict_size - 1).bit_length(), 1) if dict_size > 1 else 1
    return bytes([width]) + encode_hybrid(as_uint32(indices), width)


def gather(dictionary, indices: np.ndarray):
    """Materialize values from dictionary + indices.

    ndarray dictionaries gather with fancy indexing; ByteArrayColumn
    dictionaries gather into a new offsets+data pair (the same shape the
    Pallas dict-gather kernel produces on device)."""
    idx = np.asarray(indices)
    if isinstance(dictionary, ByteArrayColumn):
        if idx.size and (idx.min() < 0 or idx.max() >= len(dictionary)):
            raise ValueError("dictionary index out of range")
        lens = dictionary.lengths()[idx]
        offsets = np.zeros(idx.size + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        src_off = np.asarray(dictionary.offsets, dtype=np.int64)
        data = np.asarray(dictionary.data)
        total = int(offsets[-1])
        starts = src_off[idx]
        from ..native import delta_native

        nat = delta_native()
        if nat is not None:
            out = nat.gather_var(data, starts, lens, total)
            if out is not None:
                return ByteArrayColumn(offsets, out)
            from ..stats import current_stats

            st = current_stats()
            if st is not None:  # stale .so: record the quiet slow path
                st.native_fallbacks += 1
        # numpy fallback: out byte b of value i comes from
        # src_off[idx[i]] + (b - offsets[i]) — fancy indexing instead of
        # a per-value Python loop.  Value-aligned slabs bound the int64
        # position temporaries to ~3x slab size instead of ~24x the
        # whole output.
        out = np.empty(total, dtype=np.uint8)
        shift = starts - offsets[:-1]
        slab = 4 << 20
        va = 0
        while va < idx.size:
            vb = (int(np.searchsorted(offsets, offsets[va] + slab,
                                      side="left"))
                  if total - int(offsets[va]) > slab else idx.size)
            vb = max(vb, va + 1)
            lo, hi = int(offsets[va]), int(offsets[vb])
            pos = (np.arange(lo, hi, dtype=np.int64)
                   + np.repeat(shift[va:vb], lens[va:vb]))
            out[lo:hi] = data[pos]
            va = vb
        return ByteArrayColumn(offsets, out)
    arr = np.asarray(dictionary)
    if idx.size and (idx.min() < 0 or idx.max() >= len(arr)):
        raise ValueError("dictionary index out of range")
    return arr[idx]


def _first_occurrence_rank(first_idx: np.ndarray):
    """(order, rank) re-ranking sorted-unique ids by first occurrence;
    ``kind="stable"`` everywhere or tie-breaking (and file bytes)
    silently change."""
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty_like(order)
    rank[order] = np.arange(order.size)
    return order, rank


# Pluggable row hash (≙ the reference's ``DefaultHashFunc``,
# helpers.go:18-22, which it uses to intern values for dictionary
# pages).  The signature is VECTORIZED — (k, L) u8 row matrix in, (k,)
# u64 out — because the interner never touches values one at a time; a
# per-value Python hook would cost more than the encode it feeds.
# Unlike the reference (where two colliding keys silently merge into
# one dictionary slot), a replacement hash here cannot corrupt output:
# every row is byte-compared against its group's first occurrence and
# any collision falls back to the exact memcmp path below.
row_hash_func = None  # None -> the built-in FNV-style _hash_rows


def _unique_rows(rows: np.ndarray):
    """(first_idx, inverse) over the rows of a (k, L) u8 matrix.

    A vectorized FNV-style hash reduces row identity to one u64 sort
    (the direct ``np.unique`` over void rows pays a memcmp argsort, the
    hottest call in string dictionary builds); every row is then
    byte-compared against its group's first occurrence, and any
    collision falls back to the exact void path.  Sort order of the
    uniques differs between the paths, but callers only consume the
    SET via first-occurrence re-ranking, so results are identical."""
    k, L = rows.shape
    if L > 64 and L > k:
        # few, long values (blobs): one memcmp sort over k rows beats
        # O(L) vectorized hash passes
        return _unique_rows_void(rows)
    h = (row_hash_func or _hash_rows)(rows)
    h = np.asarray(h, dtype=np.uint64)
    if h.shape != (k,):
        raise ValueError(
            f"row_hash_func must return shape ({k},) u64, got {h.shape}")
    out = _unique_rows_table(rows, h)
    if out is not None:
        return out
    # np.unique(return_index=...) pays a full argsort; a plain value
    # sort + searchsorted inverse + reversed-scatter first occurrence
    # gets the same triple in O(k log k) comparisons without the
    # permutation sort
    hu = np.unique(h)
    inv = np.searchsorted(hu, h)
    first_idx = np.empty(hu.size, dtype=np.int64)
    first_idx[inv[::-1]] = np.arange(k - 1, -1, -1, dtype=np.int64)
    if np.array_equal(rows[first_idx[inv]], rows):
        return first_idx, inv
    # hash collision (vanishingly rare): exact void-row unique
    return _unique_rows_void(rows)


def _hash_rows(rows: np.ndarray) -> np.ndarray:
    """Vectorized FNV-style row hash over u64 words (zero-padded tail),
    one multiply-add pass per 8 row bytes."""
    k, L = rows.shape
    nw = (L + 7) // 8
    if L % 8:
        padded = np.zeros((k, nw * 8), dtype=np.uint8)
        padded[:, :L] = rows
    else:
        padded = np.ascontiguousarray(rows)
    words = padded.view("<u8")
    h = np.full(k, np.uint64(1469598103934665603 + 31 * L),
                dtype=np.uint64)
    prime = np.uint64(1099511628211)
    for j in range(nw):
        h = (h ^ words[:, j]) * prime
    return h


def _unique_rows_table(rows: np.ndarray, h: np.ndarray):
    """O(k + T) table interning of hashed rows — replaces the u64 sort
    (``np.unique`` + ``searchsorted``) that dominates low-cardinality
    string dictionary builds.  Each row scatters into a power-of-two
    slot table by hash; every row is then byte-compared against its
    slot's first occupant, so a slot shared by two DISTINCT values (slot
    or hash collision alike) fails the compare and returns None — the
    caller falls back to the exact sorted path.  With D distinct values
    in T ≈ 4k slots the false-fallback probability is ~D²/2T:
    negligible at dictionary-worthy cardinalities."""
    k = rows.shape[0]
    if k < 4096:
        # the sort this path replaces is near-free at small k; the
        # 64k-slot minimum table would cost more than it saves
        return None
    tbits = max(16, min(22, (4 * k - 1).bit_length()))
    T = 1 << tbits
    # Fibonacci hashing for the slot: multiply then take the HIGH bits.
    # A low-bit mask (even XOR-folded) inherits the FNV multiply's
    # linear structure — near-identical inputs collapsed 200 distinct
    # hashes onto 100 slots when this used ``(h ^ h>>32) & (T-1)``.
    slot = ((h * np.uint64(0x9E3779B97F4A7C15))
            >> np.uint64(64 - tbits)).astype(np.int64)
    first = np.full(T, k, dtype=np.int64)
    # reversed scatter keeps the LAST write = smallest original index
    first[slot[::-1]] = np.arange(k - 1, -1, -1, dtype=np.int64)
    rep = first[slot]
    if not np.array_equal(rows[rep], rows):
        return None
    present = first < k
    first_idx = first[present]
    # inverse: rank of each row's slot among occupied slots (slot order)
    lookup = np.cumsum(present) - 1
    inv = lookup[slot]
    return first_idx, inv


def _unique_rows_void(rows: np.ndarray):
    """Exact memcmp-ordered unique over fixed-width rows."""
    k, L = rows.shape
    view = np.ascontiguousarray(rows).view(
        np.dtype((np.void, L))).reshape(-1)
    _, first_idx, inv = np.unique(view, return_index=True,
                                  return_inverse=True)
    return first_idx, inv


def _gather_rows(data: np.ndarray, starts: np.ndarray, k: int,
                 L: int) -> np.ndarray:
    """(k, L) row matrix of fixed-length segments: one C memcpy pass
    when the native is present, else slab-bounded fancy indexing (the
    (k, L) int64 position temporary is 8L bytes per row — larger than
    the rows it gathers)."""
    from ..native import delta_native

    nat = delta_native()
    if nat is not None:
        out = nat.gather_segments(data, starts, L)
        if out is not None:
            return out.reshape(k, L)
    rows = np.empty((k, L), dtype=np.uint8)
    slab = max(1, (4 << 20) // L)
    for s in range(0, k, slab):
        e = min(s + slab, k)
        pos = (np.arange(L, dtype=np.int64)
               + starts[s:e][:, None])
        rows[s:e] = data[pos]
    return rows


def _build_bytes_dictionary(values: ByteArrayColumn):
    """Vectorized first-occurrence interning of variable-length bytes.

    Values group by length; within one length they compare as fixed-
    width rows via ``np.unique`` (a per-value Python dict loop costs
    more than the encode it feeds at millions of strings).  Row gathers
    walk value slabs so index temporaries stay bounded; global ids
    re-rank by first occurrence so the output is identical to the
    sequential interner — files look like the reference's."""
    n = len(values)
    if n == 0:
        return ByteArrayColumn.from_list([]), np.empty(0, dtype=np.int32)
    offsets = np.asarray(values.offsets, dtype=np.int64)
    data = np.asarray(values.data)
    lens = offsets[1:] - offsets[:-1]
    indices = np.empty(n, dtype=np.int64)
    group_firsts = []   # per group: first-occurrence value positions,
    next_id = 0         # in group-local unique-id order
    for L in np.unique(lens):
        L = int(L)
        sel = np.nonzero(lens == L)[0]
        if L == 0:
            indices[sel] = next_id
            group_firsts.append(sel[:1])
            next_id += 1
            continue
        k = sel.size
        rows = _gather_rows(data, offsets[sel], k, L)
        first_idx, inv = _unique_rows(rows)
        order, rank = _first_occurrence_rank(first_idx)
        indices[sel] = next_id + rank[inv]
        group_firsts.append(sel[first_idx[order]])
        next_id += order.size
    # global first-occurrence order across the length groups
    uniq_first = np.concatenate(group_firsts)
    gorder, grank = _first_occurrence_rank(uniq_first)
    indices = grank[indices]
    # the dictionary IS the unique values gathered in global order
    return gather(values, uniq_first[gorder]), indices.astype(np.int32)


def intern_byte_column(values: ByteArrayColumn, max_distinct: int):
    """One-pass C interning of a byte column with a distinct-count cap.

    Returns ``(dictionary, indices)`` — identical to
    :func:`build_dictionary` (first-occurrence order, exact memcmp
    identity) — or the ``TOO_MANY_DISTINCT`` sentinel once more than
    ``max_distinct`` distinct values appear (the dictionary gate would
    reject anyway, so high-cardinality columns abort in O(cap) instead
    of paying a full intern), or None when the native is unavailable
    or a custom ``row_hash_func`` is installed (the C pass has its own
    FNV and must not silently bypass the user's hook)."""
    from ..native import TOO_MANY_DISTINCT, intern_native

    if row_hash_func is not None:
        return None
    nat = intern_native()
    if nat is None:
        return None
    n = len(values)
    if n == 0:
        return None  # python path makes the canonical empty shapes
    out = nat.intern_var(values.data, values.offsets, max_distinct)
    if out is TOO_MANY_DISTINCT:
        return TOO_MANY_DISTINCT
    firsts, indices = out
    return gather(values, firsts), indices


def build_dictionary(values):
    """Return (dictionary, indices) preserving first-occurrence order.

    First-occurrence order matches what an interning writer produces, so
    files we write look like the reference's (and parquet-mr's) output.
    """
    if isinstance(values, (list, tuple)):
        # np.asarray on a list of bytes coerces to a fixed 'S' dtype that
        # strips trailing NULs — go through ByteArrayColumn instead.
        values = ByteArrayColumn.from_list(values)
    if isinstance(values, ByteArrayColumn):
        return _build_bytes_dictionary(values)
    arr = np.asarray(values)
    if arr.ndim == 2:  # FIXED_LEN_BYTE_ARRAY / INT96 rows
        uniq, first_idx, inv = np.unique(
            arr, axis=0, return_index=True, return_inverse=True
        )
    else:
        if arr.dtype.kind in "iu":
            out = _build_int_dictionary_smallrange(arr)
            if out is not None:
                return out
        uniq, first_idx, inv = np.unique(
            arr, return_index=True, return_inverse=True
        )
    # np.unique sorts; remap to first-occurrence order.
    order, rank = _first_occurrence_rank(first_idx)
    return uniq[order], rank[inv].astype(np.int32)


def _build_int_dictionary_smallrange(arr: np.ndarray):
    """O(n + range) interning for integer columns whose value range is
    small (the dictionary-friendly case: categories, codes, quantized
    measures) — replaces the sort-based ``np.unique`` whose argsort
    dominated ``write_columns`` profiles.  Returns None when the range
    is too wide to table; output is identical to the unique path
    (first-occurrence order)."""
    n = arr.size
    if n == 0:
        return None
    lo = arr.min()
    amin, amax = int(lo), int(arr.max())
    rng = amax - amin + 1  # Python ints: no wraparound on wide spans
    # the table costs O(range): past a few multiples of n the sort-based
    # unique path is cheaper than touching rng-sized arrays
    if rng > 4 * n or rng > 1 << 24:
        return None
    if arr.itemsize in (4, 8):
        # one-pass C intern (intern.c tpq_intern_range32/64): indices
        # and first-occurrence order fall out of the sequential scan,
        # replacing the widen/scatter/argsort/gather numpy passes below
        from ..native import intern_native

        nat = intern_native()
        if nat is not None:
            out = nat.intern_range(np.ascontiguousarray(arr), amin, rng)
            if out is not None:
                uniq_pos, indices = out
                return arr[uniq_pos], indices
    # Signed dtypes must widen BEFORE subtracting: an int8 span of 200
    # wraps under own-dtype subtraction, aliasing distinct values into
    # one slot.  Unsigned stays in its own dtype (a Python-int amin
    # overflows int64 for uint64 columns); the gated span fits int64.
    if arr.dtype.kind == "i":
        off = arr.astype(np.int64) - amin
    else:
        off = (arr - lo).astype(np.int64)
    # first occurrence per value: reversed fancy assignment keeps the
    # LAST write, which is the smallest original index
    first = np.full(rng, n, dtype=np.int64)
    first[off[::-1]] = np.arange(n - 1, -1, -1, dtype=np.int64)
    present = first < n
    firsts = first[present]
    order = np.argsort(firsts, kind="stable")  # D log D, D small
    d = order.size
    rank = np.empty(d, dtype=np.int64)
    rank[order] = np.arange(d)
    lookup = np.empty(rng, dtype=np.int64)
    lookup[present] = rank
    # reconstruct in the array's dtype (amin as a Python int overflows
    # int64 for uint64 columns)
    uniq = np.nonzero(present)[0][order].astype(arr.dtype) + lo
    return uniq, lookup[off].astype(np.int32)
