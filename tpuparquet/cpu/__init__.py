"""CPU oracle codecs: NumPy implementations of every Parquet encoding.

These are the bit-exact reference implementations the device kernels are
validated against (SURVEY.md §7 stage 2), and the production CPU path."""

from .bitpack import pack, unpack, pack_msb, unpack_msb  # noqa: F401
from .bss import decode_byte_stream_split, encode_byte_stream_split  # noqa: F401
from .delta import (  # noqa: F401
    decode_delta_binary_packed,
    decode_delta_byte_array,
    decode_delta_length_byte_array,
    encode_delta_binary_packed,
    encode_delta_byte_array,
    encode_delta_length_byte_array,
)
from .dictionary import (  # noqa: F401
    build_dictionary,
    decode_dict_indices,
    encode_dict_indices,
    gather,
)
from .hybrid import (  # noqa: F401
    as_uint32,
    decode_hybrid,
    decode_hybrid_prefixed,
    encode_hybrid,
    encode_hybrid_prefixed,
)
from .levels import (  # noqa: F401
    bit_width,
    decode_levels_bitpacked,
    decode_levels_raw,
    decode_levels_v1,
    encode_levels_v1,
    encode_levels_v2,
    null_mask,
)
from .plain import ByteArrayColumn, decode_plain, encode_plain  # noqa: F401
