"""Vectorized bit-packing (NumPy).

The reference ships 4,738 lines of generated fully-unrolled Go pack/unpack
functions (``/root/reference/bitbacking32.go``, ``bitpacking64.go``,
``bitpack_gen.go``).  On the NumPy/TPU side the same operation is a handful
of array ops: explode bytes to a little-endian bit matrix, regroup into
``width``-bit lanes, and reduce with powers of two — one implementation for
every width 0..64 instead of 130 generated functions.

Two bit orders exist in Parquet:

* **LSB-first** ("RLE/bit-packed hybrid" order): values occupy consecutive
  bits starting at the least-significant bit of byte 0.  Used by the hybrid
  encoding, dictionary indices, levels, and DELTA_BINARY_PACKED miniblocks.
* **MSB-first** (deprecated ``BIT_PACKED`` encoding for levels): big-endian
  bit order within each byte.
"""

from __future__ import annotations

import numpy as np

__all__ = ["unpack", "pack", "unpack_msb", "pack_msb"]


def _out_dtype(width: int):
    return np.uint64 if width > 32 else np.uint32


def unpack(data, count: int, width: int) -> np.ndarray:
    """Unpack ``count`` LSB-first ``width``-bit values from ``data``.

    Returns an unsigned array (uint32 for width<=32, else uint64).
    ``data`` may contain trailing padding bits/bytes; they are ignored.
    """
    if width == 0:
        return np.zeros(count, dtype=np.uint32)
    if not 0 < width <= 64:
        raise ValueError(f"bit width {width} out of range 0..64")
    buf = np.frombuffer(data, dtype=np.uint8)
    need_bits = count * width
    need_bytes = (need_bits + 7) // 8
    if len(buf) < need_bytes:
        raise ValueError(
            f"bit-packed input too short: need {need_bytes} bytes for "
            f"{count} x {width}-bit values, have {len(buf)}"
        )
    if width % 8 == 0:
        # Byte-aligned fast path: each value is width/8 little-endian bytes.
        k = width // 8
        padded = np.zeros((count, 8), dtype=np.uint8)
        padded[:, :k] = np.asarray(buf[:need_bytes]).reshape(count, k)
        return padded.view("<u8").reshape(count).astype(_out_dtype(width))
    bits = np.unpackbits(buf[:need_bytes], bitorder="little", count=need_bits)
    lanes = bits.reshape(count, width).astype(_out_dtype(width))
    weights = (np.uint64(1) << np.arange(width, dtype=np.uint64)).astype(
        _out_dtype(width)
    )
    return lanes @ weights if width > 1 else lanes[:, 0]


def pack(values, width: int) -> bytes:
    """Pack unsigned values into LSB-first ``width``-bit lanes.

    Output is padded with zero bits to a whole number of bytes."""
    if width == 0:
        return b""
    if not 0 < width <= 64:
        raise ValueError(f"bit width {width} out of range 0..64")
    v = np.asarray(values).astype(np.uint64, copy=False)
    from ..native import pack_native

    nat = pack_native()
    if nat is not None:  # one C pass (fit check included)
        return nat.pack(v, width).tobytes()
    _check_fits(v, width)
    if width % 8 == 0:
        k = width // 8
        vb = np.ascontiguousarray(v).view(np.uint8).reshape(-1, 8)
        return np.ascontiguousarray(vb[:, :k]).tobytes()
    # Stay in uint8 end to end: explode each value's 8 LE bytes to a 64-bit
    # row, keep the low `width` bits, and re-pack.  (A uint64 bit matrix
    # here would be 8x the memory and dominated encode time.)
    vb = np.ascontiguousarray(v).view(np.uint8).reshape(-1, 8)
    bits = np.unpackbits(vb, axis=1, bitorder="little")[:, :width]
    return np.packbits(bits.reshape(-1), bitorder="little").tobytes()


def _check_fits(v: np.ndarray, width: int) -> None:
    """Dropping high bits would silently corrupt the stream (e.g. a level 2
    written at width 1 reads back as 0 = null) — refuse instead."""
    if width < 64 and v.size and bool((v >> np.uint64(width)).any()):
        raise ValueError(
            f"value {int(v.max())} does not fit in {width} bits"
        )


def unpack_msb(data, count: int, width: int) -> np.ndarray:
    """Unpack the deprecated BIT_PACKED (MSB-first) level encoding."""
    if width == 0:
        return np.zeros(count, dtype=np.uint32)
    buf = np.frombuffer(data, dtype=np.uint8)
    need_bits = count * width
    need_bytes = (need_bits + 7) // 8
    if len(buf) < need_bytes:
        raise ValueError("bit-packed (msb) input too short")
    bits = np.unpackbits(buf[:need_bytes], bitorder="big", count=need_bits)
    lanes = bits.reshape(count, width).astype(_out_dtype(width))
    weights = (np.uint64(1) << np.arange(width - 1, -1, -1, dtype=np.uint64)).astype(
        _out_dtype(width)
    )
    return lanes @ weights if width > 1 else lanes[:, 0]


def pack_msb(values, width: int) -> bytes:
    if width == 0:
        return b""
    v = np.asarray(values).astype(np.uint64, copy=False)
    _check_fits(v, width)
    shifts = np.arange(width - 1, -1, -1, dtype=np.uint64)
    bits = ((v[:, None] >> shifts) & np.uint64(1)).astype(np.uint8)
    return np.packbits(bits.reshape(-1), bitorder="big").tobytes()
