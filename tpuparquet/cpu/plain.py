"""PLAIN codecs for the 8 physical types, plus the Arrow-style
variable-length column representation used throughout the framework.

Value representation choices (TPU-first, per SURVEY.md §7 "hard parts"):

* fixed-width types decode straight to NumPy arrays via buffer reinterpret
  (little-endian on the wire == native on every platform we target);
* BOOLEAN plain is 1 bit/value LSB-first (``type_boolean.go:54-98``);
* INT96 decodes to an ``(N, 3)`` uint32 array (12 bytes/value, the
  low 8 bytes are nanoseconds-in-day, the top 4 the Julian day —
  ``type_int96.go:21-66``, ``int96_time.go``);
* BYTE_ARRAY decodes to offsets+data (:class:`ByteArrayColumn`) rather than
  per-value objects — columnar consumers and the device path want Arrow
  layout, not boxed values (``type_bytearray.go:24-55`` materializes
  per-value slices instead);
* FIXED_LEN_BYTE_ARRAY decodes to an ``(N, L)`` uint8 matrix.
"""

from __future__ import annotations

import numpy as np

from ..format.metadata import Type
from .bitpack import pack as bitpack_pack
from .bitpack import unpack as bitpack_unpack

__all__ = ["ByteArrayColumn", "decode_plain", "encode_plain", "PHYSICAL_DTYPES"]

PHYSICAL_DTYPES = {
    Type.BOOLEAN: np.dtype(np.bool_),
    Type.INT32: np.dtype("<i4"),
    Type.INT64: np.dtype("<i8"),
    Type.FLOAT: np.dtype("<f4"),
    Type.DOUBLE: np.dtype("<f8"),
}


class ByteArrayColumn:
    """Arrow-style variable-length binary column: int32 offsets + byte data.

    ``offsets`` has ``N + 1`` entries; value ``i`` is
    ``data[offsets[i]:offsets[i+1]]``.
    """

    __slots__ = ("offsets", "data")

    def __init__(self, offsets: np.ndarray, data: np.ndarray):
        self.offsets = np.asarray(offsets, dtype=np.int64)
        self.data = np.asarray(data, dtype=np.uint8)

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, i: int) -> bytes:
        return self.data[self.offsets[i] : self.offsets[i + 1]].tobytes()

    def lengths(self) -> np.ndarray:
        return np.diff(self.offsets)

    def to_list(self) -> list[bytes]:
        data = self.data.tobytes()
        offs = self.offsets
        return [data[offs[i] : offs[i + 1]] for i in range(len(self))]

    @classmethod
    def from_list(cls, values) -> "ByteArrayColumn":
        lengths = np.fromiter(
            (len(v) for v in values), dtype=np.int64, count=len(values)
        )
        offsets = np.zeros(len(values) + 1, dtype=np.int64)
        np.cumsum(lengths, out=offsets[1:])
        data = np.frombuffer(b"".join(bytes(v) for v in values), dtype=np.uint8)
        return cls(offsets, data)

    def __eq__(self, other):
        if not isinstance(other, ByteArrayColumn):
            return NotImplemented
        return (
            np.array_equal(self.offsets, other.offsets)
            and np.array_equal(self.data, other.data)
        )

    def __repr__(self):
        return f"ByteArrayColumn(n={len(self)}, nbytes={self.data.size})"


def decode_plain(ptype: Type, data, count: int, type_length: int | None = None):
    """Decode ``count`` PLAIN-encoded values; returns an ndarray or
    ByteArrayColumn.  ``data`` may carry trailing bytes (ignored).

    Fixed-width results are **zero-copy views** over ``data`` (the point of
    the Arrow-layout design).  Callers that pass a *mutable* buffer they
    intend to reuse (a decompression scratch ``bytearray``) must copy; the
    page layer hands each page a freshly-allocated immutable buffer."""
    buf = memoryview(data) if not isinstance(data, memoryview) else data
    if ptype == Type.BOOLEAN:
        return bitpack_unpack(buf, count, 1).astype(np.bool_)
    if ptype in (Type.INT32, Type.INT64, Type.FLOAT, Type.DOUBLE):
        dt = PHYSICAL_DTYPES[ptype]
        need = count * dt.itemsize
        if len(buf) < need:
            raise ValueError(
                f"PLAIN {ptype.name}: need {need} bytes for {count} values, "
                f"have {len(buf)}"
            )
        return np.frombuffer(buf[:need], dtype=dt)
    if ptype == Type.INT96:
        need = count * 12
        if len(buf) < need:
            raise ValueError("PLAIN INT96: input too short")
        return np.frombuffer(buf[:need], dtype="<u4").reshape(count, 3)
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        if not type_length:
            raise ValueError("FIXED_LEN_BYTE_ARRAY requires type_length")
        need = count * type_length
        if len(buf) < need:
            raise ValueError("PLAIN FIXED_LEN_BYTE_ARRAY: input too short")
        return np.frombuffer(buf[:need], dtype=np.uint8).reshape(
            count, type_length
        )
    if ptype == Type.BYTE_ARRAY:
        return _decode_plain_byte_array(buf, count)
    raise ValueError(f"unsupported physical type {ptype}")


def _decode_plain_byte_array(buf: memoryview, count: int) -> ByteArrayColumn:
    """Parse ``count`` (u32-LE length, bytes) records into offsets+data.

    The length prefixes sit at data-dependent positions, so this is a
    scan — one C pass when the native library is available (prefix walk
    + variable-length gather), else Python per value.  (The device path
    replaces this wholesale.)"""
    raw = np.frombuffer(buf, dtype=np.uint8)
    from ..native import delta_native

    nat = delta_native()
    if nat is not None:
        scanned = nat.byte_array_scan(raw, count)
        if scanned is not None:
            positions, offsets = scanned
            lens = offsets[1:] - offsets[:-1]
            data = nat.gather_var(raw, positions, lens,
                                  int(offsets[-1]))
            if data is not None:
                return ByteArrayColumn(offsets, data)
    offsets = np.zeros(count + 1, dtype=np.int64)
    positions = np.zeros(count, dtype=np.int64)
    pos = 0
    total = 0
    n = len(buf)
    for i in range(count):
        if pos + 4 > n:
            raise ValueError(
                f"PLAIN BYTE_ARRAY: truncated length prefix at value {i}"
            )
        ln = int(raw[pos]) | int(raw[pos + 1]) << 8 | int(raw[pos + 2]) << 16 \
            | int(raw[pos + 3]) << 24
        pos += 4
        if ln < 0 or pos + ln > n:
            raise ValueError(
                f"PLAIN BYTE_ARRAY: length {ln} out of bounds at value {i}"
            )
        positions[i] = pos
        total += ln
        offsets[i + 1] = total
        pos += ln
    data = np.empty(total, dtype=np.uint8)
    for i in range(count):
        start = offsets[i]
        end = offsets[i + 1]
        data[start:end] = raw[positions[i] : positions[i] + (end - start)]
    return ByteArrayColumn(offsets, data)


def encode_plain(ptype: Type, values, type_length: int | None = None) -> bytes:
    """PLAIN-encode values (ndarray / ByteArrayColumn / list of bytes)."""
    if ptype == Type.BOOLEAN:
        v = np.asarray(values, dtype=np.bool_).astype(np.uint8)
        return bitpack_pack(v, 1)
    if ptype in (Type.INT32, Type.INT64, Type.FLOAT, Type.DOUBLE):
        dt = PHYSICAL_DTYPES[ptype]
        return np.ascontiguousarray(np.asarray(values, dtype=dt)).tobytes()
    if ptype == Type.INT96:
        v = np.asarray(values, dtype="<u4")
        if v.ndim != 2 or v.shape[1] != 3:
            raise ValueError("INT96 values must have shape (N, 3) uint32")
        return np.ascontiguousarray(v).tobytes()
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        if isinstance(values, ByteArrayColumn):
            values = values.to_list()
        if isinstance(values, np.ndarray):
            v = np.asarray(values, dtype=np.uint8)
            if not type_length or v.shape[-1] != type_length:
                raise ValueError("FIXED_LEN_BYTE_ARRAY length mismatch")
            return np.ascontiguousarray(v).tobytes()
        out = bytearray()
        for b in values:
            if type_length is not None and len(b) != type_length:
                raise ValueError(
                    f"FIXED_LEN_BYTE_ARRAY: value length {len(b)} != "
                    f"{type_length}"
                )
            out += bytes(b)
        return bytes(out)
    if ptype == Type.BYTE_ARRAY:
        if not isinstance(values, ByteArrayColumn):
            values = ByteArrayColumn.from_list(values)
        from ..native import delta_native

        nat = delta_native()
        if nat is not None:
            out = nat.byte_array_emit(values.data, values.offsets)
            if out is not None:
                return out.tobytes()
        lengths = values.lengths()
        if lengths.size and int(lengths.max()) > 0xFFFFFFFF:
            # the native emitter refuses this; the fallback must too
            # (an astype truncation would write a corrupt stream)
            raise ValueError("byte-array value too long for a u32 prefix")
        lengths = lengths.astype("<u4")
        out = bytearray()
        data = values.data.tobytes()
        offs = values.offsets
        lb = lengths.tobytes()
        for i in range(len(values)):
            out += lb[i * 4 : i * 4 + 4]
            out += data[offs[i] : offs[i + 1]]
        return bytes(out)
    raise ValueError(f"unsupported physical type {ptype}")
