"""RLE / bit-packed hybrid encoding (NumPy).

Wire format (same as decoded by ``/root/reference/hybrid_decoder.go:143-166``):
a stream of runs, each headed by a uvarint ``h``:

* ``h & 1 == 1``: bit-packed run of ``(h >> 1) * 8`` values, LSB-first.
* ``h & 1 == 0``: RLE run of ``h >> 1`` copies of one value stored in
  ``ceil(width / 8)`` little-endian bytes.

The level-stream/dict-index form is prefixed with a 4-byte LE total length
(``hybrid_decoder.go:57``, ``initSize``).

Unlike the reference's value-at-a-time ``next()`` (and its encoder, which
only ever emits bit-packed runs — ``hybrid_encoder.go:55-70``), decode
parses the run structure once into a run table and expands each run with
vectorized ops; encode chooses RLE for long constant stretches, which is
both legal and smaller.
"""

from __future__ import annotations

import struct

import numpy as np

from ..varint import read_uvarint, write_uvarint
from .bitpack import pack, unpack

__all__ = [
    "scan_hybrid",
    "slice_prefixed",
    "decode_hybrid",
    "decode_hybrid_prefixed",
    "encode_hybrid",
    "encode_hybrid_prefixed",
    "as_uint32",
]


def as_uint32(values) -> np.ndarray:
    """u32 array of non-negative level/index values WITHOUT the copy
    ``np.asarray(..., dtype=np.uint32)`` pays for the int32 arrays the
    write path actually holds (a reinterpreting view is exact for the
    non-negative domain; a stray negative becomes a huge value the
    encoder's width check refuses, same as the widening path would)."""
    a = np.asarray(values)
    if a.dtype == np.int32:
        return a.view(np.uint32)
    return np.asarray(a, dtype=np.uint32)


def slice_prefixed(data, pos: int = 0):
    """Validate a 4-byte-LE-length-prefixed hybrid stream and return
    ``(stream, end_pos)`` where ``stream`` is exactly the prefixed
    bytes — the single owner of the prefix bounds checks (shared by
    :func:`decode_hybrid_prefixed` and the level decoders)."""
    if pos + 4 > len(data):
        raise ValueError("truncated hybrid length prefix")
    (size,) = struct.unpack_from("<I", data, pos)
    pos += 4
    end = pos + size
    if end > len(data):
        raise ValueError(f"hybrid stream length {size} exceeds buffer")
    return data[pos:end], end


def scan_hybrid(data, count: int, width: int, pos: int = 0):
    """Pass 1 of the two-pass decode: parse run headers into a run table.

    Returns ``(run_ends, run_is_rle, run_value, run_bp_start, bp_bytes,
    n_bp_values, end_pos)`` where ``run_ends`` is the cumulative output
    count per run, ``bp_bytes`` the concatenated bit-packed segments and
    ``run_bp_start`` each run's value offset into that stream.  Uses the
    native C scanner when available (``native/hybrid.c``)."""
    buf = data if isinstance(
        data, (bytes, bytearray, memoryview, np.ndarray)
    ) else bytes(data)
    if width <= 32:
        from ..native import hybrid_native

        nat = hybrid_native()
        if nat is not None:
            return nat.scan(buf, count, width, pos)
    return _scan_hybrid_py(buf, count, width, pos)


def _scan_hybrid_py(buf, count: int, width: int, pos: int = 0):
    """Pure-Python fallback scanner (also the >32-bit-width path)."""
    if isinstance(buf, np.ndarray):
        buf = buf.tobytes()
    vbytes = (width + 7) // 8
    vmask = (1 << width) - 1 if width else 0
    ends, is_rle, values, bp_starts, bp_segments = [], [], [], [], []
    filled = 0
    n_bp = 0
    while filled < count:
        h, pos = read_uvarint(buf, pos)
        if h & 1:
            n = (h >> 1) * 8
            nbytes = (n * width + 7) // 8
            if pos + nbytes > len(buf):
                raise ValueError("truncated bit-packed run")
            bp_segments.append(np.frombuffer(buf, np.uint8, nbytes, pos))
            bp_starts.append(n_bp)
            values.append(0)
            is_rle.append(False)
            pos += nbytes
            take = min(n, count - filled)
            # the unpacked stream keeps the full n values; consumers index
            # through run_bp_start so padding values are never selected
            n_bp += n
            filled += take
        else:
            n = h >> 1
            if n == 0:
                raise ValueError("zero-length RLE run")
            if pos + vbytes > len(buf):
                raise ValueError("truncated RLE run value")
            v = int.from_bytes(buf[pos : pos + vbytes], "little")
            if v & ~vmask:
                raise ValueError("RLE run value exceeds bit width")
            pos += vbytes
            values.append(v)
            is_rle.append(True)
            bp_starts.append(n_bp)
            take = min(n, count - filled)
            filled += take
        ends.append(filled)
    bp_bytes = (np.concatenate(bp_segments) if bp_segments
                else np.zeros(0, dtype=np.uint8))
    vdtype = np.uint64 if width > 32 else np.uint32
    return (
        np.asarray(ends, dtype=np.int32),
        np.asarray(is_rle, dtype=bool),
        np.asarray(values, dtype=vdtype),
        np.asarray(bp_starts, dtype=np.int32),
        bp_bytes,
        n_bp,
        pos,
    )


def expand_scan(run_ends, run_is_rle, run_value, run_bp_start, bp_bytes,
                n_bp: int, count: int, width: int) -> np.ndarray:
    """Pass 2 (vectorized): expand a run table to ``count`` values."""
    dtype = np.uint64 if width > 32 else np.uint32
    if count == 0 or len(run_ends) == 0:
        return np.zeros(count, dtype=dtype)
    if len(run_ends) == 1:
        # single-run fast paths (every stream our own writer emits):
        # no searchsorted, no per-position gather
        if run_is_rle[0]:
            return np.full(count, run_value[0], dtype=dtype)
        return unpack(bp_bytes, n_bp, width)[:count].astype(dtype,
                                                           copy=False)
    if run_is_rle.all():
        # all-RLE streams (typical pyarrow level data): one repeat
        lens = np.diff(run_ends, prepend=np.int32(0))
        return np.repeat(run_value.astype(dtype, copy=False),
                         lens)[:count]
    if width <= 32:
        from ..native import pack_native

        nat = pack_native()
        if nat is not None:
            out = nat.hybrid_expand(run_ends, run_is_rle, run_value,
                                    run_bp_start, bp_bytes, n_bp,
                                    count, width)
            if out is not None:
                return out.astype(dtype, copy=False)
    unpacked = (unpack(bp_bytes, n_bp, width) if n_bp
                else np.zeros(1, dtype=dtype))
    idx = np.arange(count, dtype=np.int64)
    run = np.searchsorted(run_ends, idx, side="right")
    run = np.minimum(run, len(run_ends) - 1)
    run_start = np.where(run > 0, run_ends[run - 1], 0)
    bp_pos = np.minimum(run_bp_start[run] + (idx - run_start),
                        max(n_bp - 1, 0))
    return np.where(run_is_rle[run], run_value[run],
                    unpacked[bp_pos]).astype(dtype, copy=False)


def decode_hybrid(data, count: int, width: int, pos: int = 0) -> np.ndarray:
    """Decode exactly ``count`` values of the given bit ``width``.

    Trailing bytes after the needed runs are ignored (pages may pad).
    Two-pass: run-header scan (native C when available) + vectorized
    expand."""
    if width == 0:
        return np.zeros(count, dtype=np.uint32)
    ends, is_rle, value, bp_start, bp_bytes, n_bp, _ = scan_hybrid(
        data, count, width, pos
    )
    return expand_scan(ends, is_rle, value, bp_start, bp_bytes, n_bp,
                       count, width)


def decode_hybrid_prefixed(data, count: int, width: int, pos: int = 0):
    """Decode the 4-byte-length-prefixed form; returns (values, end_pos)."""
    stream, end = slice_prefixed(data, pos)
    return decode_hybrid(stream, count, width), end


_MIN_RLE_RUN = 8  # break even vs bit-packing for typical widths


def encode_hybrid(values, width: int) -> bytes:
    """Encode values with RLE for constant stretches >= 8, else bit-packing.

    Bit-packed runs cover groups of 8 values; the final partial group is
    padded with zeros (readers stop at the value count)."""
    v0 = np.asarray(values)
    if width == 0 or v0.size == 0:
        return b""
    from ..native import pack_native

    nat = pack_native()
    if nat is not None:
        if 0 < width <= 32 and (
                v0.dtype == np.uint32
                or (v0.dtype == np.int32 and width < 32)):
            # dict indices / levels arrive as (u)int32: encode straight
            # from them instead of paying the u64-widening copy.  int32
            # is excluded at width 32 only: there a negative's u32 view
            # would fit and encode silently where the widening path
            # refuses it.
            enc = nat.hybrid_encode32(as_uint32(v0), width)
            if enc is not None:
                return enc.tobytes()
        enc = nat.hybrid_encode(
            np.asarray(values, dtype=np.uint64), width)
        if enc is not None:
            return enc.tobytes()
    v = np.asarray(values, dtype=np.uint64)
    out = bytearray()
    vbytes = (width + 7) // 8

    # Find constant runs via change points, then consider only the runs
    # long enough for RLE — random data has ~n runs and looping them all
    # in Python would dominate encode time.
    change = np.nonzero(np.diff(v))[0] + 1
    starts = np.concatenate(([0], change))
    ends = np.concatenate((change, [v.size]))
    long_runs = np.nonzero(ends - starts >= _MIN_RLE_RUN)[0]

    def emit_bitpacked(lo: int, hi: int) -> None:
        n = hi - lo
        if n <= 0:
            return
        groups = (n + 7) // 8
        padded = np.zeros(groups * 8, dtype=np.uint64)
        padded[:n] = v[lo:hi]
        write_uvarint(out, (groups << 1) | 1)
        out.extend(pack(padded, width))

    # Greedily emit: RLE for long constant runs, bit-packed for the rest.
    # Bit-packed runs must cover a multiple of 8 values, so the boundary
    # in front of an RLE run is rounded to the pending-group edge and the
    # overhang carved off the front of the RLE run.
    pending = 0  # start of the current not-yet-emitted bit-packed region
    for ri in long_runs:
        s = int(starts[ri])
        e = int(ends[ri])
        flush_end = s
        if (flush_end - pending) % 8:
            flush_end = min(pending + ((s - pending + 7) // 8) * 8, e)
        emit_bitpacked(pending, flush_end)
        if e - flush_end >= 1:
            if width < 64 and int(v[s]) >> width:
                # pack() guards the bit-packed runs; the RLE value
                # needs the same refusal or the stream corrupts at
                # read time ("RLE run value exceeds bit width")
                raise ValueError(
                    f"value {int(v[s])} does not fit in {width} bits")
            write_uvarint(out, (e - flush_end) << 1)
            out.extend(int(v[s]).to_bytes(vbytes, "little"))
        pending = e
    emit_bitpacked(pending, v.size)
    return bytes(out)


def encode_hybrid_prefixed(values, width: int) -> bytes:
    body = encode_hybrid(values, width)
    return struct.pack("<I", len(body)) + body
