"""DELTA_BINARY_PACKED, DELTA_LENGTH_BYTE_ARRAY, DELTA_BYTE_ARRAY (NumPy).

Wire format (as parsed by ``/root/reference/deltabp_decoder.go:52-175``):
header = ``block_size`` uvarint, ``miniblocks_per_block`` uvarint,
``total_value_count`` uvarint, ``first_value`` zigzag varint; then per
block: ``min_delta`` zigzag varint, one width byte per miniblock, and the
bit-packed miniblock delta payloads (LSB-first).  Values are the prefix sum
``v[i+1] = v[i] + min_delta + delta[i]`` with two's-complement wraparound at
the target width.

One implementation parameterized by dtype replaces the reference's
copy-paste 32/64-bit twins (its own comment calls them out,
``deltabp_decoder.go:10-12``).  Encoder defaults match the reference's call
sites: block 128, 4 miniblocks of 32 (``type_bytearray.go:176-180``).
"""

from __future__ import annotations

import numpy as np

from ..varint import read_uvarint, read_zigzag, write_uvarint, write_zigzag
from .bitpack import pack, unpack
from .plain import ByteArrayColumn

__all__ = [
    "decode_delta_binary_packed",
    "encode_delta_binary_packed",
    "decode_delta_length_byte_array",
    "scan_delta_length_byte_array",
    "encode_delta_length_byte_array",
    "decode_delta_byte_array",
    "encode_delta_byte_array",
    "widths_from_max",
]


def widths_from_max(mb_max: np.ndarray) -> np.ndarray:
    """Vectorized bit_length: per-miniblock packing width from the max
    adjusted delta.  Shared with the device encoder
    (``kernels/encode.py``) — the wire format depends on both sides
    choosing identical widths."""
    widths = np.zeros(mb_max.shape, dtype=np.int64)
    m = mb_max.astype(np.uint64, copy=True)
    for s in (32, 16, 8, 4, 2, 1):
        big = m >= (np.uint64(1) << np.uint64(s))
        widths[big] += s
        m[big] >>= np.uint64(s)
    widths += (m > 0)
    return widths




class DeltaStructure:
    """Parsed DELTA_BINARY_PACKED layout: per-miniblock bookkeeping
    from one cheap varint walk, shared by the CPU decoder and the
    device planner (``kernels/decode.py``) so the parsing and
    validation rules cannot drift.  Zero-width miniblocks are omitted
    (their deltas are zero; ``min_delta`` carries the value)."""

    __slots__ = ("block_size", "mb_size", "total", "first", "md_blocks",
                 "mb_w", "mb_pos", "mb_start", "end_pos")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)

    def grouped(self):
        """Per-width (w, positions, starts, takes) with contiguity
        precomputed: yields ``(w, seg_slice_or_None, p_w, s_w, t_w,
        dest_contiguous)`` per distinct width."""
        if len(self.mb_w) == 0:  # list (Python scan) or ndarray (native)
            return
        n_deltas = self.total - 1
        w_np = np.asarray(self.mb_w, dtype=np.int64)
        p_np = np.asarray(self.mb_pos, dtype=np.int64)
        s_np = np.asarray(self.mb_start, dtype=np.int64)
        t_np = np.minimum(self.mb_size, n_deltas - s_np)
        for w in np.unique(w_np):
            w = int(w)
            nbytes = self.mb_size * w // 8
            m = w_np == w
            p_w, s_w, t_w = p_np[m], s_np[m], t_np[m]
            k = len(p_w)
            src_contig = k == 1 or (np.diff(p_w) == nbytes).all()
            dst_contig = k == 1 or (np.diff(s_w) == self.mb_size).all()
            yield w, src_contig, p_w, s_w, t_w, dst_contig


def scan_delta_structure(data, pos: int = 0,
                         max_width: int = 64) -> DeltaStructure:
    """One structure pass over a DELTA_BINARY_PACKED stream: headers
    validated, per-miniblock (width, payload position, delta start)
    collected — a per-miniblock ``unpack()`` call costs a Python call
    per 32 values (~370k for a 12M-value chunk); callers batch-decode
    from this structure instead."""
    block_size, pos = read_uvarint(data, pos)
    n_miniblocks, pos = read_uvarint(data, pos)
    if block_size <= 0 or block_size % 128:
        raise ValueError(f"invalid delta block size {block_size}")
    if n_miniblocks <= 0 or block_size % n_miniblocks:
        raise ValueError(f"invalid miniblock count {n_miniblocks}")
    mb_size = block_size // n_miniblocks
    if mb_size % 32:
        raise ValueError(f"miniblock size {mb_size} not a multiple of 32")
    total, pos = read_uvarint(data, pos)
    first, pos = read_zigzag(data, pos)
    # bound the header values to int64: Python varints are arbitrary
    # precision, and an out-of-range total/first would otherwise surface
    # later as an OverflowError from np.asarray instead of a clean
    # malformed-input rejection
    if (total >= 1 << 63 or block_size >= 1 << 31
            or not -(1 << 63) <= first < 1 << 63):
        raise ValueError("delta header value out of range")
    n_deltas = max(total - 1, 0)
    data_len = len(data)

    from ..native import delta_native

    nat = delta_native()
    if nat is not None:
        md_np, w_np, p_np, s_np, end = nat.scan_blocks(
            data, pos, n_deltas, mb_size, n_miniblocks, max_width)
        return DeltaStructure(
            block_size=block_size, mb_size=mb_size, total=total,
            first=first, md_blocks=md_np, mb_w=w_np, mb_pos=p_np,
            mb_start=s_np, end_pos=end)

    md_blocks: list[int] = []
    mb_w: list[int] = []
    mb_pos: list[int] = []
    mb_start: list[int] = []
    got = 0
    while got < n_deltas:
        min_delta, pos = read_zigzag(data, pos)
        if not -(1 << 63) <= min_delta < 1 << 63:
            raise ValueError("delta header value out of range")
        md_blocks.append(min_delta)
        if pos + n_miniblocks > data_len:
            raise ValueError("truncated miniblock width list")
        widths = bytes(data[pos : pos + n_miniblocks])
        pos += n_miniblocks
        for w in widths:
            if got >= n_deltas:
                break  # unused trailing miniblocks carry no payload
            if w > max_width:
                raise ValueError(
                    f"delta miniblock width {w} > {max_width} for this "
                    "column's physical type")
            nbytes = mb_size * w // 8
            if pos + nbytes > data_len:
                raise ValueError("truncated miniblock payload")
            if w:
                mb_w.append(w)
                mb_pos.append(pos)
                mb_start.append(got)
            pos += nbytes
            got += mb_size  # final miniblock may overshoot; clamped later
    return DeltaStructure(
        block_size=block_size, mb_size=mb_size, total=total, first=first,
        md_blocks=md_blocks, mb_w=mb_w, mb_pos=mb_pos, mb_start=mb_start,
        end_pos=pos)


# Cap per-unpack batch size on the host: unpack() materializes a
# (count, width) lane matrix, so an unbounded batch over a 12M-value
# chunk at width 40 would transiently allocate ~4 GB.  1M values keeps
# the working set ~tens of MB with the vectorization intact.
_UNPACK_SLAB_VALUES = 1 << 20


def decode_delta_binary_packed(data, dtype=np.int64, pos: int = 0):
    """Decode one DELTA_BINARY_PACKED stream; returns (values, end_pos).

    ``end_pos`` is where the stream's payload ends, which callers need when
    another stream follows (DELTA_LENGTH_BYTE_ARRAY data, suffix streams).
    """
    dtype = np.dtype(dtype)
    st = scan_delta_structure(data, pos)
    if st.total == 0:
        return np.empty(0, dtype=dtype), st.end_pos

    from ..native import delta_native

    nat = delta_native()
    if nat is not None:
        # one GIL-releasing C pass (unpack + min_delta + prefix sum):
        # the numpy formulation below costs five full-size temporaries
        out = nat.decode_all(data, st)
        if out is not None:
            return out.view(np.int64).astype(dtype, copy=False), \
                st.end_pos

    # All arithmetic in uint64: two's-complement wraparound for free, for
    # both the 32- and 64-bit cases (final cast truncates to the target).
    n_deltas = st.total - 1
    mb_size = st.mb_size
    deltas = np.zeros(n_deltas, dtype=np.uint64)  # w==0 blocks stay 0
    buf = (data if isinstance(data, np.ndarray)
           else np.frombuffer(data, dtype=np.uint8))
    for w, src_contig, p_w, s_w, t_w, dst_contig in st.grouped():
        nbytes = mb_size * w // 8
        k = len(p_w)
        slab = max(_UNPACK_SLAB_VALUES // mb_size, 1)
        for lo_i in range(0, k, slab):
            hi_i = min(lo_i + slab, k)
            kk = hi_i - lo_i
            if src_contig:
                seg = buf[p_w[lo_i] : p_w[lo_i] + nbytes * kk]
            else:
                seg = np.concatenate(
                    [buf[p : p + nbytes] for p in p_w[lo_i:hi_i]])
            vals = unpack(seg, mb_size * kk, w).astype(np.uint64)
            s_s, t_s = s_w[lo_i:hi_i], t_w[lo_i:hi_i]
            if dst_contig:
                # only the globally-last miniblock can be partial
                n_take = int(t_s.sum())
                deltas[s_s[0] : s_s[0] + n_take] = vals[:n_take]
            else:
                vals = vals.reshape(kk, mb_size)
                keep = np.arange(mb_size)[None, :] < t_s[:, None]
                deltas[(s_s[:, None]
                        + np.arange(mb_size)[None, :])[keep]] = vals[keep]
    # per-block min_delta, expanded once (one repeat, no per-miniblock
    # slice assignments)
    deltas += np.repeat(
        np.asarray(st.md_blocks, dtype=np.int64).view(np.uint64),
        st.block_size)[:n_deltas]
    out = np.empty(st.total, dtype=np.uint64)
    out[0] = np.uint64(st.first & 0xFFFFFFFFFFFFFFFF)
    np.cumsum(deltas, out=out[1:])
    out[1:] += out[0]
    return out.view(np.int64).astype(dtype), st.end_pos


def encode_delta_binary_packed(
    values, block_size: int = 128, n_miniblocks: int = 4,
    is32: bool | None = None,
) -> bytes:
    """Encode int32/int64 values; overflow-safe via uint64 delta arithmetic.

    ``is32`` should be passed by callers that know the column's physical
    type; when None it is inferred from the array dtype."""
    v0 = np.asarray(values)
    # int32 columns must wrap deltas at 32 bits: otherwise values spanning
    # the full int32 range produce 33-bit miniblock widths, which int32
    # delta decoders (parquet-mr, our device kernel) reject.  The wrapped
    # deltas reconstruct identically modulo 2^32.
    if is32 is None:
        is32 = v0.dtype in (np.dtype(np.int32), np.dtype(np.uint32))
    v = v0.astype(np.int64, copy=False)
    out = bytearray()
    write_uvarint(out, block_size)
    write_uvarint(out, n_miniblocks)
    write_uvarint(out, v.size)
    mb_size = block_size // n_miniblocks
    if v.size == 0:
        write_zigzag(out, 0)
        return bytes(out)
    write_zigzag(out, int(v[0]))
    # Two's-complement-safe deltas (wraparound matches decode's uint64 sum).
    deltas = np.diff(v.view(np.uint64)).view(np.int64)
    if is32:
        deltas = deltas.astype(np.int32).astype(np.int64)

    # Whole-stream vectorization: per-miniblock pack() calls cost more
    # interpreter overhead than the packing itself at scale (2.6 -> ~25
    # M values/s), so compute every block's min/widths in one shot and
    # batch the payload packing by width.
    n = deltas.size
    n_blocks = (n + block_size - 1) // block_size
    padded_n = n_blocks * block_size
    blk = np.full(padded_n, np.iinfo(np.int64).max, dtype=np.int64)
    blk[:n] = deltas
    blk2 = blk.reshape(n_blocks, block_size)
    min_deltas = blk2.min(axis=1)                       # padding never wins
    adj = blk2.view(np.uint64) - min_deltas.view(np.uint64)[:, None]
    adj.reshape(-1)[n:] = 0                             # padded lanes are 0
    mb = adj.reshape(n_blocks * n_miniblocks, mb_size)
    widths = widths_from_max(mb.max(axis=1))

    from ..native import pack_native

    nat = pack_native()
    if nat is not None:
        body = nat.delta_emit(mb, widths, mb_size, min_deltas,
                              n_miniblocks)
        if body is not None:
            # out holds only the few header bytes here; one concat
            return bytes(out) + body.tobytes()

    # pack all miniblocks of one width in a single pack() call, then
    # carve the concatenated bytes back into per-miniblock payloads
    payloads: list[bytes] = [b""] * len(widths)
    for w in np.unique(widths):
        w = int(w)
        if w == 0:
            continue
        idx = np.nonzero(widths == w)[0]
        packed = pack(mb[idx].reshape(-1), w)
        step = mb_size * w // 8
        for j, i in enumerate(idx):
            payloads[i] = packed[j * step : (j + 1) * step]

    widths_b = widths.astype(np.uint8).tobytes()
    for b in range(n_blocks):
        write_zigzag(out, int(min_deltas[b]))
        out.extend(widths_b[b * n_miniblocks : (b + 1) * n_miniblocks])
        for p in payloads[b * n_miniblocks : (b + 1) * n_miniblocks]:
            out.extend(p)
    return bytes(out)


# -- DELTA_LENGTH_BYTE_ARRAY ------------------------------------------------

def scan_delta_length_byte_array(data, count: int, pos: int = 0):
    """Validated DLBA structure without materializing the payload:
    returns (offsets, data_pos) where the byte payload is
    ``data[data_pos : data_pos + offsets[-1]]``.  Shared by the CPU
    decoder and the device path's zero-copy staging so the validation
    rules cannot drift."""
    lengths, pos = decode_delta_binary_packed(data, np.int64, pos)
    if lengths.size != count:
        raise ValueError(
            f"DELTA_LENGTH_BYTE_ARRAY: length stream has {lengths.size} "
            f"entries, expected {count}"
        )
    if (lengths < 0).any():
        raise ValueError("negative byte-array length")
    offsets = np.zeros(count + 1, dtype=np.int64)
    np.cumsum(lengths, out=offsets[1:])
    if pos + int(offsets[-1]) > len(data):
        raise ValueError("DELTA_LENGTH_BYTE_ARRAY: truncated data section")
    return offsets, pos


def decode_delta_length_byte_array(data, count: int, pos: int = 0):
    """Lengths (delta-bp int32) then concatenated bytes; returns
    (ByteArrayColumn, end_pos) — ``type_bytearray.go:98-140`` equivalent."""
    offsets, pos = scan_delta_length_byte_array(data, count, pos)
    total = int(offsets[-1])
    payload = np.frombuffer(data, dtype=np.uint8, count=total, offset=pos)
    return ByteArrayColumn(offsets, payload.copy()), pos + total


def encode_delta_length_byte_array(values) -> bytes:
    if not isinstance(values, ByteArrayColumn):
        values = ByteArrayColumn.from_list(values)
    out = bytearray(encode_delta_binary_packed(values.lengths()))
    out.extend(values.data.tobytes())
    return bytes(out)


# -- DELTA_BYTE_ARRAY (front coding) ----------------------------------------

def assemble_delta_byte_array(prefix_lens, suffix_offsets,
                              suffix_data) -> ByteArrayColumn:
    """Front-coded reconstruction from the parsed streams (validation
    included); shared by the CPU decoder and the device planner's
    non-expanding fallback so neither re-parses nor re-implements the
    fill (``type_bytearray.go:189-240``)."""
    count = len(prefix_lens)
    suffix_lens = np.diff(suffix_offsets)
    total_lens = prefix_lens + suffix_lens
    offsets = np.zeros(count + 1, dtype=np.int64)
    np.cumsum(total_lens, out=offsets[1:])
    from ..native import delta_native

    nat = delta_native()
    if nat is not None:
        out = nat.dba_assemble(prefix_lens, suffix_offsets, suffix_data,
                               offsets, int(offsets[-1]))
        if out is not None:
            return ByteArrayColumn(offsets, out)
    out = np.empty(int(offsets[-1]), dtype=np.uint8)
    sdata = suffix_data
    soffs = suffix_offsets
    prev_start = 0
    for i in range(count):
        start = int(offsets[i])
        plen = int(prefix_lens[i])
        if i == 0 and plen != 0:
            raise ValueError("DELTA_BYTE_ARRAY: first prefix must be 0")
        if plen < 0 or plen > (int(offsets[i]) - prev_start if i else 0):
            raise ValueError(
                f"DELTA_BYTE_ARRAY: prefix {plen} longer than previous value"
            )
        if plen:
            out[start : start + plen] = out[prev_start : prev_start + plen]
        out[start + plen : int(offsets[i + 1])] = sdata[soffs[i] : soffs[i + 1]]
        prev_start = start
    return ByteArrayColumn(offsets, out)


def decode_delta_byte_array(data, count: int, pos: int = 0):
    """Prefix lengths (delta-bp) + suffixes (delta-length); front-coded
    reconstruction (``type_bytearray.go:189-240``)."""
    prefix_lens, pos = decode_delta_binary_packed(data, np.int64, pos)
    if prefix_lens.size != count:
        raise ValueError("DELTA_BYTE_ARRAY: prefix count mismatch")
    suffixes, pos = decode_delta_length_byte_array(data, count, pos)
    return assemble_delta_byte_array(
        prefix_lens, suffixes.offsets, suffixes.data), pos


def encode_delta_byte_array(values) -> bytes:
    if not isinstance(values, ByteArrayColumn):
        values = ByteArrayColumn.from_list(values)
    vals = values.to_list()
    prefix_lens = np.zeros(len(vals), dtype=np.int64)
    suffixes = []
    prev = b""
    for i, v in enumerate(vals):
        if i:
            n = 0
            limit = min(len(prev), len(v))
            while n < limit and prev[n] == v[n]:
                n += 1
            prefix_lens[i] = n
            suffixes.append(v[n:])
        else:
            suffixes.append(v)
        prev = v
    out = bytearray(encode_delta_binary_packed(prefix_lens))
    out.extend(encode_delta_length_byte_array(suffixes))
    return bytes(out)
