"""Runtime lock-order recorder (``TPQ_LOCKCHECK``): the dynamic half
of the tpq-analyze v2 concurrency gate.

The static pass (``tools/analyze/threads.py``) computes a
*lock-acquisition graph* — "while holding lock A, code may acquire
lock B" — by whole-program AST analysis and rejects cycles.  Static
analysis over-approximates (name fanout, callback-as-call edges), so
a clean static graph does not prove the analysis MODELS reality.
This module closes the loop from the other side: with
``TPQ_LOCKCHECK=1`` in the environment, :func:`install` (invoked at
the top of ``tpuparquet/__init__`` before any submodule import)
replaces ``threading.Lock``/``threading.RLock`` with recording
wrappers.  Every acquisition appends *held-set → acquired* edges to a
process-global graph keyed by the lock's **creation site**
(``relpath:lineno`` of the ``threading.Lock()`` call), which is
exactly the identity the static pass exports — so the two graphs are
directly comparable:

* a **cycle** among repo locks at runtime is a real (at least
  latent) deadlock → recorded as a violation; ``TPQ_LOCKCHECK=strict``
  raises :class:`LockOrderError` at the acquisition that closed the
  cycle;
* a recorded edge **absent from the static graph** means the static
  analysis failed to model a call path — each side validates the
  other (checked by ``python -m tools.analyze --verify-lockcheck`` and
  ``tests/test_lockcheck.py``).

Scope: edges where BOTH locks were created inside ``tpuparquet/`` are
checked; foreign locks (stdlib, jax, numpy internals) are recorded
with their real paths but excluded from the cycle/subgraph verdicts —
their ordering is not this repo's contract.

Overhead is confined to the gated runs (tier-1 under the CI stage-15
leg, ``tools/soak.py``, the chaos harness); production processes never
import this module unless the env knob is set.

Env knobs: ``TPQ_LOCKCHECK`` (``1`` = record, ``strict`` = raise on
cycle), ``TPQ_LOCKCHECK_OUT`` (dump the observed graph as JSON at
interpreter exit, written atomically).
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading

__all__ = [
    "LockOrderError",
    "install",
    "uninstall",
    "installed",
    "set_wait_hooks",
    "edges",
    "locks_seen",
    "violations",
    "reset",
    "check_dag",
    "dump",
    "repo_site",
]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# originals captured at import, before any patching
_RealLock = threading.Lock
_RealRLock = threading.RLock

_installed = False
_strict = False

# registry state, guarded by a REAL (unwrapped) lock so the recorder
# never records itself
_reg_lock = _RealLock()
_edges: dict[tuple, int] = {}       # (site_a, site_b) -> count
_sites: set = set()                 # every creation site seen
_violations: list[dict] = []

_tls = threading.local()            # .held: list of [site, depth]

#: Wait-edge hooks ``(begin, end)`` the sampling profiler installs
#: when it arms (:func:`set_wait_hooks`): a CONTENDED acquire — the
#: non-blocking first attempt failed — is bracketed so off-CPU samples
#: taken while the thread blocks attribute to this lock's creation
#: site (the same ``relpath:lineno`` identity the order graph keys
#: on).  One tuple, swapped atomically, so a reader never sees a
#: begin without its end.  None when no profiler is armed — the
#: acquire fast path is then one global load + ``is None``.
_wait_hooks: tuple | None = None


class LockOrderError(RuntimeError):
    """A lock acquisition closed a cycle in the observed lock-order
    graph (``TPQ_LOCKCHECK=strict``)."""


def repo_site(site: str) -> bool:
    """Is this creation site inside the repo (vs stdlib/jax)?  Cycle
    checks cover all repo locks; the static-subgraph comparison in
    ``tools.analyze`` further restricts itself to ``tpuparquet/``."""
    return (site.startswith("tpuparquet/")
            or site.startswith("tools/")
            or site.startswith("tests/"))


def _caller_site() -> str:
    """Creation site of the lock: the IMMEDIATE caller of the patched
    constructor, repo-relative when inside the repo.  Deliberately not
    a walk to the nearest repo frame: a lock the stdlib creates on the
    repo's behalf (``threading.Thread``/``Event``/``Condition``
    internals) has no ``threading.Lock()`` call in repo source for the
    static pass to model, so it must stay FOREIGN here or the
    runtime-subgraph check would flag edges static analysis can never
    see.  Only textual ``threading.Lock()``/``RLock()`` calls in repo
    files become repo sites — the exact set the AST pass keys on."""
    f = sys._getframe(2)
    this = __file__
    while f is not None and f.f_code.co_filename == this:
        f = f.f_back
    if f is None:
        return "<unknown>:0"
    fn = f.f_code.co_filename
    try:
        rel = os.path.relpath(fn, _REPO_ROOT)
    except ValueError:
        rel = fn
    if not rel.startswith(".."):
        fn = rel.replace(os.sep, "/")
    return f"{fn}:{f.f_lineno}"


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _would_cycle(a: str, b: str) -> list | None:
    """Path b -> ... -> a over repo-lock edges (callers hold
    ``_reg_lock``); adding a->b then closes the cycle."""
    if a == b:
        return [a, b]
    stack = [(b, [a, b])]
    seen = {b}
    while stack:
        node, path = stack.pop()
        for (x, y) in _edges:
            if x != node or y in seen:
                continue
            if not (repo_site(x) and repo_site(y)):
                continue
            if y == a:
                return path + [y]
            seen.add(y)
            stack.append((y, path + [y]))
    return None


def _record_acquire(site: str, reentrant: bool) -> None:
    held = _held()
    for ent in held:
        if ent[0] == site:
            if reentrant:
                ent[1] += 1
                return
            break  # non-reentrant self-acquire would deadlock for real
    cycle = None
    with _reg_lock:
        _sites.add(site)
        for ent in held:
            a = ent[0]
            if a == site:
                continue
            key = (a, site)
            fresh = key not in _edges
            _edges[key] = _edges.get(key, 0) + 1
            if fresh and repo_site(a) and repo_site(site):
                cycle = _would_cycle(a, site)
                if cycle is not None:
                    _violations.append(
                        {"kind": "lock-cycle", "cycle": cycle})
    held.append([site, 1])
    if cycle is not None and _strict:
        raise LockOrderError(
            "lock-order cycle closed at acquisition: "
            + " -> ".join(cycle))


def _record_release(site: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] == site:
            held[i][1] -= 1
            if held[i][1] <= 0:
                del held[i]
            return


class _CheckedLock:
    """Recording wrapper over a real ``threading.Lock``."""

    _reentrant = False
    __slots__ = ("_inner", "_site", "__weakref__")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        hooks = _wait_hooks
        if hooks is None or not blocking:
            got = self._inner.acquire(blocking, timeout)
        else:
            # profiler armed: try without blocking first — only a
            # CONTENDED acquire gets the wait bracket, so uncontended
            # locks never produce false off-CPU samples
            got = self._inner.acquire(False)
            if not got:
                tok = hooks[0]("lock", self._site)
                try:
                    got = self._inner.acquire(True, timeout)
                finally:
                    hooks[1](tok)
        if got:
            try:
                _record_acquire(self._site, self._reentrant)
            except LockOrderError:
                # strict verdict: fail the acquisition outright — the
                # caller sees the raise, so it must not be left
                # holding the lock (or the held-set record of it)
                _record_release(self._site)
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        self._inner.release()
        _record_release(self._site)

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib modules (concurrent.futures.thread, logging) register
        # this with os.register_at_fork — delegate, and drop any held
        # recording for this site in the child
        self._inner._at_fork_reinit()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<lockcheck {type(self).__name__} {self._site}>"


class _CheckedRLock(_CheckedLock):
    """Recording wrapper over a real ``threading.RLock``; carries the
    owner/save/restore surface ``threading.Condition`` relies on."""

    _reentrant = True
    __slots__ = ()

    # Condition protocol -------------------------------------------------
    def _release_save(self):
        state = self._inner._release_save()
        _record_release(self._site)
        return state

    def _acquire_restore(self, state) -> None:
        hooks = _wait_hooks
        if hooks is None:
            self._inner._acquire_restore(state)
        else:
            # Condition.wait re-acquire: almost always contended (the
            # notifier holds the lock), so bracket it unconditionally
            tok = hooks[0]("lock", self._site)
            try:
                self._inner._acquire_restore(state)
            finally:
                hooks[1](tok)
        _record_acquire(self._site, True)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def _lock_factory():
    return _CheckedLock(_RealLock(), _caller_site())


def _rlock_factory():
    return _CheckedRLock(_RealRLock(), _caller_site())


def install(strict: bool | None = None) -> None:
    """Patch ``threading.Lock``/``RLock`` with recording wrappers.
    Idempotent.  ``strict`` raises on a cycle at the closing
    acquisition (default: ``TPQ_LOCKCHECK=strict``)."""
    global _installed, _strict
    if strict is not None:
        _strict = bool(strict)
    else:
        _strict = os.environ.get("TPQ_LOCKCHECK", "") == "strict"
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _installed = True
    out = os.environ.get("TPQ_LOCKCHECK_OUT")
    if out:
        atexit.register(dump, out)


def uninstall() -> None:
    """Restore the real constructors (already-wrapped locks keep
    recording — the registry stays consistent)."""
    global _installed
    threading.Lock = _RealLock
    threading.RLock = _RealRLock
    _installed = False


def installed() -> bool:
    return _installed


def set_wait_hooks(begin, end) -> None:
    """Install (or clear, with ``None, None``) the profiler's wait
    bracket around contended acquires.  The profiler calls this when
    it arms/disarms (``obs.profiler.set_profiling``); lockcheck keeps
    no dependency on obs — the hooks are opaque callables.  The swap
    is a single reference assignment (readers grab one snapshot), but
    it runs under ``_reg_lock`` anyway so two racing arm/disarm calls
    serialize."""
    global _wait_hooks
    with _reg_lock:
        _wait_hooks = (begin, end) if begin is not None else None


def enabled_from_env() -> bool:
    return os.environ.get("TPQ_LOCKCHECK", "") not in ("", "0")


def edges() -> list[tuple[str, str, int]]:
    """Observed (held, acquired, count) edges, sorted."""
    with _reg_lock:
        return sorted((a, b, n) for (a, b), n in _edges.items())


def locks_seen() -> list[str]:
    with _reg_lock:
        return sorted(_sites)


def violations() -> list[dict]:
    with _reg_lock:
        return [dict(v) for v in _violations]


def reset() -> None:
    """Forget every recorded edge/violation (tests)."""
    with _reg_lock:
        _edges.clear()
        _sites.clear()
        del _violations[:]


def check_dag() -> list[dict]:
    """Full-graph re-check over the repo-lock subgraph; returns cycle
    violations (the incremental acquire-time check should have caught
    them already — this is the belt to its braces)."""
    with _reg_lock:
        repo_edges = [(a, b) for (a, b) in _edges
                      if repo_site(a) and repo_site(b)]
    graph: dict[str, list[str]] = {}
    for a, b in repo_edges:
        graph.setdefault(a, []).append(b)
    out: list[dict] = []
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}

    def visit(n, path):
        color[n] = GREY
        for m in graph.get(n, ()):
            if color.get(m, WHITE) == GREY:
                out.append({"kind": "lock-cycle",
                            "cycle": path + [n, m]})
            elif color.get(m, WHITE) == WHITE:
                visit(m, path + [n])
        color[n] = BLACK
    for n in sorted(graph):
        if color[n] == WHITE:
            visit(n, [])
    return out


def snapshot() -> dict:
    """The observed graph as one JSON-ready document."""
    return {
        "locks": locks_seen(),
        "edges": [[a, b, n] for a, b, n in edges()],
        "violations": violations() + check_dag(),
    }


def dump(path: str) -> None:
    """Write :func:`snapshot` to ``path`` atomically."""
    doc = snapshot()
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
