"""tpuparquet — a TPU-native Apache Parquet framework.

A from-scratch reimplementation of the capabilities of fraugster/parquet-go
(the reference at ``/root/reference``), designed TPU-first: plain-Python host
side (thrift metadata, schema tree, orchestration), NumPy CPU oracle codecs,
and a JAX/Pallas batch-decode data plane that stages column-chunk pages to
HBM and decodes them in parallel, sharding row groups across a device mesh.
"""

__version__ = "0.1.0"

import os as _os

# Lock-order recorder: must patch threading.Lock/RLock BEFORE any
# submodule import so module-level locks are created wrapped.
if _os.environ.get("TPQ_LOCKCHECK", "") not in ("", "0"):
    from . import lockcheck as _lockcheck

    _lockcheck.install()

from .compress import (  # noqa: F401
    BlockCompressor,
    register_block_compressor,
)
from .format import (  # noqa: F401
    CompressionCodec,
    ConvertedType,
    Encoding,
    FieldRepetitionType,
    PageType,
    Type,
)
from .format.builder import (  # noqa: F401
    logical_bson,
    logical_date,
    logical_decimal,
    logical_enum,
    logical_int,
    logical_json,
    logical_string,
    logical_time,
    logical_timestamp,
    logical_uuid,
    new_data_column,
    new_group,
    new_list_column,
    new_map_column,
    new_root,
)
from .format.dsl import SchemaDefinition, parse_schema_definition  # noqa: F401
from .format.schema import Schema  # noqa: F401
from . import obs  # noqa: F401  (pure-stdlib telemetry surface)
from .errors import (  # noqa: F401  (structured error taxonomy)
    CorruptChunkError,
    CorruptFooterError,
    CorruptPageError,
    DeadlineExceededError,
    DeviceDispatchError,
    DispatchDeadlineError,
    ScanError,
    TransientIOError,
)
from .faults import QuarantineReport, inject_faults, retry_transient  # noqa: F401
from .io import FileReader, FileWriter  # noqa: F401
from .dataset import DatasetScan, DatasetWriter, compact_dataset  # noqa: F401
from .filter import Filter, col, parse_filter  # noqa: F401
from .stats import DecodeStats, collect_stats, trace  # noqa: F401
