"""Predicate pushdown: filter expressions, pruning, late materialization.

The analytics face of the scan path (ROADMAP item 1): a small
expression layer — column comparisons, ``IN``, null tests, ``&``/``|``
composition — evaluated at three escalating costs:

1. **Chunk statistics** (:func:`may_match_stats`): the footer's
   per-chunk ``Statistics`` min/max/null_count prove many row groups
   can contain no matching row; those are dropped before scan units
   are even formed.  Pure metadata — no I/O beyond the footer.
2. **Page index + bloom filters** (:func:`candidate_mask`, bloom
   probes inside :func:`may_match_stats`): the ``ColumnIndex`` /
   ``OffsetIndex`` written after the row groups narrow the candidate
   rows to the pages whose min/max admit a match, and split-block
   bloom filters (``format/bloom.py``) refute ``==``/``IN`` probes
   outright.  Conservative by construction: a page/chunk is only
   skipped when NO row in it can match.
3. **Exact evaluation** (:func:`evaluate_exact`): the filter columns
   decode first (late materialization), the predicate runs exactly on
   their values, and only surviving rows of the remaining columns are
   gathered (:func:`gather_chunk_rows`) — so filtered output is
   bit-identical to a full decode followed by a post-filter, at a
   fraction of the decode and transfer cost.

Semantics are SQL-flavored: comparisons and ``IN`` match only non-null
values; ``is_null``/``not_null`` test validity; NaN compares IEEE
(never equal, never ordered — ``!=`` is deliberately never pruned from
float statistics because NaN rows match it invisibly to min/max).

Usage::

    from tpuparquet.filter import col
    f = (col("price") > 100.0) & col("vendor").isin(["A", "B"])
    ShardedScan(paths, "price", "vendor", "ts", filter=f)

Every pruning decision lands in ``DecodeStats``
(``row_groups_pruned`` / ``pages_pruned`` / ``rows_pruned`` /
``bloom_hits`` / ``filter_rows_in`` / ``filter_rows_out``) and the
flight recorder, and surfaces in ``parquet-tool profile``.
"""

from __future__ import annotations

import os

import numpy as np

from .cpu.plain import ByteArrayColumn
from .format.metadata import Type

__all__ = [
    "col", "Col", "Filter", "Cmp", "In", "IsNull", "And", "Or",
    "bind_filter", "prune_enabled", "parse_filter",
    "may_match_stats", "candidate_mask", "evaluate_exact",
    "chunk_stats_tuple", "row_group_stats", "prune_row_group_stats",
    "gather_chunk_rows", "PruneVerdict", "read_row_group_filtered",
]


def parse_filter(expr: str) -> "Filter":
    """Parse a tiny textual predicate (the CLI/bench surface):
    comparisons ``name OP literal`` (OP in ``== != <= >= < >``),
    ``name in (a, b, c)``, ``name is null`` / ``name is not null``,
    joined by ``&`` / ``|`` with parentheses.  Literals: ints, floats,
    single/double-quoted strings.  Example::

        parquet-tool profile --filter "price > 100 & vendor in ('A','B')"
    """
    import re

    tokens = re.findall(
        r"\(|\)|&|\||==|!=|<=|>=|<|>|,|'[^']*'|\"[^\"]*\""
        r"|[A-Za-z_][\w.]*|-?\d+\.\d*(?:[eE][-+]?\d+)?|-?\.\d+"
        r"|-?\d+(?:[eE][-+]?\d+)?|\S", expr)
    pos = [0]

    def peek():
        return tokens[pos[0]] if pos[0] < len(tokens) else None

    def take(expect=None):
        t = peek()
        if t is None or (expect is not None and t != expect):
            raise ValueError(
                f"filter syntax error at token {pos[0]} "
                f"({t!r}, expected {expect!r}) in {expr!r}")
        pos[0] += 1
        return t

    def literal(t):
        if t and t[0] in "'\"":
            return t[1:-1]
        try:
            return int(t)
        except ValueError:
            return float(t)

    def atom():
        if peek() == "(":
            take("(")
            node = disjunction()
            take(")")
            return node
        name = take()
        if not re.fullmatch(r"[A-Za-z_][\w.]*", name):
            raise ValueError(f"expected a column name, got {name!r}")
        t = take()
        if t == "is":
            if peek() == "not":
                take("not")
                take("null")
                return IsNull(name, True)
            take("null")
            return IsNull(name, False)
        if t == "in":
            take("(")
            vals = [literal(take())]
            while peek() == ",":
                take(",")
                vals.append(literal(take()))
            take(")")
            return In(name, vals)
        if t not in _CMP_OPS:
            raise ValueError(f"unknown operator {t!r} in {expr!r}")
        return Cmp(name, t, literal(take()))

    def conjunction():
        node = atom()
        while peek() == "&":
            take("&")
            node = node & atom()
        return node

    def disjunction():
        node = conjunction()
        while peek() == "|":
            take("|")
            node = node | conjunction()
        return node

    node = disjunction()
    if pos[0] != len(tokens):
        raise ValueError(
            f"trailing tokens {tokens[pos[0]:]!r} in filter {expr!r}")
    return node


def prune_enabled() -> bool:
    """Read-side static pruning gate (``TPQ_PRUNE``, default on).
    ``TPQ_PRUNE=0`` disables every metadata-driven skip — filters are
    then applied purely by exact evaluation over a full decode, the
    parity escape hatch (results are identical either way)."""
    return os.environ.get("TPQ_PRUNE", "1") != "0"

_CMP_OPS = ("==", "!=", "<", "<=", ">", ">=")


class Filter:
    """Base predicate node.  Compose with ``&`` (and) / ``|`` (or)."""

    def __and__(self, other):
        return And([self, other])

    def __or__(self, other):
        return Or([self, other])

    def columns(self) -> set:
        raise NotImplementedError

    def __repr__(self):
        return self.describe()

    def describe(self) -> str:
        raise NotImplementedError


class Col:
    """A column reference; comparison operators build predicates."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __eq__(self, v):  # noqa: A003 - predicate builder, not identity
        return Cmp(self.name, "==", v)

    def __ne__(self, v):
        return Cmp(self.name, "!=", v)

    def __lt__(self, v):
        return Cmp(self.name, "<", v)

    def __le__(self, v):
        return Cmp(self.name, "<=", v)

    def __gt__(self, v):
        return Cmp(self.name, ">", v)

    def __ge__(self, v):
        return Cmp(self.name, ">=", v)

    def isin(self, values):
        return In(self.name, list(values))

    def is_null(self):
        return IsNull(self.name, False)

    def not_null(self):
        return IsNull(self.name, True)

    def __hash__(self):  # __eq__ is a builder; keep Col hashable
        return hash(self.name)


def col(name: str) -> Col:
    """Entry point: ``col("x") > 5``, ``col("s").isin([...])`` ..."""
    return Col(name)


class _Leaf(Filter):
    __slots__ = ("column", "_h")

    def columns(self) -> set:
        return {self.column}


class Cmp(_Leaf):
    # _stored/_logical are filled by bind_filter
    __slots__ = ("op", "value", "_stored", "_logical")

    def __init__(self, column: str, op: str, value):
        if op not in _CMP_OPS:
            raise ValueError(f"unknown comparison {op!r}")
        if value is None:
            raise ValueError(
                "comparisons never match NULL; use col().is_null() / "
                "not_null() to test validity")
        self.column = column
        self.op = op
        self.value = value
        self._h = None

    def describe(self) -> str:
        return f"({self.column} {self.op} {self.value!r})"


class In(_Leaf):
    # _stored/_logical are filled by bind_filter
    __slots__ = ("values", "_stored", "_logical")

    def __init__(self, column: str, values):
        vals = list(values)
        if not vals:
            raise ValueError("IN () matches nothing; build it explicitly"
                             " if you mean that")
        if any(v is None for v in vals):
            raise ValueError("IN never matches NULL; use is_null()")
        self.column = column
        self.values = vals
        self._h = None

    def describe(self) -> str:
        return f"({self.column} in {self.values!r})"


class IsNull(_Leaf):
    __slots__ = ("invert",)

    def __init__(self, column: str, invert: bool):
        self.column = column
        self.invert = invert  # True = NOT NULL
        self._h = None

    def describe(self) -> str:
        return f"({self.column} is {'not ' if self.invert else ''}null)"


class _Junction(Filter):
    __slots__ = ("parts",)

    def __init__(self, parts):
        flat = []
        for p in parts:
            if not isinstance(p, Filter):
                raise TypeError(
                    f"filter parts must be Filter nodes, not "
                    f"{type(p).__name__}")
            if type(p) is type(self):
                flat.extend(p.parts)
            else:
                flat.append(p)
        if not flat:
            raise ValueError("empty filter junction")
        self.parts = flat

    def columns(self) -> set:
        out = set()
        for p in self.parts:
            out |= p.columns()
        return out


class And(_Junction):
    def describe(self) -> str:
        return "(" + " & ".join(p.describe() for p in self.parts) + ")"


class Or(_Junction):
    def describe(self) -> str:
        return "(" + " | ".join(p.describe() for p in self.parts) + ")"


# ----------------------------------------------------------------------
# Binding: resolve columns against a schema, coerce predicate values
# ----------------------------------------------------------------------

def _coerce_leaf_value(handler, v):
    """Coerce one predicate constant to the column's comparison domain:
    the STORAGE value for bloom/array compares plus the LOGICAL value
    for statistics compares.  Returns (storage, logical)."""
    stored = handler.coerce_one(v)
    logical = stored
    if handler.unsigned and handler.ptype in (Type.INT32, Type.INT64):
        width = 32 if handler.ptype == Type.INT32 else 64
        logical = stored + (1 << width) if stored < 0 else stored
    if handler.ptype in (Type.FLOAT, Type.DOUBLE):
        # compare in the column's own precision: a float32 column's
        # values round-trip through float32, so the constant must too
        # (0.1 != float32(0.1) in float64)
        logical = float(np.float32(stored)) \
            if handler.ptype == Type.FLOAT else float(stored)
        stored = logical
    return stored, logical


def bind_filter(f: Filter, schema) -> Filter:
    """Validate a filter against a file's schema (in place, idempotent):
    every referenced column must be a NON-REPEATED leaf (filters
    evaluate row-wise; list semantics are out of scope), and leaf
    constants are coerced to the column's type once.  Returns ``f``.

    Raises ``ValueError`` for unknown/repeated columns, ``TypeError``
    for constants the column cannot hold — at bind time, before any
    decode work."""
    from .io.values import handler_for

    for leaf, _ in _walk_leaves(f):
        node = schema.leaf(leaf.column)
        if node is None:
            raise ValueError(
                f"filter references unknown column {leaf.column!r}")
        if node.max_rep_level:
            raise ValueError(
                f"filter column {leaf.column!r} is repeated; filters "
                "evaluate row-wise on non-repeated columns")
        h = handler_for(node.element)
        if h.ptype == Type.INT96 and not isinstance(leaf, IsNull):
            raise ValueError(
                f"filter column {leaf.column!r} is INT96, whose "
                "ordering the spec leaves undefined")
        leaf._h = h
        if isinstance(leaf, Cmp):
            leaf._stored, leaf._logical = _coerce_leaf_value(h, leaf.value)
        elif isinstance(leaf, In):
            pairs = [_coerce_leaf_value(h, v) for v in leaf.values]
            leaf._stored = [p[0] for p in pairs]
            leaf._logical = [p[1] for p in pairs]
    return f


def _walk_leaves(f: Filter):
    """Yield ``(leaf, negated_context)`` pairs — context unused today
    (no NOT node) but keeps the walk shape future-proof."""
    if isinstance(f, _Junction):
        for p in f.parts:
            yield from _walk_leaves(p)
    else:
        yield f, False


# ----------------------------------------------------------------------
# Level 1: chunk statistics (and bloom) — may this row group match?
# ----------------------------------------------------------------------

def _range_may_match(leaf, mn, mx, null_count, num_values) -> bool:
    """Conservative leaf verdict from a min/max/null_count summary.
    ``mn``/``mx`` are decoded LOGICAL values (None = unknown);
    ``null_count`` None = unknown.  True = cannot rule a match out."""
    if num_values is not None and num_values == 0:
        return False  # nothing there matches anything
    if isinstance(leaf, IsNull):
        if leaf.invert:  # NOT NULL: any non-null value?
            if null_count is not None and num_values is not None:
                return num_values - null_count > 0
            return True
        if null_count is not None:
            return null_count > 0
        return True
    # Cmp / In match only non-null values
    if null_count is not None and num_values is not None \
            and null_count == num_values:
        return False  # all null
    if mn is None or mx is None:
        return True  # no usable bounds
    if isinstance(leaf, In):
        return any(mn <= v <= mx for v in leaf._logical)
    v = leaf._logical
    op = leaf.op
    if op == "==":
        return mn <= v <= mx
    if op == "!=":
        # floats: NaN rows match != but are invisible to min/max —
        # never prune.  Other types: all non-null equal v => no match.
        if leaf._h is not None and leaf._h.ptype in (Type.FLOAT,
                                                     Type.DOUBLE):
            return True
        return not (mn == mx == v)
    if op == "<":
        return mn < v
    if op == "<=":
        return mn <= v
    if op == ">":
        return mx > v
    if op == ">=":
        return mx >= v
    raise AssertionError(op)


def chunk_stats_tuple(cm, handler):
    """Decode one chunk's ``Statistics`` into the logical summary
    ``(mn, mx, null_count, num_values)`` the leaf verdicts consume.
    Prefers min_value/max_value (v2 fields, typed order) and falls
    back to the deprecated signed min/max only where those are sound
    (signed numeric columns)."""
    st = cm.statistics
    num = cm.num_values
    if st is None:
        return None, None, None, num
    if not handler.stats_bytewise_comparable():
        # DECIMAL byte columns: stats sort numerically, predicates
        # compare bytewise — bounds are unusable, null_count is not
        return None, None, st.null_count, num
    mn_b, mx_b = st.min_value, st.max_value
    if mn_b is None and mx_b is None and not handler.unsigned \
            and handler.ptype not in (Type.BYTE_ARRAY,
                                      Type.FIXED_LEN_BYTE_ARRAY):
        mn_b, mx_b = st.min, st.max
    mn = handler.decode_stat_logical(mn_b) if mn_b is not None else None
    mx = handler.decode_stat_logical(mx_b) if mx_b is not None else None
    return mn, mx, st.null_count, num


def may_match_stats(f: Filter, stats_by_col: dict,
                    bloom_probe=None) -> bool:
    """May any row of a row group match ``f``?  ``stats_by_col`` maps
    column name -> ``(mn, mx, null_count, num_values)`` (absent column
    = no information).  ``bloom_probe(column, stored_values) -> bool``
    optionally refutes equality leaves: False = every probed value is
    definitely absent (the caller counts ``bloom_hits``)."""
    if isinstance(f, And):
        return all(may_match_stats(p, stats_by_col, bloom_probe)
                   for p in f.parts)
    if isinstance(f, Or):
        return any(may_match_stats(p, stats_by_col, bloom_probe)
                   for p in f.parts)
    summary = stats_by_col.get(f.column)
    if summary is not None:
        if not _range_may_match(f, *summary):
            return False
    if bloom_probe is not None and isinstance(f, (Cmp, In)):
        if isinstance(f, Cmp) and f.op == "==":
            probes = [f._stored]
        elif isinstance(f, In):
            probes = f._stored
        else:
            probes = None
        if probes is not None and bloom_probe(f.column, probes) is False:
            return False
    return True


def row_group_stats(rg, schema, wanted) -> dict:
    """``{column: (mn, mx, null_count, num_values)}`` for the
    ``wanted`` columns of one row group — the shared stats-gathering
    loop behind :func:`prune_row_group_stats` and
    ``FileReader.prune_row_group``."""
    from .io.values import handler_for

    stats = {}
    for cc in rg.columns:
        cm = cc.meta_data
        path = ".".join(cm.path_in_schema)
        if path not in wanted:
            continue
        node = schema.leaf(path)
        if node is None:
            continue
        stats[path] = chunk_stats_tuple(cm, handler_for(node.element))
    return stats


def prune_row_group_stats(f: Filter, rg, schema) -> bool:
    """True when chunk ``Statistics`` prove NO row of ``rg`` matches —
    the metadata-only verdict for callers without a reader (no bloom /
    page-index access).  ``f`` must be bound (:func:`bind_filter`)."""
    return not may_match_stats(f, row_group_stats(rg, schema,
                                                  f.columns()))


# ----------------------------------------------------------------------
# Level 2: page index — which rows may match?
# ----------------------------------------------------------------------

def candidate_mask(f: Filter, pages_by_col: dict,
                   num_rows: int) -> np.ndarray:
    """Boolean mask over the row group's rows: True where the page
    index cannot rule a match out.  ``pages_by_col`` maps column name
    -> list of ``(row_start, row_end, mn, mx, null_count, null_page)``
    per data page (absent column / None = no index = all rows may
    match).  Page summaries use the same conservative leaf verdicts as
    the chunk level, so the mask is a superset of the true matches."""
    if isinstance(f, And):
        m = candidate_mask(f.parts[0], pages_by_col, num_rows)
        for p in f.parts[1:]:
            m &= candidate_mask(p, pages_by_col, num_rows)
        return m
    if isinstance(f, Or):
        m = candidate_mask(f.parts[0], pages_by_col, num_rows)
        for p in f.parts[1:]:
            m |= candidate_mask(p, pages_by_col, num_rows)
        return m
    pages = pages_by_col.get(f.column)
    if pages is None:
        return np.ones(num_rows, dtype=bool)
    m = np.zeros(num_rows, dtype=bool)
    for r0, r1, mn, mx, nulls, null_page in pages:
        if null_page:
            may = isinstance(f, IsNull) and not f.invert
        else:
            may = _range_may_match(f, mn, mx, nulls, r1 - r0)
        if may:
            m[max(r0, 0):min(r1, num_rows)] = True
    return m


# ----------------------------------------------------------------------
# Level 3: exact evaluation on decoded filter columns
# ----------------------------------------------------------------------

def _cmp_array(handler, arr, op, stored):
    """Elementwise compare of a packed fixed-width value array."""
    if handler.unsigned and handler.ptype in (Type.INT32, Type.INT64):
        arr = arr.view(np.uint32 if handler.ptype == Type.INT32
                       else np.uint64)
        stored = stored & ((1 << (32 if handler.ptype == Type.INT32
                                  else 64)) - 1)
    if handler.ptype == Type.FIXED_LEN_BYTE_ARRAY:
        return _bytes_rows_cmp(arr, op, stored)
    if op == "==":
        return arr == stored
    if op == "!=":
        return arr != stored
    if op == "<":
        return arr < stored
    if op == "<=":
        return arr <= stored
    if op == ">":
        return arr > stored
    if op == ">=":
        return arr >= stored
    raise AssertionError(op)


def _bytes_rows_cmp(rows: np.ndarray, op: str, v: bytes):
    """Compare (N, L) fixed byte rows against a constant, bytewise
    unsigned (the FLBA sort order)."""
    vals = [bytes(r) for r in rows]
    return _py_cmp_list(vals, op, v)


def _py_cmp_list(vals, op, v):
    import operator as _op

    fn = {"==": _op.eq, "!=": _op.ne, "<": _op.lt, "<=": _op.le,
          ">": _op.gt, ">=": _op.ge}[op]
    return np.fromiter((fn(x, v) for x in vals), dtype=bool,
                       count=len(vals))


def _ba_eq_mask(col: ByteArrayColumn, v: bytes) -> np.ndarray:
    """Vectorized equality of a ByteArrayColumn against one constant."""
    offs = np.asarray(col.offsets, dtype=np.int64)
    data = np.asarray(col.data)
    lens = offs[1:] - offs[:-1]
    out = lens == len(v)
    if len(v) and out.any():
        starts = offs[:-1][out]
        rows = data[starts[:, None] + np.arange(len(v), dtype=np.int64)]
        out[out.copy()] = (rows == np.frombuffer(v, np.uint8)).all(axis=1)
    return out


def _ba_cmp(colv: ByteArrayColumn, op: str, v: bytes) -> np.ndarray:
    if op == "==":
        return _ba_eq_mask(colv, v)
    if op == "!=":
        return ~_ba_eq_mask(colv, v)
    # ordering: bytewise lexicographic; per-value Python compare (the
    # ordered-predicate-on-strings case is rare and test-sized)
    return _py_cmp_list(colv.to_list(), op, v)


def _leaf_exact(leaf, packed, valid) -> np.ndarray:
    """Row-domain bool mask for one leaf: ``packed`` holds the valid
    rows' values in row order, ``valid`` the row-aligned validity."""
    n = valid.shape[0]
    if isinstance(leaf, IsNull):
        return valid.copy() if leaf.invert else ~valid
    out = np.zeros(n, dtype=bool)
    if packed is None or (hasattr(packed, "__len__")
                          and len(packed) == 0):
        return out
    h = leaf._h
    if isinstance(packed, ByteArrayColumn):
        if isinstance(leaf, In):
            sub = np.zeros(len(packed), dtype=bool)
            for v in leaf._stored:
                sub |= _ba_eq_mask(packed, v)
        else:
            sub = _ba_cmp(packed, leaf.op, leaf._stored)
    else:
        arr = np.asarray(packed)
        if isinstance(leaf, In):
            sub = np.zeros(arr.shape[0], dtype=bool)
            for v in leaf._stored:
                sub |= np.asarray(_cmp_array(h, arr, "==", v))
        else:
            sub = np.asarray(_cmp_array(h, arr, leaf.op, leaf._stored))
    out[valid] = sub
    return out


def evaluate_exact(f: Filter, cols: dict, num_rows: int) -> np.ndarray:
    """Exact row mask over a shared row domain.  ``cols`` maps column
    name -> ``(packed_values, valid)`` where ``valid`` is a bool array
    of ``num_rows`` and ``packed_values`` holds the values of the
    valid rows in row order (ndarray, (N, L) byte rows, or
    :class:`ByteArrayColumn`)."""
    if isinstance(f, And):
        m = evaluate_exact(f.parts[0], cols, num_rows)
        for p in f.parts[1:]:
            if not m.any():
                break
            m &= evaluate_exact(p, cols, num_rows)
        return m
    if isinstance(f, Or):
        m = evaluate_exact(f.parts[0], cols, num_rows)
        for p in f.parts[1:]:
            if m.all():
                break
            m |= evaluate_exact(p, cols, num_rows)
        return m
    packed, valid = cols[f.column]
    return _leaf_exact(f, packed, valid)


# ----------------------------------------------------------------------
# Late materialization: gather surviving rows out of decoded chunks
# ----------------------------------------------------------------------

def _gather_bytes(colv: ByteArrayColumn, vidx: np.ndarray):
    offs = np.asarray(colv.offsets, dtype=np.int64)
    data = np.asarray(colv.data)
    lens = (offs[1:] - offs[:-1])[vidx]
    starts = offs[:-1][vidx]
    new_offs = np.zeros(vidx.size + 1, dtype=np.int64)
    np.cumsum(lens, out=new_offs[1:])
    total = int(new_offs[-1])
    if total == 0:
        return ByteArrayColumn(new_offs, np.zeros(0, dtype=np.uint8))
    # vectorized variable-length gather: absolute source index per
    # output byte = repeat(starts) + (arange - repeat(dest starts))
    rep_starts = np.repeat(starts, lens)
    rep_dest = np.repeat(new_offs[:-1], lens)
    idx = rep_starts + (np.arange(total, dtype=np.int64) - rep_dest)
    return ByteArrayColumn(new_offs, data[idx])


def gather_chunk_rows(cd, node, sel: np.ndarray):
    """Gather selected ROWS (records) out of a decoded chunk.

    ``cd`` is an :class:`~tpuparquet.io.chunk.ChunkData`; ``sel`` the
    sorted local row indices to keep.  Handles flat columns (one slot
    per row) and repeated columns (records bounded by rep==0 slots).
    Returns a new ChunkData holding exactly the selected records,
    bit-identical to post-filtering a full decode."""
    from .io.chunk import ChunkData

    sel = np.asarray(sel, dtype=np.int64)
    dl = cd.def_levels
    rep = cd.rep_levels
    max_def = node.max_def_level
    if node.max_rep_level and rep.size:
        starts = np.flatnonzero(rep == 0)
        bounds = np.concatenate([starts, [dl.size]])
        slot_lens = (bounds[1:] - bounds[:-1])[sel]
        slot_starts = bounds[:-1][sel]
        total = int(slot_lens.sum())
        rep_starts = np.repeat(slot_starts, slot_lens)
        rep_dest = np.repeat(np.cumsum(slot_lens) - slot_lens, slot_lens)
        slots = rep_starts + (np.arange(total, dtype=np.int64) - rep_dest)
    else:
        slots = sel
    new_dl = dl[slots] if dl.size else dl[:0]
    new_rep = rep[slots] if rep.size else rep[:0]
    if max_def:
        valid = dl == max_def
        pidx = np.cumsum(valid) - 1
        vsel = valid[slots]
        vidx = pidx[slots][vsel].astype(np.int64)
    else:
        vidx = slots
    vals = cd.values
    if isinstance(vals, ByteArrayColumn):
        new_vals = _gather_bytes(vals, vidx)
    else:
        new_vals = np.asarray(vals)[vidx]
    null_count = int((new_dl != max_def).sum()) if max_def else 0
    return ChunkData(new_vals, new_rep, new_dl, null_count)


class PruneVerdict:
    """One row group's pruning outcome: ``skip`` (no row can match),
    the static ``candidate`` row mask (page-index level, None = all),
    and the counters the decision earned.  ``reason`` names the layer
    that proved the skip ("stats" / "bloom" / "pages" / "exact")."""

    __slots__ = ("skip", "reason", "candidate", "pages_by_col",
                 "bloom_hits")

    def __init__(self, skip=False, reason=None, candidate=None,
                 pages_by_col=None, bloom_hits=0):
        self.skip = skip
        self.reason = reason
        self.candidate = candidate
        self.pages_by_col = pages_by_col or {}
        self.bloom_hits = bloom_hits


# ----------------------------------------------------------------------
# The filtered row-group decode (late materialization)
# ----------------------------------------------------------------------

def _empty_chunks(reader, rg):
    """Schema-shaped zero-row output for a fully pruned row group."""
    from .io.chunk import ChunkData
    from .io.values import handler_for

    out = {}
    for path, node, _cm in reader.selected_chunks(rg):
        out[path] = ChunkData(
            handler_for(node.element).finalize([]),
            np.empty(0, dtype=np.int32), np.empty(0, dtype=np.int32), 0)
    return out


def read_row_group_filtered(reader, rg_index: int, f: Filter,
                            verdict: PruneVerdict | None = None):
    """Decode one row group under a filter, late-materialized.

    Three escalating stages, every one conservative until the last:

    1. the static verdict (chunk stats → bloom → page index) may prove
       the whole row group empty — nothing is read;
    2. the FILTER columns decode first, skipping pages outside the
       candidate row set (``read_chunk(keep_rows=)``), and the
       predicate evaluates exactly on candidate rows;
    3. only then do the remaining projected columns decode — pages
       holding no surviving row are skipped — and every column gathers
       exactly the surviving rows.

    Returns ``(chunks, surviving_rows)``: ``chunks`` maps each SELECTED
    column to a :class:`~tpuparquet.io.chunk.ChunkData` holding exactly
    the surviving rows (bit-identical to a full decode followed by a
    post-filter), ``surviving_rows`` the sorted local row indices.
    Counters: ``row_groups_pruned``/``rows_pruned``/``pages_pruned``/
    ``filter_rows_in``/``filter_rows_out`` on the active collector."""
    from .io.chunk import read_chunk
    from .io.reader import _rebase
    from .stats import current_stats

    bind_filter(f, reader.schema)
    rg = reader.meta.row_groups[rg_index]
    num_rows = rg.num_rows
    st = current_stats()
    if verdict is None:
        verdict = reader.prune_row_group(f, rg_index)
        if st is not None and verdict.bloom_hits:
            st.bloom_hits += verdict.bloom_hits
    if verdict.skip:
        if st is not None:
            st.row_groups_pruned += 1
            st.rows_pruned += num_rows
        return _empty_chunks(reader, rg), np.empty(0, dtype=np.int64)

    cand = verdict.candidate  # bool mask over rows, or None = all
    cand_rows = (np.flatnonzero(cand) if cand is not None
                 else np.arange(num_rows, dtype=np.int64))
    if st is not None and cand is not None:
        st.rows_pruned += num_rows - cand_rows.size

    cms = {".".join(cc.meta_data.path_in_schema): cc.meta_data
           for cc in rg.columns}
    verify_crc = getattr(reader, "_verify_crc", None)

    def _decode(path, keep):
        cm = cms[path]
        node = reader.schema.leaf(path)
        blob, start = reader.chunk_blob(cm, path)
        cmr = _rebase(cm, start)
        if keep is not None and not node.max_rep_level:
            cd, kept = read_chunk(memoryview(blob), cmr, node,
                                  verify_crc=verify_crc, keep_rows=keep)
        else:
            cd = read_chunk(memoryview(blob), cmr, node,
                            verify_crc=verify_crc)
            kept = np.arange(num_rows, dtype=np.int64)
        return node, cd, kept

    # stage 2: filter columns decode first, predicate runs exactly on
    # the candidate rows (kept is a page-granular superset of cand).
    # Remote sources batch-prefetch exactly the chunks each stage is
    # about to read — the filter columns here, the undecoded survivor
    # columns below — so late materialization doesn't turn into one
    # round trip per column.
    pf = getattr(reader, "prefetch_ranges", None)
    fcols = sorted(f.columns())
    if pf is not None:
        pf([(reader._chunk_start(cms[p]), cms[p].total_compressed_size,
             p) for p in fcols if p in cms])
    decoded = {}
    for path in fcols:
        if path not in cms:
            raise ValueError(
                f"filter references column {path!r} absent from row "
                f"group {rg_index}")
        decoded[path] = _decode(path, cand)
    cols_eval = {}
    for path, (node, cd, kept) in decoded.items():
        loc = np.searchsorted(kept, cand_rows)
        sub = (cd if cand_rows.size == num_rows
               and kept.size == num_rows
               else gather_chunk_rows(cd, node, loc))
        valid = (sub.def_levels == node.max_def_level
                 if node.max_def_level
                 else np.ones(cand_rows.size, dtype=bool))
        cols_eval[path] = (sub.values, valid)
    mask = evaluate_exact(f, cols_eval, cand_rows.size)
    surviving = cand_rows[mask]
    if st is not None:
        st.filter_rows_in += cand_rows.size
        st.filter_rows_out += int(surviving.size)

    # stage 3: gather survivors; undecoded columns skip pages that
    # hold none of them
    keep2 = None
    if surviving.size < num_rows:
        keep2 = np.zeros(num_rows, dtype=bool)
        keep2[surviving] = True
    out = {}
    sel = reader.selected_chunks(rg)
    if pf is not None:
        pf([(reader._chunk_start(cm), cm.total_compressed_size, p)
            for p, _n, cm in sel if p not in decoded])
    for path, node, _cm in sel:
        if path in decoded:
            node, cd, kept = decoded[path]
        else:
            node, cd, kept = _decode(path, keep2)
        if surviving.size == num_rows and kept.size == num_rows:
            out[path] = cd  # everything survived: the decode IS the answer
            continue
        loc = np.searchsorted(kept, surviving)
        out[path] = gather_chunk_rows(cd, node, loc)
    return out, surviving
