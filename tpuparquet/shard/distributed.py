"""Multi-host scan driver (SURVEY.md §5 "distributed communication
backend": multi-file scans shard (file x row-group) work lists across
processes via ``jax.distributed``).

Each process decodes its slice of the global work list on its local
devices (ICI-parallel via :class:`~tpuparquet.shard.scan.ShardedScan`);
cross-host exchange uses the XLA collectives JAX places on DCN.  All
entry points degrade to single-process behavior, so the same driver
script runs unchanged from a laptop to a pod.
"""

from __future__ import annotations

import os
import time

import jax
import numpy as np

__all__ = [
    "initialize",
    "process_units",
    "MultiHostScan",
    "allgather_host",
    "allgather_bytes",
    "allgather_stats",
    "allgather_metrics",
    "allgather_digests",
    "allgather_profiles",
]

from .scan import DurableScanMixin as _DurableScanMixin  # noqa: E402


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Bring up the JAX distributed runtime (no-op if single-process
    args are absent and the environment provides no cluster config).

    Mirrors the reference's absent-but-implied multi-process story: the
    runtime handles barrier/NCCL-equivalent transport; we only shard
    work lists."""
    if coordinator_address is None and num_processes is None:
        return  # single process; jax.process_count() == 1
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_units(units, process_index: int | None = None,
                  process_count: int | None = None) -> list:
    """This process's slice of the global (file, row-group) work list.

    Strided assignment (unit i -> process i % P): deterministic across
    processes with no coordination, and balanced when row-group sizes
    are i.i.d. — the same policy the in-process scan uses per device."""
    p = jax.process_index() if process_index is None else process_index
    n = jax.process_count() if process_count is None else process_count
    return [u for i, u in enumerate(units) if i % n == p]


def allgather_host(local_rows: np.ndarray) -> np.ndarray:
    """All-gather variable host arrays across processes (DCN).

    Single-process: identity.  Multi-process: delegates to
    ``jax.experimental.multihost_utils.process_allgather``.

    64-bit payloads ship as (lo, hi) u32 lanes: JAX's default 32-bit
    mode silently truncates int64/float64 in transit — checksums over
    2**32 came back wrapped (caught by the at-scale two-process run;
    the framework's device buffers use the same lane convention)."""
    a = np.asarray(local_rows)
    if jax.process_count() == 1:
        return a
    from jax.experimental import multihost_utils

    if a.dtype.itemsize == 8:
        a1 = np.atleast_1d(a)  # 0-d arrays refuse the itemsize re-view
        lanes = np.ascontiguousarray(a1).view(np.uint32).reshape(
            a1.shape + (2,))
        out = np.asarray(multihost_utils.process_allgather(lanes))
        res = np.ascontiguousarray(out).view(a.dtype).reshape(
            out.shape[:-1])
        if a.ndim == 0:  # drop the atleast_1d axis: (procs, 1) -> (procs,)
            res = res.reshape(res.shape[0])
        return res
    return np.asarray(multihost_utils.process_allgather(a))


def allgather_bytes(payload: bytes) -> list[bytes]:
    """All-gather one variable-length byte payload per process.

    Two collectives: lengths first (so every process can pad to the
    common maximum — ``process_allgather`` requires identical shapes),
    then the padded u8 buffers.  Single-process: ``[payload]``."""
    if jax.process_count() == 1:
        return [payload]
    lens = allgather_host(np.asarray(len(payload), dtype=np.int64))
    lens = lens.reshape(-1)
    L = max(int(lens.max()), 1)
    buf = np.zeros(L, dtype=np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    out = allgather_host(buf).reshape(len(lens), L)
    return [out[i, : int(lens[i])].tobytes() for i in range(len(lens))]


def allgather_stats(st) -> "DecodeStats":
    """Fold every host's ``DecodeStats`` — counters AND log2-bucket
    histograms — into one fleet-wide collector, identical on every
    process (rank 0 reports it; the others get it for free, the
    all-gather is symmetric).

    Counters ship EXACT (``to_state``, not the display-rounded
    ``as_dict``) as JSON over :func:`allgather_bytes`, so the fleet
    totals equal the elementwise sum of the per-host counters and the
    fleet histograms are the exact bucket-wise sums (the
    ``obs.Histogram`` merge property).  ``wall_s`` folds as the MAX
    across hosts — the hosts decode concurrently, so the fleet
    values/sec is fleet values over the slowest host's wall, not over
    the summed walls.  Per-page event logs stay host-local (per-page
    detail does not aggregate; export it per host instead)."""
    import json

    from ..stats import DecodeStats

    payloads = allgather_bytes(json.dumps(st.to_state()).encode())
    total = DecodeStats()
    wall = 0.0
    for p in payloads:
        host = DecodeStats.from_state(json.loads(p))
        total.merge_from(host)
        wall = max(wall, host.wall_s)
    total.wall_s = wall
    return total


def allgather_metrics(reg=None) -> "MetricsRegistry":
    """Fold every host's live metrics registry
    (:mod:`tpuparquet.obs.live`) into one fleet-wide registry,
    identical on every process — the always-on counterpart of
    :func:`allgather_stats`, same wire (exact JSON state over
    :func:`allgather_bytes`), same exactness: fleet counters are the
    elementwise sums and fleet histograms the exact bucket-wise sums
    of the per-host registries, so the merged snapshot equals the
    single-host snapshot of the union corpus.  Gauges are
    instantaneous, not cumulative — each host's land under a
    ``p<idx>_`` prefix instead of being summed.  ``reg`` defaults to
    this process's registry."""
    import json as _json

    from ..obs.live import MetricsRegistry, registry

    if reg is None:
        reg = registry()
    payloads = allgather_bytes(_json.dumps(reg.to_state()).encode())
    total = MetricsRegistry()
    for i, p in enumerate(payloads):
        state = _json.loads(p)
        gauges = state.pop("gauges", {}) or {}
        total.merge_from(MetricsRegistry.from_state(state))
        for k, v in gauges.items():
            total.gauge(f"p{i}_{k}", v)
    return total


def allgather_digests(reg=None) -> "DigestRegistry":
    """Fold every host's latency quantile digests
    (:mod:`tpuparquet.obs.digest`) into one fleet-wide registry,
    identical on every process — same wire as
    :func:`allgather_metrics` (exact JSON state over
    :func:`allgather_bytes`), same exactness: the digests' fixed
    sub-octave buckets sum elementwise, so the merged digest equals
    the single-host digest of the union corpus bucket-for-bucket
    (what the soak harness pins).  ``reg`` defaults to this process's
    active digest registry; an unarmed process contributes an empty
    state."""
    import json as _json

    from ..obs.digest import DigestRegistry, digests

    if reg is None:
        reg = digests()
    state = {} if reg is None else reg.to_state()
    payloads = allgather_bytes(_json.dumps(state).encode())
    total = DigestRegistry()
    for p in payloads:
        total.merge_state(_json.loads(p))
    return total


def allgather_traces(spans=None) -> list[dict]:
    """Fold every host's causal-trace spans
    (:mod:`tpuparquet.obs.trace`) into one fleet-wide span list,
    identical on every process — the tracing sibling of
    :func:`allgather_metrics` (same wire: JSON over
    :func:`allgather_bytes`).  Each span gains a ``proc`` field naming
    its origin process (trace ids already embed the origin pid, so
    merged trees never collide); parent/child links are host-local by
    construction and survive the merge untouched.  ``spans`` defaults
    to this process's tracer snapshot ([] when tracing is off —
    the merge then returns only the hosts that traced)."""
    import json as _json

    from ..obs.trace import snapshot_spans

    if spans is None:
        spans = snapshot_spans()
    payloads = allgather_bytes(_json.dumps(spans).encode())
    merged: list[dict] = []
    for i, p in enumerate(payloads):
        for s in _json.loads(p):
            s["proc"] = i
            merged.append(s)
    merged.sort(key=lambda s: (s.get("proc", 0), s.get("t0", 0.0)))
    return merged


def allgather_profiles(state=None) -> dict:
    """Fold every host's sampling-profile state
    (:mod:`tpuparquet.obs.profiler`) into one fleet-wide profile,
    identical on every process — same wire as
    :func:`allgather_digests` (exact JSON state over
    :func:`allgather_bytes`), same exactness: sample counters and
    per-(label, stage) stack tallies sum elementwise, so the merged
    profile equals the single-host profile of the union sample set
    bucket-for-bucket.  ``state`` defaults to this process's armed
    profiler; an unarmed process contributes an empty state."""
    import json as _json

    from ..obs import profiler as _profiler
    from ..obs.profiler import merge_profile_states

    if state is None:
        p = _profiler.profiler()
        state = p.to_state() if p is not None else None
    payloads = allgather_bytes(
        _json.dumps(state or {}).encode())
    return merge_profile_states(
        [_json.loads(pl) for pl in payloads])


def allgather_ledgers() -> dict:
    """Fold every host's per-scan attribution ledgers
    (:mod:`tpuparquet.obs.attribution`) into one fleet-wide
    ``{label: ScanLedger}``, identical on every process: counters sum
    label-wise (exact — the merged ledger equals the single-host
    ledger of the union corpus), peaks fold as max (per-host arena
    occupancy is concurrent, not additive)."""
    import json as _json

    from ..obs.attribution import ledgers_state, merge_ledger_states

    payloads = allgather_bytes(_json.dumps(ledgers_state()).encode())
    return merge_ledger_states([_json.loads(p) for p in payloads])


class MultiHostScan(_DurableScanMixin):
    """Decode many files across processes *and* local devices.

    The global unit list (file x row-group) is strided over processes;
    each process runs a local :class:`ShardedScan`-style loop over its
    units on its own mesh.  ``run`` returns this process's decoded
    units; ``counts_allgather`` exchanges per-unit row counts so every
    process knows the global shape (the usual precursor to a global
    reshard).

    ``on_error="quarantine"`` isolates failing units per host instead
    of aborting the fleet (coordinates + error class in
    :attr:`quarantine`, same semantics as
    :class:`~tpuparquet.shard.scan.ShardedScan`); files whose footer
    fails to open/validate are quarantined (or, with ``salvage=True``,
    salvaged to their readable prefix) at FILE granularity — see
    :func:`~tpuparquet.shard.scan.open_sources`;
    :meth:`allgather_quarantine` folds every host's report into the
    fleet-wide list.

    Time/crash domain (same knobs as ``ShardedScan``):
    ``unit_deadline``/``scan_deadline`` bound hung units and the whole
    scan.  CAUTION — ``scan_deadline`` is evaluated PER HOST on its
    local clock and raises non-collectively: a host whose units finish
    under budget never raises, so a caller that follows ``run_iter``
    with a collective (``allgather_quarantine``, ``allgather_stats``,
    a gather) must reach that collective on EVERY host — catch
    ``DeadlineExceededError`` and fall through to the collective (the
    cursor is already checkpointed), or exchange a done/expired flag
    first; letting one host exit while its siblings enter the
    collective stalls the fleet.  Sources may be replica groups hedged
    after ``hedge_delay``;
    ``resume_from=base`` checkpoints durably to a PER-HOST file
    (``base.p<process_index>`` —
    :func:`~tpuparquet.shard.scan.host_cursor_path`, so hosts never
    race on one file) and resume validates fleet agreement: every
    host must see the same unit list and the same
    have-a-checkpoint answer, or the resume raises instead of
    silently re-decoding or skipping a shard.

    Output placement: ``out_sharding=``/``gather_to=`` (env
    ``TPQ_GATHER_TO``) set this PROCESS's default for
    :meth:`gather_column`/:meth:`gather_byte_column` — each host
    gathers its own local units onto its local target (the spec must
    be fully addressable from the process; cross-host exchange stays
    with the DCN collectives above).  Semantics otherwise identical to
    :class:`~tpuparquet.shard.scan.ShardedScan`."""

    def __init__(self, sources, *columns: str, mesh=None, resume=None,
                 on_error: str = "raise", retries: int | None = None,
                 salvage: bool = False,
                 strict_metadata: bool | None = None,
                 unit_deadline: float | None = None,
                 scan_deadline: float | None = None,
                 hedge_delay: float | None = None,
                 read_deadline: float | None = None,
                 resume_from: str | None = None,
                 checkpoint_every: int | None = None,
                 progress_export: str | None = None,
                 postmortem=None,
                 filter=None,
                 out_sharding=None, gather_to=None):
        from ..faults import QuarantineReport
        from ..obs.progress import progress_export_default
        from .mesh import make_mesh, resolve_out_sharding
        from .scan import (
            host_cursor_path,
            load_cursor_file,
            open_sources,
            scan_units,
        )

        if on_error not in ("raise", "quarantine"):
            raise ValueError(
                f"on_error must be 'raise' or 'quarantine', "
                f"not {on_error!r}")
        p0 = jax.process_index()
        self._init_durable(
            on_error=on_error, unit_deadline=unit_deadline,
            scan_deadline=scan_deadline, resume=resume,
            resume_from=resume_from, checkpoint_every=checkpoint_every,
            checkpoint_path=(None if resume_from is None
                             else host_cursor_path(resume_from, p0)),
            postmortem=postmortem)
        # every process opens every source (salvage is deterministic,
        # so all hosts derive the identical reader/unit list), but a
        # failed/salvaged FILE is recorded by exactly one process
        # (index mod grid) so fleet-folded counters and the
        # allgathered quarantine count each file once
        p, n = jax.process_index(), jax.process_count()
        self._open_quarantine = QuarantineReport()
        self.readers = open_sources(
            sources, columns, on_error=on_error,
            quarantine=self._open_quarantine, salvage=salvage,
            strict_metadata=strict_metadata,
            record_for=lambda i: i % n == p,
            entry_extra={"process_index": p},
            hedge_delay=hedge_delay, read_deadline=read_deadline,
            postmortem=self._postmortem_path)
        # pruning verdicts are a pure function of the footers, so every
        # host derives the identical filtered unit list (the same
        # determinism contract salvage relies on)
        self._init_filter(filter, self.readers)
        self.global_units = scan_units(self.readers, filter=self.filter,
                                       verdicts=self._verdicts,
                                       pruned=self._pruned)
        self.local_units = process_units(self.global_units)
        # per-host status file (base.p<idx>, like the checkpoints) so
        # hosts never race on one progress file; parquet-tool top takes
        # several paths and renders the fleet side by side.  The path
        # is fully resolved HERE ("" when disabled — never None, which
        # _init_telemetry would re-default from the env without the
        # per-host suffix)
        pe = (progress_export if progress_export is not None
              else progress_export_default())
        self._init_telemetry(
            len(self.local_units),
            (f"{pe}.p{p0}" if pe and n > 1 else pe) or "",
            f"scan.p{p0}")
        # make_mesh defaults to LOCAL devices (see its docstring; the
        # 2-process integration test caught the global-devices variant)
        self.mesh = mesh if mesh is not None else make_mesh()
        # scan-level output placement default (per PROCESS: each host
        # gathers its own units onto its local target — the resolver
        # rejects non-addressable specs; see resolve_out_sharding)
        self.out_sharding = resolve_out_sharding(
            self.mesh, out_sharding, gather_to)
        self.devices = list(self.mesh.devices.flat)
        self.on_error = on_error
        self.retries = retries
        self.quarantine = QuarantineReport(
            self._open_quarantine.as_dicts())
        self._next_local = 0
        if resume is None and self._checkpoint_path is not None:
            found = os.path.exists(self._checkpoint_path)
            if n > 1:
                self._validate_resume_agreement(found)
            if found:
                resume = load_cursor_file(self._checkpoint_path)
        if resume is not None:
            self._load_cursor(resume)

    def _validate_resume_agreement(self, found: bool) -> None:
        """Collective resume sanity: every host must derive the same
        global unit list AND give the same have-a-checkpoint answer.
        A host resuming while a sibling starts fresh would silently
        re-decode (or a diverged unit list silently misassign) its
        stride of the fleet's work — fail loudly instead."""
        import json
        import zlib

        units_crc = zlib.crc32(json.dumps(
            [list(u) for u in self.global_units]).encode())
        payloads = allgather_bytes(json.dumps(
            {"found": bool(found), "units_crc": units_crc}).encode())
        states = [json.loads(b) for b in payloads]
        crcs = {s["units_crc"] for s in states}
        if len(crcs) > 1:
            raise ValueError(
                "checkpoint resume: hosts disagree on the scan's unit "
                "list (sources changed on some hosts?)")
        founds = {s["found"] for s in states}
        if len(founds) > 1:
            missing = [i for i, s in enumerate(states)
                       if not s["found"]]
            raise ValueError(
                "checkpoint resume: only some hosts have a checkpoint "
                f"file (missing on process(es) {missing}); restore the "
                "missing per-host file(s) or delete them all to start "
                "fresh")

    def _load_cursor(self, cursor: dict) -> None:
        from ..faults import QuarantineReport
        from .scan import cursor_load

        # process grid coordinates are identity: a cursor restored on
        # the wrong process (or grid size) would silently skip or
        # re-decode units of the strided assignment
        self._next_local = cursor_load(
            cursor, self.global_units, "next_local_unit",
            len(self.local_units),
            process_count=jax.process_count(),
            process_index=jax.process_index(),
        )
        self.quarantine = QuarantineReport.from_dicts(
            cursor.get("quarantine"))
        # dedup against the re-opened sources' fresh file entries —
        # same fix as ShardedScan._load_cursor
        self.quarantine.merge_unique(self._open_quarantine.as_dicts())

    def state(self) -> dict:
        """JSON-serializable per-process cursor (resume with
        ``MultiHostScan(sources, ..., resume=state)`` on the SAME
        process of the SAME grid).  Valid between :meth:`run_iter`
        steps; carries this host's quarantine report."""
        from .scan import cursor_state

        return cursor_state(
            self.global_units, "next_local_unit", self._next_local,
            process_count=jax.process_count(),
            process_index=jax.process_index(),
            quarantine=self.quarantine.as_dicts(),
        )

    def _progress(self):
        return self._next_local, len(self.local_units)

    def _advance(self, k: int) -> None:
        self._next_local = k + 1

    def _unit_coords(self, k: int) -> tuple[int, int]:
        return self.local_units[k]

    def run_iter(self):
        """Yield ``(local_index, {path: DeviceColumn})`` from the cursor
        position, advancing it after each unit.  Quarantine mode skips
        (and records) failing units, like ``ShardedScan.run_iter``;
        the durable per-host checkpoint, the scan budget, and the live
        telemetry (per-host :attr:`progress` status file, ambient
        metrics, automatic post-mortems) apply exactly as there."""
        from .scan import pipelined_unit_scan, resilient_unit_scan

        self._run_t0 = time.monotonic()
        if self.filter is not None and self._next_local == 0:
            # each dropped row group / kept verdict counts on exactly
            # one host, so the fleet-folded counters stay exact
            p, n = jax.process_index(), jax.process_count()
            local = set(self.local_units)
            self._count_pruned(
                select_pruned=lambda j: j % n == p,
                select_kept=lambda key: key in local)
        if self.on_error == "raise":
            gen = pipelined_unit_scan(
                self.readers, self.local_units,
                lambda i: self.devices[i % len(self.devices)],
                start=self._next_local, filter=self.filter,
                verdicts=self._verdicts)
        else:
            gen = resilient_unit_scan(
                self.readers, self.local_units,
                lambda i: self.devices[i % len(self.devices)],
                start=self._next_local, retries=self.retries,
                quarantine=self.quarantine,
                entry_extra={"process_index": jax.process_index()},
                unit_deadline=self.unit_deadline,
                postmortem=self._postmortem_path,
                filter=self.filter, verdicts=self._verdicts)
        yield from self._drive(gen)

    def allgather_quarantine(self) -> list[dict]:
        """Every host's quarantine entries, identical on every process
        (JSON over :func:`allgather_bytes`, like the stats fold)."""
        import json

        payloads = allgather_bytes(
            json.dumps(self.quarantine.as_dicts()).encode())
        out: list[dict] = []
        for p in payloads:
            out.extend(json.loads(p))
        return out

    def run(self) -> list[dict]:
        """Decode ALL of this process's units (position i of the result
        is local unit i; always a full scan — resume via run_iter).

        Host planning of unit N+1 overlaps device transfer of unit N
        (same pipeline as :class:`~tpuparquet.shard.scan.ShardedScan`).
        In quarantine mode the result holds only the units that
        decoded; :attr:`quarantine` names the rest."""
        from ..faults import QuarantineReport

        self._next_local = 0
        if self.on_error == "quarantine":
            self.quarantine = QuarantineReport(
                self._open_quarantine.as_dicts())
        return [out for _, out in self.run_iter()]

    def run_with_stats(self, events: bool = False):
        """Decode ALL of this process's units under a collector and
        aggregate across the fleet.

        Returns ``(results, fleet, local)``: this process's decoded
        units (as :meth:`run`), the fleet-wide
        :class:`~tpuparquet.stats.DecodeStats` (identical on every
        process — ``fleet.summary()`` is the pod-level throughput
        line), and this process's own collector (which carries the
        per-page event log when ``events=True``; events stay
        host-local by design)."""
        from ..stats import collect_stats

        with collect_stats(events=events) as local:
            results = self.run()
        return results, allgather_stats(local), local

    def counts_allgather(self) -> np.ndarray:
        """(global_units,) row counts, identical on every process."""
        counts = np.zeros(len(self.global_units), dtype=np.int64)
        p = jax.process_index()
        n = jax.process_count()
        for j, (fi, rgi) in enumerate(self.global_units):
            if j % n == p:
                counts[j] = self.readers[fi].meta.row_groups[rgi].num_rows
        if n == 1:
            return counts
        return allgather_host(counts).reshape(n, -1).sum(axis=0)
