"""Multi-chip sharding: row-group data parallelism over a ``jax.sharding.Mesh``.

The reference is single-process (SURVEY.md §2.8 — no goroutine fan-out,
no distributed layer); this package is the TPU-native scale-out that takes
its place: (file × row-group) units shard across the mesh, each chip
decodes its shard with the device kernels, and decoded columns are
exchanged with XLA collectives over ICI (``all_gather``) rather than any
NCCL/MPI-style backend.
"""

from .mesh import (  # noqa: F401
    BatchedHybridPlan,
    assign_units,
    decode_step_spmd,
    make_mesh,
    resolve_out_sharding,
    sharded_dict_decode,
    stack_hybrid_plans,
)
from .scan import (  # noqa: F401
    ShardedScan,
    gather_byte_column,
    gather_column,
    host_cursor_path,
    load_cursor_file,
    save_cursor_file,
    scan_units,
)
from .distributed import (  # noqa: F401
    MultiHostScan,
    allgather_digests,
    allgather_host,
    allgather_ledgers,
    allgather_traces,
    process_units,
)
