"""Multi-file sharded scan driver.

The scan unit is (file, row-group) — the reference's outer loop
(``file_reader.go:51-57``) turned into a work list, sharded round-robin
over the mesh devices (SURVEY.md §5 "distributed communication backend").
Each unit decodes entirely on its assigned device via the kernel path;
cross-device exchange happens only at :func:`gather_column`, as one XLA
all-gather of the decoded column shards.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..io.reader import FileReader
from ..kernels.decode import scatter_to_dense
from ..kernels.device import DeviceColumn, read_row_group_device

__all__ = ["ShardedScan", "scan_units", "gather_column",
           "gather_byte_column"]


def scan_units(readers: list[FileReader]) -> list[tuple[int, int]]:
    """Flatten files into (file_index, row_group_index) work units."""
    return [
        (fi, rgi)
        for fi, r in enumerate(readers)
        for rgi in range(r.row_group_count())
    ]


class ShardedScan:
    """Decode many files' row groups data-parallel across a mesh.

    ``sources`` are paths or file objects; ``columns`` optionally project.
    :meth:`run` decodes every unit on its round-robin device and returns
    per-unit ``{path: DeviceColumn}`` dicts; results stay device-resident
    and sharded until explicitly gathered.
    """

    def __init__(self, sources, *columns: str, mesh=None):
        from .mesh import make_mesh

        self.mesh = mesh if mesh is not None else make_mesh()
        self.readers = [FileReader(s, *columns) for s in sources]
        self.units = scan_units(self.readers)
        self.devices = list(self.mesh.devices.flat)

    def device_for(self, unit_index: int):
        return self.devices[unit_index % len(self.devices)]

    def run(self) -> list[dict[str, DeviceColumn]]:
        out = []
        for i, (fi, rgi) in enumerate(self.units):
            with jax.default_device(self.device_for(i)):
                out.append(read_row_group_device(self.readers[fi], rgi))
        return out

    def close(self):
        for r in self.readers:
            r.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()


def gather_column(mesh, results: list[dict[str, DeviceColumn]], path: str):
    """All-gather one fixed-width column across the mesh.

    Builds a (U, L, lanes) global array sharded unit-wise over the "rg"
    axis from the per-device results (null slots zero-filled, units
    padded to a common length L), then runs one jitted identity with
    replicated output sharding — which XLA lowers to the all-gather
    collective over ICI.  Returns (values (U, L, lanes) ndarray,
    per-unit true counts); callers unpad with the counts.
    """
    cols = [r[path] for r in results]
    if any(c.offsets is not None for c in cols):
        raise TypeError("gather_column handles fixed-width columns; "
                        "use gather_byte_column for BYTE_ARRAY")
    dense = [
        scatter_to_dense(
            c.data if c.data.ndim > 1 else c.data[:, None],
            c.mask, c.positions,
        )
        for c in cols
    ]
    counts = np.asarray([d.shape[0] for d in dense], dtype=np.int64)
    L = int(counts.max()) if len(counts) else 0
    lanes = dense[0].shape[1] if dense else 1
    n_dev = len(list(mesh.devices.flat))
    U = max(len(dense), 1)
    U = ((U + n_dev - 1) // n_dev) * n_dev
    stacked = jnp.zeros((U, L, lanes), dtype=jnp.uint32)
    for i, d in enumerate(dense):
        stacked = stacked.at[i, : d.shape[0]].set(d.astype(jnp.uint32))
    sharded = jax.device_put(stacked, NamedSharding(mesh, P("rg")))
    gathered = jax.jit(
        lambda x: x, out_shardings=NamedSharding(mesh, P())
    )(sharded)
    return np.asarray(gathered)[: len(dense)], counts


def gather_byte_column(mesh, results: list[dict[str, DeviceColumn]],
                       path: str):
    """All-gather one BYTE_ARRAY column across the mesh.

    Each unit's shard densifies on its own device first: null record
    slots become zero-length values (their bytes are already absent, so
    the packed data buffer IS the dense data buffer — only the offsets
    re-derive), then padded (offsets to Lmax+1 with the byte total,
    keeping them monotone; data to Bmax with zeros) and stacked into
    (U, Lmax+1) / (U, Bmax) globals sharded unit-wise over "rg".  One
    jitted identity with replicated out-sharding lowers to the
    all-gather over ICI, exactly like :func:`gather_column`.

    Returns ``(offsets (U, Lmax+1) ndarray, data (U, Bmax) u8 ndarray,
    row_counts, byte_counts)``; row i of unit u spans
    ``data[u, offsets[u, i]:offsets[u, i+1]]``.
    """
    cols = [r[path] for r in results]
    if any(c.offsets is None for c in cols):
        raise TypeError("gather_byte_column handles BYTE_ARRAY columns; "
                        "use gather_column for fixed-width types")
    dense_offs = []
    datas = []
    for c in cols:
        offs = c.offsets[: c.n_packed + 1]
        lens = offs[1:] - offs[:-1]
        if c.num_values == c.n_packed and c._mask_p is None:
            dl = lens
        else:
            dl = jnp.where(c.mask, lens[c.positions],
                           jnp.zeros((), dtype=lens.dtype))
        do = jnp.concatenate(
            [jnp.zeros((1,), dtype=lens.dtype), jnp.cumsum(dl)]
        )
        dense_offs.append(do)
        datas.append(c.data)
    row_counts = np.asarray([d.shape[0] - 1 for d in dense_offs],
                            dtype=np.int64)
    byte_counts = np.asarray([d.shape[0] for d in datas], dtype=np.int64)
    L = int(row_counts.max()) + 1 if len(cols) else 1
    B = max(int(byte_counts.max()), 1) if len(cols) else 1
    n_dev = len(list(mesh.devices.flat))
    U = max(len(cols), 1)
    U = ((U + n_dev - 1) // n_dev) * n_dev
    offs_stack = jnp.zeros((U, L), dtype=dense_offs[0].dtype if cols
                           else jnp.int32)
    data_stack = jnp.zeros((U, B), dtype=jnp.uint8)
    for i, (do, d) in enumerate(zip(dense_offs, datas)):
        offs_stack = offs_stack.at[i, : do.shape[0]].set(do)
        if do.shape[0] < L:  # keep padding monotone at the byte total
            offs_stack = offs_stack.at[i, do.shape[0]:].set(do[-1])
        if d.shape[0]:
            data_stack = data_stack.at[i, : d.shape[0]].set(d)
    spec = NamedSharding(mesh, P("rg"))
    rep = NamedSharding(mesh, P())
    o_sh = jax.device_put(offs_stack, spec)
    d_sh = jax.device_put(data_stack, spec)
    o_g, d_g = jax.jit(
        lambda o, d: (o, d), out_shardings=(rep, rep)
    )(o_sh, d_sh)
    return (np.asarray(o_g)[: len(cols)], np.asarray(d_g)[: len(cols)],
            row_counts, byte_counts)
