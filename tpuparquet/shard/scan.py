"""Multi-file sharded scan driver.

The scan unit is (file, row-group) — the reference's outer loop
(``file_reader.go:51-57``) turned into a work list, sharded round-robin
over the mesh devices (SURVEY.md §5 "distributed communication backend").
Each unit decodes entirely on its assigned device via the kernel path;
cross-device exchange happens only at :func:`gather_column`, as one XLA
all-gather of the decoded column shards.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..errors import (
    QUARANTINE_ERRORS,
    DeadlineExceededError,
    never_quarantine,
)
from ..faults import QuarantineReport
from ..io.reader import FileReader
from ..obs import digest as _digest
from ..obs import profiler as _profiler
from ..obs import recorder as _flightrec
from ..obs import timeseries as _timeseries
from ..obs import trace as _trace
from ..obs.postmortem import postmortem_path_for, record_incident
from ..obs.recorder import flight
from ..kernels.decode import scatter_to_dense
from ..kernels.device import (
    DeviceColumn,
    read_row_group_device,
    read_row_group_device_resilient,
)

__all__ = ["ShardedScan", "scan_units", "open_sources",
           "pipelined_unit_scan", "resilient_unit_scan",
           "gather_column", "gather_byte_column",
           "save_cursor_file", "load_cursor_file", "host_cursor_path",
           "checkpoint_every_default"]


def scan_units(readers: list[FileReader], filter=None,
               verdicts: dict | None = None,
               pruned: list | None = None) -> list[tuple[int, int]]:
    """Flatten files into (file_index, row_group_index) work units.
    ``None`` entries (files quarantined at open time) contribute no
    units but keep the file-index space stable.

    With ``filter`` (a bound :mod:`tpuparquet.filter` expression), row
    groups the static verdict proves empty are DROPPED before units
    form — the scan never reads them.  Surviving verdicts land in
    ``verdicts`` (keyed ``(file, rg)``) so the per-unit decode reuses
    the candidate masks; dropped coordinates land in ``pruned`` as
    ``(file, rg, num_rows, reason, bloom_hits)`` for the driver's
    counters.  Deterministic given the footers, so every host of a
    multi-process scan derives the identical filtered unit list.

    The verdicts read the page-index / bloom blobs serially on the
    constructor's path — a handful of small seeks per filtered row
    group, fine for today's local seekable sources.  When remote
    object-store sources land (ROADMAP item 3), the page-index level
    should defer to the per-unit decode (pipelined + hedged) and unit
    forming should stop at the footer-only stats level."""
    units = []
    for fi, r in enumerate(readers):
        if r is None:
            continue
        for rgi in range(r.row_group_count()):
            if filter is not None:
                v = r.prune_row_group(filter, rgi)
                if v.skip:
                    if pruned is not None:
                        pruned.append(
                            (fi, rgi,
                             r.meta.row_groups[rgi].num_rows,
                             v.reason, v.bloom_hits))
                    if _flightrec._active is not None:
                        _flightrec.flight(
                            "row_group_pruned", site="shard.scan",
                            file=fi, row_group=rgi, reason=v.reason)
                    continue
                if verdicts is not None:
                    verdicts[(fi, rgi)] = v
            units.append((fi, rgi))
    return units


def _replicas(src) -> list:
    """A source entry is either one source or a replica group
    ``[primary, mirror, ...]`` of byte-identical copies."""
    if isinstance(src, (list, tuple)):
        if not src:
            raise ValueError("empty replica group in sources")
        return list(src)
    return [src]


def open_sources(sources, columns, *, on_error: str,
                 quarantine: QuarantineReport,
                 salvage: bool = False,
                 strict_metadata: bool | None = None,
                 record_for=None,
                 entry_extra: dict | None = None,
                 hedge_delay: float | None = None,
                 read_deadline: float | None = None,
                 postmortem: str | None = None) -> list:
    """Open scan sources with the file-level fault policy.

    Returns a reader list aligned with ``sources`` (``None`` where the
    file was quarantined).  Under ``on_error="raise"`` any open or
    strict-validation failure propagates — the seed behavior.  Under
    ``"quarantine"``, a failing file is isolated into ``quarantine``
    as a FILE-granularity entry and the scan proceeds without it; with
    ``salvage=True`` a failing file is first retried through the
    salvage path (its own hint, else the first healthy file as schema
    donor — every shard of a homogeneous dataset is a donor), keeping
    the recovered row-group prefix and quarantining only the torn
    remainder.  Salvage is deterministic, so every host of a
    multi-process scan derives the identical reader/unit list;
    ``record_for(i)`` optionally filters which file indices THIS
    process records (so fleet-folded counters count each file once).

    A source entry may be a replica group ``[primary, mirror, ...]``
    (byte-identical copies on independent stores): the first replica
    that OPENS becomes the reader and the others ride along as hedge
    mirrors for its chunk reads (``FileReader(mirrors=)``, the
    tail-at-scale path in ``deadline.py``); only if every replica
    fails to open is the file quarantined/salvaged.

    ``postmortem`` (a path or None) receives an automatic
    ``.postmortem.json`` incident for every file this call salvages or
    quarantines (:mod:`tpuparquet.obs.postmortem`), gated by the same
    ``record_for`` policy as the counters so a fleet writes each file's
    incident once.

    Raw crash types propagate — same contract as the unit loop.
    """
    from ..stats import current_stats

    if salvage and on_error != "quarantine":
        # under "raise" the first open failure aborts before any
        # salvage retry could run; accepting the kwarg would make the
        # explicit salvage request silently inert
        raise ValueError(
            "salvage=True requires on_error='quarantine' (under "
            "'raise' the first open failure aborts the scan)")

    readers: list = [None] * len(sources)
    failures: dict[int, BaseException] = {}
    donor = None

    def _record(i):
        return record_for is None or record_for(i)

    @contextlib.contextmanager
    def _counters_only_if_recorded(i):
        """FileReader increments files_salvaged / row_groups_recovered /
        metadata_rejects (and emits salvage/reject fault events)
        itself; on a multi-process scan every host opens every source,
        so for files this host does NOT record, roll the collector
        back — fleet-folded counters and event logs then count each
        file exactly once (matching the quarantine entries)."""
        st = current_stats()
        if st is None or _record(i):
            yield
            return
        # crc_mismatches/faults_injected too: the salvage forward scan
        # counts CRC rejects on every host that runs it
        fields = ("files_salvaged", "row_groups_recovered",
                  "metadata_rejects", "crc_mismatches",
                  "faults_injected")
        before = tuple(getattr(st, f) for f in fields)
        n_faults = len(st.events.faults) if st.events is not None \
            else None
        try:
            yield
        finally:
            for f, v in zip(fields, before):
                setattr(st, f, v)
            if n_faults is not None:
                del st.events.faults[n_faults:]

    from ..faults import retry_transient

    def _open_group(reps):
        """First replica that opens wins; the replicas NOT yet tried
        become its hedge mirrors (the ones that already failed to open
        are known-bad copies — hedging a read against them could let a
        truncated or diverged replica win the race).  All replicas
        failing re-raises the PRIMARY's error (the group's identity
        for quarantine purposes)."""
        first_err = None
        for j, rep in enumerate(reps):
            others = reps[j + 1:]
            try:
                # same retry policy as chunk reads: a flaky-store blip
                # at open time gets backoff before it can cost the
                # whole file (retry_transient re-raises non-transient
                # errors immediately)
                return retry_transient(lambda: FileReader(
                    rep, *columns, strict_metadata=strict_metadata,
                    mirrors=others, hedge_delay=hedge_delay,
                    read_deadline=read_deadline))
            except QUARANTINE_ERRORS as e:
                if never_quarantine(e):
                    raise
                if first_err is None:
                    first_err = e
        raise first_err

    for i, src in enumerate(sources):
        try:
            with _counters_only_if_recorded(i):
                readers[i] = _open_group(_replicas(src))
            if donor is None:
                donor = readers[i].meta
        except QUARANTINE_ERRORS as e:
            if never_quarantine(e) or on_error != "quarantine":
                raise
            failures[i] = e

    for i, err in sorted(failures.items()):
        primary = _replicas(sources[i])[0]
        path = primary if isinstance(primary, str) else None
        if path is not None:
            # a failing open may have been fed by (or may have seeded)
            # stale cached ranges: drop both tiers for this source so
            # the salvage retry below — and the next scan — reads the
            # store's truth, not the cache's memory of a bad file
            from ..io.rangecache import invalidate_source_caches

            invalidate_source_caches(path)
        if salvage:
            try:
                with _counters_only_if_recorded(i):
                    r = FileReader(primary, *columns, salvage=True,
                                   salvage_like=donor,
                                   strict_metadata=strict_metadata)
            except QUARANTINE_ERRORS as e2:
                if never_quarantine(e2):
                    raise
            else:
                readers[i] = r
                if _record(i):
                    extra = {"disposition": "salvaged",
                             "row_groups_recovered":
                                 r.row_group_count()}
                    if path is not None:
                        extra["path"] = path
                    if r.salvage_report:
                        for k in ("stop_reason", "bytes_lost"):
                            if k in r.salvage_report:
                                extra[k] = r.salvage_report[k]
                    entry = quarantine.add_file(file=i, error=err,
                                                **extra)
                    if entry_extra:
                        entry.update(entry_extra)
                    record_incident(postmortem, {
                        "kind": "file_salvaged",
                        "site": "shard.scan.file", **entry})
                continue
        if not _record(i):
            continue
        extra = {"disposition": "quarantined"}
        if path is not None:
            extra["path"] = path
        entry = quarantine.add_file(file=i, error=err, **extra)
        if entry_extra:
            entry.update(entry_extra)
        if _flightrec._active is not None:
            _flightrec.flight("file_quarantined",
                              site="shard.scan.file", **entry)
        record_incident(postmortem, {
            "kind": "file_quarantined", "site": "shard.scan.file",
            **entry})
        st = current_stats()
        if st is not None:
            st.files_quarantined += 1
            if st.events is not None:
                st.events.fault(site="shard.scan.file",
                                kind="file_quarantined", **entry)
    return readers


def cursor_state(units, next_key: str, next_value: int, **extra) -> dict:
    """Snapshot a JSON-serializable scan cursor (shared by ShardedScan
    and MultiHostScan so the format can't drift between them)."""
    cur = {"version": 1, next_key: next_value,
           "units": [list(u) for u in units]}
    cur.update(extra)
    return cur


def cursor_load(cursor: dict, units, next_key: str, n_units: int,
                **expected) -> int:
    """Validate a cursor against this scan's shape; returns the resume
    position.  ``expected`` pins run-identity fields (e.g. process grid
    coordinates) that must match exactly."""
    if cursor.get("version") != 1:
        raise ValueError(f"unknown cursor version {cursor.get('version')}")
    if [tuple(u) for u in cursor["units"]] != list(units):
        raise ValueError(
            "cursor does not match these sources: unit list differs "
            "(files changed since the cursor was taken?)"
        )
    for k, v in expected.items():
        if cursor.get(k) != v:
            raise ValueError(
                f"cursor {k} {cursor.get(k)!r} does not match this "
                f"run's {v!r}; resuming would misalign the unit "
                "assignment"
            )
    nxt = int(cursor[next_key])
    if not 0 <= nxt <= n_units:
        raise ValueError(f"cursor {next_key} {nxt} out of range")
    return nxt


# ----------------------------------------------------------------------
# Durable cursor checkpoints (crash-safe resume)
# ----------------------------------------------------------------------

CURSOR_FILE_FORMAT = "tpq-cursor"
CURSOR_FILE_VERSION = 1


def _canonical(obj) -> bytes:
    """The byte form the integrity checksum is computed over: key-
    sorted, separator-pinned JSON — identical before write and after a
    read-back round trip."""
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode()


def checkpoint_every_default() -> int:
    """Auto-checkpoint cadence in completed units
    (``TPQ_CHECKPOINT_EVERY``, default 16)."""
    try:
        v = int(os.environ.get("TPQ_CHECKPOINT_EVERY", ""))
    except ValueError:
        return 16
    return max(v, 1)


def save_cursor_file(cursor: dict, path: str) -> None:
    """Write a scan cursor durably and atomically.

    Versioned envelope with a CRC32 over the canonical cursor JSON;
    written tmp-in-same-dir + flush + fsync + ``os.replace`` +
    directory fsync — a SIGKILL at ANY point leaves either the
    previous complete checkpoint or the new complete checkpoint,
    never a torn one.  Counts ``DecodeStats.checkpoints_written``."""
    from ..stats import current_stats

    doc = {"format": CURSOR_FILE_FORMAT,
           "file_version": CURSOR_FILE_VERSION,
           "crc32": zlib.crc32(_canonical(cursor)),
           "cursor": cursor}
    d = os.path.dirname(os.path.abspath(path)) or "."
    tmp = os.path.join(
        d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # fsync the directory so the rename itself is durable (best
    # effort: some filesystems refuse directory fds)
    try:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    st = current_stats()
    if st is not None:
        st.checkpoints_written += 1


def load_cursor_file(path: str) -> dict:
    """Read back a :func:`save_cursor_file` checkpoint, validating
    format, version, and integrity checksum.  Raises ``ValueError``
    on anything that is not a complete, untampered cursor (atomic
    writes mean a torn file here is damage, not a crash artifact)."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"checkpoint {path!r} is not valid JSON: {e}") from e
    if not isinstance(doc, dict) \
            or doc.get("format") != CURSOR_FILE_FORMAT:
        raise ValueError(f"{path!r} is not a tpq cursor checkpoint")
    if doc.get("file_version") != CURSOR_FILE_VERSION:
        raise ValueError(
            f"unknown checkpoint file_version "
            f"{doc.get('file_version')!r} in {path!r}")
    cursor = doc.get("cursor")
    if zlib.crc32(_canonical(cursor)) != doc.get("crc32"):
        raise ValueError(
            f"checkpoint {path!r} failed its integrity checksum")
    return cursor


def host_cursor_path(base: str, process_index: int) -> str:
    """Per-host checkpoint file for a multi-process scan: each process
    owns exactly one file (no cross-host write races)."""
    return f"{base}.p{process_index}"


def pipelined_unit_scan(readers, units, device_for=None, start: int = 0,
                        filter=None, verdicts=None):
    """Yield ``(unit_index, {path: DeviceColumn})`` for ``units[start:]``,
    overlapping host planning with device transfer/dispatch — the shared
    pipeline in :func:`tpuparquet.kernels.device.pipelined_reads`, with
    (file, row-group) units and per-unit device placement.  With
    ``filter`` the late-materialized pushdown pipeline runs instead
    (:func:`~tpuparquet.kernels.device.filtered_pipelined_reads`)."""
    if filter is not None:
        from ..kernels.device import filtered_pipelined_reads

        yield from filtered_pipelined_reads(
            readers, units, device_for, start, filter=filter,
            verdicts=verdicts)
        return
    from ..kernels.device import pipelined_reads

    yield from pipelined_reads(readers, units, device_for, start)


def resilient_unit_scan(readers, units, device_for, *, start: int = 0,
                        retries=None, quarantine: QuarantineReport,
                        entry_extra: dict | None = None,
                        unit_deadline: float | None = None,
                        postmortem: str | None = None,
                        filter=None, verdicts=None):
    """The quarantine-mode unit loop shared by :class:`ShardedScan`
    and :class:`MultiHostScan`: decode each unit with the full
    resilience policy (transient-I/O retry, dispatch retry, CPU
    degradation); absorb clean failures into ``quarantine`` (entries
    get ``entry_extra`` merged in) and yield ``(k, None)`` for them so
    callers can advance their cursor uniformly; yield ``(k, out)`` for
    survivors.  Raw crash types propagate — quarantine never papers
    over a bug.

    ``unit_deadline`` bounds each unit's WHOLE decode (read + retries
    + dispatch + degradation) via the watchdog
    (:func:`~tpuparquet.deadline.call_with_deadline`): a unit that
    hangs past its budget raises
    :class:`~tpuparquet.errors.DeadlineExceededError`, which this loop
    absorbs into quarantine like any other exhausted failure — a hung
    unit costs its budget, never the fleet."""
    from ..deadline import call_with_deadline
    from ..stats import current_stats

    for k in range(start, len(units)):
        fi, rgi = units[k]
        # causal trace: the resilient path decodes one unit at a time
        # on the driving thread, so the unit span pushes the ambient
        # context — retry/degrade/deadline children (including the
        # deadline worker, which adopts this context) nest under it
        usp = _trace.open_span("unit", unit=k, file=fi,
                               row_group=rgi) \
            if _trace._active is not None else None

        def _decode(k=k, fi=fi, rgi=rgi):
            # default_device is thread-local; the deadline wrapper may
            # execute this on a worker thread, so enter it inside
            with jax.default_device(device_for(k)):
                return read_row_group_device_resilient(
                    readers[fi], rgi, retries=retries, filter=filter,
                    verdict=(None if verdicts is None
                             else verdicts.get((fi, rgi))))

        try:
            if unit_deadline:
                out = call_with_deadline(
                    _decode, unit_deadline, site="shard.scan.unit",
                    file=fi, row_group=rgi)
            else:
                out = _decode()
        except QUARANTINE_ERRORS as e:
            if never_quarantine(e):
                _trace.close_span(usp, status="error",
                                  error=type(e).__name__)
                raise
            entry = quarantine.add(unit=k, file=fi, row_group=rgi,
                                   error=e)
            if entry_extra:
                entry.update(entry_extra)
            # a quarantined unit means this file's bytes can no longer
            # be trusted against its footer: drop its cached plans so a
            # later retry (or another scan in this process) re-derives
            # them from the bytes it actually reads.  Only an
            # ALREADY-COMPUTED fingerprint can have entries — never
            # compute one here (fresh footer I/O on the possibly-wedged
            # handle that just got this unit quarantined)
            from ..kernels.plancache import invalidate_fingerprint

            cached = getattr(readers[fi], "cached_plan_fingerprint",
                             None)
            if cached is not None:
                invalidate_fingerprint(cached())
            # automatic post-mortem: the trigger's exact coordinates
            # plus the flight-recorder tail and a metrics snapshot,
            # dumped beside the durable cursor (obs/postmortem.py)
            flight("quarantined", site="shard.scan.unit", **entry)
            record_incident(postmortem, {
                "kind": "quarantined", "site": "shard.scan.unit",
                **entry})
            st = current_stats()
            if st is not None:
                st.units_quarantined += 1
                if st.events is not None:
                    st.events.fault(site="shard.scan.unit",
                                    kind="quarantined", **entry)
            _trace.close_span(usp, status="error", quarantined=True,
                              error=type(e).__name__)
            yield k, None
            continue
        except BaseException:
            # raw crash types propagate — but never with a leaked
            # ambient trace context
            _trace.close_span(usp, status="error")
            raise
        _trace.close_span(usp)
        yield k, out


class DurableScanMixin:
    """Durable-checkpoint + scan-budget + live-telemetry plumbing
    shared by :class:`ShardedScan` and
    :class:`~tpuparquet.shard.distributed.MultiHostScan` (so cadence
    and expiry semantics cannot drift between them).  Hosts provide
    ``state()``, ``_checkpoint_path``/``_checkpoint_every``/
    ``_since_checkpoint``, ``scan_deadline``/``_run_t0``,
    :meth:`_progress`, :meth:`_advance`, and :meth:`_unit_coords`."""

    def _progress(self) -> tuple[int, int]:
        raise NotImplementedError

    def _advance(self, k: int) -> None:
        """Move the cursor past unit ``k``."""
        raise NotImplementedError

    def _unit_coords(self, k: int) -> tuple[int, int]:
        """``(file_index, row_group_index)`` of this driver's unit k."""
        raise NotImplementedError

    def _init_durable(self, *, on_error, unit_deadline, scan_deadline,
                      resume, resume_from, checkpoint_every,
                      checkpoint_path, postmortem=None) -> None:
        """Validate and resolve the shared time/checkpoint knobs (one
        owner for both drivers; ``checkpoint_path`` is the resolved
        per-driver file — per-host for the multi-host scan).  Call
        BEFORE opening sources: a bad knob must fail cheap.

        ``postmortem``: where automatic incident dumps go — a path to
        set it explicitly, ``False`` to disable, None to derive
        (beside the checkpoint, else ``TPQ_POSTMORTEM_DIR``, else
        off) — see :func:`tpuparquet.obs.postmortem.postmortem_path_for`."""
        from ..deadline import scan_deadline_default, unit_deadline_default

        if unit_deadline is not None and on_error != "quarantine":
            raise ValueError(
                "unit_deadline requires on_error='quarantine' (an "
                "expired unit is absorbed by the quarantine ladder)")
        if resume is not None and resume_from is not None:
            raise ValueError("pass resume= or resume_from=, not both")
        # env defaults apply only where the knob is usable: the unit
        # deadline lives in the quarantine ladder
        self.unit_deadline = unit_deadline if unit_deadline is not None \
            else (unit_deadline_default()
                  if on_error == "quarantine" else None)
        self.scan_deadline = scan_deadline if scan_deadline is not None \
            else scan_deadline_default()
        self._checkpoint_path = checkpoint_path
        self._checkpoint_every = (checkpoint_every
                                  if checkpoint_every is not None
                                  else checkpoint_every_default())
        self._since_checkpoint = 0
        self._run_t0 = None
        # cooperative drain: request_stop() (any thread) makes the
        # drive loop exit cleanly at the next unit boundary with the
        # durable cursor flushed — the serve layer's graceful-drain
        # primitive, usable by any embedder
        self._stop = threading.Event()
        self.stopped = False
        self._postmortem_path = (
            postmortem if isinstance(postmortem, str)
            else None if postmortem is False
            else postmortem_path_for(checkpoint_path))

    # -- live telemetry (obs/: progress, registry, flight recorder) ------

    def _init_telemetry(self, n_units: int,
                        progress_export: str | None,
                        label: str) -> None:
        """Arm the always-on surfaces: the :class:`~tpuparquet.obs.
        progress.ScanProgress` (exported to ``progress_export`` /
        ``TPQ_PROGRESS_EXPORT`` for ``parquet-tool top``) and, when
        live metrics are enabled, a scan-lifetime ambient collector
        that meters units nobody wrapped in ``collect_stats()`` into
        the process metrics registry.  Call AFTER the unit list
        exists."""
        from ..obs.live import LiveFold, live_enabled
        from ..obs.progress import (
            ScanProgress,
            label_slug,
            progress_export_default,
        )
        from ..stats import DecodeStats

        if progress_export is not None:
            path = progress_export
        else:
            path = progress_export_default()
            if path and label != "scan":
                # the env default names ONE file: concurrent scans
                # with distinct labels get their own (same shape as
                # the multi-host .p<idx> suffix), so two scans never
                # interleave frames in one status file
                path = f"{path}.{label_slug(label)}"
        self.progress = ScanProgress(n_units, label=label,
                                     export=path or None)
        self._live_stats = DecodeStats() if live_enabled() else None
        self._live_fold = LiveFold()
        # per-scan-label attribution ledger (obs/attribution.py): fed
        # the SAME counter deltas the registry fold applies, so
        # sum-over-ledgers equals the registry totals exactly; gated
        # by the same live-metrics switch for that conservation
        from ..obs import attribution as _attribution

        self._ledger = (_attribution.ledger(label)
                        if live_enabled() else None)
        self._attr_fold = LiveFold()
        self._attr_src = None
        # scan-end trace export (TPQ_TRACE_EXPORT): per-label suffix
        # exactly like the progress file, so concurrent scans and the
        # multi-host drivers never clobber one shared export
        tpath = _trace.trace_export_default()
        if tpath and label != "scan":
            tpath = f"{tpath}.{label_slug(label)}"
        self._trace_export = tpath or None
        self._trace_ctx = None
        # scan-end profile export (TPQ_PROFILE_EXPORT): same per-label
        # suffixing as the trace file, for the same two reasons
        ppath = _profiler.profile_export_default()
        if ppath and label != "scan":
            ppath = f"{ppath}.{label_slug(label)}"
        self._profile_export = ppath or None
        # arm the time-series ring now if TPQ_TIMESERIES_DIR appeared
        # after import, so the scan-end flush below has somewhere to
        # land even for scans shorter than the exporter interval
        _timeseries.maybe_start_ring()

    def _finish_telemetry(self, t_scan: float, troot,
                          status: str) -> None:
        """Scan-end longitudinal feeds: the whole-scan latency into
        the quantile digest (with the trace id as exemplar) and one
        ``scan_end`` frame onto the time-series ring — so a scan
        shorter than the exporter interval still leaves history.
        Both off-by-default, one ``is None`` check each."""
        if _digest._active is not None:
            _digest.observe(
                self.progress.label, "scan",
                int((time.monotonic() - t_scan) * 1e6),
                trace=(troot["trace"] if troot is not None else None),
                status=status)
        if _timeseries._active is not None:
            _timeseries.tick("scan_end")

    def _adopted(self):
        """Context installing the scan's ambient collector for one
        bounded step — ONLY when the caller has no collector of their
        own (a user's ``collect_stats`` always wins, and its scope
        exit folds to the registry instead)."""
        from ..stats import adopt_stats, current_stats

        if self._live_stats is not None and current_stats() is None:
            return adopt_stats(self._live_stats)
        return contextlib.nullcontext()

    def _fold_live(self) -> None:
        """Incrementally fold the ambient collector's delta into the
        process registry (unit-boundary cadence: a Prometheus scrape
        mid-scan sees the units decoded so far) AND the same delta
        into this scan's attribution ledger — one delta, two exact
        sinks, so per-scan ledgers sum to the registry totals."""
        from ..stats import current_stats

        delta = None
        if self._live_stats is not None:
            delta = self._live_fold.fold(self._live_stats)
        # the profile brief rides the progress frame independently of
        # live metrics: `top` shows PROFILE whenever a sampler is armed
        if _profiler._active is not None:
            self.progress.set_profile(_profiler._active.brief())
        led = self._ledger
        if led is None:
            return
        st = current_stats() or self._live_stats
        if st is not None:
            if st is self._live_stats:
                attr_delta = delta or {}
            else:
                # a user collector shadows the ambient one: track its
                # deltas with a dedicated baseline fold (registry gets
                # the user scope's own fold at scope exit)
                if st is not self._attr_src:
                    from ..obs.live import LiveFold

                    self._attr_src = st
                    self._attr_fold = LiveFold()
                attr_delta = self._attr_fold.delta_only(st)
            if attr_delta:
                led.fold_delta(attr_delta)
        from ..kernels.arena import take_arena_peak

        led.note_peak(take_arena_peak())
        # the live surfaces see the same numbers: the progress frame
        # (parquet-tool top) carries the ledger's cpu_s/bytes view
        view = led.as_dict()
        self.progress.set_attribution({
            "cpu_s": view["cpu_s"],
            "bytes": view["bytes"],
            "peak_arena_bytes": led.peak_arena_bytes,
        })

    def _export_trace(self, troot) -> None:
        """Publish this trace at scan end (``TPQ_TRACE_EXPORT``, the
        per-label path resolved at init): the traced spans plus the
        process attribution ledgers, atomically — the file
        ``parquet-tool doctor`` walks.  Best-effort by contract."""
        if troot is None or self._trace_export is None:
            return
        tr = _trace._active
        if tr is None:
            return
        from ..obs.attribution import ledgers_snapshot
        from ..obs.export import write_trace_file

        write_trace_file(tr.snapshot(troot["trace"]),
                         self._trace_export,
                         ledgers=ledgers_snapshot(),
                         anchor=tr.anchor())

    def _export_profile(self) -> None:
        """Publish the sampling profile at scan end
        (``TPQ_PROFILE_EXPORT``, the per-label path resolved at
        init).  Independent of tracing: a profile without a trace is
        still a flamegraph.  Best-effort by contract."""
        p = _profiler._active
        if p is None or self._profile_export is None:
            return
        from ..obs.profiler import write_profile_file

        try:
            write_profile_file(p.to_state(), self._profile_export)
        except OSError:
            pass

    def _init_filter(self, filter, readers) -> None:
        """Shared filter plumbing: bind once against the (homogeneous)
        dataset schema, then let :func:`scan_units` prune row groups
        statically.  Call BEFORE forming units."""
        self.filter = filter
        self._verdicts: dict = {}
        self._pruned: list = []
        if filter is None:
            return
        from ..filter import bind_filter

        for r in readers:
            if r is not None:
                bind_filter(filter, r.schema)
                break

    def _count_pruned(self, select_pruned=None,
                      select_kept=None) -> None:
        """Fold the unit-forming pruning decisions into the active (or
        ambient) collector — called at RUN start, not construction, so
        ``run_with_stats``/``collect_stats`` wrappers see them.  The
        selectors filter which pruned entries / kept-unit verdicts
        THIS process records (multi-host: each row group counts once
        across the fleet)."""
        if self.filter is None:
            return
        from ..stats import current_stats

        with self._adopted():
            st = current_stats()
            if st is None:
                return
            hits = 0
            for j, (_fi, _rgi, n_rows, _reason, bh) in enumerate(
                    self._pruned):
                if select_pruned is not None and not select_pruned(j):
                    continue
                st.row_groups_pruned += 1
                st.rows_pruned += n_rows
                hits += bh
            # kept row groups' verdicts may also carry refuting probes
            # (an Or branch the bloom killed while another matched)
            for key, v in self._verdicts.items():
                if select_kept is not None and not select_kept(key):
                    continue
                hits += v.bloom_hits
            st.bloom_hits += hits

    def _drive(self, gen):
        """The shared unit loop around an inner unit generator
        (pipelined or resilient): progress ticks, ambient metering,
        registry folds, then the checkpoint/deadline bookkeeping —
        one owner for both drivers.  Yields ``(k, out)`` for units
        that decoded; quarantine-mode ``None`` results tick progress
        but are not yielded (the existing contract)."""
        from ..stats import current_stats

        prog = self.progress
        nxt0, n_total = self._progress()
        if prog.units_done != nxt0 or prog.state != "pending":
            # a fresh drive of an already-used progress: run() after a
            # partial run_iter (cursor reset to 0), a cursor resume
            # (resumed units count as already done), or CONTINUING a
            # stopped run_iter — all restart the clock and tallies, so
            # elapsed/rows_per_s describe this run, not the idle gap
            prog.restart(done=nxt0)
        prog.begin()
        # causal trace root: one trace per drive; the sampling verdict
        # is whole-trace, and every unit/stage span below parents into
        # this root's context (None = tracing off or unsampled)
        troot = None
        if _trace._active is not None:
            from ..kernels.device import _usable_cpus

            troot = _trace.start_trace(
                prog.label, units=n_total, resumed_at=nxt0,
                usable_cpus=_usable_cpus())
        self._trace_ctx = _trace.ctx_of(troot)
        if self._ledger is not None:
            self._ledger.scans += 1
        t_scan = time.monotonic()
        try:
            with self._adopted():
                self._check_scan_deadline()
            while True:
                if self._stop.is_set():
                    gen.close()
                    with self._adopted():
                        self._flush_checkpoint()
                    self._fold_live()
                    self.stopped = True
                    prog.finish("stopped")
                    self._finish_telemetry(t_scan, troot, "stopped")
                    _trace.end_trace(troot, status="cancelled")
                    self._export_trace(troot)
                    self._export_profile()
                    return
                nxt, _ = self._progress()
                prog.unit_started(nxt)
                t_unit = time.monotonic()
                try:
                    with self._adopted():
                        k, out = next(gen)
                except StopIteration:
                    prog.unit_cancelled(nxt)
                    break
                self._advance(k)
                fi, rgi = self._unit_coords(k)
                rows = (self.readers[fi].meta.row_groups[rgi].num_rows
                        if out is not None else 0)
                # staged bytes come from whichever collector actually
                # metered this unit: the caller's (a user collect_stats
                # scope shadows the ambient collector) or the ambient
                # one — else `top` would show staged 0 exactly on the
                # post-hoc-regime path
                st = current_stats() or self._live_stats
                prog.unit_done(
                    k, rows=rows, quarantined=out is None,
                    bytes_staged=(st.bytes_staged
                                  if st is not None else None))
                if _flightrec._active is not None:
                    _flightrec.flight(
                        "unit_done" if out is not None
                        else "unit_quarantined",
                        site="shard.scan", unit=k, file=fi,
                        row_group=rgi, rows=rows)
                if _digest._active is not None:
                    _digest.observe(
                        prog.label, "unit",
                        int((time.monotonic() - t_unit) * 1e6),
                        trace=(troot["trace"] if troot is not None
                               else None),
                        unit=k, file=fi, row_group=rgi)
                self._fold_live()
                if out is not None:
                    yield k, out
                with self._adopted():
                    self._maybe_checkpoint()
                    self._check_scan_deadline()
        except GeneratorExit:
            prog.finish("stopped")
            self._fold_live()
            self._finish_telemetry(t_scan, troot, "stopped")
            _trace.end_trace(troot, status="cancelled")
            self._export_trace(troot)
            self._export_profile()
            raise
        except BaseException:
            prog.finish("error")
            self._fold_live()
            self._finish_telemetry(t_scan, troot, "error")
            _trace.end_trace(troot, status="error")
            self._export_trace(troot)
            self._export_profile()
            raise
        with self._adopted():
            self._flush_checkpoint()
        self._fold_live()
        prog.finish("done")
        self._finish_telemetry(t_scan, troot, "done")
        _trace.end_trace(troot)
        self._export_trace(troot)
        self._export_profile()

    # -- consumer-aligned gathers (scan-level placement default) ---------

    def _gather_placement(self, out_sharding, gather_to):
        """An explicit per-call spec wins; else the scan-level default
        (which already folded the ``TPQ_GATHER_TO`` env).
        ``out_sharding="replicated"`` explicitly requests the seed
        replicated-ndarray gather even when a scan default is armed —
        None cannot express that (it means "use the default")."""
        if out_sharding is not None or gather_to is not None:
            from .mesh import resolve_out_sharding

            return resolve_out_sharding(self.mesh, out_sharding,
                                        gather_to)
        return self.out_sharding

    def gather_column(self, results, path: str, *, out_sharding=None,
                      gather_to=None):
        """:func:`gather_column` over this scan's mesh, defaulting to
        the placement the scan was constructed with
        (``out_sharding="replicated"`` forces the seed replicated
        gather past an armed default).  Runs under the scan's ambient
        collector and trace context, so gather counters land in this
        scan's attribution ledger and the gather span attaches to the
        scan's trace."""
        with self._adopted(), _trace.adopt(self._trace_ctx):
            out = gather_column(
                self.mesh, results, path,
                out_sharding=self._gather_placement(out_sharding,
                                                    gather_to))
        self._fold_live()
        return out

    def gather_byte_column(self, results, path: str, *,
                           out_sharding=None, gather_to=None):
        """:func:`gather_byte_column` over this scan's mesh,
        defaulting to the placement the scan was constructed with
        (``out_sharding="replicated"`` forces the seed replicated
        gather past an armed default).  Metered like
        :meth:`gather_column`."""
        with self._adopted(), _trace.adopt(self._trace_ctx):
            out = gather_byte_column(
                self.mesh, results, path,
                out_sharding=self._gather_placement(out_sharding,
                                                    gather_to))
        self._fold_live()
        return out

    def request_stop(self) -> None:
        """Ask a running :meth:`run_iter` to stop cooperatively: the
        drive loop exits BEFORE starting another unit, flushes the
        durable cursor (when checkpointing is configured), and marks
        progress/trace ``stopped`` — then sets :attr:`stopped` so the
        caller can distinguish a drain from completion.  Safe from
        any thread and before the run starts (the loop checks first);
        the serve layer's graceful-drain hook."""
        self._stop.set()

    def cursor_save(self, path: str | None = None) -> None:
        """Durably checkpoint :meth:`state` (atomic tmp + fsync +
        rename, integrity checksum — :func:`save_cursor_file`).
        ``path`` defaults to this scan's configured checkpoint
        file."""
        path = path if path is not None else self._checkpoint_path
        if path is None:
            raise ValueError("no checkpoint path: pass path= or "
                             "construct with resume_from=")
        save_cursor_file(self.state(), path)
        self._since_checkpoint = 0

    def _maybe_checkpoint(self) -> None:
        """Auto-checkpoint cadence: called once per completed unit
        AFTER the consumer's iteration step returned, so a unit is
        only ever covered by a checkpoint once the caller had its
        chance to persist the result — a crash re-decodes at most the
        units since the last checkpoint (bit-exact, so a keyed
        consumer converges to the identical union)."""
        if self._checkpoint_path is None:
            return
        self._since_checkpoint += 1
        if self._since_checkpoint >= self._checkpoint_every:
            self.cursor_save()

    def _flush_checkpoint(self) -> None:
        if self._checkpoint_path is not None and self._since_checkpoint:
            self.cursor_save()

    def _check_scan_deadline(self) -> None:
        """Whole-scan budget, checked between units: expiry flushes a
        fresh durable cursor (when checkpointing is on) and raises —
        the caller reschedules and resumes, no work is lost."""
        if not self.scan_deadline or self._run_t0 is None:
            return
        elapsed = time.monotonic() - self._run_t0
        if elapsed <= self.scan_deadline:
            return
        from ..deadline import record_expiry
        from ..stats import current_stats

        done, total = self._progress()
        record_expiry(current_stats(), "shard.scan", elapsed,
                      self.scan_deadline, {"next_unit": done})
        record_incident(self._postmortem_path, {
            "kind": "scan_deadline", "site": "shard.scan",
            "elapsed_s": round(elapsed, 3),
            "budget_s": self.scan_deadline, "next_unit": done,
            "units_total": total})
        self._flush_checkpoint()
        raise DeadlineExceededError(
            f"scan exceeded its {self.scan_deadline:g}s budget at "
            f"unit {done}/{total}; the cursor is intact — resume to "
            "continue",
            elapsed=elapsed, budget=self.scan_deadline,
            site="shard.scan")


class ShardedScan(DurableScanMixin):
    """Decode many files' row groups data-parallel across a mesh.

    ``sources`` are paths or file objects, opened by the scan itself
    (lazily tolerant — see :func:`open_sources`), so a corrupt FILE is
    a policy decision, not a constructor crash; ``columns`` optionally
    project.
    :meth:`run` decodes every unit on its round-robin device and returns
    per-unit ``{path: DeviceColumn}`` dicts; results stay device-resident
    and sharded until explicitly gathered.  Host planning of unit N+1
    overlaps device transfer of unit N (:func:`pipelined_unit_scan`).

    Resumable (SURVEY.md §5 checkpoint/resume — the row group as the
    restart unit): :meth:`state` snapshots a cursor after any number of
    :meth:`run_iter` steps; pass it back as ``resume=`` to continue from
    the first undecoded unit in a fresh process.  The cursor is plain
    JSON-serializable data.

    Epoch shuffling (training loaders): ``shuffle_seed=`` +
    ``epoch=`` permute the unit list deterministically per epoch —
    identical on every host, applied before the cursor exists, so
    checkpoint/resume of a shuffled epoch stays duplicate-free (the
    cursor records the shuffle identity and refuses a mismatched
    resume).  With ``shuffle_seed=None`` (default) the natural order
    is untouched and ``epoch`` is ignored.

    Fault tolerance (``on_error``):

    * ``"raise"`` (default) — first failure aborts the scan, exactly
      the seed behavior, on the fully pipelined path.
    * ``"quarantine"`` — each unit decodes independently (transient
      I/O retried with backoff, device dispatch retried then degraded
      to the bit-exact CPU decode); a unit that still fails is
      isolated into :attr:`quarantine` (a
      :class:`~tpuparquet.faults.QuarantineReport` with exact
      file/row-group/column/page coordinates and the error class) and
      the scan continues.  Decoded units are bit-exact or absent —
      never wrong.  The cursor advances past quarantined units and
      carries the report, so a resumed scan neither re-decodes nor
      forgets them.  This mode trades the plan/transfer pipeline
      overlap for isolation (units decode one at a time).

    File-level policy (this round):

    * ``strict_metadata`` — validate every footer at open
      (``format/validate.py``); under ``"quarantine"`` a rejected file
      becomes a file-granularity quarantine entry instead of an abort.
    * ``salvage`` — auto-salvage-then-quarantine-remainder: a file
      whose footer is torn/invalid is reopened through the salvage
      path (``FileReader(salvage=True)``, schema donated by its hint
      or the first healthy file); its recovered row groups join the
      unit list, and only the unreadable remainder lands in
      :attr:`quarantine`.

    Time/crash domain (deadline round, ``tpuparquet/deadline.py``):

    * ``unit_deadline`` (env ``TPQ_UNIT_DEADLINE_S``; quarantine mode
      only) — watchdog budget per unit: a hung unit is abandoned and
      quarantined as :class:`~tpuparquet.errors.DeadlineExceededError`
      instead of stalling the scan.
    * ``scan_deadline`` (env ``TPQ_SCAN_DEADLINE_S``) — whole-scan
      budget, checked between units; expiry raises with the cursor
      intact so the caller reschedules and resumes.
    * replica groups + ``hedge_delay``/``read_deadline`` — a source
      may be ``[primary, mirror, ...]``; slow chunk reads hedge
      against the mirrors after the hedge delay (env
      ``TPQ_HEDGE_DELAY_S``, default rolling p95), first success wins.
    * ``resume_from=path`` + ``checkpoint_every`` (env
      ``TPQ_CHECKPOINT_EVERY``, default 16) — durable crash-safe
      cursor: the scan resumes from ``path`` when it exists and
      auto-checkpoints to it atomically as units complete, so a
      SIGKILL'd process resumes with no unit lost; re-decoded units
      (at most one checkpoint window) are bit-exact, so a keyed
      consumer converges to the identical union.  :meth:`cursor_save`
      checkpoints explicitly.

    Predicate pushdown (this round): ``filter=`` takes a
    :mod:`tpuparquet.filter` expression (``col("x") > 5``).  Row
    groups the chunk statistics / bloom filters / page index prove
    empty are dropped BEFORE units form (``row_groups_pruned``/
    ``rows_pruned``); surviving units decode late-materialized —
    filter columns first, exact predicate, only surviving rows of the
    other columns staged — so each yielded unit holds exactly the
    matching rows, bit-identical to a full scan post-filtered
    (``TPQ_PRUNE=0`` forces that reference path).  A cursor taken
    under one filter resumes only under the same filter (the unit
    list is part of the cursor's identity).

    Output placement (this round): ``out_sharding=`` (a
    ``NamedSharding`` over the consumer's mesh, or a ``PartitionSpec``
    over the scan mesh) or ``gather_to=`` (a single device, or its
    index in ``jax.local_devices()``; env default ``TPQ_GATHER_TO``)
    set the scan-level default placement for :meth:`gather_column` /
    :meth:`gather_byte_column` — decoded columns assemble directly
    onto the shards that will consume them instead of being
    all-gathered to every device (cost flat in mesh size for a
    singular consumer, proportional to true fan-out otherwise).
    Decode placement is unchanged (units still round-robin the scan
    mesh); only the gather's output layout moves.
    """

    def __init__(self, sources, *columns: str, mesh=None, resume=None,
                 on_error: str = "raise", retries: int | None = None,
                 salvage: bool = False,
                 strict_metadata: bool | None = None,
                 unit_deadline: float | None = None,
                 scan_deadline: float | None = None,
                 hedge_delay: float | None = None,
                 read_deadline: float | None = None,
                 resume_from: str | None = None,
                 checkpoint_every: int | None = None,
                 progress_export: str | None = None,
                 progress_label: str = "scan",
                 postmortem=None,
                 filter=None,
                 shuffle_seed: int | None = None, epoch: int = 0,
                 out_sharding=None, gather_to=None):
        from .mesh import make_mesh, resolve_out_sharding

        if on_error not in ("raise", "quarantine"):
            raise ValueError(
                f"on_error must be 'raise' or 'quarantine', "
                f"not {on_error!r}")
        self._init_durable(
            on_error=on_error, unit_deadline=unit_deadline,
            scan_deadline=scan_deadline, resume=resume,
            resume_from=resume_from, checkpoint_every=checkpoint_every,
            checkpoint_path=resume_from, postmortem=postmortem)
        self.mesh = mesh if mesh is not None else make_mesh()
        # resolve the scan-level placement default EARLY: a bad spec
        # must fail before any source opens
        self.out_sharding = resolve_out_sharding(
            self.mesh, out_sharding, gather_to)
        # file-level entries recorded at open time live in their own
        # report so run() can reset the unit-level entries without
        # forgetting the files that never produced units
        self._open_quarantine = QuarantineReport()
        self.readers = open_sources(
            sources, columns, on_error=on_error,
            quarantine=self._open_quarantine, salvage=salvage,
            strict_metadata=strict_metadata, hedge_delay=hedge_delay,
            read_deadline=read_deadline,
            postmortem=self._postmortem_path)
        self._init_filter(filter, self.readers)
        self.units = scan_units(self.readers, filter=self.filter,
                                verdicts=self._verdicts,
                                pruned=self._pruned)
        # epoch shuffling for training loaders: a deterministic
        # per-epoch permutation of the unit list, applied BEFORE any
        # cursor/telemetry sees the units — the cursor stores (and
        # resume validates) the permuted order, so a resumed epoch
        # stays duplicate-free, and every host derives the identical
        # permutation (string-seeded Random hashes with sha512, so
        # PYTHONHASHSEED cannot skew it).  ``shuffle_seed=None`` (the
        # default) leaves the natural file/row-group order untouched —
        # byte-identical to a scan without the feature, epoch ignored.
        self.shuffle_seed = shuffle_seed
        self.epoch = int(epoch)
        if shuffle_seed is not None:
            import random

            random.Random(
                f"{int(shuffle_seed)}:{self.epoch}").shuffle(self.units)
        # progress_label keys this scan's registry gauges (see
        # obs/progress.py): concurrent scans in one serve process pass
        # distinct labels so their gauges don't clobber each other
        self._init_telemetry(len(self.units), progress_export,
                             progress_label)
        self.devices = list(self.mesh.devices.flat)
        self.on_error = on_error
        self.retries = retries
        self.quarantine = QuarantineReport(
            self._open_quarantine.as_dicts())
        self._next_unit = 0
        if resume is None and resume_from is not None \
                and os.path.exists(resume_from):
            resume = load_cursor_file(resume_from)
        if resume is not None:
            self._load_cursor(resume)

    def _load_cursor(self, cursor: dict) -> None:
        expected = {}
        if self.shuffle_seed is not None:
            # shuffle identity is part of the cursor: resuming under a
            # different seed/epoch would re-decode or skip units
            expected["shuffle"] = [int(self.shuffle_seed), self.epoch]
        self._next_unit = cursor_load(cursor, self.units, "next_unit",
                                      len(self.units), **expected)
        self.quarantine = QuarantineReport.from_dicts(
            cursor.get("quarantine"))
        # the resumed scan re-opened its sources, so a file already
        # quarantined in the checkpointed cursor was rejected AGAIN at
        # open time — merge the fresh open entries deduped by
        # coordinates instead of double-listing the file
        self.quarantine.merge_unique(self._open_quarantine.as_dicts())

    def state(self) -> dict:
        """JSON-serializable cursor: resume with
        ``ShardedScan(sources, ..., resume=state)``.  Valid between
        :meth:`run_iter` steps; decoding restarts at the first unit not
        yet yielded.  Quarantined units ride along (coordinates +
        error class), so a resumed scan's report stays complete."""
        extra = {}
        if self.shuffle_seed is not None:
            extra["shuffle"] = [int(self.shuffle_seed), self.epoch]
        return cursor_state(self.units, "next_unit", self._next_unit,
                            quarantine=self.quarantine.as_dicts(),
                            **extra)

    def device_for(self, unit_index: int):
        return self.devices[unit_index % len(self.devices)]

    def _progress(self) -> tuple[int, int]:
        return self._next_unit, len(self.units)

    def _advance(self, k: int) -> None:
        self._next_unit = k + 1

    def _unit_coords(self, k: int) -> tuple[int, int]:
        return self.units[k]

    def run_iter(self):
        """Yield ``(unit_index, {path: DeviceColumn})`` from the cursor
        position, advancing it after each unit.  In quarantine mode,
        failed units are skipped (recorded in :attr:`quarantine`), so
        the yielded unit indices identify exactly what decoded.

        With ``resume_from=`` configured the cursor auto-checkpoints
        durably every ``checkpoint_every`` completed units (and at
        scan end); with ``scan_deadline`` set the scan stops between
        units once the budget is spent, raising
        :class:`~tpuparquet.errors.DeadlineExceededError` with the
        cursor intact.

        Live telemetry (this round): :attr:`progress` ticks at every
        unit boundary (``parquet-tool top`` watches the exported
        status file), units decode under the scan's ambient collector
        when the caller has none (so the always-on metrics registry
        moves mid-scan), and quarantine/deadline events dump automatic
        post-mortems beside the durable cursor."""
        self._run_t0 = time.monotonic()
        if self.filter is not None and self._next_unit == 0:
            # fresh run: fold the unit-forming prune decisions exactly
            # once (a cursor resume already counted them in its run)
            self._count_pruned()
        if self.on_error == "raise":
            gen = pipelined_unit_scan(
                self.readers, self.units, self.device_for,
                start=self._next_unit, filter=self.filter,
                verdicts=self._verdicts)
        else:
            gen = resilient_unit_scan(
                self.readers, self.units, self.device_for,
                start=self._next_unit, retries=self.retries,
                quarantine=self.quarantine,
                unit_deadline=self.unit_deadline,
                postmortem=self._postmortem_path,
                filter=self.filter, verdicts=self._verdicts)
        yield from self._drive(gen)

    def run(self) -> list[dict[str, DeviceColumn]]:
        """Decode ALL units (position i of the result is unit i).

        Always a full scan — the cursor resets to the start first, so a
        resumed instance cannot return a dense list whose positions
        silently stop matching unit indices (``gather_column`` et al.
        index results positionally).  To continue a partial scan from a
        cursor, use :meth:`run_iter`, which labels each result with its
        unit index.

        In quarantine mode the list holds only the units that decoded
        (fewer, never wrong); :attr:`quarantine` names the missing ones
        by exact coordinates, and :meth:`run_iter` labels survivors
        with their true unit indices for positional consumers."""
        self._next_unit = 0
        if self.on_error == "quarantine":
            self.quarantine = QuarantineReport(
                self._open_quarantine.as_dicts())
        return [out for _, out in self.run_iter()]

    def run_with_stats(self, events: bool = False):
        """:meth:`run` under a fresh collector; returns
        ``(results, stats)``.  ``events=True`` attaches the per-page
        event log (``stats.events``) — the single-process counterpart
        of ``MultiHostScan.run_with_stats``, whose fleet aggregate
        (``shard.distributed.allgather_stats``) folds exactly these
        collectors across hosts."""
        from ..stats import collect_stats

        with collect_stats(events=events) as st:
            results = self.run()
        return results, st

    def close(self):
        for r in self.readers:
            if r is not None:
                r.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()


def gather_column(mesh, results: list[dict[str, DeviceColumn]], path: str,
                  *, out_sharding=None, gather_to=None):
    """Gather one fixed-width column across the mesh, placed where the
    consumer wants it.

    Builds a (U, L, lanes) global array sharded unit-wise over the "rg"
    axis from the per-device results (null slots zero-filled, units
    padded to a common length L), then reshards it to the requested
    output placement:

    * default (no spec) — replicate everywhere: one jitted identity
      whose replicated out-sharding XLA lowers to the all-gather
      collective over ICI.  Returns ``(values (U, L, lanes) ndarray,
      per-unit true counts)`` — the seed contract, unchanged.  (The
      ``TPQ_GATHER_TO`` env default applies at the SCAN level —
      ``ShardedScan(gather_to=)`` and the scan's gather methods — not
      here: an env knob must not silently change this function's
      return type under existing callers.)
    * ``out_sharding=`` (a ``NamedSharding`` over the consumer's mesh,
      or a ``PartitionSpec`` over the scan mesh) / ``gather_to=`` (a
      single device) — assemble directly onto the shards that will
      consume the column instead of all-gathering every byte to every
      device.  Cost is flat in mesh size for a singular consumer and
      proportional only to true fan-out otherwise.  Returns a
      device-resident ``jax.Array`` of shape (U', L, lanes) under the
      requested sharding, where U' pads the unit axis up to the
      spec's unit-axis partition count (rows ``>= len(counts)`` are
      zero); slice with the counts as usual.

    Placement resolution (and the mesh-mismatch errors) live in
    :func:`~tpuparquet.shard.mesh.resolve_out_sharding`.  The phase is
    metered: ``DecodeStats.gather_bytes_moved`` / ``_replicated`` /
    ``gather_reshard_s`` decompose what the reshard actually shipped.
    """
    from .mesh import resolve_out_sharding

    placement = resolve_out_sharding(mesh, out_sharding, gather_to,
                                     env_default=False)
    cols = [r[path] for r in results]
    if any(c.offsets is not None for c in cols):
        raise TypeError("gather_column handles fixed-width columns; "
                        "use gather_byte_column for BYTE_ARRAY")
    lanes = cols[0].lanes if cols else 1
    if any(c.lanes != lanes for c in cols):
        raise TypeError("gather_column units disagree on value width")
    # flat (num_values*lanes,) per unit: device buffers stay 1-D (a 2-D
    # (n, lanes) stack would tile T(8,128) on TPU — 64x HBM padding)
    dense = [
        scatter_to_dense(c.data, c.mask, c.positions, lanes=lanes)
        for c in cols
    ]
    counts = np.asarray([c.num_values for c in cols], dtype=np.int64)
    L = int(counts.max()) if len(counts) else 0
    padded = [jnp.pad(d.astype(jnp.uint32), (0, L * lanes - d.shape[0]))
              for d in dense]
    (gathered,), perm = _assemble_and_gather(
        mesh, [(padded, (L * lanes,), jnp.uint32)],
        placement=placement, out_row_shapes=[(L, lanes)])
    if placement is not None:
        return gathered, counts
    # host-side reshape to the (U, L, lanes) view callers index; the
    # shard-major assembly order un-permutes here
    out = np.asarray(gathered).reshape(gathered.shape[0], L, lanes)
    return out[perm[: len(dense)]], counts


def _count_gather(arrays, placement) -> None:
    """Meter one gather's reshard outcome: what each destination shard
    actually received (``gather_bytes_moved``), how much of that was
    pure replication beyond one copy of each global byte
    (``gather_bytes_replicated``).  Exact integers off the output
    shardings — no estimation."""
    from ..stats import current_stats

    st = current_stats()
    if st is None and _flightrec._active is None:
        return
    moved = extra = 0
    for a in arrays:
        nb = int(np.prod(a.shape, dtype=np.int64)) * a.dtype.itemsize
        per = int(np.prod(a.sharding.shard_shape(a.shape),
                          dtype=np.int64)) * a.dtype.itemsize
        tot = per * len(a.sharding.device_set)
        moved += tot
        extra += max(0, tot - nb)
    if st is not None:
        st.gather_bytes_moved += moved
        st.gather_bytes_replicated += extra
    if _flightrec._active is not None:
        _flightrec.flight(
            "gather", site="shard.scan.gather", streams=len(arrays),
            bytes_moved=moved, bytes_replicated=extra,
            placement=("replicated" if placement is None
                       else repr(placement)))


def _assemble_and_gather(mesh, streams, placement=None,
                         out_row_shapes=None):
    """Reshard per-unit device arrays into globals under the requested
    output placement, WITHOUT funneling them through a single device.

    The naive route (``jnp.stack`` then ``device_put`` with the sharded
    layout) materializes the whole global on ONE device before the
    reshard — on a real mesh that is a full extra trip over PCIe/ICI
    for every byte, and it serializes on device 0 (the worst overhead
    found by ``tools/scan_scale_curve.py``).  Instead: stack each rg
    block's units on the block's own device (units were placed
    round-robin, so rows are grouped shard-major), assemble each global
    zero-copy with :func:`jax.make_array_from_single_device_arrays`,
    then reshard in ONE step:

    * ``placement is None`` — one jitted identity over all streams
      whose replicated out-shardings lower to the all-gather
      collectives (the seed behavior, byte-identical).
    * ``placement`` (a resolved ``Sharding``) — one jitted
      permute-to-unit-order whose out-shardings ARE the consumer's
      spec, so each destination shard receives exactly its rows (plus
      the spec's true fan-out); when the target's device set differs
      from the mesh's (a single device, a consumer sub-mesh), the
      assembled globals hop via ``jax.device_put`` resharding first —
      still one data-sized move, never an all-gather.

    ``streams`` is a list of ``(padded_units, row_shape, dtype)`` — all
    streams must have the same unit count.  ``out_row_shapes``
    optionally reshapes each placed stream's rows (placed outputs
    cannot reshape host-side).  Returns ``(arrays, perm)``: with no
    placement, ``arrays[i]`` holds the unit at shard-major row i and
    ``perm`` maps unit index -> row; with a placement, ``arrays[i]``
    is already unit-ordered (rows past the true unit count are zero)
    and ``perm`` still maps unit -> shard-major assembly row.
    """
    # generalize over mesh rank: an rg-only mesh (no "sp" axis) is the
    # sp == 1 layout — callers may build their own 1-D mesh
    n_rg = mesh.shape["rg"]
    sp = dict(mesh.shape).get("sp", 1)
    grid = np.asarray(mesh.devices).reshape(n_rg, sp)
    n_dev = n_rg * sp
    n_true = len(streams[0][0])
    U = max(((n_true + n_dev - 1) // n_dev) * n_dev, n_dev)
    t_parts = 1
    if placement is not None:
        from .mesh import dim0_partitions

        # the assembled global may itself hop through a device_put
        # reshard to the target, so its unit axis must divide by the
        # target's unit-axis partition count too (jax requires
        # divisible shardings)
        t_parts = dim0_partitions(placement)
        while U % t_parts:
            U += n_dev
        if placement.device_set != set(mesh.devices.flat) \
                and _dim0_only(placement):
            # consumer outside the scan mesh (single sink device,
            # consumer sub-mesh): skip the shard-major global — each
            # unit row goes point-to-point to its destination shard,
            # once.  The whole step is the reshard.
            from ..stats import current_stats

            st = current_stats()
            t0 = time.perf_counter()
            # stage hint: keep sampled gather time inside the same
            # window the span times (doctor cross-checks the two)
            ptok = _profiler.stage_begin("gather") \
                if _profiler._active is not None else None
            try:
                out = _assemble_direct(placement, streams, n_true,
                                       t_parts, out_row_shapes)
                jax.block_until_ready(out)
            finally:
                if ptok is not None:
                    _profiler.stage_end(ptok)
            t1 = time.perf_counter()
            if st is not None:
                st.gather_reshard_s += t1 - t0
            if _trace._active is not None:
                _trace.emit_span("gather", t0, t1 - t0,
                                 streams=len(out), direct=True)
            _count_gather(out, placement)
            return list(out), np.arange(n_true, dtype=np.int64)
    rows_per_block = U // n_rg
    order = []   # shard-major: unit index per gathered row
    # P("rg") shards rows over rg only: rg block r spans the units the
    # round-robin placed on its sp sibling devices, and the whole block
    # replicates across those siblings
    blocks = [
        [u for u in range(n_true)
         if r * sp <= (u % n_dev) < (r + 1) * sp]
        for r in range(n_rg)
    ]
    for r, mine in enumerate(blocks):
        order.extend(mine)
        order.extend([-1] * (rows_per_block - len(mine)))
    stacked_all = []
    for padded, row_shape, dtype in streams:
        zero = None
        shards = []  # one per device, grid order (sp fastest)
        for r, mine in enumerate(blocks):
            # explicit placement BEFORE the stack: rows of an sp > 1
            # block live on sibling devices, and a jitted stack over
            # mixed committed devices is backend-dependent (no-op
            # transfer when the scan already placed the unit here)
            rows = [jax.device_put(padded[u], grid[r, 0]) for u in mine]
            if len(rows) < rows_per_block:
                if zero is None:
                    zero = np.zeros(row_shape, dtype=dtype)
                rows += [zero] * (rows_per_block - len(rows))
            block = jnp.stack(rows)
            for s in range(sp):
                shards.append(jax.device_put(block, grid[r, s]))
        sharding = NamedSharding(mesh, P("rg"))
        global_shape = (U,) + tuple(shards[0].shape[1:])
        stacked_all.append(jax.make_array_from_single_device_arrays(
            global_shape, sharding, shards))
    # perm[u] = shard-major assembly row of unit u
    perm = np.empty(n_true, dtype=np.int64)
    for row, u in enumerate(order):
        if u >= 0:
            perm[u] = row
    from ..stats import current_stats

    st = current_stats()
    t0 = time.perf_counter()
    ptok = _profiler.stage_begin("gather") \
        if _profiler._active is not None else None
    try:
        if placement is None:
            rep = NamedSharding(mesh, P())
            out = jax.jit(
                lambda *xs: xs,
                out_shardings=tuple(rep for _ in stacked_all)
            )(*stacked_all)
        else:
            out = _place_streams(mesh, stacked_all, placement, perm,
                                 n_true, t_parts, out_row_shapes)
        jax.block_until_ready(out)
    finally:
        if ptok is not None:
            _profiler.stage_end(ptok)
    t1 = time.perf_counter()
    if st is not None:
        st.gather_reshard_s += t1 - t0
    if _trace._active is not None:
        _trace.emit_span("gather", t0, t1 - t0, streams=len(out),
                         placement=("replicated" if placement is None
                                    else "placed"))
    _count_gather(out, placement)
    return list(out), perm


def _place_streams(mesh, stacked, placement, perm, n_true: int,
                   t_parts: int, out_row_shapes):
    """The consumer-aligned reshard: permute the shard-major assembly
    rows back to unit order INSIDE the placing computation, so the
    collective and the un-permute are one step and no byte detours
    through the host.  Output unit axis pads to a multiple of the
    target's partition count (rows >= ``n_true`` zeroed)."""
    u_out = ((max(n_true, 1) + t_parts - 1) // t_parts) * t_parts
    rows = np.zeros(u_out, dtype=np.int64)
    rows[:n_true] = perm
    valid = (np.arange(u_out) < n_true)
    shapes = [tuple(x.shape[1:]) if out_row_shapes is None
              else tuple(out_row_shapes[i])
              for i, x in enumerate(stacked)]

    def place(*xs):
        outs = []
        for x, shp in zip(xs, shapes):
            y = x[rows]
            mask = valid.reshape((u_out,) + (1,) * (y.ndim - 1))
            y = jnp.where(mask, y, jnp.zeros((), dtype=y.dtype))
            outs.append(y.reshape((u_out,) + shp))
        return tuple(outs)

    specs = tuple(placement for _ in stacked)
    if placement.device_set == set(mesh.devices.flat):
        # same device set: the permute + reshard compile as one
        # program; XLA emits exactly the collectives the spec implies
        return jax.jit(place, out_shardings=specs)(*stacked)
    # different device set (single consumer device, consumer
    # sub-mesh): hop the assembled shards to the target layout first —
    # one data-sized reshard, flat in mesh size — then permute locally
    # on the consumer's devices.  (Reached only for specs that shard
    # more than the unit axis; dim0-only specs take the cheaper direct
    # assembly in _assemble_and_gather and never build `stacked`.)
    # The hop carries only the spec's UNIT-axis partitioning: the
    # assembled intermediates are flat 2-D (U, row) — the full spec
    # describes the reshaped outputs and would mis-rank (or
    # mis-divide) against them; the jit below applies it.
    if isinstance(placement, NamedSharding):
        spec = placement.spec
        hop = NamedSharding(placement.mesh,
                            P(spec[0] if len(spec) else None))
    else:
        hop = placement
    moved = [jax.device_put(x, hop) for x in stacked]
    return jax.jit(place, out_shardings=specs)(*moved)


def _dim0_only(placement) -> bool:
    """Does this placement shard nothing beyond the unit axis?  (The
    precondition for direct per-destination assembly: a unit's whole
    row then lives on each of its destination devices.)"""
    if isinstance(placement, NamedSharding):
        spec = placement.spec
        return all(spec[i] is None for i in range(1, len(spec)))
    return True  # SingleDeviceSharding


def _assemble_direct(placement, streams, n_true: int, t_parts: int,
                     out_row_shapes):
    """Point-to-point assembly for consumer targets OUTSIDE the scan
    mesh's device set (a single sink device, a consumer sub-mesh):
    each unit's padded row hops straight to its destination shard(s)
    and stacks there in unit order.  The data moves exactly once per
    destination copy — true fan-out only, no collective, no permute,
    no intermediate global.  Requires a dim-0-only spec
    (:func:`_dim0_only`); rows >= ``n_true`` are zero."""
    u_out = ((max(n_true, 1) + t_parts - 1) // t_parts) * t_parts
    outs = []
    for i, (padded, row_shape, dtype) in enumerate(streams):
        shp = tuple(row_shape) if out_row_shapes is None \
            else tuple(out_row_shapes[i])
        gshape = (u_out,) + shp
        zero = None
        shards = []
        for dev, idx in placement.devices_indices_map(gshape).items():
            sl = idx[0]
            start = sl.start or 0
            stop = u_out if sl.stop is None else sl.stop
            rows = []
            for u in range(start, stop):
                if u < n_true:
                    rows.append(jax.device_put(padded[u], dev))
                else:
                    if zero is None:
                        zero = np.zeros(row_shape, dtype=dtype)
                    rows.append(jax.device_put(zero, dev))
            block = jnp.stack(rows).reshape((stop - start,) + shp)
            shards.append(jax.device_put(block, dev))
        outs.append(jax.make_array_from_single_device_arrays(
            gshape, placement, shards))
    return tuple(outs)


def gather_byte_column(mesh, results: list[dict[str, DeviceColumn]],
                       path: str, *, out_sharding=None, gather_to=None):
    """Gather one BYTE_ARRAY column across the mesh, placed where the
    consumer wants it.

    Each unit's shard densifies on its own device first: null record
    slots become zero-length values (their bytes are already absent, so
    the packed data buffer IS the dense data buffer — only the offsets
    re-derive), then padded (offsets to Lmax+1 with the byte total,
    keeping them monotone; data to Bmax with zeros) and stacked into
    (U, Lmax+1) / (U, Bmax) globals sharded unit-wise over "rg",
    resharded to the requested placement exactly like
    :func:`gather_column` (same ``out_sharding=``/``gather_to=``
    semantics, same default-replicated seed contract, same counters).

    Returns ``(offsets (U, Lmax+1), data (U, Bmax) u8, row_counts,
    byte_counts)``; row i of unit u spans
    ``data[u, offsets[u, i]:offsets[u, i+1]]``.  Offsets are PER-UNIT
    relative (each row's offsets start at 0), which makes them
    placement-invariant: a destination shard holds matching
    (offsets, data) rows, so the rebase is already per-destination-
    shard and no global offset rebase is needed under any spec.  With
    a placement the two returned arrays are device-resident
    ``jax.Array``\\ s whose unit axis pads to the spec's partition
    count (rows ``>= len(row_counts)`` zero) and whose dim-0
    shardings match, row for row.
    """
    from .mesh import resolve_out_sharding

    placement = resolve_out_sharding(mesh, out_sharding, gather_to,
                                     env_default=False)
    cols = [r[path] for r in results]
    if any(c.offsets is None for c in cols):
        raise TypeError("gather_byte_column handles BYTE_ARRAY columns; "
                        "use gather_column for fixed-width types")
    dense_offs = []
    datas = []
    for c in cols:
        offs = c.offsets[: c.n_packed + 1]
        lens = offs[1:] - offs[:-1]
        if c.num_values == c.n_packed and c._mask_p is None:
            dl = lens
        else:
            dl = jnp.where(c.mask, lens[c.positions],
                           jnp.zeros((), dtype=lens.dtype))
        do = jnp.concatenate(
            [jnp.zeros((1,), dtype=lens.dtype), jnp.cumsum(dl)]
        )
        dense_offs.append(do)
        datas.append(c.data)
    row_counts = np.asarray([d.shape[0] - 1 for d in dense_offs],
                            dtype=np.int64)
    byte_counts = np.asarray([d.shape[0] for d in datas], dtype=np.int64)
    L = int(row_counts.max()) + 1 if len(cols) else 1
    B = max(int(byte_counts.max()), 1) if len(cols) else 1
    # pad each unit on its own device (edge-padding keeps the offsets
    # monotone at the byte total), then assemble shard-major and
    # all-gather without funneling through one device
    offs_dtype = dense_offs[0].dtype if cols else jnp.int32
    offs_padded = [jnp.pad(do, (0, L - do.shape[0]), mode="edge")
                   for do in dense_offs]
    data_padded = [jnp.pad(d, (0, B - d.shape[0])) for d in datas]
    (o_g, d_g), perm = _assemble_and_gather(
        mesh, [(offs_padded, (L,), offs_dtype),
               (data_padded, (B,), jnp.uint8)],
        placement=placement)
    if placement is not None:
        return o_g, d_g, row_counts, byte_counts
    return (np.asarray(o_g)[perm[: len(cols)]],
            np.asarray(d_g)[perm[: len(cols)]],
            row_counts, byte_counts)
