"""Mesh construction and the fully-jittable SPMD decode step.

Two parallel axes, chosen for how parquet decode actually scales
(SURVEY.md §5 "long-context" mapping):

* ``"rg"`` — data parallel over (file × row-group × page) *units*: the
  embarrassingly parallel outer loop of the reference
  (``file_reader.go:51-57``).  Units shard across this axis; no
  communication until the final all-gather of decoded columns.
* ``"sp"`` — sequence parallel over the *value axis within a unit*: each
  shard expands a contiguous slice of output positions from the shared
  run table (the hybrid run structure is random-access after planning, so
  splitting the position axis needs no halo exchange at all).

Both collectives (`all_gather` over "sp" then "rg") ride ICI inside a
slice; across slices XLA places them on DCN — nothing in this module is
topology-specific.

Static-shape discipline: every unit's plan is padded to the batch-wide
bucket (run-count, bp-word-count, value-count), so one compiled program
serves the whole scan regardless of per-page variation.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..kernels.decode import bucket
from ..kernels.hybrid import HybridPlan, expand_hybrid_core, plan_hybrid

__all__ = [
    "make_mesh",
    "assign_units",
    "resolve_out_sharding",
    "placement_devices",
    "dim0_partitions",
    "BatchedHybridPlan",
    "stack_hybrid_plans",
    "decode_step_spmd",
    "sharded_dict_decode",
]


def make_mesh(n_devices: int | None = None, sp: int | None = None,
              devices=None) -> Mesh:
    """Build a ("rg", "sp") mesh over the first ``n_devices`` devices.

    ``sp`` defaults to 2 when the device count is even and >2 (so both
    axes are exercised), else 1 — pass explicitly for real topologies.

    Defaults to this process's LOCAL devices: in a multi-process
    runtime ``jax.devices()`` includes other hosts' non-addressable
    devices, and a scan mesh containing those yields arrays the
    process cannot read (identical to ``jax.devices()`` when
    single-process).  Cross-host layouts pass ``devices`` explicitly.
    """
    devs = list(devices if devices is not None else jax.local_devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if sp is None:
        sp = 2 if n > 2 and n % 2 == 0 else 1
    if n % sp:
        raise ValueError(f"{n} devices not divisible by sp={sp}")
    arr = np.asarray(devs).reshape(n // sp, sp)
    return Mesh(arr, ("rg", "sp"))


def assign_units(n_units: int, n_shards: int) -> list[list[int]]:
    """Round-robin unit indices over shards (static, deterministic)."""
    out: list[list[int]] = [[] for _ in range(n_shards)]
    for i in range(n_units):
        out[i % n_shards].append(i)
    return out


# ----------------------------------------------------------------------
# Consumer-aligned output placement (the gather-wall fix)
# ----------------------------------------------------------------------

def _gather_to_env():
    """``TPQ_GATHER_TO``: default ``gather_to`` device INDEX (into this
    process's ``jax.local_devices()``) for scans and the free gather
    functions when no explicit placement is passed.  Unset/empty =
    replicated (the seed behavior).  A malformed or out-of-range value
    raises — a placement knob that silently replicated everything
    would defeat its own purpose."""
    raw = os.environ.get("TPQ_GATHER_TO", "")
    if not raw:
        return None
    try:
        idx = int(raw)
    except ValueError:
        raise ValueError(
            f"TPQ_GATHER_TO={raw!r} is not a device index") from None
    devs = jax.local_devices()
    if not 0 <= idx < len(devs):
        raise ValueError(
            f"TPQ_GATHER_TO={idx} out of range: this process has "
            f"{len(devs)} addressable devices")
    return devs[idx]


def resolve_out_sharding(mesh, out_sharding=None, gather_to=None,
                         env_default: bool = True):
    """Resolve a consumer placement request into a ``jax.sharding.
    Sharding`` — or None, meaning the seed's replicate-everywhere
    gather.

    ``out_sharding`` is a ``NamedSharding`` over the CONSUMER's mesh
    (preferred — it carries its own mesh), a bare ``PartitionSpec``
    (interpreted over ``mesh``, the scan's mesh), an already-resolved
    ``NamedSharding``/``SingleDeviceSharding``, or the string
    ``"replicated"`` (the explicit spelling of the seed gather, for
    overriding an armed scan-level/env default).  ``gather_to`` is a
    single target device (a ``jax.Device`` or an index into this
    process's ``jax.local_devices()``) — sugar for a
    ``SingleDeviceSharding``.  At most one may be given; with
    neither, the ``TPQ_GATHER_TO`` env default applies (when
    ``env_default``), else replicated.

    Multi-host semantics: the gather assembles THIS process's decoded
    units on this process's mesh, so the target must be fully
    addressable from this process — each host of a ``MultiHostScan``
    places its own shard of the results (cross-host exchange stays
    with the DCN collectives in ``shard.distributed``).  A target
    naming non-addressable devices is rejected loudly.
    """
    from jax.sharding import SingleDeviceSharding

    if out_sharding is not None and gather_to is not None:
        raise ValueError("pass out_sharding= or gather_to=, not both "
                         "(they are two spellings of one placement)")
    if out_sharding == "replicated":
        # the explicit spelling of the seed replicate-everywhere
        # gather: None cannot express it where a scan-level or env
        # default is armed (None means "use the default" there)
        return None
    if out_sharding is None and gather_to is None:
        if not env_default:
            return None
        gather_to = _gather_to_env()
        if gather_to is None:
            return None
    if gather_to is not None:
        if isinstance(gather_to, int):
            devs = jax.local_devices()
            if not 0 <= gather_to < len(devs):
                raise ValueError(
                    f"gather_to={gather_to} out of range: this process "
                    f"has {len(devs)} addressable devices")
            gather_to = devs[gather_to]
        return SingleDeviceSharding(gather_to)
    if isinstance(out_sharding, P):
        if mesh is None:
            raise ValueError(
                "a bare PartitionSpec has no mesh to bind against "
                "here; pass a NamedSharding over the consumer's mesh")
        try:
            return NamedSharding(mesh, out_sharding)
        except ValueError as e:
            raise ValueError(
                f"out_sharding {out_sharding} does not fit the scan "
                f"mesh (axes {tuple(mesh.axis_names)}): {e}; pass a "
                "NamedSharding over the consumer's mesh to shard "
                "along consumer axes") from e
    if isinstance(out_sharding, jax.sharding.Sharding):
        if not isinstance(out_sharding, (NamedSharding,
                                         SingleDeviceSharding)):
            # the gather's unit-axis padding (dim0_partitions) cannot
            # be derived from other sharding flavors; accepting one
            # would trade this loud rejection for a raw divisibility
            # crash deep inside jax
            raise ValueError(
                f"out_sharding must be a NamedSharding or a single "
                f"device, not {type(out_sharding).__name__}; wrap "
                "the consumer's layout in a NamedSharding over its "
                "mesh")
        if not out_sharding.is_fully_addressable:
            raise ValueError(
                "out_sharding places shards on devices this process "
                "cannot address; a multi-host scan gathers each "
                "host's results onto its LOCAL mesh — pass a "
                "per-process sharding (see MultiHostScan docs)")
        return out_sharding
    raise ValueError(
        f"out_sharding must be a NamedSharding, a PartitionSpec, or "
        f"a Sharding, not {type(out_sharding).__name__}")


def placement_devices(sharding) -> list:
    """The ordered device list of a resolved placement target — the
    order unit round-robin placement uses when decoding directly onto
    consumer shards (``read_row_groups_device(out_sharding=)``)."""
    if isinstance(sharding, NamedSharding):
        return list(sharding.mesh.devices.flat)
    return sorted(sharding.device_set, key=lambda d: d.id)


def dim0_partitions(sharding) -> int:
    """How many ways a resolved placement splits axis 0 (the unit
    axis of every gathered global).  The gather pads its unit axis to
    a multiple of this so the placed arrays satisfy jax's divisible-
    sharding requirement."""
    if isinstance(sharding, NamedSharding):
        spec = sharding.spec
        if len(spec) == 0 or spec[0] is None:
            return 1
        names = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
        t = 1
        shape = dict(sharding.mesh.shape)
        for nm in names:
            t *= shape[nm]
        return t
    return 1


class BatchedHybridPlan:
    """A stack of :class:`HybridPlan` padded to common static shapes.

    Array shapes (U = padded unit count, R = run bucket, B = bp-word
    bucket): ``bp_words`` (U, B_blocks*width) — flat per-unit rows, the
    unpack kernel reshapes inside its jit — ``run_ends`` /
    ``run_is_rle`` / ``run_value`` / ``run_bp_start`` (U, R).  ``count``
    is the padded per-unit value count; ``counts`` the true per-unit
    counts (for unpadding on the host afterwards).
    """

    __slots__ = ("bp_words", "run_ends", "run_is_rle", "run_value",
                 "run_bp_start", "count", "width", "n_bp", "counts",
                 "n_units")

    def __init__(self, bp_words, run_ends, run_is_rle, run_value,
                 run_bp_start, count, width, n_bp, counts, n_units):
        self.bp_words = bp_words
        self.run_ends = run_ends
        self.run_is_rle = run_is_rle
        self.run_value = run_value
        self.run_bp_start = run_bp_start
        self.count = count
        self.width = width
        self.n_bp = n_bp
        self.counts = counts
        self.n_units = n_units

    def arrays(self):
        return (self.bp_words, self.run_ends, self.run_is_rle,
                self.run_value, self.run_bp_start)


def stack_hybrid_plans(plans: list[HybridPlan], n_units: int | None = None,
                       count: int | None = None) -> BatchedHybridPlan:
    """Pad+stack host plans into one batch (see class docstring).

    Padding semantics: extra runs repeat the final ``run_end`` (so
    ``searchsorted(..., side="right")`` never selects them for real
    positions); extra units are all-RLE zero plans; positions past a
    unit's true count land in its final run and are masked off by the
    caller via ``counts``.
    """
    if not plans:
        raise ValueError("no plans to stack")
    width = max(p.width for p in plans)
    if any(p.width not in (width, 0) for p in plans):
        raise ValueError("mixed widths in one batch")
    true_n = len(plans)
    n_units = n_units or true_n
    R = bucket(max(len(p.run_ends) for p in plans))
    n_bp = bucket(max(p.n_bp_values for p in plans))
    count = count or bucket(max(p.count for p in plans))
    n_blocks = (n_bp + 31) // 32

    bp_words = np.zeros((n_units, n_blocks, max(width, 1)), dtype=np.uint32)
    run_ends = np.full((n_units, R), count, dtype=np.int32)
    run_is_rle = np.ones((n_units, R), dtype=bool)
    run_value = np.zeros((n_units, R), dtype=np.uint32)
    run_bp_start = np.zeros((n_units, R), dtype=np.int32)
    counts = np.zeros((n_units,), dtype=np.int32)

    for u, p in enumerate(plans):
        nb = p.bp_words.shape[0]
        bp_words[u, :nb, : p.bp_words.shape[1]] = p.bp_words
        nr = len(p.run_ends)
        run_ends[u, :nr] = p.run_ends
        run_ends[u, nr:] = max(int(p.run_ends[-1]), p.count) if nr else count
        run_is_rle[u, :nr] = p.run_is_rle
        run_value[u, :nr] = p.run_value
        run_bp_start[u, :nr] = p.run_bp_start
        counts[u] = p.count
    # per-unit bp words flatten to (U, B_blocks*width): a <=32 minor
    # dim would tile to 128 lanes on TPU; the unpack kernel reshapes
    # its 1-D row inside the jit
    return BatchedHybridPlan(bp_words.reshape(n_units, -1), run_ends,
                             run_is_rle, run_value, run_bp_start, count,
                             width, n_bp, counts, true_n)


def _expand_slice(bw, re, rr, rv, rs, idx, width: int, n_bp: int):
    """vmap body: one unit's plan, one slice of output positions."""
    return expand_hybrid_core(bw, re, rr, rv, rs, idx, width, n_bp)


def decode_step_spmd(mesh: Mesh, count: int, width: int, n_bp: int,
                     lanes: int):
    """Build the jitted SPMD decode step for one batch geometry.

    The step signature is ``step(bp_words, run_ends, run_is_rle,
    run_value, run_bp_start, dictionary) -> (U, count, lanes) u32`` with
    inputs sharded unit-wise over "rg" (dictionary replicated) and the
    output fully replicated (all-gathered over both axes) — the flagship
    "forward step" of the framework: hybrid-RLE/BP index expand +
    dictionary gather, data- and sequence-parallel.
    """
    sp = mesh.shape["sp"]
    if count % sp:
        raise ValueError(f"count={count} not divisible by sp={sp}")

    def step(bw, re, rr, rv, rs, dictionary):
        # Per-shard slice of the value axis (sequence parallel): shard i
        # of "sp" computes positions [i*count/sp, (i+1)*count/sp).
        i_sp = jax.lax.axis_index("sp")
        local = count // sp
        idx = i_sp * local + jnp.arange(local, dtype=jnp.int32)
        expand = jax.vmap(
            functools.partial(_expand_slice, width=width, n_bp=n_bp),
            in_axes=(0, 0, 0, 0, 0, None),
        )
        indices = expand(bw, re, rr, rv, rs, idx)          # (U_loc, local)
        vals = dictionary[jnp.minimum(indices, dictionary.shape[0] - 1)]
        # Reassemble the value axis, then gather units: both collectives
        # are XLA all-gathers over ICI (SURVEY.md §5 "distributed").
        vals = jax.lax.all_gather(vals, "sp", axis=1, tiled=True)
        return jax.lax.all_gather(vals, "rg", axis=0, tiled=True)

    spec_unit = P("rg")
    in_specs = (spec_unit, spec_unit, spec_unit, spec_unit, spec_unit, P())
    try:
        # check_vma=False: the output *is* replicated (all-gathered over
        # both axes) but the checker can't infer that through the gather.
        sharded = jax.shard_map(step, mesh=mesh, in_specs=in_specs,
                                out_specs=P(), check_vma=False)
    except (AttributeError, TypeError):  # older jax
        from jax.experimental.shard_map import shard_map

        sharded = shard_map(step, mesh=mesh, in_specs=in_specs,
                            out_specs=P(), check_rep=False)
    return jax.jit(sharded)


def sharded_dict_decode(mesh: Mesh, streams, counts, width: int,
                        dictionary: np.ndarray):
    """End-to-end sharded decode of many dict-index streams.

    ``streams``: list of raw hybrid-encoded index byte streams;
    ``counts``: per-stream value counts; ``dictionary``: (D, lanes) u32.
    Returns a list of (count_i, lanes) numpy arrays — the all-gathered,
    unpadded results, bit-identical on every host.
    """
    n_rg = mesh.shape["rg"]
    plans = [plan_hybrid(s, c, width) for s, c in zip(streams, counts)]
    n_units = max(len(plans), n_rg)
    n_units = ((n_units + n_rg - 1) // n_rg) * n_rg  # divisible by rg axis
    batch = stack_hybrid_plans(plans, n_units=n_units)
    count = batch.count
    sp = mesh.shape["sp"]
    if count % sp:
        count = int(math.ceil(count / sp) * sp)
        batch = stack_hybrid_plans(plans, n_units=n_units, count=count)
    step = decode_step_spmd(mesh, batch.count, batch.width, batch.n_bp,
                            dictionary.shape[1])
    unit_sharding = NamedSharding(mesh, P("rg"))
    rep = NamedSharding(mesh, P())
    args = [jax.device_put(a, unit_sharding) for a in batch.arrays()]
    dict_dev = jax.device_put(dictionary.astype(np.uint32), rep)
    out = np.asarray(step(*args, dict_dev))
    return [out[u, : batch.counts[u]] for u in range(batch.n_units)]
