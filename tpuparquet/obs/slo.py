"""SLO objectives, error budgets, and burn rates over the ring.

The serve regime's operator question is not "what is the p99 right
now" but "am I *keeping my promise*, and how fast am I spending the
slack" — Service Level Objectives evaluated into error budgets and
multi-window burn rates (the SRE-workbook alerting discipline: page
on a fast-window burn confirmed by the slow window, so a blip
doesn't page and a slow leak still does).

Objectives are declarative JSON (``TPQ_SLO_FILE``), one per scan
label::

    [{"label": "scan",
      "latency_stage": "unit",          // digest stage to test
      "latency_p": 0.99,                // which percentile
      "latency_target_ms": 250,         // promise: p99 unit < 250ms
      "error_rate_target": 0.001,       // promise: <0.1% units fail
      "window_s": 3600}]                // budget window

Evaluation (:func:`evaluate`) runs over the frames of a time-series
ring (``obs/timeseries.py``).  Everything in a frame is cumulative,
so a window's worth of anything is *last frame minus the frame just
before the window* — and because digests and ledgers merge by
elementwise integer math on fixed buckets, that subtraction is exact
bucket-for-bucket, the same property the cross-host merges lean on.
A process restart (pid change) resets cumulatives; deltas clamp at
the raw last value so a restart under-counts briefly instead of
going negative.

Vocabulary: per label, **errors** are ``units_quarantined +
deadline_exceeded`` out of **attempts** (``row_groups`` decoded +
quarantined units) — the same conservation counters the ledgers pin.
The **error budget** is ``error_rate_target × attempts``; **burn
rate** is ``actual_rate / target`` over a window (burn 1.0 = spending
exactly at budget; 14.4 = the classic page-now threshold).
"""

from __future__ import annotations

import json
import os
import time

from .digest import QuantileDigest

__all__ = ["load_objectives", "evaluate", "format_report",
           "slo_file_default", "window_digest", "window_ledger",
           "error_rate",
           "DEFAULT_FAST_WINDOW_S", "DEFAULT_SLOW_WINDOW_S"]

DEFAULT_FAST_WINDOW_S = 300.0
DEFAULT_SLOW_WINDOW_S = 3600.0

_ERROR_COUNTERS = ("units_quarantined", "deadline_exceeded")


def slo_file_default() -> str | None:
    """Objectives path from ``TPQ_SLO_FILE`` (None = no objectives)."""
    return os.environ.get("TPQ_SLO_FILE") or None


def load_objectives(path: str | None = None) -> list[dict]:
    """Load + normalize objectives (defaults filled, types coerced).
    ``path`` defaults to ``TPQ_SLO_FILE``; no path → ``[]``.  Raises
    ``ValueError`` on a file that is not an objective list."""
    if path is None:
        path = slo_file_default()
    if not path:
        return []
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"SLO file {path!r} is not valid JSON: {e}") \
                from e
    if isinstance(doc, dict):
        doc = doc.get("objectives")
    if not isinstance(doc, list):
        raise ValueError(f"SLO file {path!r}: expected a list of "
                         f"objectives (or {{'objectives': [...]}})")
    out = []
    for i, o in enumerate(doc):
        if not isinstance(o, dict) or not o.get("label"):
            raise ValueError(f"SLO file {path!r}: objective #{i} "
                             f"needs a 'label'")
        out.append({
            "label": str(o["label"]),
            "latency_stage": str(o.get("latency_stage", "unit")),
            "latency_p": float(o.get("latency_p", 0.99)),
            "latency_target_ms": (
                None if o.get("latency_target_ms") is None
                else float(o["latency_target_ms"])),
            "error_rate_target": (
                None if o.get("error_rate_target") is None
                else float(o["error_rate_target"])),
            "window_s": float(o.get("window_s", DEFAULT_SLOW_WINDOW_S)),
        })
    return out


# ----------------------------------------------------------------------
# Windowed deltas over ring frames (exact on the fixed buckets)
# ----------------------------------------------------------------------

def _baseline_frame(frames: list[dict], start_ts: float) -> dict | None:
    """The newest frame at-or-before the window start — the cumulative
    baseline the window subtracts.  None = window spans the whole
    ring (baseline zero)."""
    base = None
    for f in frames:
        if f.get("ts", 0.0) <= start_ts:
            base = f
        else:
            break
    return base


def _same_epoch(a: dict | None, b: dict) -> bool:
    """Cumulative subtraction only makes sense within one process
    epoch (counters reset at restart)."""
    return a is not None and a.get("pid") == b.get("pid")


def window_digest(frames: list[dict], label: str, stage: str,
                  window_s: float, now: float) -> QuantileDigest:
    """The digest of observations that landed inside the window:
    last frame's cumulative digest minus the baseline frame's,
    bucket-for-bucket (exact — fixed global buckets)."""
    out = QuantileDigest()
    if not frames:
        return out
    last = frames[-1]
    ld = ((last.get("digests") or {}).get(label) or {}).get(stage)
    if not ld:
        return out
    out = QuantileDigest.from_dict(ld)
    base = _baseline_frame(frames, now - window_s)
    if _same_epoch(base, last):
        bd = ((base.get("digests") or {}).get(label) or {}).get(stage)
        if bd:
            bg = QuantileDigest.from_dict(bd)
            for i, c in bg.counts.items():
                left = out.counts.get(i, 0) - c
                if left > 0:
                    out.counts[i] = left
                else:
                    out.counts.pop(i, None)
            out.n = max(out.n - bg.n, 0)
            out.total = max(out.total - bg.total, 0)
    return out


def window_ledger(frames: list[dict], label: str,
                  window_s: float, now: float) -> dict:
    """Per-label counter deltas inside the window (ledger cumulative
    last-minus-baseline, clamped at the raw last value across
    process restarts)."""
    if not frames:
        return {}
    last = frames[-1]
    lc = ((last.get("ledgers") or {}).get(label) or {}).get("counters")
    if not lc:
        return {}
    out = dict(lc)
    base = _baseline_frame(frames, now - window_s)
    if _same_epoch(base, last):
        bc = ((base.get("ledgers") or {}).get(label) or {}) \
            .get("counters") or {}
        for k, v in bc.items():
            out[k] = max(out.get(k, 0) - v, 0)
    return {k: v for k, v in out.items() if v}


def error_rate(counters: dict) -> tuple[float | None, int, int]:
    """(rate, errors, attempts) from a windowed ledger-counter dict;
    rate None when nothing ran in the window.  Public because the
    serve arbiter prices its adaptive burn feedback with EXACTLY this
    derivation — the rebalancer and the SLO evaluator must agree on
    what an error is by construction."""
    errors = sum(int(counters.get(k, 0)) for k in _ERROR_COUNTERS)
    attempts = int(counters.get("row_groups", 0)) \
        + int(counters.get("units_quarantined", 0))
    if attempts <= 0:
        return None, errors, 0
    return errors / attempts, errors, attempts


_error_rate = error_rate  # internal alias (pre-serve call sites)


# ----------------------------------------------------------------------
# Evaluation
# ----------------------------------------------------------------------

def evaluate(frames: list[dict], objectives: list[dict],
             now: float | None = None) -> dict:
    """Evaluate every objective over the ring frames into one report:
    windowed latency percentile vs target, windowed error rate vs
    target, error-budget consumption, and fast/slow burn rates.
    ``ok`` flags are None (no verdict) when the window saw no work."""
    if now is None:
        now = time.time()
    rows = []
    for o in objectives:
        label = o["label"]
        row: dict = {"label": label, "window_s": o["window_s"]}

        # latency leg
        if o["latency_target_ms"] is not None:
            dig = window_digest(frames, label, o["latency_stage"],
                                o["window_s"], now)
            val_ms = (dig.quantile(o["latency_p"]) / 1000.0
                      if dig.n else None)
            row["latency"] = {
                "stage": o["latency_stage"],
                "p": o["latency_p"],
                "target_ms": o["latency_target_ms"],
                "value_ms": val_ms,
                "n": dig.n,
                "ok": (None if val_ms is None
                       else val_ms <= o["latency_target_ms"]),
            }

        # error-rate leg + budget + burn
        if o["error_rate_target"] is not None:
            target = o["error_rate_target"]
            rate, errors, attempts = _error_rate(
                window_ledger(frames, label, o["window_s"], now))
            budget_allowed = target * attempts
            consumed = (min(errors / budget_allowed, 1e9)
                        if budget_allowed > 0 else (1.0 if errors else 0.0))
            burns = {}
            for wname, ws in (("fast", DEFAULT_FAST_WINDOW_S),
                              ("slow", DEFAULT_SLOW_WINDOW_S)):
                r, _, att = _error_rate(
                    window_ledger(frames, label, ws, now))
                burns[wname] = (None if r is None or target <= 0
                                else r / target)
                burns[f"{wname}_window_s"] = ws
            row["errors"] = {
                "target": target,
                "rate": rate,
                "errors": errors,
                "attempts": attempts,
                "ok": None if rate is None else rate <= target,
            }
            row["budget"] = {
                "allowed": budget_allowed,
                "consumed_fraction": consumed,
                "remaining_fraction": max(1.0 - consumed, 0.0),
            }
            row["burn"] = burns
        rows.append(row)
    return {
        "format": "tpq-slo-report",
        "version": 1,
        "ts": now,
        "frames": len(frames),
        "objectives": rows,
    }


def _fmt_pct(x: float | None) -> str:
    return "-" if x is None else f"{100.0 * x:.2f}%"


def _fmt_burn(x: float | None) -> str:
    return "-" if x is None else f"{x:.1f}x"


def format_report(report: dict) -> str:
    """Human-readable report (one block per objective) for
    ``parquet-tool slo report``."""
    lines = [f"SLO report over {report['frames']} frames"]
    for row in report["objectives"]:
        lines.append(f"  {row['label']}  (window {row['window_s']:g}s)")
        lat = row.get("latency")
        if lat:
            v = ("-" if lat["value_ms"] is None
                 else f"{lat['value_ms']:.1f}ms")
            verdict = {True: "OK", False: "VIOLATED", None: "no data"}[
                lat["ok"]]
            lines.append(
                f"    latency  p{int(lat['p'] * 100)} {lat['stage']} "
                f"= {v}  target {lat['target_ms']:g}ms  [{verdict}] "
                f"(n={lat['n']})")
        err = row.get("errors")
        if err:
            verdict = {True: "OK", False: "VIOLATED", None: "no data"}[
                err["ok"]]
            lines.append(
                f"    errors   {err['errors']}/{err['attempts']} "
                f"= {_fmt_pct(err['rate'])}  target "
                f"{_fmt_pct(err['target'])}  [{verdict}]")
            b = row["budget"]
            lines.append(
                f"    budget   {_fmt_pct(b['remaining_fraction'])} "
                f"remaining (consumed {_fmt_pct(b['consumed_fraction'])})")
            burn = row["burn"]
            lines.append(
                f"    burn     fast {_fmt_burn(burn['fast'])} "
                f"({burn['fast_window_s']:g}s)  slow "
                f"{_fmt_burn(burn['slow'])} ({burn['slow_window_s']:g}s)")
    return "\n".join(lines)
