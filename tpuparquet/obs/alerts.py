"""Rule-based alerting over the ring: the thing that pages.

A small, dependency-free rule engine in the Prometheus-alerting
shape, evaluated over time-series ring frames
(``obs/timeseries.py``).  Three rule kinds cover the serve regime's
paging needs:

* **threshold** — a windowed counter delta crossed a bound
  (``units_quarantined >= 1 in 300s``).  Per-label rules read the
  ledger cumulatives; global rules sum the frames' exact ``delta``
  maps.
* **absence** — the signal went away: no frame landed inside the
  window (writer silent — the exporter died with the process), or a
  counter that should be moving didn't.
* **burn_rate** — the SRE-workbook multi-window page: the error
  budget is burning faster than ``threshold``× in BOTH the fast and
  slow windows (fast catches it now, slow confirms it's not a blip).

Delivery is **sinks** — plain callables taking the alert dict.
:func:`stdout_sink` prints one line; :func:`file_sink` appends to
the postmortem-style atomic alert record (capped JSON document,
tmp + ``os.replace``, oldest dropped) that ``TPQ_ALERTS_EXPORT``
also arms process-wide; any callback does anything else.  The
engine is edge-triggered per sink (an alert firing across ten
evaluations delivers once, with ``since`` carrying the first firing
time) while :meth:`AlertEngine.evaluate` always returns the full
currently-firing list (``parquet-tool watch`` renders state, not
edges).

Push path: library code emits ad-hoc alerts through
:func:`emit_alert` — off by default behind the one-is-None gate
(armed by ``TPQ_ALERTS_EXPORT``), call-guarded at hot sites with
``_alerts._active is not None`` per the recorder-guard discipline.
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["AlertRule", "AlertEngine", "emit_alert", "engine",
           "set_engine", "alerts_export_default", "default_rules",
           "record_alert", "load_alerts", "stdout_sink", "file_sink",
           "ALERT_CAP"]

ALERT_CAP = 64

_OPS = {
    ">=": lambda a, b: a >= b,
    ">": lambda a, b: a > b,
    "<=": lambda a, b: a <= b,
    "<": lambda a, b: a < b,
    "==": lambda a, b: a == b,
}


def alerts_export_default() -> str | None:
    """Alert-record path from ``TPQ_ALERTS_EXPORT`` (None = off)."""
    return os.environ.get("TPQ_ALERTS_EXPORT") or None


# ----------------------------------------------------------------------
# Durable alert records (postmortem discipline: atomic, capped)
# ----------------------------------------------------------------------

# serializes the load-append-write: concurrent scans share one record
# file, and an unlocked read-modify-write would drop the loser's alert
_record_lock = threading.Lock()


def record_alert(path: str | None, alert: dict) -> str | None:
    """Append one alert to the record file at ``path`` (no-op → None
    when ``path`` is None).  Read-modify-write under the atomic
    tmp + ``os.replace`` discipline, capped at :data:`ALERT_CAP`
    (oldest dropped); ``OSError`` swallowed — best-effort telemetry."""
    if not path:
        return None
    from .live import atomic_write_text

    with _record_lock:
        try:
            doc = load_alerts(path)
        except (OSError, ValueError):
            doc = {"format": "tpq-alerts", "version": 1, "alerts": []}
        doc["alerts"].append(alert)
        if len(doc["alerts"]) > ALERT_CAP:
            doc["alerts"] = doc["alerts"][-ALERT_CAP:]
        if not atomic_write_text(path, json.dumps(doc, sort_keys=True)):
            return None
    return path


def load_alerts(path: str) -> dict:
    """Read an alert record file back, validating the envelope."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("format") != "tpq-alerts":
        raise ValueError(f"{path!r} is not a tpq alert record")
    doc.setdefault("alerts", [])
    return doc


# ----------------------------------------------------------------------
# Sinks
# ----------------------------------------------------------------------

def stdout_sink(alert: dict) -> None:
    """One line per newly-firing alert, greppable."""
    label = f" label={alert['label']}" if alert.get("label") else ""
    print(f"ALERT [{alert.get('severity', 'page')}] "
          f"{alert['name']}{label}: {alert.get('msg', '')}", flush=True)


def file_sink(path: str):
    """A sink appending to the atomic alert record at ``path``."""
    def sink(alert: dict) -> None:
        record_alert(path, alert)
    return sink


# ----------------------------------------------------------------------
# Rules
# ----------------------------------------------------------------------

def _global_delta(frames: list[dict], counter: str,
                  window_s: float, now: float) -> float:
    """Windowed delta of a global registry counter: the frames' exact
    per-frame ``delta`` maps are summable by construction."""
    lo = now - window_s
    return sum(f.get("delta", {}).get(counter, 0)
               for f in frames if f.get("ts", 0.0) > lo)


class AlertRule:
    """One declarative rule; see the module docstring for kinds.

    Normalized fields: ``name``, ``kind``, ``severity``; threshold
    rules add ``counter``/``op``/``value``/``window_s`` and optional
    ``label``; absence rules add ``window_s`` and optional
    ``counter``; burn-rate rules add ``label``/
    ``error_rate_target``/``threshold``."""

    def __init__(self, name: str, kind: str, *, severity: str = "page",
                 label: str | None = None, counter: str | None = None,
                 op: str = ">=", value: float = 1.0,
                 window_s: float = 300.0,
                 error_rate_target: float = 0.001,
                 threshold: float = 1.0):
        if kind not in ("threshold", "absence", "burn_rate"):
            raise ValueError(f"unknown alert rule kind {kind!r}")
        if kind == "threshold" and counter is None:
            raise ValueError(f"threshold rule {name!r} needs a counter")
        if op not in _OPS:
            raise ValueError(f"unknown threshold op {op!r}")
        self.name = name
        self.kind = kind
        self.severity = severity
        self.label = label
        self.counter = counter
        self.op = op
        self.value = value
        self.window_s = window_s
        self.error_rate_target = error_rate_target
        self.threshold = threshold

    @classmethod
    def from_dict(cls, d: dict) -> "AlertRule":
        d = dict(d)
        return cls(d.pop("name"), d.pop("kind"), **d)

    def check(self, frames: list[dict], now: float) -> dict | None:
        """Evaluate against the ring; a firing rule returns the alert
        dict (without ``since`` — the engine owns firing state)."""
        if self.kind == "threshold":
            return self._check_threshold(frames, now)
        if self.kind == "absence":
            return self._check_absence(frames, now)
        return self._check_burn(frames, now)

    def _alert(self, msg: str, **fields) -> dict:
        a = {"name": self.name, "kind": self.kind,
             "severity": self.severity, "msg": msg}
        if self.label:
            a["label"] = self.label
        a.update(fields)
        return a

    def _check_threshold(self, frames: list[dict],
                         now: float) -> dict | None:
        from .slo import window_ledger

        if self.label:
            v = window_ledger(frames, self.label, self.window_s,
                              now).get(self.counter, 0)
        else:
            v = _global_delta(frames, self.counter, self.window_s, now)
        if _OPS[self.op](v, self.value):
            return self._alert(
                f"{self.counter} {self.op} {self.value:g} over "
                f"{self.window_s:g}s (observed {v:g})",
                counter=self.counter, observed=v)
        return None

    def _check_absence(self, frames: list[dict],
                       now: float) -> dict | None:
        lo = now - self.window_s
        recent = [f for f in frames if f.get("ts", 0.0) > lo]
        if not recent:
            return self._alert(
                f"no telemetry frame in {self.window_s:g}s "
                f"(writer silent)", observed=0)
        if self.counter is not None:
            v = _global_delta(frames, self.counter, self.window_s, now)
            if not v:
                return self._alert(
                    f"{self.counter} flat over {self.window_s:g}s",
                    counter=self.counter, observed=0)
        return None

    def _check_burn(self, frames: list[dict],
                    now: float) -> dict | None:
        from .slo import (DEFAULT_FAST_WINDOW_S, DEFAULT_SLOW_WINDOW_S,
                          _error_rate, window_ledger)

        target = self.error_rate_target
        if target <= 0 or not self.label:
            return None
        burns = []
        for ws in (DEFAULT_FAST_WINDOW_S, DEFAULT_SLOW_WINDOW_S):
            rate, _, _ = _error_rate(
                window_ledger(frames, self.label, ws, now))
            burns.append(None if rate is None else rate / target)
        fast, slow = burns
        if fast is not None and slow is not None \
                and fast >= self.threshold and slow >= self.threshold:
            return self._alert(
                f"error budget burning {fast:.1f}x (fast) / "
                f"{slow:.1f}x (slow), threshold {self.threshold:g}x",
                fast_burn=fast, slow_burn=slow)
        return None


def default_rules(objectives: list[dict]) -> list[AlertRule]:
    """The standing rule set ``parquet-tool watch`` arms: one
    burn-rate rule per objective with an error target, plus one
    absence rule on the writer itself."""
    rules = [AlertRule("telemetry_absent", "absence", window_s=60.0,
                       severity="ticket")]
    for o in objectives:
        if o.get("error_rate_target"):
            rules.append(AlertRule(
                f"burn_{o['label']}", "burn_rate", label=o["label"],
                error_rate_target=o["error_rate_target"]))
    return rules


# ----------------------------------------------------------------------
# Engine
# ----------------------------------------------------------------------

class AlertEngine:
    """Holds the rules, the firing state, and the sinks.

    :meth:`evaluate` is level-style (returns everything currently
    firing); sink delivery is edge-style (each alert delivered once
    per firing episode).  Thread-safe — watch loops and the soak
    harness evaluate from wherever."""

    def __init__(self, rules: list[AlertRule] | None = None,
                 sinks: list | None = None,
                 record_path: str | None = None):
        self._lock = threading.Lock()
        self.rules: list[AlertRule] = list(rules or [])
        self.sinks = list(sinks or [])
        self.record_path = (record_path if record_path is not None
                            else alerts_export_default())
        self._firing: dict[tuple, float] = {}   # key -> since ts

    def evaluate(self, frames: list[dict],
                 now: float | None = None) -> list[dict]:
        """Run every rule; return the currently-firing alerts (each
        carrying ``ts`` and ``since``); deliver newly-firing ones to
        the sinks and the durable record."""
        if now is None:
            now = time.time()
        firing: list[dict] = []
        fresh: list[dict] = []
        with self._lock:
            seen = set()
            for rule in self.rules:
                a = rule.check(frames, now)
                if a is None:
                    continue
                key = (a["name"], a.get("label"))
                seen.add(key)
                new = key not in self._firing
                if new:
                    self._firing[key] = now
                a["ts"] = now
                a["since"] = self._firing[key]
                firing.append(a)
                if new:
                    fresh.append(a)
            self._firing = {k: t for k, t in self._firing.items()
                            if k in seen}
        for a in fresh:
            self._deliver(a)
        return firing

    def emit(self, alert: dict) -> None:
        """Push path: deliver an ad-hoc alert (``emit_alert`` hook)
        straight to the sinks + record, no rule involved."""
        alert.setdefault("ts", time.time())
        alert.setdefault("severity", "page")
        self._deliver(alert)

    def _deliver(self, alert: dict) -> None:
        record_alert(self.record_path, alert)
        for sink in self.sinks:
            try:
                sink(alert)
            except Exception:
                pass  # a broken sink must not break the others


# ----------------------------------------------------------------------
# Module gate — the one-is-None idiom (recorder/trace/faults shape)
# ----------------------------------------------------------------------

_lock = threading.Lock()

#: The active engine, or None when alerting is off — the single gate
#: the push-path hook checks.  Armed from ``TPQ_ALERTS_EXPORT`` at
#: import; reconfigure with :func:`set_engine`.
_active: AlertEngine | None = None


def _init_from_env() -> None:
    global _active
    path = alerts_export_default()
    with _lock:
        _active = AlertEngine(record_path=path) if path else None


_init_from_env()


def engine() -> AlertEngine | None:
    """The active engine (None when alerting is off)."""
    return _active


def set_engine(e: AlertEngine | None) -> AlertEngine | None:
    """Runtime reconfigure (tests / the soak harness / watch)."""
    global _active
    with _lock:
        _active = e
        return _active


def emit_alert(name: str, severity: str = "page", **fields) -> None:
    """Instrumentation hook: push one ad-hoc alert.  No-op (one
    global ``is None`` check) when alerting is off.  Hot sites guard
    the CALL itself (``_alerts._active is not None``) per the
    recorder-guard discipline."""
    eng = _active
    if eng is not None:
        a = {"name": name, "severity": severity, "kind": "emit"}
        a.update(fields)
        eng.emit(a)
