"""Automatic post-mortems: dump the evidence when something dies.

When a quarantine, salvage, or deadline event fires, the scan drivers
call :func:`record_incident` and a ``.postmortem.json`` lands beside
the durable cursor checkpoint (or under ``TPQ_POSTMORTEM_DIR`` for
checkpoint-less scans).  Each incident carries everything an operator
needs to start the investigation without reproducing the failure:

* the **trigger** — kind, site, and the exact
  file/row-group/column/page coordinates plus error class/message the
  quarantine entry recorded;
* the trailing **flight-recorder ring**
  (:mod:`~tpuparquet.obs.recorder`) — what every thread was doing in
  the moments before;
* a **metrics snapshot** of the live registry
  (:mod:`~tpuparquet.obs.live`) — cumulative counters at incident
  time;
* process identity and wall-clock timestamps.

File format (spec — the README documents this verbatim)::

    {
      "format": "tpq-postmortem",
      "version": 1,
      "incidents": [                     // oldest first, capped
        {
          "t": 1700000000.123,           // unix seconds
          "iso": "2023-11-14T22:13:20Z",
          "pid": 4242,
          "trigger": {"kind": "quarantined",
                      "site": "shard.scan.unit",
                      "unit": 3, "file": 1, "row_group": 0,
                      "column": "fare", "page": 2,
                      "error": "CorruptPageError",
                      "message": "..."},
          "recorder": [ {"t": ..., "kind": ..., "site": ..., ...} ],
          "metrics": {"counters": {...}, "gauges": {...},
                      "hists": {...}},
          "stats": {...} | null      // in-flight DecodeStats.to_state()
        }
      ]
    }

Writes are read-modify-write with the atomic tmp + ``os.replace``
discipline of the checkpoint layer, capped at :data:`INCIDENT_CAP`
incidents (oldest dropped) so a pathological corpus cannot grow the
file without bound.  Post-mortems are best-effort telemetry: an
``OSError`` writing one is swallowed — the quarantine/deadline event
it describes already handled the failure.
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["record_incident", "postmortem_path_for", "load_postmortem",
           "INCIDENT_CAP", "POSTMORTEM_SUFFIX"]

POSTMORTEM_SUFFIX = ".postmortem.json"
INCIDENT_CAP = 16

# serializes the load-append-write below: two scans in one process can
# share a post-mortem file (TPQ_POSTMORTEM_DIR keys on pid alone), and
# an unlocked read-modify-write would silently drop the loser's
# incident even with atomic replaces
_write_lock = threading.Lock()

#: recorder records attached per incident (the trailing window)
_RECORDER_TAIL = 128


def postmortem_path_for(checkpoint_path: str | None) -> str | None:
    """Resolve where a scan's post-mortems go: beside the durable
    cursor checkpoint when one is configured, else under
    ``TPQ_POSTMORTEM_DIR`` (one file per process), else nowhere
    (None — post-mortems off)."""
    if checkpoint_path:
        return checkpoint_path + POSTMORTEM_SUFFIX
    d = os.environ.get("TPQ_POSTMORTEM_DIR")
    if d:
        return os.path.join(d, f"scan-{os.getpid()}{POSTMORTEM_SUFFIX}")
    return None


def record_incident(path: str | None, trigger: dict) -> str | None:
    """Append one incident to the post-mortem file at ``path``
    (no-op returning None when ``path`` is None).  Returns the path
    on success; swallows ``OSError`` (best-effort — see module
    docstring)."""
    if not path:
        return None
    from ..stats import current_stats
    from .live import registry
    from .recorder import recorder

    rec = recorder()
    now = time.time()
    st = current_stats()
    incident = {
        "t": now,
        "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(now)),
        "pid": os.getpid(),
        "trigger": _jsonable(trigger),
        "recorder": ([] if rec is None
                     else rec.snapshot(last=_RECORDER_TAIL)),
        "metrics": registry().snapshot(),
        # the in-flight collector (scan-ambient or user scope): exact
        # counters AT incident time, ahead of the unit-boundary fold
        "stats": None if st is None else st.to_state(),
    }
    from .live import atomic_write_text

    with _write_lock:
        try:
            doc = load_postmortem(path)
        except (OSError, ValueError):
            doc = {"format": "tpq-postmortem", "version": 1,
                   "incidents": []}
        doc["incidents"].append(incident)
        del doc["incidents"][:-INCIDENT_CAP]
        body = json.dumps(doc, sort_keys=True, default=str)
        return path if atomic_write_text(path, body) else None


def load_postmortem(path: str) -> dict:
    """Read back a post-mortem file, validating the envelope."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) \
            or doc.get("format") != "tpq-postmortem" \
            or not isinstance(doc.get("incidents"), list):
        raise ValueError(f"{path!r} is not a tpq post-mortem file")
    return doc


def _jsonable(d: dict) -> dict:
    """Coerce a trigger dict to JSON-safe values (error objects and
    exotic coordinates stringify rather than fail the dump)."""
    out = {}
    for k, v in d.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        else:
            out[k] = str(v)
    return out
