"""Continuous sampling profiler with trace-correlated attribution.

The round-16 doctor names the bounding *stage* of a scan and the
round-16 sentinel detects that a leg got slower — but neither can name
the *function* responsible.  This module closes that gap the way
production fleets do (Google-Wide Profiling, Ren et al., IEEE Micro
2010): a background daemon walks ``sys._current_frames()`` on a
grid-jittered cadence (``TPQ_PROFILE``, ``TPQ_PROFILE_HZ``; default
off) and aggregates per-thread stack samples into a mergeable
per-``(label, stage)`` stack trie.

Every sample is tagged with the ambient causal context.  Contextvars
cannot be read across threads, so the profiler keeps its own mirror:
:func:`ctx_push`/:func:`ctx_pop` (called from the round-16 tracer at
every context push/pop/adopt) maintain a per-thread stack of open
``(trace, span, name)`` entries plus a bounded ``trace → label`` map,
and :func:`stage_begin`/:func:`stage_end` let the hot stage regions
that only ``emit_span`` *after* measuring (chunk reads, page
encode/compress, gathers) declare their stage while the work runs.

**Off-CPU** samples are classified separately ("The Tail at Scale"
motivates the wait half): :func:`wait_begin`/:func:`wait_end` mark a
thread as blocked, and the sampler appends a synthetic leaf frame so
the wait shows up as a first-class frame in every flame view —

* lock acquisition: the round-19 lockcheck wrappers install the wait
  hooks (``lockcheck.set_wait_hooks``) when the profiler arms, so a
  contended acquire is attributed to the lockcheck *site identity*
  (``relpath:lineno`` of the ``threading.Lock()`` creation call) as
  ``[lock-wait <site>]``;
* IO stalls: the chunk fetch path marks ``io.*`` waits, so a hung
  read (the seeded ``io.chunk.hang`` fault included) samples as
  ``[io-wait io.reader.chunk_read]`` under the ``read`` stage.

Exactness discipline matches every other obs structure: bucket counts
and the ``profile_samples`` / ``profile_samples_offcpu`` /
``profile_drops`` counters are integers, folds are elementwise adds
(``to_state``/:func:`merge_profile_states`), and
``shard.distributed.allgather_profiles`` folds hosts over the same
JSON-over-``allgather_bytes`` wire as digests.  Export is atomic and
suffix-routed like trace files (:func:`write_profile_file`):
``*.collapsed`` → collapsed-stack text (flamegraph.pl /
speedscope-ready), ``*.chrome.json``/``*.perfetto.json`` → Chrome
trace events, anything else → the native ``tpq-profile`` envelope
``parquet-tool flame``/``doctor --profile`` read.

Cost model — the recorder/tracer discipline exactly: off (default),
every entry point is one module-global load + ``is None`` check, and
hot sites guard the CALL itself (``if _profiler._active is not
None:``) so not even arguments are built; enforced structurally by the
``tools/analyze`` recorder-guard pass.  Armed, the sampler owns the
walk cost (~tens of microseconds per pass at default 50 Hz) and the
instrumented threads pay only dict/list pokes at span/stage/wait
boundaries — never per value.

Teardown: the atexit flush serializes with the round-17 snapshot
writer's final flush via the shared :data:`live._flush_lock`, so a
profile export can never interleave with (or truncate) a timeseries
ring frame.
"""

from __future__ import annotations

import json
import os
import random
import sys
import threading
import time
from collections import deque

from .attribution import STAGE_OF

__all__ = [
    "Profiler", "profiler", "set_profiling", "profile_default",
    "profile_hz_default", "profile_export_default",
    "ctx_push", "ctx_pop", "span_note", "stage_begin", "stage_end",
    "wait_begin", "wait_end",
    "merge_profile_states", "write_profile_file", "load_profile_file",
    "collapsed_lines", "top_frames", "diff_states",
    "profile_consistency", "final_flush", "export_now",
]

PROFILE_FILE_FORMAT = "tpq-profile"

_DEFAULT_HZ = 50.0
_MAX_DEPTH = 96        # frames kept per sampled stack
_MAX_LABELS = 512      # bounded trace -> label map
_MAX_SPAN_STAGES = 4096  # bounded (trace, span) -> stage map
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def profile_default() -> bool:
    """Profiler master switch (``TPQ_PROFILE``, default off — the
    armed sampler owns a thread, so arming is an explicit choice)."""
    return os.environ.get("TPQ_PROFILE", "0") != "0"


def profile_hz_default() -> float:
    """Sampling cadence from ``TPQ_PROFILE_HZ`` (default 50; clamped
    to [1, 1000] — above 1 kHz the walk cost dominates the signal)."""
    try:
        v = float(os.environ.get("TPQ_PROFILE_HZ", ""))
    except ValueError:
        return _DEFAULT_HZ
    if v <= 0:
        return _DEFAULT_HZ
    return min(max(v, 1.0), 1000.0)


def profile_export_default() -> str | None:
    """Flush/exit profile export path (``TPQ_PROFILE_EXPORT``;
    None=off)."""
    return os.environ.get("TPQ_PROFILE_EXPORT") or None


def _short_path(fn: str, cache: dict) -> str:
    s = cache.get(fn)
    if s is None:
        try:
            rel = os.path.relpath(fn, _REPO_ROOT)
        except ValueError:
            rel = fn
        if rel.startswith(".."):
            rel = os.path.basename(fn)
        s = cache[fn] = rel.replace(os.sep, "/")
    return s


class Profiler:
    """The armed sampler: per-``(label, stage)`` stack buckets with
    exact integer counts, the per-thread tag mirror the tracer feeds,
    and the wait/stage marker state.

    Thread model: the tag mirror (``_threads``/``_stages``/``_waits``)
    is written by the instrumented threads themselves (plain dict/list
    pokes — GIL-atomic, no locks on the instrumented path) and read by
    the sampler, which tolerates a momentarily-stale tag (a sample is
    a statistical observation, not a ledger entry).  The BUCKETS are
    the ledger: only the sampler writes them, under ``_lock``, and
    every snapshot/merge is an exact integer fold."""

    def __init__(self, hz: float = _DEFAULT_HZ):
        self.hz = float(hz)
        self.period = 1.0 / self.hz
        self._lock = threading.Lock()
        # (label, stage) -> {"samples", "offcpu", "stacks": {str: int}}
        self._buckets: dict = {}
        self.samples = 0
        self.samples_offcpu = 0
        self.drops = 0
        # tag mirror (written by instrumented threads, read by sampler)
        self._threads: dict = {}   # tid -> [(trace, span, name, stage)]
        self._stages: dict = {}    # tid -> [stage, ...] (hot-site hints)
        self._waits: dict = {}     # tid -> (kind, site)
        self._labels: dict = {}    # trace -> label (bounded)
        self._span_stage: dict = {}  # (trace, span) -> stage (bounded)
        # recent sample tags, for correlation checks and the live brief
        self.recent: deque = deque(maxlen=512)
        self._path_cache: dict = {}
        self._rng = random.Random(os.getpid())
        self._t0 = time.monotonic()
        self._rate_win: deque = deque(maxlen=64)  # (t, samples_total)
        self._pushed: dict = {}    # registry-mirror baselines
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- sampler lifecycle -------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        # Shrink the interpreter switch interval while armed: the
        # sampler needs the GIL to walk frames, and at the default 5ms
        # it acquires it preferentially when instrumented threads sit
        # in GIL-RELEASING C calls — every sample scheduled during a
        # pure-Python stretch slides forward into the next C call,
        # over-counting C-heavy stages ~1.3x (measured on the dispatch
        # stage).  A switch interval well under the sampling period
        # bounds that relocation to noise.  Restored on stop().
        self._prev_switch = sys.getswitchinterval()
        sys.setswitchinterval(max(min(self._prev_switch,
                                      self.period / 10.0), 1e-4))
        self._stop.clear()
        t = threading.Thread(target=self._run, daemon=True,
                             name="tpq-profiler")
        self._thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        prev = getattr(self, "_prev_switch", None)
        if prev is not None:
            self._prev_switch = None
            sys.setswitchinterval(prev)

    def _delay(self) -> float:
        """One inter-sample sleep: the period grid jittered across the
        FULL period — uniform in ``[0.5p, 1.5p]``, mean exactly ``p``
        (the configured cadence), with the sample phase doing a random
        walk whose stationary distribution is uniform over the grid
        cell.  Small jitter is not enough: scan units run ~one sampler
        period long, and a phase that only wobbles 25% of the grid
        stays correlated with that structure for many samples,
        over-counting whichever stage beats against it (measured 1.4x
        on the dispatch stage before this went full-period)."""
        return self.period * (0.5 + self._rng.random())

    def _run(self) -> None:
        while True:
            d = self._delay()
            due = time.monotonic() + d
            if self._stop.wait(d):
                return
            # Late-wakeup censoring: when the wait expires while an
            # instrumented thread holds the GIL (a long native call),
            # this thread only runs once that call RELEASES it — so a
            # late pass observes the process exactly at a GIL-release
            # boundary, not at its scheduled instant, over-counting
            # whichever code releases the GIL (measured +37% on the
            # dispatch stage).  A pass that fires well past its due
            # time is a biased observation: record a drop instead of
            # a sample (the "no drops" certificate stays honest).
            if time.monotonic() - due > 0.25 * self.period:
                with self._lock:
                    self.drops += 1
                continue
            try:
                self.sample_once()
            except Exception:
                # the profiler must never take down the process it
                # observes; a failed pass is a dropped sample
                with self._lock:
                    self.drops += 1

    # -- one sampling pass -------------------------------------------------

    def _stack_of(self, frame) -> list[str]:
        cache = self._path_cache
        out: list[str] = []
        f = frame
        while f is not None and len(out) < _MAX_DEPTH:
            co = f.f_code
            out.append(f"{_short_path(co.co_filename, cache)}:"
                       f"{co.co_name}")
            f = f.f_back
        out.reverse()
        return out

    def _tag_of(self, tid: int):
        """(trace, span, label, stage) for one sampled thread, from
        the mirror — reads race the owner thread's pokes by design
        (worst case: one sample carries the just-closed tag)."""
        trace = span = None
        label = ""
        stage = None
        stk = self._threads.get(tid)
        if stk:
            try:
                trace, span = stk[-1][0], stk[-1][1]
                for ent in reversed(stk):
                    if ent[3] is not None:
                        stage = ent[3]
                        break
            except IndexError:
                pass  # emptied between check and read
        if trace is not None:
            label = self._labels.get(trace, "")
        hints = self._stages.get(tid)
        if hints:
            try:
                stage = hints[-1]
            except IndexError:
                pass
        return trace, span, label, stage

    def sample_once(self, now: float | None = None) -> int:
        """Walk every thread once; returns the samples recorded.
        Public so tests (and the sentinel's bounded capture) can drive
        the sampler deterministically without wall-clock waits."""
        t_wall = time.perf_counter()
        me = threading.get_ident()
        sampler = self._thread.ident if self._thread is not None else me
        frames = sys._current_frames()
        batch = []
        for tid, frame in frames.items():
            if tid == me or tid == sampler:
                continue
            trace, span, label, stage = self._tag_of(tid)
            wait = self._waits.get(tid)
            stack = self._stack_of(frame)
            offcpu = False
            if wait is not None:
                offcpu = True
                kind, site = wait
                stack.append(f"[{kind}-wait {site}]")
                if stage is None and kind == "io":
                    stage = "read"
            if stage is None:
                stage = "other"
            batch.append((label, stage, ";".join(stack), offcpu,
                          trace, span, stack[-1]))
        alive = frames.keys()
        with self._lock:
            for label, stage, stack, offcpu, trace, span, leaf in batch:
                b = self._buckets.get((label, stage))
                if b is None:
                    b = self._buckets[(label, stage)] = {
                        "samples": 0, "offcpu": 0, "stacks": {}}
                st = b["stacks"]
                st[stack] = st.get(stack, 0) + 1
                b["samples"] += 1
                self.samples += 1
                if offcpu:
                    b["offcpu"] += 1
                    self.samples_offcpu += 1
                self.recent.append({
                    "t": t_wall, "trace": trace, "span": span,
                    "label": label, "stage": stage, "offcpu": offcpu,
                    "leaf": leaf})
            # mirror-state hygiene rides the sampler (single writer):
            # dead threads' tags go, and the label map stays bounded
            for d in (self._threads, self._stages, self._waits):
                for tid in [t for t in d if t not in alive]:
                    d.pop(tid, None)
            while len(self._labels) > _MAX_LABELS:
                self._labels.pop(next(iter(self._labels)), None)
            while len(self._span_stage) > _MAX_SPAN_STAGES:
                self._span_stage.pop(next(iter(self._span_stage)),
                                     None)
            self._rate_win.append((time.monotonic(), self.samples))
        elapsed = time.perf_counter() - t_wall
        if elapsed > self.period:
            # the walk overran the cadence: the grid points we slept
            # through are samples that never happened — count them so
            # "no drops" certifies a complete sampling record
            with self._lock:
                self.drops += int(elapsed / self.period)
        self._mirror_registry()
        return len(batch)

    def _mirror_registry(self) -> None:
        """Push counter deltas + live gauges into the process metrics
        registry so ring frames (``parquet-tool watch``) and snapshot
        exports see the profiler without a dedicated surface.  Exact:
        deltas from remembered baselines, applied on the sampler's own
        shard."""
        from . import live as _live

        if not _live.live_enabled():
            return
        reg = _live._registry
        base = self._pushed
        for name, v in (("profile_samples", self.samples),
                        ("profile_samples_offcpu", self.samples_offcpu),
                        ("profile_drops", self.drops)):
            d = v - base.get(name, 0)
            if d:
                reg.counter(name, d)
                base[name] = v
        br = self.brief()
        reg.gauge("profile_rate_hz", br["rate_hz"])
        reg.gauge("profile_offcpu_share", br["offcpu_share"])
        if br["top_frame"]:
            reg.gauge("profile_top_frame", br["top_frame"])

    # -- reading -----------------------------------------------------------

    def brief(self) -> dict:
        """The one-line live summary ``top``/``watch`` render:
        cumulative counters, the observed sample rate over the recent
        window, the off-CPU share, and the top self-time frame."""
        with self._lock:
            samples = self.samples
            offcpu = self.samples_offcpu
            drops = self.drops
            win = list(self._rate_win)
            top = None
            best = 0
            for b in self._buckets.values():
                for stack, n in b["stacks"].items():
                    leaf = stack.rsplit(";", 1)[-1]
                    if n > best:
                        best, top = n, leaf
        if len(win) >= 2 and win[-1][0] > win[0][0]:
            rate = (win[-1][1] - win[0][1]) / (win[-1][0] - win[0][0])
        else:
            up = max(time.monotonic() - self._t0, 1e-9)
            rate = samples / up
        return {
            "samples": samples,
            "offcpu": offcpu,
            "drops": drops,
            "rate_hz": round(rate, 2),
            "offcpu_share": round(offcpu / samples, 4) if samples else 0.0,
            "top_frame": top,
            "period_s": self.period,
        }

    def to_state(self) -> dict:
        """JSON-serializable exact state: the counters, the period,
        and the buckets nested ``{label: {stage: {...}}}``."""
        with self._lock:
            buckets: dict = {}
            for (label, stage), b in sorted(self._buckets.items()):
                buckets.setdefault(label, {})[stage] = {
                    "samples": b["samples"],
                    "offcpu": b["offcpu"],
                    "stacks": dict(b["stacks"]),
                }
            return {
                "period_s": self.period,
                "hz": self.hz,
                "counters": {
                    "profile_samples": self.samples,
                    "profile_samples_offcpu": self.samples_offcpu,
                    "profile_drops": self.drops,
                },
                "buckets": buckets,
            }

    def merge_state(self, d: dict) -> None:
        """Exact fold of another profiler's ``to_state`` into this
        one (elementwise integer adds, the digest discipline)."""
        with self._lock:
            c = d.get("counters") or {}
            self.samples += int(c.get("profile_samples", 0))
            self.samples_offcpu += int(
                c.get("profile_samples_offcpu", 0))
            self.drops += int(c.get("profile_drops", 0))
            for label, stages in (d.get("buckets") or {}).items():
                for stage, sb in stages.items():
                    b = self._buckets.get((label, stage))
                    if b is None:
                        b = self._buckets[(label, stage)] = {
                            "samples": 0, "offcpu": 0, "stacks": {}}
                    b["samples"] += int(sb.get("samples", 0))
                    b["offcpu"] += int(sb.get("offcpu", 0))
                    st = b["stacks"]
                    for stack, n in (sb.get("stacks") or {}).items():
                        st[stack] = st.get(stack, 0) + int(n)


# ----------------------------------------------------------------------
# Module gate — the one-is-None idiom (recorder/trace/digest shape)
# ----------------------------------------------------------------------

_lock = threading.Lock()

#: The active profiler, or None when off — the single gate every
#: entry point checks (one global load + ``is None``).  Armed from the
#: environment at import; reconfigure at runtime with
#: :func:`set_profiling`.
_active: Profiler | None = None

_atexit_registered = False


def profiler() -> Profiler | None:
    """The active profiler (None when off)."""
    return _active


def _install_hooks(p: Profiler | None) -> None:
    from .. import lockcheck as _lockcheck

    if p is None:
        _lockcheck.set_wait_hooks(None, None)
    else:
        _lockcheck.set_wait_hooks(wait_begin, wait_end)


def set_profiling(on: bool = True, *, hz: float | None = None,
                  start: bool = True) -> Profiler | None:
    """Runtime reconfigure: ``True`` installs a FRESH profiler (and
    starts its sampler thread unless ``start=False`` — tests drive
    ``sample_once`` by hand), ``False`` disarms and stops the sampler.
    Arming installs the lockcheck wait hooks and registers the atexit
    flush; returns the new profiler."""
    global _active, _atexit_registered
    with _lock:
        old = _active
        if old is not None:
            _active = None
            old.stop()
        if not on:
            _install_hooks(None)
            return None
        p = Profiler(hz if hz is not None else profile_hz_default())
        _active = p
        _install_hooks(p)
        if not _atexit_registered:
            import atexit

            atexit.register(final_flush)
            _atexit_registered = True
        if start:
            p.start()
        return p


def _init_from_env() -> None:
    if profile_default():
        set_profiling(True)


# (the env arming itself happens at the END of the module: arming
# installs wait_begin/wait_end into lockcheck, so every hook must be
# defined first)


# ----------------------------------------------------------------------
# Tag-mirror hooks (fed by obs.trace at every context transition)
# ----------------------------------------------------------------------

def ctx_push(trace, span, name, label=None) -> None:
    """Mirror one ambient-context push for the sampler.  Called from
    ``start_trace``/``open_span(push=True)``/``adopt`` under the
    tracer's own ``_active`` guard; cheap (one list append) and
    per-span, never per value."""
    p = _active
    if p is None:
        return
    tid = threading.get_ident()
    stk = p._threads.get(tid)
    if stk is None:
        stk = p._threads[tid] = []
    if name is not None:
        stage = STAGE_OF.get(name)
        p._span_stage[(trace, span)] = stage
    else:
        # an adopt joins a span opened elsewhere — resolve its stage
        # from the side-map the opening site registered
        stage = p._span_stage.get((trace, span))
    stk.append((trace, span, name, stage))
    if label is not None:
        p._labels[trace] = label


def ctx_pop(trace, span) -> None:
    """Mirror the matching pop: drops the entry (and anything stacked
    above it — a non-LIFO close truncates defensively, matching the
    tracer's own conditional-reset semantics)."""
    p = _active
    if p is None:
        return
    stk = p._threads.get(threading.get_ident())
    if not stk:
        return
    for i in range(len(stk) - 1, -1, -1):
        if stk[i][0] == trace and stk[i][1] == span:
            del stk[i:]
            return


def span_note(trace, span, name) -> None:
    """Register a ``push=False`` span's stage without touching any
    thread's mirror (the opener's ambient context is deliberately left
    alone) — workers that later :func:`adopt` the span's ctx then
    resolve its stage.  Called from ``open_span`` under the tracer's
    guard."""
    p = _active
    if p is None:
        return
    p._span_stage[(trace, span)] = STAGE_OF.get(name)


def stage_begin(stage: str):
    """Declare the calling thread to be inside a pipeline stage for
    the duration of a region (the hot stages — chunk reads, page
    encode/compress, gathers — only ``emit_span`` after measuring, so
    the span mirror alone can't see them while they run).  Returns a
    token for :func:`stage_end`; hot sites guard the CALL with
    ``_profiler._active is not None`` (recorder-guard discipline)."""
    p = _active
    if p is None:
        return None
    tid = threading.get_ident()
    lst = p._stages.get(tid)
    if lst is None:
        lst = p._stages[tid] = []
    lst.append(stage)
    return (p, tid)


def stage_end(token) -> None:
    """Close a :func:`stage_begin` region (None token = profiler was
    off at entry; a token from a since-replaced profiler pops its own
    instance's state — exempt from the guard rule like
    ``close_span``: handle-taking, no kwargs)."""
    if token is None:
        return
    p, tid = token
    lst = p._stages.get(tid)
    if lst:
        try:
            lst.pop()
        except IndexError:
            pass


def wait_begin(kind: str, site: str):
    """Mark the calling thread as blocked (off-CPU) at ``site`` until
    :func:`wait_end`.  ``kind`` is ``"lock"`` (installed into the
    lockcheck wrappers when the profiler arms — ``site`` is the lock's
    creation-site identity) or ``"io"`` (the chunk fetch path).
    Nested waits restore the outer marker on exit."""
    p = _active
    if p is None:
        return None
    tid = threading.get_ident()
    prev = p._waits.get(tid)
    p._waits[tid] = (kind, site)
    return (p, tid, prev)


def wait_end(token) -> None:
    if token is None:
        return
    p, tid, prev = token
    if prev is None:
        p._waits.pop(tid, None)
    else:
        p._waits[tid] = prev


# ----------------------------------------------------------------------
# State algebra (cross-host folds) + analysis
# ----------------------------------------------------------------------

def _empty_state() -> dict:
    return {"period_s": 0.0, "hz": 0.0,
            "counters": {"profile_samples": 0,
                         "profile_samples_offcpu": 0,
                         "profile_drops": 0},
            "buckets": {}}


def merge_profile_states(states: list[dict]) -> dict:
    """Fold per-host ``to_state`` dicts into one exact fleet-wide
    state (counters and bucket/stack counts sum elementwise — the
    single-host profile of the union run).  The period comes from the
    first state carrying one; mixed-cadence merges keep their counts
    exact but the seconds view uses that first period."""
    out = _empty_state()
    for d in states:
        if not d:
            continue
        if not out["period_s"] and d.get("period_s"):
            out["period_s"] = float(d["period_s"])
            out["hz"] = float(d.get("hz") or 0.0)
        c = d.get("counters") or {}
        for k in out["counters"]:
            out["counters"][k] += int(c.get(k, 0))
        for label, stages in (d.get("buckets") or {}).items():
            for stage, sb in stages.items():
                b = out["buckets"].setdefault(label, {}).setdefault(
                    stage, {"samples": 0, "offcpu": 0, "stacks": {}})
                b["samples"] += int(sb.get("samples", 0))
                b["offcpu"] += int(sb.get("offcpu", 0))
                st = b["stacks"]
                for stack, n in (sb.get("stacks") or {}).items():
                    st[stack] = st.get(stack, 0) + int(n)
    return out


def _iter_buckets(state: dict, label=None, stage=None):
    for lb, stages in (state.get("buckets") or {}).items():
        if label is not None and lb != label:
            continue
        for st, b in stages.items():
            if stage is not None and st != stage:
                continue
            yield lb, st, b


def top_frames(state: dict, *, label=None, stage=None,
               n: int = 15) -> list[dict]:
    """Top frames by self samples over the matching buckets.  Each
    row: the frame, self/total sample counts (total counts a frame
    once per stack it appears in), the seconds view at the state's
    period, and the self share of the selection."""
    period = float(state.get("period_s") or 0.0)
    self_c: dict = {}
    total_c: dict = {}
    total = 0
    for _lb, _st, b in _iter_buckets(state, label, stage):
        for stack, cnt in (b.get("stacks") or {}).items():
            frames = stack.split(";")
            total += cnt
            leaf = frames[-1]
            self_c[leaf] = self_c.get(leaf, 0) + cnt
            for f in set(frames):
                total_c[f] = total_c.get(f, 0) + cnt
    rows = []
    for f, s in sorted(self_c.items(), key=lambda kv: (-kv[1], kv[0])):
        rows.append({
            "frame": f,
            "self": s,
            "total": total_c.get(f, s),
            "self_s": round(s * period, 6),
            "total_s": round(total_c.get(f, s) * period, 6),
            "share": round(s / total, 4) if total else 0.0,
        })
        if len(rows) >= n:
            break
    return rows


def diff_states(a: dict, b: dict, *, n: int = 15) -> list[dict]:
    """Weighted stack diff for regression localization: each state's
    stacks normalize to shares of its own sample total (so runs of
    different length compare), then per-frame share deltas (a frame
    counts once per stack) rank what grew from A to B."""
    def shares(state: dict) -> tuple[dict, int]:
        per: dict = {}
        total = 0
        for _lb, _st, bk in _iter_buckets(state):
            for stack, cnt in (bk.get("stacks") or {}).items():
                total += cnt
                for f in set(stack.split(";")):
                    per[f] = per.get(f, 0) + cnt
        return per, total

    pa, ta = shares(a)
    pb, tb = shares(b)
    rows = []
    for f in set(pa) | set(pb):
        sa = pa.get(f, 0) / ta if ta else 0.0
        sb = pb.get(f, 0) / tb if tb else 0.0
        rows.append({"frame": f, "share_a": round(sa, 4),
                     "share_b": round(sb, 4),
                     "delta": round(sb - sa, 4)})
    rows.sort(key=lambda r: (-abs(r["delta"]), r["frame"]))
    return rows[:n]


def profile_consistency(state: dict, stages_s: dict,
                        slack: float = 1.25) -> list[str]:
    """The doctor's cross-check: per-stage sampled seconds
    (samples x period) must not exceed the span-derived stage wall —
    a violation means the profile and the trace describe different
    runs (or the tag mirror is lying).  ``slack`` is multiplicative;
    the additive allowance is Poisson-scale (3 sqrt(n) samples, floor
    two periods): a 0.06s stage at 200 Hz expects ~12 samples with a
    ~3.5-sample standard deviation, so a fixed two-sample allowance
    would fire on pure counting noise while being irrelevant to a
    stage carrying thousands of samples."""
    period = float(state.get("period_s") or 0.0)
    if period <= 0:
        return []
    per_stage: dict = {}
    for _lb, st, b in _iter_buckets(state):
        per_stage[st] = per_stage.get(st, 0) + int(b.get("samples", 0))
    out = []
    for st, cnt in sorted(per_stage.items()):
        wall = float(stages_s.get(st) or 0.0)
        if wall <= 0:
            continue
        sampled = cnt * period
        noise = max(3.0 * (cnt ** 0.5), 2.0) * period
        if sampled > wall * slack + noise:
            out.append(
                f"stage {st}: {sampled:.3f}s of samples exceeds the "
                f"{wall:.3f}s span-derived wall — profile and trace "
                f"disagree")
    return out


# ----------------------------------------------------------------------
# Export surfaces (atomic, suffix-routed — the trace-file discipline)
# ----------------------------------------------------------------------

def collapsed_lines(state: dict) -> list[str]:
    """Collapsed-stack text: ``label;stage;frame;...;frame count``
    per line (flamegraph.pl / speedscope input), label ``-`` for
    untagged samples.  Deterministic order (sorted) so byte-identical
    states render byte-identical files."""
    lines = []
    for lb, st, b in sorted(_iter_buckets(state),
                            key=lambda t: (t[0], t[1])):
        prefix = f"{lb or '-'};{st}"
        for stack, cnt in sorted((b.get("stacks") or {}).items()):
            lines.append(f"{prefix};{stack} {cnt}")
    return lines


def profile_chrome_trace(state: dict) -> dict:
    """The aggregate trie as Chrome trace events: one track per
    ``(label, stage)``, stacks laid out sequentially with width
    ``count x period`` and frames nested by depth — a flamegraph a
    Perfetto tab can open next to the span trace."""
    period_us = float(state.get("period_s") or 0.0) * 1e6
    events = []
    tracks = []
    for lb, st, b in sorted(_iter_buckets(state),
                            key=lambda t: (t[0], t[1])):
        tid = len(tracks)
        tracks.append((lb, st))
        events.append({
            "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
            "args": {"name": f"{lb or '-'}/{st}"}})
        cursor = 0.0
        stacks = sorted((b.get("stacks") or {}).items(),
                        key=lambda kv: (-kv[1], kv[0]))
        for stack, cnt in stacks:
            width = max(cnt * period_us, 1.0)
            for depth, frame in enumerate(stack.split(";")):
                events.append({
                    "name": frame, "cat": "profile", "ph": "X",
                    "ts": round(cursor + depth * 0.01, 2),
                    "dur": round(max(width - depth * 0.02, 0.01), 2),
                    "pid": 0, "tid": tid,
                    "args": {"samples": cnt}})
            cursor += width
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_profile_file(state: dict, path: str) -> bool:
    """Publish a profile state atomically (tmp + ``os.replace`` via
    :func:`~tpuparquet.obs.live.atomic_write_text` — telemetry must
    never fail the work it describes).  Format by suffix:
    ``*.collapsed`` → collapsed-stack text, ``*.chrome.json`` /
    ``*.perfetto.json`` → Chrome trace events, else the native
    ``tpq-profile`` envelope ``parquet-tool flame`` reads."""
    from .live import atomic_write_text

    if path.endswith(".collapsed"):
        body = "\n".join(collapsed_lines(state)) + "\n"
    elif path.endswith((".chrome.json", ".perfetto.json")):
        body = json.dumps(profile_chrome_trace(state), sort_keys=True)
    else:
        obj = {"format": PROFILE_FILE_FORMAT, "version": 1, **state}
        body = json.dumps(obj, sort_keys=True)
    return atomic_write_text(path, body)


def load_profile_file(path: str) -> dict:
    """Read back a native ``tpq-profile`` envelope (the analysis
    surfaces need the exact state; collapsed/Chrome exports are
    one-way render targets).  Raises ``ValueError`` otherwise."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"profile file {path!r} is not valid JSON: {e}") from e
    if isinstance(doc, dict) and doc.get("format") == PROFILE_FILE_FORMAT:
        return doc
    raise ValueError(f"{path!r} is not a tpq profile export")


def export_now(path: str | None = None) -> str | None:
    """Write the active profiler's state (atomic); returns the path
    written, or None when the profiler is off or no path is
    configured (``TPQ_PROFILE_EXPORT``)."""
    p = _active
    if p is None:
        return None
    if path is None:
        path = profile_export_default()
    if not path:
        return None
    return path if write_profile_file(p.to_state(), path) else None


def final_flush() -> None:
    """The atexit flush: one last export, serialized with the
    round-17 snapshot writer's final flush through the shared
    :data:`live._flush_lock` so a profile export can never interleave
    with a metrics/timeseries frame mid-write.  Callable directly
    (tests, explicit shutdown)."""
    from . import live as _live

    if _active is None:
        return
    with _live._flush_lock:
        export_now()


_init_from_env()
