"""Mergeable latency quantile digests with exact cross-host merges.

The log2 :class:`~tpuparquet.obs.histogram.Histogram` answers "p99
page is 1-2 MB" — factor-of-two resolution, fine for sizes, too
coarse for latency SLOs ("p99 under 250 ms" and "p99 under 400 ms"
land in the same bucket).  The digest here keeps the property that
makes the histogram fleet-safe — fixed global bucket boundaries, so
merging is elementwise integer addition, no re-binning, no float
error, identical totals regardless of merge order — but subdivides
every octave into 8 sub-buckets keyed by the top four significant
bits: for ``v > 0`` with ``e = v.bit_length() - 1``,

    sub = ((v >> (e - 3)) if e >= 3 else (v << (3 - e))) - 8
    idx = e * 8 + sub + 1          # idx 0 holds exactly 0

Bucket width is ``lo/8``, i.e. every reported quantile bound is
within ~6% relative error of the true value — t-digest-grade
accuracy for tail latencies, with none of t-digest's merge-order
dependence (two t-digests merged A+B and B+A disagree; these never
do, which is what lets the soak harness assert per-label digests sum
to process totals *exactly*).

Values are non-negative integers by convention, microseconds for the
latency digests the scan drivers feed (``unit``/``scan`` stages per
scan label).  Each bucket optionally keeps one **exemplar** — the
first ``(trace, value, coords)`` observed in it — linking a hot
latency bucket straight to a round-16 causal trace id
(``parquet-tool trace``).  Exemplars are debugging breadcrumbs, not
counters: merges keep the existing exemplar and adopt missing ones,
so they ride along without being part of the exact-merge contract.

Collection discipline matches every other obs structure: per-thread
shards in a :class:`~tpuparquet.obs.recorder.ThreadSlots` (no locks
on the observe path), snapshot folds are exact, cross-host
aggregation goes through ``to_state``/``merge_state`` over the same
``allgather_bytes`` wire as metrics and ledgers
(``shard.distributed.allgather_digests``).  The module gate is the
one-is-None idiom: ``TPQ_LATENCY_DIGEST=1`` arms :data:`_active`;
hot sites guard the call itself (``_digest._active is not None``) so
the disabled path is one global load + ``is None``.
"""

from __future__ import annotations

import os
import threading

__all__ = ["QuantileDigest", "DigestRegistry", "observe", "digests",
           "set_digests", "digest_enabled_default",
           "bucket_index", "bucket_lo", "bucket_hi"]

_SUBS = 8  # sub-buckets per octave (top-4-significant-bits binning)


def bucket_index(value) -> int:
    """Global fixed bucket index of a non-negative integer value
    (negatives clamp to 0).  Index 0 holds exactly 0; octave ``e``
    (values with ``bit_length() == e+1``) spans indices
    ``e*8+1 .. e*8+8``."""
    v = int(value)
    if v <= 0:
        return 0
    e = v.bit_length() - 1
    sub = ((v >> (e - 3)) if e >= 3 else (v << (3 - e))) - _SUBS
    return e * _SUBS + sub + 1


def bucket_lo(idx: int) -> int:
    """Inclusive lower bound of bucket ``idx``."""
    if idx <= 0:
        return 0
    j = idx - 1
    e, sub = divmod(j, _SUBS)
    m = _SUBS + sub
    return (m << (e - 3)) if e >= 3 else (m >> (3 - e))


def bucket_hi(idx: int) -> int:
    """Exclusive upper bound of bucket ``idx``."""
    if idx <= 0:
        return 1
    j = idx - 1
    e, sub = divmod(j, _SUBS)
    m = _SUBS + sub + 1
    if e >= 3:
        return m << (e - 3)
    # low octaves have fewer than 8 distinct integers: several
    # sub-buckets share a floor-divided bound; each occupied bucket
    # still holds exactly one integer
    return max(bucket_lo(idx) + 1, m >> (3 - e))


class QuantileDigest:
    """Sparse counts over the fixed sub-octave buckets, plus the exact
    value sum and sample count, plus one exemplar per bucket.

    ``counts`` is a plain dict keyed by bucket index — latency
    distributions touch a few dozen of the conceptual buckets, so the
    sparse form is both the memory layout and the wire form."""

    __slots__ = ("counts", "n", "total", "exemplars")

    def __init__(self):
        self.counts: dict[int, int] = {}
        self.n = 0
        self.total = 0
        # idx -> {"value": v, "trace": tid?, **coords} (first wins)
        self.exemplars: dict[int, dict] = {}

    def observe(self, value, trace=None, **coords) -> None:
        v = int(value)
        if v < 0:
            v = 0
        i = bucket_index(v)
        c = self.counts
        c[i] = c.get(i, 0) + 1
        self.n += 1
        self.total += v
        if i not in self.exemplars:
            ex = {"value": v}
            if trace is not None:
                ex["trace"] = trace
            if coords:
                ex.update(coords)
            self.exemplars[i] = ex

    def merge_from(self, other: "QuantileDigest") -> None:
        """Exact fold: elementwise integer adds on counts/n/total.
        Exemplars keep ours, adopt theirs for buckets we lack."""
        c = self.counts
        for i, k in other.counts.items():
            c[i] = c.get(i, 0) + k
        self.n += other.n
        self.total += other.total
        for i, ex in other.exemplars.items():
            if i not in self.exemplars:
                self.exemplars[i] = dict(ex)

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def quantile(self, q: float) -> int:
        """Exclusive upper bound of the bucket containing the
        q-quantile — within ~6% relative of the true value."""
        if self.n == 0:
            return 0
        target = q * self.n
        seen = 0
        last = 0
        for i in sorted(self.counts):
            seen += self.counts[i]
            last = i
            if seen >= target:
                return bucket_hi(i)
        return bucket_hi(last)

    def as_dict(self) -> dict:
        return {
            "n": self.n,
            "total": self.total,
            "counts": {str(i): c for i, c in sorted(self.counts.items())},
            "exemplars": {str(i): ex for i, ex in
                          sorted(self.exemplars.items())},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "QuantileDigest":
        g = cls()
        g.n = int(d.get("n", 0))
        g.total = int(d.get("total", 0))
        g.counts = {int(i): int(c)
                    for i, c in (d.get("counts") or {}).items()}
        g.exemplars = {int(i): dict(ex)
                       for i, ex in (d.get("exemplars") or {}).items()}
        return g

    def __repr__(self):
        return (f"QuantileDigest(n={self.n}, total={self.total}, "
                f"p50<{self.quantile(0.5)}, p99<{self.quantile(0.99)})")


def _fold_shard(dst: dict, src: dict) -> None:
    """Exact fold of one thread shard (dead-owner retirement)."""
    for key, g in src.items():
        tot = dst.get(key)
        if tot is None:
            tot = dst[key] = QuantileDigest()
        tot.merge_from(g)


class DigestRegistry:
    """Process-wide digests keyed ``(label, stage)`` with the same
    per-thread-shard exactness discipline as the metrics registry:
    observes land on the calling thread's private dict, snapshots
    fold with exact merges, dead threads retire into a base shard."""

    def __init__(self):
        from .recorder import ThreadSlots

        self._slots = ThreadSlots(make=dict, fold=_fold_shard)

    def observe(self, label: str, stage: str, value,
                trace=None, **coords) -> None:
        shard = self._slots.get()
        g = shard.get((label, stage))
        if g is None:
            g = shard[(label, stage)] = QuantileDigest()
        g.observe(value, trace=trace, **coords)

    def snapshot(self) -> dict:
        """Exact fold of every thread shard:
        ``{(label, stage): QuantileDigest}`` (merged copies)."""
        out: dict = {}
        for shard in self._slots.all():
            for key, g in list(shard.items()):
                tot = out.get(key)
                if tot is None:
                    tot = out[key] = QuantileDigest()
                tot.merge_from(g)
        return out

    # -- exact wire form (cross-host aggregation) ------------------------

    def to_state(self) -> dict:
        """JSON-serializable exact state, nested
        ``{label: {stage: digest_dict}}``."""
        state: dict = {}
        for (label, stage), g in sorted(self.snapshot().items()):
            state.setdefault(label, {})[stage] = g.as_dict()
        return state

    @classmethod
    def from_state(cls, d: dict) -> "DigestRegistry":
        reg = cls()
        shard = reg._slots.get()
        for label, stages in (d or {}).items():
            for stage, gd in stages.items():
                shard[(label, stage)] = QuantileDigest.from_dict(gd)
        return reg

    def merge_state(self, d: dict) -> None:
        """Exact fold of another registry's ``to_state`` into this
        one (bucket-for-bucket adds)."""
        shard = self._slots.get()
        for label, stages in (d or {}).items():
            for stage, gd in stages.items():
                tot = shard.get((label, stage))
                if tot is None:
                    tot = shard[(label, stage)] = QuantileDigest()
                tot.merge_from(QuantileDigest.from_dict(gd))


# ----------------------------------------------------------------------
# Module gate — the one-is-None idiom (recorder/trace/faults shape)
# ----------------------------------------------------------------------

_lock = threading.Lock()

#: The active digest registry, or None when disabled — the single
#: gate every hot-path hook checks.  Armed from the environment at
#: import; reconfigure at runtime with :func:`set_digests`.
_active: DigestRegistry | None = None


def digest_enabled_default() -> bool:
    """Digest master switch (``TPQ_LATENCY_DIGEST``, default off —
    the always-on layer stays within noise of round-16)."""
    return os.environ.get("TPQ_LATENCY_DIGEST", "0") != "0"


def _init_from_env() -> None:
    global _active
    with _lock:
        _active = DigestRegistry() if digest_enabled_default() else None


_init_from_env()


def digests() -> DigestRegistry | None:
    """The active digest registry (None when disabled)."""
    return _active


def set_digests(on: bool) -> DigestRegistry | None:
    """Runtime reconfigure: ``True`` installs a FRESH registry,
    ``False`` disables.  Returns the new registry (tests and the soak
    harness flip this without re-importing)."""
    global _active
    with _lock:
        _active = DigestRegistry() if on else None
        return _active


def observe(label: str, stage: str, value, trace=None, **coords) -> None:
    """Instrumentation hook: record one latency observation.  No-op
    (one global ``is None`` check) when digests are off.

    Hot per-unit sites guard the CALL itself with
    ``_digest._active is not None`` so the disabled path skips even
    argument evaluation — the flight/emit_span discipline, enforced
    structurally by the ``recorder-guard`` analyze pass."""
    reg = _active
    if reg is not None:
        reg.observe(label, stage, value, trace=trace, **coords)
