"""Always-on live metrics: the process-wide MetricsRegistry.

``DecodeStats`` is *post-hoc* telemetry — it exists only inside a
``collect_stats()`` scope and is read after the scan finishes.  A
long-lived serve process needs the other regime: metrics that are
always there, observable *while* work runs and *after* it died,
without anyone having opened a scope first (Dapper's lesson: the
tracing you need most is the tracing that was on before the
incident).

One process-wide :class:`MetricsRegistry` (:func:`registry`) holds

* **counters** — monotonic floats/ints (``pages``, ``values``,
  ``hedges_won``, ``plan_s`` ...), fed by exact folds of every
  outermost ``collect_stats()`` scope (``stats.collect_stats`` calls
  :func:`fold_stats` on exit) and, incrementally per scan unit, by the
  scan drivers' own ambient collectors (``shard/scan.py``) — so a scan
  nobody wrapped in a collector still shows up;
* **gauges** — last-write-wins instantaneous values (scan progress,
  ring sizes);
* **histograms** — the same fixed log2-bucket
  :class:`~tpuparquet.obs.histogram.Histogram` as ``DecodeStats``,
  merged bucket-wise.

Exactness discipline matches ``DecodeStats``: writes land on
**per-thread shards** (no cross-thread ``+=``, no lost increments);
:meth:`~MetricsRegistry.snapshot` folds the shards with integer adds,
so the registry total equals the sum of everything folded into it,
regardless of thread interleaving.  ``to_state``/``from_state``/
``merge_from`` give the exact cross-host wire form
(``shard.distributed.allgather_metrics``): merged host registries
equal the single-host registry of the union corpus, counter for
counter and bucket for bucket.

Export surfaces:

* :meth:`~MetricsRegistry.prometheus_text` — Prometheus text
  exposition (counters as ``tpq_<name>_total``, gauges as
  ``tpq_<name>``, histograms as cumulative ``_bucket{le=...}``
  series at the log2 boundaries);
* :meth:`~MetricsRegistry.as_json` — the same snapshot as JSON;
* an optional background snapshot-writer thread: set
  ``TPQ_METRICS_EXPORT`` to a path (``.json`` → JSON, else
  Prometheus text) and snapshots are written atomically every
  ``TPQ_METRICS_INTERVAL_S`` seconds (default 10) — node-exporter
  textfile-collector style, no HTTP server to babysit.

``TPQ_LIVE_METRICS=0`` disables the folds (the registry then never
moves); the fold itself costs one pass over ~40 fields per outermost
collector scope or scan unit — nothing per page.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .histogram import Histogram

__all__ = [
    "MetricsRegistry",
    "registry",
    "live_enabled",
    "fold_stats",
    "LiveFold",
    "maybe_start_exporter",
    "export_now",
    "reset_registry",
    "atomic_write_text",
]


def atomic_write_text(path: str, body: str) -> bool:
    """Best-effort atomic file publish shared by every always-on
    export surface (metrics snapshots here, progress frames, post-
    mortems): dot-prefixed ``tmp.<pid>`` in the same directory +
    ``os.replace``, so readers only ever see complete files.  Returns
    False (after cleaning the tmp) instead of raising on ``OSError``
    — telemetry must never fail the work it describes.  The durable
    cursor checkpoint (``shard.scan.save_cursor_file``) deliberately
    does NOT use this: it fsyncs and raises, because a checkpoint
    that silently didn't happen is data loss, not missing telemetry."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    # pid AND thread id in the tmp name: the background exporter and
    # an on-demand export_now() may write the same path concurrently,
    # and two writers truncating one shared tmp inode could promote a
    # torn body through os.replace
    tmp = os.path.join(
        d, f".{os.path.basename(path)}.tmp."
           f"{os.getpid()}.{threading.get_ident()}")
    try:
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    return True


def live_enabled() -> bool:
    """Live-metrics master switch (``TPQ_LIVE_METRICS``, default on)."""
    return os.environ.get("TPQ_LIVE_METRICS", "1") != "0"


class _Shard:
    """One thread's private write surface: plain dict writes, no locks
    on the hot path (the GIL serializes dict item ops; the snapshot
    reader tolerates a momentarily-stale view, never a lost add)."""

    __slots__ = ("counters", "hists")

    def __init__(self):
        self.counters: dict = {}
        self.hists: dict[str, Histogram] = {}


def _fold_shard(dst: _Shard, src: _Shard) -> None:
    """Exact fold of one shard into another (dead-shard retirement)."""
    for k, v in src.counters.items():
        dst.counters[k] = dst.counters.get(k, 0) + v
    for k, h in src.hists.items():
        tot = dst.hists.get(k)
        if tot is None:
            tot = dst.hists[k] = Histogram()
        tot.merge_from(h)


class MetricsRegistry:
    """Process-wide counters/gauges/histograms with exact merges.

    Shards live in a :class:`~tpuparquet.obs.recorder.ThreadSlots`
    (per-thread registration, dead-owner retirement folding into one
    base shard — exact, counters are cumulative and a dead thread can
    no longer write), so a serve process running scopes on
    short-lived threads keeps live-threads + 1 shards, not
    threads-ever."""

    def __init__(self):
        from .recorder import ThreadSlots

        self._lock = threading.Lock()  # guards _gauges only
        self._slots = ThreadSlots(make=_Shard, fold=_fold_shard)
        self._gauges: dict = {}

    # -- writing ---------------------------------------------------------

    def _shard(self) -> _Shard:
        return self._slots.get()

    def counter(self, name: str, n=1) -> None:
        """Add ``n`` (int or float seconds) to a monotonic counter."""
        c = self._shard().counters
        c[name] = c.get(name, 0) + n

    def gauge(self, name: str, value) -> None:
        """Set an instantaneous value (last write wins, process-wide)."""
        self._gauges[name] = value

    def hist(self, name: str) -> Histogram:
        """This thread's shard of the named log2 histogram."""
        h = self._shard().hists.get(name)
        if h is None:
            h = self._shard().hists[name] = Histogram()
        return h

    # -- reading ---------------------------------------------------------

    def snapshot(self) -> dict:
        """Exact fold of every thread shard:
        ``{"counters": {...}, "gauges": {...}, "hists": {name:
        Histogram.as_dict()}}``.  Monotonic-read consistent: an
        increment racing the snapshot lands in this snapshot or the
        next, never nowhere."""
        counters: dict = {}
        hists: dict[str, Histogram] = {}
        shards = self._slots.all()
        with self._lock:
            gauges = dict(self._gauges)
        for s in shards:
            for k, v in list(s.counters.items()):
                counters[k] = counters.get(k, 0) + v
            for k, h in list(s.hists.items()):
                tot = hists.get(k)
                if tot is None:
                    tot = hists[k] = Histogram()
                tot.merge_from(h)
        return {
            "counters": counters,
            "gauges": gauges,
            "hists": {k: h.as_dict() for k, h in sorted(hists.items())},
        }

    # -- exact wire form (cross-host aggregation) ------------------------

    def to_state(self) -> dict:
        """JSON-serializable exact state (== :meth:`snapshot`)."""
        return self.snapshot()

    @classmethod
    def from_state(cls, d: dict) -> "MetricsRegistry":
        reg = cls()
        s = reg._shard()
        s.counters.update(d.get("counters") or {})
        for k, h in (d.get("hists") or {}).items():
            s.hists[k] = Histogram.from_dict(h)
        reg._gauges.update(d.get("gauges") or {})
        return reg

    def merge_from(self, other: "MetricsRegistry") -> None:
        """Exact fold of another registry's snapshot into this one
        (counters sum, histograms bucket-wise; gauges last-write-wins
        — they are instantaneous, not cumulative)."""
        snap = other.snapshot()
        s = self._shard()
        for k, v in snap["counters"].items():
            s.counters[k] = s.counters.get(k, 0) + v
        for k, hd in snap["hists"].items():
            h = s.hists.get(k)
            if h is None:
                h = s.hists[k] = Histogram()
            h.merge_from(Histogram.from_dict(hd))
        self._gauges.update(snap["gauges"])

    # -- export surfaces -------------------------------------------------

    def as_json(self) -> str:
        snap = self.snapshot()
        snap["ts"] = time.time()
        return json.dumps(snap, sort_keys=True)

    def prometheus_text(self, prefix: str = "tpq") -> str:
        """Prometheus text exposition format, parseable by any scraper.

        Counters append ``_total`` (convention); histogram buckets are
        cumulative at the log2 upper bounds, sparse below the highest
        non-empty bucket, always closed by ``+Inf``."""
        from .histogram import bucket_hi

        snap = self.snapshot()
        lines: list[str] = []
        for name in sorted(snap["counters"]):
            v = snap["counters"][name]
            m = f"{prefix}_{name}_total"
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {_fmt_value(v)}")
        for name in sorted(snap["gauges"]):
            v = snap["gauges"][name]
            if not isinstance(v, (int, float)) \
                    or isinstance(v, bool):
                continue  # text/labels don't fit the gauge line format
            m = f"{prefix}_{name}"
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt_value(v)}")
        for name in sorted(snap["hists"]):
            d = snap["hists"][name]
            m = f"{prefix}_{name}"
            lines.append(f"# TYPE {m} histogram")
            counts = {int(k): c for k, c in
                      (d.get("counts") or {}).items()}
            cum = 0
            for i in sorted(counts):
                cum += counts[i]
                lines.append(
                    f'{m}_bucket{{le="{bucket_hi(i)}"}} {cum}')
            # Histogram.record bumps the bucket BEFORE n, so a snapshot
            # racing a record can carry a bucket sum one ahead of n;
            # render +Inf/_count from the larger so the exposition
            # stays monotone (a scraper's histogram_quantile chokes on
            # a cumulative bucket above +Inf)
            n = max(cum, d["n"])
            lines.append(f'{m}_bucket{{le="+Inf"}} {n}')
            lines.append(f"{m}_sum {d['total']}")
            lines.append(f"{m}_count {n}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_value(v) -> str:
    if isinstance(v, float):
        return repr(v)
    return str(v)


# ----------------------------------------------------------------------
# The process registry + DecodeStats folds
# ----------------------------------------------------------------------

_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry (and the exporter trigger: first
    access after ``TPQ_METRICS_EXPORT`` is set arms the writer)."""
    maybe_start_exporter()
    return _registry


def reset_registry() -> MetricsRegistry:
    """Fresh process registry (tests / explicit reset); returns it."""
    global _registry
    _registry = MetricsRegistry()
    return _registry


def fold_stats(st, reg: MetricsRegistry | None = None) -> None:
    """Fold one ``DecodeStats`` collector into a registry, exactly:
    every ``_MERGE_FIELDS`` counter adds, every histogram merges
    bucket-wise.  Called by ``collect_stats`` for each OUTERMOST scope
    on exit (inner scopes shadow the outer and are folded on their own
    exits, so each count lands exactly once) — the bridge that makes
    the Prometheus counters equal the sum of every collector that ever
    ran in this process.  No-op under ``TPQ_LIVE_METRICS=0``."""
    if not live_enabled():
        return
    if reg is None:
        reg = registry()
    s = reg._shard()
    c = s.counters
    for f in st._MERGE_FIELDS:
        v = getattr(st, f)
        if v:
            c[f] = c.get(f, 0) + v
    if st.wall_s:
        c["wall_s"] = c.get("wall_s", 0) + st.wall_s
    for name, h in st.hists.items():
        tot = s.hists.get(name)
        if tot is None:
            tot = s.hists[name] = Histogram()
        tot.merge_from(h)


class LiveFold:
    """Incremental fold of a LONG-LIVED collector into the registry.

    The scan drivers meter their units into one scan-lifetime
    ``DecodeStats`` (stable identity — the pipelined reader captures
    its collector once); folding that collector whole at scan end
    would leave the registry flat for the whole scan.  ``fold(st)``
    instead folds the delta since the previous fold — called at each
    unit boundary, so a Prometheus scrape mid-scan sees the units
    decoded so far.  Exact: baselines are remembered per counter and
    per histogram bucket, so sum(deltas) == final totals."""

    def __init__(self):
        self._base: dict = {}
        self._hist_base: dict[str, list[int]] = {}

    def delta_only(self, st) -> dict:
        """Counter deltas since the last call, advancing the
        baselines WITHOUT applying anything to a registry — the
        attribution ledger's tracker for a USER collector, whose own
        registry fold happens at its scope exit (folding it here too
        would double-count)."""
        delta: dict = {}
        for f in st._MERGE_FIELDS:
            v = getattr(st, f)
            d = v - self._base.get(f, 0)
            if d:
                delta[f] = d
                self._base[f] = v
        return delta

    def fold(self, st, reg: MetricsRegistry | None = None) -> dict:
        """Fold the delta since the last fold; returns the counter
        deltas applied (empty when disabled/flat) so a second exact
        sink — the per-scan attribution ledger
        (:mod:`~tpuparquet.obs.attribution`) — can account the SAME
        numbers the registry received (conservation by
        construction)."""
        delta: dict = {}
        if not live_enabled():
            return delta
        if reg is None:
            reg = registry()
        s = reg._shard()
        c = s.counters
        for f in st._MERGE_FIELDS:
            v = getattr(st, f)
            d = v - self._base.get(f, 0)
            if d:
                c[f] = c.get(f, 0) + d
                self._base[f] = v
                delta[f] = d
        for name, h in st.hists.items():
            base = self._hist_base.get(name)
            if base is None:
                base = self._hist_base[name] = [0] * len(h.counts)
            tot = s.hists.get(name)
            if tot is None:
                tot = s.hists[name] = Histogram()
            for i, n in enumerate(h.counts):
                d = n - base[i]
                if d:
                    tot.counts[i] += d
                    tot.n += d
                    base[i] = n
            # total tracks the value sum, not the sample count: fold
            # its delta separately so hist sums stay exact too
            dt = h.total - self._base.get(("hist_total", name), 0)
            if dt:
                tot.total += dt
                self._base[("hist_total", name)] = h.total
        return delta


# ----------------------------------------------------------------------
# Background snapshot writer (TPQ_METRICS_EXPORT / TPQ_TIMESERIES_DIR)
# ----------------------------------------------------------------------

_exporter_lock = threading.Lock()
_exporter: threading.Thread | None = None
_atexit_registered = False

#: Serializes the interpreter-exit flushes of every always-on export
#: surface: the metrics/timeseries final flush here and the profiler's
#: atexit export (``obs.profiler.final_flush``) both take this lock,
#: so one teardown writer can never interleave with — or observe a
#: half-written frame from — the other.  atexit runs callbacks LIFO
#: on one thread, but both flushes are also callable directly (tests,
#: explicit shutdown) from arbitrary threads.
_flush_lock = threading.Lock()


def _metrics_interval() -> float:
    try:
        v = float(os.environ.get("TPQ_METRICS_INTERVAL_S", ""))
    except ValueError:
        return 10.0
    return max(v, 0.05)


def _grid_delay(now: float, interval: float) -> float:
    """Seconds until the next tick on the interval grid
    (``ceil(now / interval) * interval``), floored at a tenth of the
    interval so a tick landing just past a grid point doesn't fire a
    second, nearly-empty tick immediately.  Grid-aligned sleeps keep
    ring timestamps from drifting: N ticks land near N grid points,
    not N * (interval + write_cost)."""
    d = interval - (now % interval)
    if d < 0.1 * interval:
        d += interval
    return d


def export_now(path: str | None = None) -> str | None:
    """Write one snapshot atomically (tmp + ``os.replace``); returns
    the path written or None when no path is configured.  ``.json``
    suffix → JSON, anything else → Prometheus text."""
    if path is None:
        path = os.environ.get("TPQ_METRICS_EXPORT") or None
    if not path:
        return None
    body = (_registry.as_json() if path.endswith(".json")
            else _registry.prometheus_text())
    return path if atomic_write_text(path, body) else None


def _final_flush() -> None:
    """One last snapshot at interpreter exit (atexit): the frame that
    carries a short-lived process's totals — without it a batch job
    shorter than the interval leaves no ring frame and an empty
    metrics file.  Callable directly (tests, explicit shutdown)."""
    from . import timeseries as _timeseries

    with _flush_lock:
        export_now()
        if _timeseries._active is not None:
            _timeseries.tick("final")


def maybe_start_exporter() -> None:
    """Arm the background snapshot-writer daemon if either export
    surface (``TPQ_METRICS_EXPORT`` file, ``TPQ_TIMESERIES_DIR``
    ring) is configured and it isn't running (restart-safe across
    fork — threads do not survive one).  Arming also registers the
    atexit final flush."""
    from . import timeseries as _timeseries

    if not (os.environ.get("TPQ_METRICS_EXPORT")
            or _timeseries.timeseries_dir_default()):
        return
    global _exporter, _atexit_registered
    t = _exporter
    if t is not None and t.is_alive():
        return
    with _exporter_lock:
        t = _exporter
        if t is not None and t.is_alive():
            return
        if not _atexit_registered:
            import atexit

            atexit.register(_final_flush)
            _atexit_registered = True

        def run():
            while True:
                time.sleep(_grid_delay(time.time(), _metrics_interval()))
                if not (os.environ.get("TPQ_METRICS_EXPORT")
                        or _timeseries.timeseries_dir_default()):
                    return  # unset: stand down (tests flip this)
                export_now()
                if _timeseries.maybe_start_ring() is not None:
                    _timeseries.tick("tick")

        t = threading.Thread(target=run, daemon=True,
                             name="tpq-metrics-export")
        t.start()
        _exporter = t
