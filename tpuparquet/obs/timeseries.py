"""Bounded on-disk ring of metric snapshots — the time dimension.

The metrics registry (``obs/live.py``) answers "what are the totals
*now*"; the ring here answers "what were they over the last hour" —
the substrate the SLO engine (``obs/slo.py``) computes error budgets
and burn rates over, and what ``parquet-tool watch`` / ``slo
report`` render.  Frames are **delta-aware**: each carries both the
cumulative counters and the exact delta since the previous frame of
this ring (per-counter baselines, the ``LiveFold`` discipline), so a
reader computes rates without differencing across process restarts.

Layout: a directory of append-only JSONL segments
(``segment-<n>.jsonl``), one frame per line.  A segment rotates at
``TPQ_TIMESERIES_SEGMENT_FRAMES`` frames (default 256) and the ring
keeps at most ``TPQ_TIMESERIES_SEGMENTS`` segments (default 8),
unlinking the oldest — bounded disk, no compaction.  Appends are a
single ``write()`` of one ``\\n``-terminated line in binary append
mode, so a crash can tear at most the final line; the loader
tolerates exactly that (a torn trailing line is damage the format
expects, unlike the atomically-published progress/metrics files).
Restart-safe: a new process resumes numbering after the segments
already on disk.

Frame shape::

    {"format": "tpq-timeseries", "version": 1, "ts": ..., "pid": ...,
     "seq": ..., "kind": "tick" | "scan_end" | "final",
     "counters": {...cumulative...}, "delta": {...since prev frame...},
     "gauges": {...}, "ledgers": {label: ledger_state},
     "digests": {label: {stage: digest_dict}}}

Feeds: the background snapshot writer (``obs/live.py``) appends a
``tick`` frame on every interval, the scan drivers append a
``scan_end`` frame as each scan finishes (so short scans are visible
between ticks), and the atexit flush appends a ``final`` frame.  All
of it is off by default behind the one-is-None gate: set
``TPQ_TIMESERIES_DIR`` to arm :data:`_active`; hot sites guard the
call itself.
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["MetricRing", "load_ring", "tick", "ring",
           "maybe_start_ring", "set_ring_dir",
           "timeseries_dir_default", "segment_frames_default",
           "segments_default", "FRAME_FORMAT"]

FRAME_FORMAT = "tpq-timeseries"
_SEG_PREFIX = "segment-"
_SEG_SUFFIX = ".jsonl"


def timeseries_dir_default() -> str | None:
    """Ring directory from ``TPQ_TIMESERIES_DIR`` (None = off)."""
    return os.environ.get("TPQ_TIMESERIES_DIR") or None


def segment_frames_default() -> int:
    """Frames per segment from ``TPQ_TIMESERIES_SEGMENT_FRAMES``
    (default 256, floor 1)."""
    try:
        v = int(os.environ.get("TPQ_TIMESERIES_SEGMENT_FRAMES", "256"))
    except ValueError:
        return 256
    return max(v, 1)


def segments_default() -> int:
    """Segment-count cap from ``TPQ_TIMESERIES_SEGMENTS`` (default 8,
    floor 2 — one filling, one of history)."""
    try:
        v = int(os.environ.get("TPQ_TIMESERIES_SEGMENTS", "8"))
    except ValueError:
        return 8
    return max(v, 2)


def _segment_no(name: str) -> int | None:
    if not (name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX)):
        return None
    try:
        return int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
    except ValueError:
        return None


def _list_segments(dirpath: str) -> list[tuple[int, str]]:
    """(number, path) of every segment on disk, ascending."""
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    out = []
    for name in names:
        n = _segment_no(name)
        if n is not None:
            out.append((n, os.path.join(dirpath, name)))
    out.sort()
    return out


class MetricRing:
    """Appender side of the ring: builds frames from the live
    registry (+ armed digests), applies the delta baselines, writes
    and rotates.  Thread-safe; every filesystem failure is swallowed
    (telemetry must never fail the work it describes — the
    atomic_write_text contract)."""

    def __init__(self, dirpath: str, *, segment_frames: int | None = None,
                 segments: int | None = None):
        self.dir = dirpath
        self.env_armed = False      # True when maybe_start_ring installed it
        self.segment_frames = segment_frames or segment_frames_default()
        self.segments = segments or segments_default()
        self._lock = threading.Lock()
        self._base: dict = {}       # counter -> cumulative at last frame
        self._seq = 0
        segs = _list_segments(dirpath)
        # resume after what's on disk: never rewrite history
        self._seg_no = (segs[-1][0] + 1) if segs else 0
        self._frames_in_seg = 0

    # -- frame construction ----------------------------------------------

    def build_frame(self, kind: str) -> dict:
        """One JSON-serializable frame from the process telemetry
        (cumulative counters + exact delta since the previous frame
        of THIS ring + gauges + armed digests)."""
        from . import digest as _digest
        from .attribution import ledgers_state
        from .live import registry

        snap = registry().snapshot()
        counters = snap["counters"]
        delta = {}
        with self._lock:
            for k, v in counters.items():
                d = v - self._base.get(k, 0)
                if d:
                    delta[k] = d
                    self._base[k] = v
            seq = self._seq
            self._seq += 1
        frame = {
            "format": FRAME_FORMAT,
            "version": 1,
            "ts": time.time(),
            "pid": os.getpid(),
            "seq": seq,
            "kind": kind,
            "counters": counters,
            "delta": delta,
            "gauges": snap["gauges"],
        }
        leds = ledgers_state()
        if leds:
            frame["ledgers"] = leds
        if _digest._active is not None:
            frame["digests"] = _digest._active.to_state()
        return frame

    # -- append + rotation -----------------------------------------------

    def append(self, kind: str = "tick") -> bool:
        """Build and append one frame; rotate/trim as needed.
        Returns False (best-effort) on any filesystem error."""
        frame = self.build_frame(kind)
        line = (json.dumps(frame, sort_keys=True) + "\n").encode("utf-8")
        with self._lock:
            seg = os.path.join(
                self.dir, f"{_SEG_PREFIX}{self._seg_no}{_SEG_SUFFIX}")
            try:
                os.makedirs(self.dir, exist_ok=True)
                # one write() of one terminated line in O_APPEND mode:
                # a crash tears at most the trailing line, which the
                # loader tolerates by design
                with open(seg, "ab") as f:
                    f.write(line)
            except OSError:
                return False
            self._frames_in_seg += 1
            if self._frames_in_seg >= self.segment_frames:
                self._seg_no += 1
                self._frames_in_seg = 0
                # keep the newest `segments` numbers (including the
                # one the next append will create); unlink the rest
                floor = self._seg_no - self.segments
                for n, path in _list_segments(self.dir):
                    if n <= floor:
                        try:
                            os.unlink(path)
                        except OSError:
                            pass
        return True


def load_ring(dirpath: str) -> list[dict]:
    """Read every frame in the ring, oldest first (segment order,
    then line order).  A torn or garbage line — the expected crash
    artifact at a segment tail — is skipped, not fatal; a frame
    without the ``tpq-timeseries`` envelope is skipped too."""
    frames: list[dict] = []
    for _, path in _list_segments(dirpath):
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            try:
                doc = json.loads(line)
            except (ValueError, UnicodeDecodeError):
                continue
            if isinstance(doc, dict) and doc.get("format") == FRAME_FORMAT:
                frames.append(doc)
    return frames


# ----------------------------------------------------------------------
# Module gate — the one-is-None idiom (recorder/trace/faults shape)
# ----------------------------------------------------------------------

_lock = threading.Lock()

#: The active ring appender, or None when disabled — the single gate
#: every feed site checks.  Armed from ``TPQ_TIMESERIES_DIR`` at
#: import / first registry access; reconfigure with :func:`set_ring_dir`.
_active: MetricRing | None = None


def _init_from_env() -> None:
    global _active
    d = timeseries_dir_default()
    with _lock:
        _active = MetricRing(d) if d else None


_init_from_env()


def ring() -> MetricRing | None:
    """The active ring appender (None when disabled)."""
    return _active


def maybe_start_ring() -> MetricRing | None:
    """Arm the ring if ``TPQ_TIMESERIES_DIR`` is set and the active
    appender doesn't match it (restart-safe; tests flip the env).
    Unsetting the env stands down only an env-armed ring — one
    installed programmatically via :func:`set_ring_dir` stays up."""
    global _active
    d = timeseries_dir_default()
    with _lock:
        r = _active
        if d is None:
            if r is not None and r.env_armed:
                _active = None
        elif r is None or r.dir != d:
            _active = MetricRing(d)
            _active.env_armed = True
        return _active


def set_ring_dir(dirpath: str | None) -> MetricRing | None:
    """Runtime reconfigure: a path installs a FRESH appender on that
    directory, None disables.  Returns the new appender."""
    global _active
    with _lock:
        _active = MetricRing(dirpath) if dirpath else None
        return _active


def tick(kind: str = "tick") -> None:
    """Feed hook: append one frame to the armed ring.  No-op (one
    global ``is None`` check) when the ring is off.  Feed sites on
    scan paths guard the CALL itself (``_timeseries._active is not
    None``) per the recorder-guard discipline."""
    r = _active
    if r is not None:
        r.append(kind)
