"""Per-scan resource attribution + the span-graph critical-path walk.

Two halves, one consumer (``parquet-tool doctor`` and the admission
controller the serve layer will grow):

**Ledgers** — exact per-scan-label resource accounting.  The scan
drivers already fold their ambient collector's *delta* into the
process :class:`~tpuparquet.obs.live.MetricsRegistry` at every unit
boundary (``LiveFold``); this module gives each scan label a
:class:`ScanLedger` fed the *same* delta dict, so by construction

    sum over scan ledgers of counter X  ==  registry total of X

for every counter the scans produced — the conservation property any
per-tenant byte/deadline budget must meter against.  Ledgers expose
the derived views an operator wants (cpu-seconds by stage, bytes
read/staged/moved, pages decoded, peak arena occupancy) and merge
exactly across threads (per-unit folds are driver-thread-serial) and
hosts (``shard.distributed.allgather_ledgers``: counter-wise sums,
peak as max).

**Span analysis** — the critical-path walk over a trace
(:mod:`~tpuparquet.obs.trace`).  For every span, its *exclusive* time
is its duration minus the union of its children's intervals; summing
exclusive time by stage over a unit's subtree decomposes the unit
wall exactly (buckets sum to the unit duration, gaps land in
``driver``).  :func:`diagnose` folds that into the bound verdict
(read-bound / plan-bound / decompress-bound / decode-bound /
gather-bound), ranks straggler units against the rolling p95 of unit
walls (:class:`~tpuparquet.deadline.LatencyTracker` — the same
detector the live progress view uses), and flags plan-pool
oversubscription (total plan seconds ≫ plan wall window × usable
cores — the PLAN_SCALE_r06 thread-degradation signature).
"""

from __future__ import annotations

import threading

__all__ = [
    "ScanLedger", "ledger", "ledgers_snapshot", "reset_ledgers",
    "merge_ledger_states", "stage_seconds", "stage_verdict",
    "remote_report",
    "STAGE_OF", "VERDICT_OF",
    "span_tree", "exclusive_times", "unit_reports", "diagnose",
    "format_diagnosis",
]

#: span name -> canonical stage bucket
STAGE_OF = {
    "read": "read", "read_replica": "read", "retry": "read",
    "plan": "plan",
    "decompress": "decompress",
    "transfer": "transfer", "stage": "transfer",
    "dispatch": "dispatch",
    "gather": "gather",
    "page_write": "write", "encode": "write", "compress": "write",
}

#: stage bucket -> doctor verdict (transfer and dispatch are both the
#: decode side of the wall: bytes moving to, and kernels running on,
#: the device)
VERDICT_OF = {
    "read": "read-bound", "plan": "plan-bound",
    "decompress": "decompress-bound", "transfer": "decode-bound",
    "dispatch": "decode-bound", "gather": "gather-bound",
}

#: DecodeStats counter -> stage, for the ledger/profile cpu_s view
#: (decompress rides inside plan_s on the live pipeline — the plan
#: phase decompresses page bodies; it stays a separate bucket only
#: where a trace carries explicit decompress spans)
_STAGE_COUNTERS = {
    "read": "read_s", "plan": "plan_s", "transfer": "transfer_s",
    "dispatch": "dispatch_s", "gather": "gather_reshard_s",
}


def stage_seconds(counters: dict) -> dict:
    """Per-stage cpu-seconds view over a counter dict (a ledger's, a
    ``DecodeStats.as_dict()``, or a registry snapshot) — the shared
    derivation ``parquet-tool profile``/``top``/``doctor`` all print,
    so the surfaces agree on numbers by construction.

    The buckets are DISJOINT: ``read_s`` accrues inside the plan
    timing window (``chunk_blob`` is called by the plan phase), so the
    ``plan`` bucket here is ``plan_s - read_s`` (clamped at zero for
    the CPU read paths that fetch chunks outside any plan timer) —
    exactly the subtraction the trace-based doctor performs when it
    takes the plan span's exclusive time over its child read span."""
    out = {stage: round(float(counters.get(c, 0) or 0), 6)
           for stage, c in _STAGE_COUNTERS.items()}
    out["plan"] = round(max(out["plan"] - out["read"], 0.0), 6)
    return out


def remote_report(counters: dict,
                  verdict: str | None = None) -> dict | None:
    """The doctor's REMOTE section over one counter dict (a ledger's
    ``counters``, a ``DecodeStats.as_dict()``, or a registry
    snapshot), or None when the scan never touched a remote source or
    a range cache.

    ``hit_ratio`` is cache hits (mem + disk) over total range demand
    (hits + origin fetches) — the fraction of range reads the cache
    absorbed.  ``origin_bound`` fires only when the trace already says
    ``read-bound`` (pass :func:`diagnose`'s ``verdict``) AND the
    origin absorbed at least half the demand: a read-bound scan whose
    cache is doing its job is disk-bound, not origin-bound, and the
    cures differ (more spindles vs deeper prefetch / bigger cache)."""
    fetched = int(counters.get("remote_ranges_fetched", 0) or 0)
    hits = (int(counters.get("cache_hits_mem", 0) or 0)
            + int(counters.get("cache_hits_disk", 0) or 0))
    misses = (int(counters.get("cache_misses_mem", 0) or 0)
              + int(counters.get("cache_misses_disk", 0) or 0))
    retries = int(counters.get("remote_retry", 0) or 0)
    if not (fetched or hits or misses or retries):
        return None
    demand = hits + fetched
    ratio = hits / demand if demand > 0 else 0.0
    return {
        "origin_fetches": fetched,
        "origin_bytes": int(counters.get("remote_bytes", 0) or 0),
        "ranges_coalesced": int(
            counters.get("ranges_coalesced", 0) or 0),
        "cache_hits_mem": int(counters.get("cache_hits_mem", 0) or 0),
        "cache_hits_disk": int(
            counters.get("cache_hits_disk", 0) or 0),
        "cache_misses_disk": int(
            counters.get("cache_misses_disk", 0) or 0),
        "cache_evictions_disk": int(
            counters.get("cache_evictions_disk", 0) or 0),
        "hit_ratio": round(ratio, 4),
        "retries": retries,
        "hedges_issued": int(counters.get("hedges_issued", 0) or 0),
        "hedges_won": int(counters.get("hedges_won", 0) or 0),
        "origin_bound": bool(verdict == "read-bound"
                             and fetched > 0 and ratio < 0.5),
    }


def stage_verdict(counters: dict) -> str | None:
    """Counter-only doctor verdict: the :data:`VERDICT_OF` name of
    the dominant :func:`stage_seconds` bucket, or None when nothing
    has accrued.  The trace-based :func:`diagnose` is strictly richer
    (exclusive times, tails, oversubscription); this is the cheap
    always-available form the serve arbiter's adaptive loop feeds on
    — same buckets, same vocabulary, so ``parquet-tool doctor`` and
    the rebalancer never disagree about what a tenant is bound by."""
    stages = stage_seconds(counters)
    stage = max(stages, key=lambda s: stages[s])
    if stages[stage] <= 0:
        return None
    return VERDICT_OF.get(stage)


class ScanLedger:
    """Exact resource ledger for one scan label.

    ``fold_delta`` accumulates counter deltas (the same dicts
    ``LiveFold`` applies to the registry — counters are EXACT);
    ``note_peak`` keeps the max of observed arena-occupancy high-water
    marks, which is process-shared telemetry, not an exact per-scan
    number: arenas are one pool, so a scan's ``peak_arena_bytes`` is
    the highest shared-pool occupancy seen during its unit windows —
    an upper bound that includes concurrent scans' borrows (see
    :func:`tpuparquet.kernels.arena.take_arena_peak`).  Thread model:
    folds happen on the scan's driving thread at unit boundaries; the
    snapshot readers copy under the GIL (same discipline as the
    registry shards)."""

    __slots__ = ("label", "counters", "peak_arena_bytes", "scans")

    def __init__(self, label: str):
        self.label = label
        self.counters: dict = {}
        self.peak_arena_bytes = 0
        self.scans = 0

    def fold_delta(self, delta: dict) -> None:
        c = self.counters
        for k, v in delta.items():
            c[k] = c.get(k, 0) + v

    def note_peak(self, peak_bytes: int) -> None:
        if peak_bytes > self.peak_arena_bytes:
            self.peak_arena_bytes = peak_bytes

    def as_dict(self) -> dict:
        c = dict(self.counters)
        return {
            "label": self.label,
            "scans": self.scans,
            "cpu_s": stage_seconds(c),
            "bytes": {
                "read": c.get("bytes_read", 0),
                "staged": c.get("bytes_staged", 0),
                "moved": c.get("gather_bytes_moved", 0),
            },
            "pages": c.get("pages", 0),
            "rows": c.get("values", 0),
            "peak_arena_bytes": self.peak_arena_bytes,
            "counters": c,
        }

    # -- exact wire form (cross-host merge) --------------------------------

    def to_state(self) -> dict:
        return {"label": self.label, "scans": self.scans,
                "counters": dict(self.counters),
                "peak_arena_bytes": self.peak_arena_bytes}

    @classmethod
    def from_state(cls, d: dict) -> "ScanLedger":
        led = cls(d["label"])
        led.scans = int(d.get("scans", 0))
        led.counters = dict(d.get("counters") or {})
        led.peak_arena_bytes = int(d.get("peak_arena_bytes", 0))
        return led

    def merge_from(self, other: "ScanLedger") -> None:
        """Exact fold: counters sum, peak is the max (occupancy peaks
        on different hosts are concurrent, not additive), scan count
        sums."""
        self.fold_delta(other.counters)
        self.note_peak(other.peak_arena_bytes)
        self.scans += other.scans


_lock = threading.Lock()
_ledgers: dict[str, ScanLedger] = {}


def ledger(label: str) -> ScanLedger:
    """Get-or-create the process ledger for a scan label (two scans
    sharing a label share a ledger — per-tenant accounting keys on
    the label, exactly like the progress gauges)."""
    with _lock:
        led = _ledgers.get(label)
        if led is None:
            led = _ledgers[label] = ScanLedger(label)
        return led


def ledgers_snapshot() -> dict:
    """``{label: ScanLedger.as_dict()}`` for every scan label this
    process has run."""
    with _lock:
        items = list(_ledgers.items())
    return {label: led.as_dict() for label, led in sorted(items)}


def ledgers_state() -> dict:
    """Exact wire form of every ledger (cross-host merge)."""
    with _lock:
        items = list(_ledgers.items())
    return {label: led.to_state() for label, led in items}


def reset_ledgers() -> None:
    with _lock:
        _ledgers.clear()


def merge_ledger_states(states: list[dict]) -> dict:
    """Fold per-host ``ledgers_state()`` dicts into one exact
    fleet-wide ``{label: ScanLedger}`` (counters sum label-wise — the
    single-host ledger of the union corpus)."""
    out: dict[str, ScanLedger] = {}
    for state in states:
        for label, d in state.items():
            led = ScanLedger.from_state(d)
            if label in out:
                out[label].merge_from(led)
            else:
                out[label] = led
    return out


# ----------------------------------------------------------------------
# Span analysis (the doctor's walk)
# ----------------------------------------------------------------------

def span_tree(spans: list[dict]) -> tuple[dict, dict, list[dict]]:
    """Index a span list: ``(by_id, children, roots)``.  Spans whose
    parent is absent from the list (a trimmed ring) are treated as
    roots of their own subtree rather than dropped — the walk then
    reports what it can see."""
    by_id = {s["span"]: s for s in spans}
    children: dict = {}
    roots = []
    for s in spans:
        p = s.get("parent")
        if p is not None and p in by_id:
            children.setdefault(p, []).append(s)
        else:
            roots.append(s)
    return by_id, children, roots


def _union_len(intervals: list[tuple[float, float]]) -> float:
    """Total length of the union of (start, end) intervals."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_a, cur_b = intervals[0]
    for a, b in intervals[1:]:
        if a > cur_b:
            total += cur_b - cur_a
            cur_a, cur_b = a, b
        elif b > cur_b:
            cur_b = b
    return total + (cur_b - cur_a)


def exclusive_times(spans: list[dict]) -> dict:
    """``{span_id: exclusive_seconds}``: each span's duration minus
    the union of its children's intervals clipped to it.  Summing a
    subtree's exclusive times reproduces the subtree root's duration
    exactly (plus nothing, minus nothing) — the invariant the stage
    decomposition rests on."""
    _, children, _ = span_tree(spans)
    out = {}
    for s in spans:
        t0, t1 = s["t0"], s["t0"] + s.get("dur", 0.0)
        kids = []
        for c in children.get(s["span"], ()):
            a = max(c["t0"], t0)
            b = min(c["t0"] + c.get("dur", 0.0), t1)
            if b > a:
                kids.append((a, b))
        out[s["span"]] = max(s.get("dur", 0.0) - _union_len(kids), 0.0)
    return out


def _subtree_stages(root: dict, children: dict, excl: dict) -> dict:
    """Exclusive-time-by-stage over one span's subtree.  The root's
    own exclusive time lands in ``driver`` (loop bookkeeping, window
    gaps) so the buckets always sum to the root's duration."""
    stages: dict = {}
    stack = [(root, True)]
    while stack:
        s, is_root = stack.pop()
        if is_root:
            bucket = "driver"
        elif s.get("status") == "cancelled":
            # abandoned work (hedge losers, dropped pipeline units):
            # real seconds, but duplicate/discarded — kept out of the
            # stage buckets so it cannot tilt a bound verdict
            bucket = "cancelled"
        else:
            bucket = STAGE_OF.get(s.get("name"), "other")
        stages[bucket] = stages.get(bucket, 0.0) + excl.get(s["span"],
                                                           0.0)
        for c in children.get(s["span"], ()):
            stack.append((c, False))
    return stages


def _top_child(root: dict, children: dict) -> dict | None:
    """The longest direct child span (the straggler's offender)."""
    kids = children.get(root["span"])
    if not kids:
        return None
    return max(kids, key=lambda c: c.get("dur", 0.0))


def _coords(s: dict) -> dict:
    return {k: s[k] for k in ("unit", "file", "row_group", "column",
                              "page", "replica") if k in s}


def unit_reports(spans: list[dict]) -> list[dict]:
    """Per-unit decomposition: one row per ``name == "unit"`` span
    with its wall, its stage buckets (summing to the wall), the stage
    that bounds it, and the coordinates of its largest child."""
    _, children, _ = span_tree(spans)
    excl = exclusive_times(spans)
    rows = []
    for s in spans:
        if s.get("name") != "unit":
            continue
        stages = _subtree_stages(s, children, excl)
        timed = {k: v for k, v in stages.items() if k in VERDICT_OF}
        bound = max(timed, key=timed.get) if timed else "driver"
        top = _top_child(s, children)
        rows.append({
            "unit": s.get("unit"),
            "coords": _coords(s),
            "status": s.get("status", "ok"),
            "dur_s": round(s.get("dur", 0.0), 6),
            "stages_s": {k: round(v, 6)
                         for k, v in sorted(stages.items())},
            "bound": bound,
            "top_child": None if top is None else {
                "name": top.get("name"), "dur_s":
                round(top.get("dur", 0.0), 6), **_coords(top)},
        })
    rows.sort(key=lambda r: (r["unit"] is None, r["unit"]))
    return rows


def diagnose(spans: list[dict], p95s: dict | None = None) -> dict:
    """The doctor's whole-trace verdict.

    Walks one trace's spans (filter by trace id first when a snapshot
    holds several): per-unit stage decomposition, scan-level stage
    totals and shares, the bound verdict, stragglers ranked against
    the rolling p95 of unit walls (``p95s`` optionally pins
    externally tracked per-stage p95s — e.g. from a live
    ``deadline.LatencyTracker`` — into the report), and the plan-pool
    concurrency note that turns the PLAN_SCALE thread-degradation
    mystery into one line."""
    from ..deadline import LatencyTracker

    by_id, children, roots = span_tree(spans)
    excl = exclusive_times(spans)
    units = unit_reports(spans)
    scan_roots = [r for r in roots if r.get("name") == "scan"]
    root = scan_roots[0] if scan_roots else (roots[0] if roots else None)
    # wall = the whole trace's envelope, not just the root span's
    # duration: post-scan gathers (emitted under the retained root
    # context after the root closed) must count toward a gather-bound
    # verdict
    wall = (max(s["t0"] + s.get("dur", 0.0) for s in spans)
            - min(s["t0"] for s in spans)) if spans else 0.0

    # scan-level stage totals: exclusive time by stage over everything
    # (cancelled spans — hedge losers, dropped units — bucket apart so
    # abandoned duplicate work cannot tilt the verdict)
    stages: dict = {}
    for s in spans:
        if s.get("status") == "cancelled":
            bucket = "cancelled"
        elif s.get("name") in ("scan", "unit"):
            bucket = "driver"
        else:
            bucket = STAGE_OF.get(s.get("name"), "other")
        stages[bucket] = stages.get(bucket, 0.0) + excl.get(s["span"],
                                                            0.0)
    timed = {k: v for k, v in stages.items() if k in VERDICT_OF}
    timed_total = sum(timed.values())
    if timed:
        bound_stage = max(timed, key=timed.get)
        verdict = VERDICT_OF[bound_stage]
        # share of the TIMED work, not of wall: stage seconds sum
        # across pool/hedge threads, so a wall-relative ratio would
        # read >100% whenever stages ran in parallel (and could crown
        # the widest-parallel stage rather than the binding one)
        share = timed[bound_stage] / timed_total if timed_total > 0 \
            else 0.0
    else:
        bound_stage, verdict, share = None, "no-spans", 0.0

    # stragglers: each unit's wall vs the LatencyTracker p95 of its
    # SIBLINGS (leave-one-out — in a small scan one huge unit IS the
    # p95, and ranking it against itself would hide it; the live
    # progress view has the same detector in rolling form).  Only
    # units already past 1.5x the global median are candidates, so
    # the LOO pass stays linear in practice.
    tracker = LatencyTracker(window=256, min_samples=4)
    for u in units:
        tracker.record(u["dur_s"])
    p95 = tracker.quantile(0.95)
    stragglers = []
    if len(units) >= 4:
        durs = sorted(u["dur_s"] for u in units)
        median = durs[len(durs) // 2]
        for u in units:
            if u["dur_s"] <= max(median * 1.5, 0.001):
                continue
            rest = list(durs)
            rest.remove(u["dur_s"])
            loo = LatencyTracker(window=256, min_samples=3)
            for d in rest[-256:]:
                loo.record(d)
            p95_loo = loo.quantile(0.95)
            if p95_loo is not None and \
                    u["dur_s"] > max(p95_loo * 1.5, 0.001):
                stragglers.append(u)
        stragglers.sort(key=lambda u: -u["dur_s"])

    # plan-pool concurrency: total plan-span seconds vs the time plan
    # work was ACTIVE (the union of the plan intervals, not the whole
    # scan window — pipelined plans run in bursts between transfers).
    # On an N-core box an active overlap well above N means the pool
    # is oversubscribed: plan tasks timeslice against each other, each
    # task's wall inflates, and pipelined plan_s degrades with thread
    # count — exactly the PLAN_SCALE_r06 signature
    plan_spans = [s for s in spans
                  if STAGE_OF.get(s.get("name")) == "plan"]
    plan_note = None
    if plan_spans:
        total = sum(s.get("dur", 0.0) for s in plan_spans)
        busy = max(_union_len(
            [(s["t0"], s["t0"] + s.get("dur", 0.0))
             for s in plan_spans]), 1e-9)
        tids = len({s.get("tid") for s in plan_spans})
        usable = root.get("usable_cpus") if root is not None else None
        concurrency = total / busy
        plan_note = {
            "plan_total_s": round(total, 6),
            "plan_busy_s": round(busy, 6),
            "concurrency": round(concurrency, 3),
            "threads": tids,
            "usable_cpus": usable,
            "oversubscribed": bool(
                usable is not None and tids > usable
                and concurrency > usable * 1.25),
        }

    return {
        "trace": root.get("trace") if root is not None else None,
        "label": root.get("label") if root is not None else None,
        "wall_s": round(wall, 6),
        "units": len(units),
        "unit_rows": units,
        "stages_s": {k: round(v, 6) for k, v in sorted(stages.items())},
        "stage_share": {k: round(v / timed_total, 4)
                        if timed_total > 0 else 0.0
                        for k, v in sorted(timed.items())},
        # timed work over wall: ~1.0 means the spans account for the
        # whole wall; >1.0 means stages genuinely ran in parallel
        # (average timed parallelism), <1.0 means untimed driver gaps
        "coverage": round(timed_total / wall, 4) if wall > 0 else 0.0,
        "bound_stage": bound_stage,
        "verdict": verdict,
        "verdict_share": round(share, 4),
        "p95_unit_s": None if p95 is None else round(p95, 6),
        "stragglers": stragglers[:8],
        "plan_pool": plan_note,
        "external_p95s": p95s or None,
    }


def format_diagnosis(d: dict, ledgers: dict | None = None) -> str:
    """Human rendering of one :func:`diagnose` report (the
    ``parquet-tool doctor`` screen)."""
    lines = []
    lines.append(
        f"trace {d.get('trace') or '?'}"
        + (f"  label={d['label']}" if d.get("label") else "")
        + f"  units={d['units']}  wall={d['wall_s']:.3f}s")
    if d.get("stages_s"):
        parts = []
        for k, v in sorted(d["stages_s"].items(),
                           key=lambda kv: -kv[1]):
            if v <= 0:
                continue
            shr = f" ({100 * v / d['wall_s']:.1f}%)" \
                if d["wall_s"] > 0 else ""
            parts.append(f"{k} {v:.3f}s{shr}")
        lines.append("  stages: " + "  ".join(parts))
    lines.append(
        f"  verdict: {d['verdict']}"
        + (f" — {d['bound_stage']} is "
           f"{100 * d['verdict_share']:.1f}% of the timed work"
           if d.get("bound_stage") else "")
        + f"  (timed work covers {100 * d.get('coverage', 0):.1f}%"
          " of wall)")
    pp = d.get("plan_pool")
    if pp:
        note = (f"  plan pool: {pp['plan_total_s']:.3f}s of plan over "
                f"{pp['plan_busy_s']:.3f}s of active plan time on "
                f"{pp['threads']} thread(s)"
                + (f", {pp['usable_cpus']} usable core(s)"
                   if pp.get("usable_cpus") is not None else "")
                + f" — concurrency {pp['concurrency']:.2f}")
        if pp.get("oversubscribed"):
            note += ("  OVERSUBSCRIBED: plan tasks timeslice against "
                     "each other; try TPQ_PLAN_THREADS="
                     + str(pp["usable_cpus"]))
        lines.append(note)
    if d.get("p95_unit_s") is not None:
        lines.append(f"  unit p95: {d['p95_unit_s']:.3f}s")
    for u in d.get("stragglers") or []:
        top = u.get("top_child")
        lines.append(
            f"  STRAGGLER unit {u['unit']} "
            f"({', '.join(f'{k}={v}' for k, v in u['coords'].items() if k != 'unit')}): "
            f"{u['dur_s']:.3f}s, bound by {u['bound']}"
            + (f" — top span {top['name']} {top['dur_s']:.3f}s "
               + " ".join(f"{k}={v}" for k, v in top.items()
                          if k not in ("name", "dur_s"))
               if top else ""))
    if d.get("unit_rows"):
        tally: dict = {}
        for u in d["unit_rows"]:
            tally[u["bound"]] = tally.get(u["bound"], 0) + 1
        lines.append("  per-unit bound: " + "  ".join(
            f"{k}:{v}" for k, v in sorted(tally.items(),
                                          key=lambda kv: -kv[1])))
    for label, led in sorted((ledgers or {}).items()):
        cpu = led.get("cpu_s", {})
        by = led.get("bytes", {})
        lines.append(
            f"  ledger[{label}]: cpu "
            + " ".join(f"{k}={v:.3f}s" for k, v in sorted(cpu.items())
                       if v)
            + f"  bytes read={by.get('read', 0):,} "
            f"staged={by.get('staged', 0):,} "
            f"moved={by.get('moved', 0):,}"
            + f"  pages={led.get('pages', 0)}"
            + (f"  peak_arena={led.get('peak_arena_bytes', 0):,}B"
               if led.get("peak_arena_bytes") else ""))
        rr = remote_report(led.get("counters") or {},
                           verdict=d.get("verdict"))
        if rr:
            lines.append(
                f"  REMOTE[{label}]: origin {rr['origin_fetches']} "
                f"fetches / {rr['origin_bytes']:,}B "
                f"(coalesced {rr['ranges_coalesced']})  cache hits "
                f"mem={rr['cache_hits_mem']} "
                f"disk={rr['cache_hits_disk']}  hit ratio "
                f"{100 * rr['hit_ratio']:.1f}%  retries={rr['retries']}"
                f"  hedges={rr['hedges_won']}/{rr['hedges_issued']}"
                + (f"  evictions={rr['cache_evictions_disk']}"
                   if rr["cache_evictions_disk"] else ""))
            if rr["origin_bound"]:
                lines.append(
                    "    ORIGIN-BOUND: read-bound and the origin "
                    f"absorbed {100 * (1 - rr['hit_ratio']):.1f}% of "
                    "range demand — deepen prefetch "
                    "(TPQ_PREFETCH_DEPTH) or grow the shared disk "
                    "cache (TPQ_CACHE_DISK_MB)")
    return "\n".join(lines)
