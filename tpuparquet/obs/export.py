"""Export surfaces for the decode telemetry.

* :func:`chrome_trace` — the host-side phase spans (plan / transfer /
  dispatch, per worker thread) and per-page instants as a Chrome
  trace-event JSON object, loadable in Perfetto (ui.perfetto.dev) or
  ``chrome://tracing``.  This is the host-side complement of
  ``stats.trace`` (the JAX profiler covers device kernels; these spans
  cover the planner/stager wall the profiler can't see).
* :func:`column_table` — the per-column transport/timing aggregate the
  ``parquet-tool profile`` subcommand prints.
"""

from __future__ import annotations

import json

from .events import EventLog

__all__ = ["chrome_trace", "write_chrome_trace", "column_table",
           "format_column_table"]


def chrome_trace(log: EventLog) -> dict:
    """Chrome trace-event format: spans as complete ("X") events,
    pages as instant ("i") events carrying the gate decision in args.
    Timestamps are microseconds relative to the log's ``t0``."""
    events = []
    for s in log.spans:
        events.append({
            "name": s["name"], "cat": s["phase"], "ph": "X",
            "ts": round(s["start"] * 1e6, 1),
            "dur": round(s["dur"] * 1e6, 1),
            "pid": 0, "tid": s["tid"], "args": s["args"],
        })
    for e in log.pages:
        events.append({
            "name": f"{e.column}[{e.page}] {e.transport}",
            "cat": "page", "ph": "i", "s": "t",
            "ts": round(e.t * 1e6, 1),
            "pid": 0, "tid": 0, "args": e.as_dict(),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(log: EventLog, path_or_file) -> None:
    obj = chrome_trace(log)
    if hasattr(path_or_file, "write"):
        json.dump(obj, path_or_file)
    else:
        with open(path_or_file, "w") as f:
            json.dump(obj, f)


def column_table(log: EventLog) -> list[dict]:
    """Per-column aggregate rows, sorted by column path.

    Each row: pages, values, transport mix, wire/raw ratio over the
    gated pages, summed per-page plan wall, and a representative gate
    reason (the modal transport's most recent reason)."""
    rows = []
    for col, events in sorted(log.by_column().items()):
        transports: dict[str, int] = {}
        wire = raw = 0
        plan_s = 0.0
        values = 0
        for e in events:
            transports[e.transport] = transports.get(e.transport, 0) + 1
            values += e.num_values
            plan_s += e.plan_s
            if e.wire_bytes is not None and e.raw_bytes:
                wire += e.wire_bytes
                raw += e.raw_bytes
        modal = max(transports, key=transports.get)
        reason = next(
            (e.reason for e in reversed(events)
             if e.transport == modal and e.reason), "")
        rows.append({
            "column": col,
            "pages": len(events),
            "values": values,
            "transports": transports,
            "wire_to_raw": round(wire / raw, 3) if raw else None,
            "plan_s": round(plan_s, 6),
            "reason": reason,
        })
    return rows


def format_column_table(rows: list[dict]) -> str:
    """Fixed-width text rendering of :func:`column_table`."""
    if not rows:
        return "(no page events)"
    headers = ["column", "pages", "values", "transports", "wire/raw",
               "plan_ms", "gate reason"]
    table = []
    for r in rows:
        mix = " ".join(f"{t}:{c}" for t, c in sorted(r["transports"]
                                                     .items()))
        table.append([
            r["column"], str(r["pages"]), f"{r['values']:,}", mix,
            "-" if r["wire_to_raw"] is None else f"{r['wire_to_raw']:.3f}",
            f"{r['plan_s'] * 1e3:.1f}",
            r["reason"] or "-",
        ])
    widths = [max(len(h), *(len(row[i]) for row in table))
              for i, h in enumerate(headers)]
    lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    lines.append("  ".join("-" * w for w in widths))
    for row in table:
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    return "\n".join(lines)
