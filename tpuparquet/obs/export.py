"""Export surfaces for the decode telemetry.

* :func:`chrome_trace` — the host-side phase spans (plan / transfer /
  dispatch, per worker thread) and per-page instants as a Chrome
  trace-event JSON object, loadable in Perfetto (ui.perfetto.dev) or
  ``chrome://tracing``.  This is the host-side complement of
  ``stats.trace`` (the JAX profiler covers device kernels; these spans
  cover the planner/stager wall the profiler can't see).
* :func:`column_table` — the per-column transport/timing aggregate the
  ``parquet-tool profile`` subcommand prints.
* :func:`spans_chrome_trace` / :func:`spans_otlp` — the CAUSAL span
  graph (:mod:`~tpuparquet.obs.trace`) as Chrome trace-event JSON
  (Perfetto renders the parent/child nesting per thread track) or
  OTLP-shaped ``resourceSpans`` JSON (what an OpenTelemetry collector
  ingests); :func:`write_trace_file` / :func:`load_trace_file` are the
  scan drivers' ``TPQ_TRACE_EXPORT`` writer and ``parquet-tool
  doctor``'s reader (format picked by filename suffix, atomic
  publish).
"""

from __future__ import annotations

import hashlib
import json

from .events import EventLog

__all__ = ["chrome_trace", "write_chrome_trace", "column_table",
           "format_column_table", "spans_chrome_trace", "spans_otlp",
           "write_trace_file", "load_trace_file"]


def chrome_trace(log: EventLog) -> dict:
    """Chrome trace-event format: spans as complete ("X") events,
    pages as instant ("i") events carrying the gate decision in args.
    Timestamps are microseconds relative to the log's ``t0``."""
    events = []
    for s in log.spans:
        events.append({
            "name": s["name"], "cat": s["phase"], "ph": "X",
            "ts": round(s["start"] * 1e6, 1),
            "dur": round(s["dur"] * 1e6, 1),
            "pid": 0, "tid": s["tid"], "args": s["args"],
        })
    for e in log.pages:
        events.append({
            "name": f"{e.column}[{e.page}] {e.transport}",
            "cat": "page", "ph": "i", "s": "t",
            "ts": round(e.t * 1e6, 1),
            "pid": 0, "tid": 0, "args": e.as_dict(),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(log: EventLog, path_or_file) -> None:
    obj = chrome_trace(log)
    if hasattr(path_or_file, "write"):
        json.dump(obj, path_or_file)
    else:
        with open(path_or_file, "w") as f:
            json.dump(obj, f)


def spans_chrome_trace(spans: list[dict]) -> dict:
    """Causal spans as Chrome trace-event JSON: complete ("X") events
    on per-thread tracks (Perfetto nests children under parents by
    interval containment), cancelled/error spans color-coded via
    ``cname``, coordinates and ids in ``args``.  Cross-host merges
    (spans carrying a ``proc`` field) land on per-process tracks."""
    events = []
    t_base = min((s["t0"] for s in spans), default=0.0)
    for s in spans:
        args = {k: v for k, v in s.items()
                if k not in ("t0", "dur", "tid", "name")}
        ev = {
            "name": s.get("name", "?"),
            "cat": s.get("status", "ok"),
            "ph": "X",
            "ts": round((s["t0"] - t_base) * 1e6, 1),
            "dur": round(s.get("dur", 0.0) * 1e6, 1),
            "pid": s.get("proc", 0),
            "tid": s.get("tid", 0),
            "args": args,
        }
        status = s.get("status")
        if status == "cancelled":
            ev["cname"] = "grey"
        elif status == "error":
            ev["cname"] = "terrible"
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _otlp_attr(k, v) -> dict:
    if isinstance(v, bool):
        val = {"boolValue": v}
    elif isinstance(v, int):
        val = {"intValue": str(v)}
    elif isinstance(v, float):
        val = {"doubleValue": v}
    else:
        val = {"stringValue": str(v)}
    return {"key": k, "value": val}


def spans_otlp(spans: list[dict], anchor: dict | None = None,
               service: str = "tpuparquet") -> dict:
    """Causal spans as OTLP-shaped JSON (``resourceSpans`` →
    ``scopeSpans`` → ``spans`` with hex ``traceId``/``spanId``/
    ``parentSpanId`` and Unix-nano timestamps) — the shape an
    OpenTelemetry collector's JSON receiver ingests.  ``anchor`` is
    the tracer's ``{"wall", "perf"}`` pair mapping the monotonic span
    starts to epoch time (without it, spans are anchored at their raw
    monotonic seconds)."""
    wall = (anchor or {}).get("wall", 0.0)
    perf = (anchor or {}).get("perf", 0.0)

    def nanos(t: float) -> str:
        return str(int((wall + (t - perf)) * 1e9))

    otlp_spans = []
    for s in spans:
        trace_hex = hashlib.md5(
            str(s.get("trace", "")).encode()).hexdigest()
        attrs = [_otlp_attr(k, v) for k, v in sorted(s.items())
                 if k not in ("t0", "dur", "tid", "name", "trace",
                              "span", "parent", "status")]
        status = s.get("status", "ok")
        rec = {
            "traceId": trace_hex,
            "spanId": f"{int(s['span']) & (2**64 - 1):016x}",
            "name": s.get("name", "?"),
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": nanos(s["t0"]),
            "endTimeUnixNano": nanos(s["t0"] + s.get("dur", 0.0)),
            "attributes": attrs,
            "status": {"code": 2 if status == "error" else 1},
        }
        if s.get("parent") is not None:
            rec["parentSpanId"] = \
                f"{int(s['parent']) & (2**64 - 1):016x}"
        otlp_spans.append(rec)
    return {"resourceSpans": [{
        "resource": {"attributes": [
            _otlp_attr("service.name", service)]},
        "scopeSpans": [{
            "scope": {"name": "tpuparquet.obs.trace"},
            "spans": otlp_spans,
        }],
    }]}


TRACE_FILE_FORMAT = "tpq-trace"


def write_trace_file(spans: list[dict], path: str, *,
                     ledgers: dict | None = None,
                     anchor: dict | None = None) -> bool:
    """Publish a span list atomically (tmp + ``os.replace`` via
    :func:`~tpuparquet.obs.live.atomic_write_text` — telemetry must
    never fail the scan it describes).  Format by suffix:
    ``*.perfetto.json``/``*.chrome.json`` → Chrome trace events,
    ``*.otlp.json`` → OTLP, else the native ``tpq-trace`` envelope
    (spans + optional per-label attribution ledgers + the wall/perf
    anchor) that ``parquet-tool doctor`` reads."""
    from .live import atomic_write_text

    if path.endswith((".perfetto.json", ".chrome.json")):
        obj = spans_chrome_trace(spans)
    elif path.endswith(".otlp.json"):
        obj = spans_otlp(spans, anchor)
    else:
        obj = {"format": TRACE_FILE_FORMAT, "version": 1,
               "spans": spans}
        if anchor is not None:
            obj["anchor"] = anchor
        if ledgers is not None:
            obj["ledgers"] = ledgers
    return atomic_write_text(path, json.dumps(obj, sort_keys=True))


def load_trace_file(path: str) -> tuple[list[dict], dict]:
    """Read back a trace for analysis: the native ``tpq-trace``
    envelope, a bare span list, or a Chrome trace whose args carry
    the span ids (a ``*.perfetto.json`` export round-trips).  Returns
    ``(spans, ledgers)``; raises ``ValueError`` for anything else."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"trace file {path!r} is not valid JSON: {e}") from e
    if isinstance(doc, list):
        return doc, {}
    if isinstance(doc, dict) and doc.get("format") == TRACE_FILE_FORMAT:
        return list(doc.get("spans") or []), dict(doc.get("ledgers")
                                                  or {})
    if isinstance(doc, dict) and "traceEvents" in doc:
        spans = []
        for ev in doc["traceEvents"]:
            if ev.get("ph") != "X":
                continue
            s = dict(ev.get("args") or {})
            s.setdefault("name", ev.get("name"))
            s["t0"] = ev.get("ts", 0.0) / 1e6
            s["dur"] = ev.get("dur", 0.0) / 1e6
            s["tid"] = ev.get("tid", 0)
            if "span" not in s:
                raise ValueError(
                    f"{path!r}: Chrome trace without tpq span ids in "
                    "args — re-export the native tpq-trace form for "
                    "doctor analysis")
            spans.append(s)
        return spans, {}
    raise ValueError(f"{path!r} is not a tpq trace export")


def column_table(log: EventLog) -> list[dict]:
    """Per-column aggregate rows, sorted by column path.

    Each row: pages, values, transport mix, wire/raw ratio over the
    gated pages, summed per-page plan wall, and a representative gate
    reason (the modal transport's most recent reason)."""
    rows = []
    for col, events in sorted(log.by_column().items()):
        transports: dict[str, int] = {}
        wire = raw = 0
        plan_s = 0.0
        values = 0
        for e in events:
            transports[e.transport] = transports.get(e.transport, 0) + 1
            values += e.num_values
            plan_s += e.plan_s
            if e.wire_bytes is not None and e.raw_bytes:
                wire += e.wire_bytes
                raw += e.raw_bytes
        modal = max(transports, key=transports.get)
        reason = next(
            (e.reason for e in reversed(events)
             if e.transport == modal and e.reason), "")
        rows.append({
            "column": col,
            "pages": len(events),
            "values": values,
            "transports": transports,
            "wire_to_raw": round(wire / raw, 3) if raw else None,
            "plan_s": round(plan_s, 6),
            "reason": reason,
        })
    return rows


def format_column_table(rows: list[dict]) -> str:
    """Fixed-width text rendering of :func:`column_table`."""
    if not rows:
        return "(no page events)"
    headers = ["column", "pages", "values", "transports", "wire/raw",
               "plan_ms", "gate reason"]
    table = []
    for r in rows:
        mix = " ".join(f"{t}:{c}" for t, c in sorted(r["transports"]
                                                     .items()))
        table.append([
            r["column"], str(r["pages"]), f"{r['values']:,}", mix,
            "-" if r["wire_to_raw"] is None else f"{r['wire_to_raw']:.3f}",
            f"{r['plan_s'] * 1e3:.1f}",
            r["reason"] or "-",
        ])
    widths = [max(len(h), *(len(row[i]) for row in table))
              for i, h in enumerate(headers)]
    lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    lines.append("  ".join("-" * w for w in widths))
    for row in table:
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    return "\n".join(lines)
