"""Flight recorder: the last N telemetry events, always.

The event log (``obs/events.py``) is rich but scoped — it exists only
under ``collect_stats(events=True)`` and grows without bound.  The
flight recorder is its always-on complement: a **bounded ring buffer
per thread** that keeps the most recent span/fault/page records at
near-zero cost, independent of any collector scope, so that when a
scan dies the post-mortem (:mod:`~tpuparquet.obs.postmortem`) can say
what the process was doing in the seconds before — the Dapper
discipline of having the trace on *before* the incident.

Cost model: one module-global load + ``is None`` check when disabled
(the same shape as ``faults.fault_point``); when enabled, one bounded
``deque.append`` of a small dict per record.  Recording sites are
chunk/page/span/fault granularity — never per value — and the rings
are ``TPQ_FLIGHT_RECORDER`` entries deep per thread (default 256;
``0`` disables recording entirely).

Thread model matches the rest of the telemetry layer: each thread
appends to its OWN ring (registered with the recorder under a lock at
first use); :meth:`FlightRecorder.snapshot` folds the rings into one
time-sorted list.  No cross-thread appends, no locks on the record
path.

Record shape: ``{"t": unix_time, "kind": ..., "site": ...,
**coordinates}`` — the same site/kind vocabulary as the event log's
fault records, so a post-mortem reads like a ``pages.jsonl`` tail.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

__all__ = ["FlightRecorder", "ThreadSlots", "flight", "recorder",
           "set_ring", "ring_default"]


class ThreadSlots:
    """Per-thread write slots with dead-owner retirement — the shared
    registration machinery under the flight recorder's rings and the
    metrics registry's shards (one owner so the retirement logic
    can't drift between them).

    Each thread lazily gets its own slot (``make()``) registered
    under a lock; when a NEW thread registers, slots whose owner
    thread has exited are folded into one retained ``retired`` slot
    (``fold(retired, dead_slot)`` — exact, the dead owner can no
    longer write) and dropped.  Total slots stay bounded by live
    threads + 1 under arbitrary thread churn (the deadline/hedge
    layers spawn a disposable worker per bounded unit/read)."""

    def __init__(self, make, fold):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._slots: list[tuple] = []   # [(owner_thread, slot)]
        self._make = make
        self._fold = fold
        self.retired = make()

    def get(self):
        """The calling thread's slot (created + registered on first
        use)."""
        s = getattr(self._tls, "slot", None)
        if s is None:
            s = self._make()
            me = threading.current_thread()
            with self._lock:
                self._retire_dead_locked()
                self._slots.append((me, s))
            self._tls.slot = s
        return s

    def _retire_dead_locked(self) -> None:
        live = []
        for owner, s in self._slots:
            if owner.is_alive():
                live.append((owner, s))
            else:
                self._fold(self.retired, s)
        if len(live) != len(self._slots):
            self._slots = live

    def all(self) -> list:
        """Every live slot plus the retired fold (snapshot reads)."""
        with self._lock:
            return [s for _, s in self._slots] + [self.retired]


def ring_default() -> int:
    """Per-thread ring depth from ``TPQ_FLIGHT_RECORDER`` (default
    256; 0/invalid-negative disables)."""
    try:
        v = int(os.environ.get("TPQ_FLIGHT_RECORDER", "256"))
    except ValueError:
        return 256
    return max(v, 0)


class FlightRecorder:
    """Per-thread bounded rings of recent telemetry records.

    Rings live in a :class:`ThreadSlots` (per-thread registration,
    dead-owner retirement), so memory stays bounded under thread
    churn; a dead worker's trailing records survive in the retired
    ring — an abandoned hedge worker's last reads are exactly the
    records a post-mortem wants."""

    def __init__(self, ring: int = 256):
        self.ring = ring
        self._slots = ThreadSlots(
            make=lambda: deque(maxlen=ring),
            fold=lambda retired, dead: retired.extend(dead))

    def record(self, kind: str, site: str | None = None, **fields):
        rec = {"t": time.time(), "kind": kind}
        if site is not None:
            rec["site"] = site
        if fields:
            rec.update(fields)
        self._slots.get().append(rec)

    def snapshot(self, last: int | None = None) -> list[dict]:
        """All rings (live + retired) folded into one time-sorted
        list (oldest first); ``last`` trims to the trailing N
        records.  Safe against concurrent appends (each ring is
        copied under the GIL)."""
        out: list[dict] = []
        for r in self._slots.all():
            out.extend(list(r))
        out.sort(key=lambda e: e["t"])
        if last is not None and len(out) > last:
            out = out[-last:]
        return out

    def clear(self) -> None:
        for r in self._slots.all():
            r.clear()

    def __len__(self) -> int:
        return len(self.snapshot())


#: The active recorder, or None when disabled — the single gate every
#: hot-path hook checks (one global load + `is None`, exactly the
#: fault_point discipline).  Initialized from the environment at
#: import; reconfigure at runtime with :func:`set_ring`.
_active: FlightRecorder | None = None


def _init_from_env() -> None:
    global _active
    n = ring_default()
    _active = FlightRecorder(n) if n > 0 else None


_init_from_env()


def recorder() -> FlightRecorder | None:
    """The active recorder (None when disabled)."""
    return _active


def set_ring(n: int) -> FlightRecorder | None:
    """Reconfigure at runtime: ``n > 0`` installs a FRESH recorder
    with that ring depth, ``0`` disables.  Returns the new recorder
    (tests and A/B benches flip this without re-importing)."""
    global _active
    _active = FlightRecorder(n) if n > 0 else None
    return _active


def flight(kind: str, site: str | None = None, **fields) -> None:
    """Instrumentation hook: record onto the calling thread's ring.
    No-op (one global ``is None`` check) when the recorder is off.

    Hot per-page/per-chunk sites guard the CALL itself with
    ``recorder._active is not None`` so the disabled path skips even
    the kwargs construction and argument evaluation — the same shape
    as the ``st is not None`` stats discipline.  Cold sites (faults,
    quarantines, retries) just call ``flight`` directly."""
    rec = _active
    if rec is not None:
        rec.record(kind, site, **fields)
