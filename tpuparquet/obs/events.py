"""Structured decode telemetry: the per-page event log.

``DecodeStats`` says *how many* pages took each transport;
this log says *which* pages, *why* the gate chose that transport (the
wire-size numbers from the competition in ``kernels/device.py``), and
where each page's host plan time went.  One :class:`PageEvent` per data
page, plus host-side phase :meth:`spans <EventLog.span>` (plan /
transfer / dispatch) that the Perfetto exporter (``obs.export``) turns
into a timeline.

Activation rides the existing collector fast path: the decode hot
paths check ``current_stats() is None`` first and only then
``st.events`` — with no collector (or a plain ``collect_stats()``)
nothing is allocated per page.  Enable with
``collect_stats(events=True)``.

Thread model matches ``DecodeStats``: each worker thread records into
its own ``EventLog`` (via ``worker_stats(like=parent)``) and the
coordinator folds with :meth:`EventLog.merge_from` — no cross-thread
appends.  Worker logs share the parent's ``t0`` so merged span
timestamps stay on one clock.
"""

from __future__ import annotations

import json
import time

__all__ = ["PageEvent", "EventLog", "TRANSPORT_COUNTER",
           "counter_counts", "event_summary", "fault_counts_by_column",
           "plan_cache_span_counts", "load_jsonl"]

# transport label -> the DecodeStats counter that transport increments
# (transports absent here increment none of the per-transport counters:
# they are dedicated device kernels — dict / bss / delta-bp / ... — or
# the CPU-oracle path's "cpu").  tools/check_device_paths.py --events
# and tests/test_fallback_matrix.py enforce event/counter agreement
# through this table.
TRANSPORT_COUNTER = {
    "snappy-tokens": "pages_device_snappy",
    "planes": "pages_device_planes",
    "delta-lanes": "pages_device_delta_lanes",
    "host": "pages_host_values",
    # graceful degradation (kernels/device.py cpu_fallback_values):
    # pages decoded by the CPU oracle because device dispatch failed —
    # deliberately NOT "host", so the fallback-matrix golden set stays
    # about routing decisions, not fault handling
    "host-degraded": "pages_degraded",
}


class PageEvent:
    """One decoded data page: identity, routing decision, and cost."""

    __slots__ = ("column", "page", "page_type", "encoding", "codec",
                 "num_values", "non_null", "transport", "wire_bytes",
                 "raw_bytes", "gate", "reason", "plan_s", "t")

    def __init__(self, column, page, page_type, encoding, codec,
                 num_values, non_null, transport, wire_bytes=None,
                 raw_bytes=None, gate=None, reason=None, plan_s=0.0,
                 t=0.0):
        self.column = column          # dotted path_in_schema
        self.page = page              # ordinal within the chunk
        self.page_type = page_type    # "v1" | "v2"
        self.encoding = encoding      # Encoding name
        self.codec = codec            # CompressionCodec name
        self.num_values = num_values  # record slots (levels included)
        self.non_null = non_null
        self.transport = transport    # see TRANSPORT_COUNTER
        self.wire_bytes = wire_bytes  # chosen transport's wire cost
        self.raw_bytes = raw_bytes    # what raw staging would have cost
        self.gate = gate              # {candidate: wire | "declined" ...}
        self.reason = reason          # human gate verdict
        self.plan_s = plan_s          # host plan wall for this page
        self.t = t                    # log-relative start time (s)

    def as_dict(self) -> dict:
        d = {
            "column": self.column, "page": self.page,
            "page_type": self.page_type, "encoding": self.encoding,
            "codec": self.codec, "num_values": self.num_values,
            "non_null": self.non_null, "transport": self.transport,
            "plan_s": round(self.plan_s, 6), "t": round(self.t, 6),
        }
        if self.wire_bytes is not None:
            d["wire_bytes"] = self.wire_bytes
        if self.raw_bytes is not None:
            d["raw_bytes"] = self.raw_bytes
        if self.gate:
            d["gate"] = self.gate
        if self.reason:
            d["reason"] = self.reason
        return d

    def __repr__(self):
        return (f"PageEvent({self.column}[{self.page}] {self.encoding}"
                f" -> {self.transport})")


class EventLog:
    """In-process, queryable event store with a JSON-lines surface."""

    __slots__ = ("pages", "spans", "faults", "t0")

    def __init__(self, t0: float | None = None):
        self.pages: list[PageEvent] = []
        self.spans: list[dict] = []
        # fault-tolerance records: injected faults, retries, CRC
        # rejections, degradations, quarantines — whatever the
        # resilience layer wants on the timeline (tpuparquet/faults.py
        # and the resilient read/scan paths emit these)
        self.faults: list[dict] = []
        self.t0 = time.perf_counter() if t0 is None else t0

    # -- recording (single-thread per log; see module docstring) ---------

    def page(self, **kw) -> None:
        kw.setdefault("t", time.perf_counter() - self.t0)
        self.pages.append(PageEvent(**kw))

    def span(self, name: str, phase: str, start: float, end: float,
             tid: int = 0, **args) -> None:
        """One host-side phase span; ``start``/``end`` are
        ``perf_counter()`` readings (rebased to ``t0`` on export)."""
        self.spans.append({
            "name": name, "phase": phase,
            "start": start - self.t0, "dur": end - start,
            "tid": tid, "args": args,
        })

    def fault(self, **kw) -> None:
        """One fault-layer record (site/kind plus whatever coordinates
        the site knew); timestamped like pages."""
        kw.setdefault("t", time.perf_counter() - self.t0)
        self.faults.append(kw)

    def merge_from(self, other: "EventLog") -> None:
        self.pages.extend(other.pages)
        self.spans.extend(other.spans)
        self.faults.extend(other.faults)

    # -- queries ---------------------------------------------------------

    def transport_counts(self) -> dict:
        out: dict[str, int] = {}
        for e in self.pages:
            out[e.transport] = out.get(e.transport, 0) + 1
        return out

    def by_column(self) -> dict:
        out: dict[str, list[PageEvent]] = {}
        for e in self.pages:
            out.setdefault(e.column, []).append(e)
        return out

    def pages_for(self, column: str | None = None,
                  transport: str | None = None) -> list[PageEvent]:
        return [e for e in self.pages
                if (column is None or e.column == column)
                and (transport is None or e.transport == transport)]

    # -- serialization ---------------------------------------------------

    def to_jsonl(self) -> str:
        """JSON-lines: one object per record, pages then spans then
        faults, each tagged with ``"kind"`` — greppable, streamable,
        diffable.  Fault records carry their OWN kind (``hedge_won``,
        ``deadline_exceeded``, ...) which the envelope tag must not
        clobber: it moves to ``"fault_kind"`` on the wire and
        :func:`load_jsonl` moves it back, so the round trip is
        lossless."""
        lines = []
        for e in self.pages:
            d = e.as_dict()
            d["kind"] = "page"
            lines.append(json.dumps(d, sort_keys=True))
        for s in self.spans:
            d = dict(s)
            d["kind"] = "span"
            lines.append(json.dumps(d, sort_keys=True))
        for fv in self.faults:
            d = dict(fv)
            if "kind" in d:
                d["fault_kind"] = d.pop("kind")
            d["kind"] = "fault"
            lines.append(json.dumps(d, sort_keys=True, default=str))
        return "\n".join(lines) + ("\n" if lines else "")

    def write_jsonl(self, path_or_file) -> None:
        if hasattr(path_or_file, "write"):
            path_or_file.write(self.to_jsonl())
        else:
            with open(path_or_file, "w") as f:
                f.write(self.to_jsonl())


def load_jsonl(path_or_file) -> EventLog:
    """Rebuild an :class:`EventLog` from a :meth:`EventLog.write_jsonl`
    dump — the round trip that lets ``parquet-tool profile`` analyze a
    SAVED ``pages.jsonl`` instead of re-running the decode.  Unknown
    keys on page records are dropped (a newer writer's extra fields
    must not break an older analyzer); span/fault records pass through
    as the dicts they are."""
    if hasattr(path_or_file, "read"):
        lines = path_or_file.read().splitlines()
    else:
        with open(path_or_file) as f:
            lines = f.read().splitlines()
    log = EventLog(t0=0.0)
    page_keys = set(PageEvent.__slots__)
    for line in lines:
        line = line.strip()
        if not line:
            continue
        d = json.loads(line)
        kind = d.pop("kind", None)
        if kind == "page":
            log.pages.append(
                PageEvent(**{k: v for k, v in d.items()
                             if k in page_keys}))
        elif kind == "span":
            log.spans.append(d)
        elif kind == "fault":
            if "fault_kind" in d:
                d["kind"] = d.pop("fault_kind")
            log.faults.append(d)
        else:
            raise ValueError(
                f"not a tpq event log line (kind={kind!r}): "
                f"{line[:80]!r}")
    return log


def counter_counts(pages) -> dict:
    """Fold page events into per-``DecodeStats``-counter tallies via
    :data:`TRANSPORT_COUNTER` — the single definition of the
    event/counter agreement invariant that
    ``tests/test_fallback_matrix.py`` and
    ``tools/check_device_paths.py --events`` both enforce: for every
    transport counter, ``counter_counts(events)[counter] ==
    st.as_dict()[counter]``."""
    out: dict[str, int] = {}
    for e in pages:
        c = TRANSPORT_COUNTER.get(e.transport)
        if c is not None:
            out[c] = out.get(c, 0) + 1
    return out


def fault_counts_by_column(log: "EventLog | None",
                           kinds=("hedge_issued", "hedge_won",
                                  "deadline_exceeded")) -> dict:
    """Per-column tallies of time-domain fault records: ``{column:
    {kind: count}}`` (records without a column fold under ``"-"``).
    The observability face of the hedge/deadline layer — ``parquet-tool
    profile`` prints this so a degraded replica shows up as WHICH
    column's reads are hedging, not just a global count."""
    out: dict[str, dict[str, int]] = {}
    if log is None:
        return out
    for f in log.faults:
        k = f.get("kind")
        if k not in kinds:
            continue
        col = f.get("column") or "-"
        row = out.setdefault(col, {})
        row[k] = row.get(k, 0) + 1
    return out


def plan_cache_span_counts(log: "EventLog | None") -> dict:
    """Plan-span cache verdicts: ``{"hit": n, "miss": n, "off": n}``
    over the per-column plan spans (each carries the footer-keyed plan
    cache's lookup outcome in its ``cache`` arg — ``kernels/device.py``
    ``_plan_one_column``).  The observability face of the plan cache:
    ``parquet-tool profile`` prints this next to the hit/miss counters
    so cache effectiveness is visible per run, and a per-span ``plan_s``
    comparison between hit and miss spans measures what a warm re-read
    actually saves."""
    out: dict[str, int] = {}
    if log is None:
        return out
    for s in log.spans:
        if s.get("name") != "plan":
            continue
        verdict = (s.get("args") or {}).get("cache")
        if verdict:
            out[verdict] = out.get(verdict, 0) + 1
    return out


def event_summary(log: "EventLog | None") -> dict:
    """Compact per-run digest of an event log (what ``bench.py``
    attaches to each config): device-path page count, transport mix,
    and the wire-vs-raw ratio over the pages that had a competition.
    CPU-oracle pages (transport ``"cpu"``) are excluded so a run that
    decodes both paths (the bench parity gate) reports the device mix."""
    if log is None:
        return {}
    dev = [e for e in log.pages if e.transport != "cpu"]
    transports: dict[str, int] = {}
    wire = raw = 0
    for e in dev:
        transports[e.transport] = transports.get(e.transport, 0) + 1
        if e.wire_bytes is not None and e.raw_bytes:
            wire += e.wire_bytes
            raw += e.raw_bytes
    out = {"pages": len(dev), "transports": transports}
    if raw:
        out["wire_bytes"] = wire
        out["raw_bytes"] = raw
        out["wire_to_raw"] = round(wire / raw, 3)
    return out
