"""Live scan progress: units, rates, ETA, stragglers.

The scan drivers (``shard/scan.py``, ``shard/distributed.py``) own a
:class:`ScanProgress` each and tick it at unit boundaries; anything —
the driving process itself, or ``parquet-tool top`` in another
terminal — can watch the scan *while it runs* through
:meth:`ScanProgress.snapshot` (in-process) or the exported JSON status
file (cross-process; ``TPQ_PROGRESS_EXPORT`` / ``progress_export=``,
written atomically and throttled so a 10k-unit scan doesn't fsync 10k
times).

Rates and ETA use an EWMA of per-unit wall time (alpha 0.2 — a few
units of memory, so a straggler bends the ETA without whiplashing
it).  Straggler detection reuses the deadline round's
:class:`~tpuparquet.deadline.LatencyTracker`: completed unit walls
feed a rolling window, and an IN-FLIGHT unit whose elapsed exceeds
the window p95 (with a small multiplier and floor) is flagged — the
Tail-at-Scale observable, surfaced before any deadline kills it.

Progress gauges also land on the live metrics registry, named by the
scan's sanitized label (``scan_units_done``/``scan_units_total``/
``scan_rows_per_s`` for the default ``label="scan"``;
``scan_p0_units_done``... for a multi-host driver's ``scan.p0``), so a
Prometheus scrape sees the same numbers as ``parquet-tool top`` and
two scans with distinct labels never clobber each other's gauges.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

__all__ = ["ScanProgress", "progress_export_default",
           "read_progress_file", "label_slug"]

_EWMA_ALPHA = 0.2
_STRAGGLER_FACTOR = 1.5
_STRAGGLER_FLOOR_S = 0.05


def progress_export_default() -> str | None:
    """Status-file path from ``TPQ_PROGRESS_EXPORT`` (None = off)."""
    return os.environ.get("TPQ_PROGRESS_EXPORT") or None


def label_slug(label: str) -> str:
    """Prometheus-/filename-safe slug of a scan label (shared by the
    gauge naming below and the scan drivers' per-label status-file
    suffixing, so the two derivations cannot drift)."""
    return re.sub(r"[^A-Za-z0-9_]", "_", label) or "scan"


class ScanProgress:
    """Progress of one scan run: tick at unit boundaries, watch live.

    Thread-safe (the scan ticks from its driving thread; ``top`` /
    exporters snapshot from anywhere).  ``export`` is the optional
    status-file path; ``min_export_interval`` throttles rewrites
    (state transitions always flush)."""

    def __init__(self, total_units: int, *, label: str = "scan",
                 export: str | None = None,
                 min_export_interval: float = 0.2):
        from ..deadline import LatencyTracker

        self.label = label
        # gauge-name key: Prometheus-safe slug of the label, so
        # concurrent scans with distinct labels (e.g. the multi-host
        # driver's scan.p<idx>) keep separate gauges
        self._slug = label_slug(label)
        self.total_units = total_units
        self.export_path = export
        self._min_export = min_export_interval
        self._lock = threading.Lock()
        self._tracker = LatencyTracker(window=64, min_samples=4)
        self._inflight: dict[int, float] = {}   # unit -> monotonic start
        self._t0 = None
        self._last_export = 0.0
        self._ewma_unit_s: float | None = None
        self.units_done = 0
        self.units_quarantined = 0
        self.rows_done = 0
        self.bytes_staged = 0
        self.attribution: dict | None = None
        self.profile: dict | None = None
        self.state = "pending"     # -> running -> done | error | stopped

    # -- ticks (called by the scan driver) -------------------------------

    def begin(self) -> None:
        with self._lock:
            if self._t0 is None:
                self._t0 = time.monotonic()
            self.state = "running"
        self._export(force=True)

    def restart(self, done: int = 0) -> None:
        """Fresh run of the same scan (``run()`` after a partial
        ``run_iter``, or a cursor resume): zero the tallies, prime
        ``units_done`` with the cursor position (resumed units count
        as done — the operator wants whole-scan progress), restart
        the clock."""
        with self._lock:
            self._t0 = None
            self._inflight.clear()
            self._tracker.reset()
            self._ewma_unit_s = None
            self.units_done = done
            self.units_quarantined = 0
            self.rows_done = 0
            self.bytes_staged = 0
            self.state = "pending"

    def unit_started(self, unit: int) -> None:
        with self._lock:
            self._inflight[unit] = time.monotonic()
        # a frame at unit START too (throttled): the status file's ts
        # then moves at every unit boundary, so a watcher's staleness
        # verdict keys off real writer silence, not unit length alone
        self._export()

    def unit_cancelled(self, unit: int) -> None:
        """The unit marked started never existed (generator was
        already exhausted) — drop it from the in-flight set."""
        with self._lock:
            self._inflight.pop(unit, None)

    def unit_done(self, unit: int, *, rows: int = 0,
                  quarantined: bool = False,
                  bytes_staged: int | None = None) -> None:
        now = time.monotonic()
        with self._lock:
            start = self._inflight.pop(unit, None)
            if start is not None:
                dt = now - start
                self._tracker.record(dt)
                self._ewma_unit_s = dt if self._ewma_unit_s is None \
                    else (_EWMA_ALPHA * dt
                          + (1.0 - _EWMA_ALPHA) * self._ewma_unit_s)
            self.units_done += 1
            if quarantined:
                self.units_quarantined += 1
            self.rows_done += rows
            if bytes_staged is not None:
                self.bytes_staged = bytes_staged
        self._export()
        self._gauges()

    def set_attribution(self, d: dict | None) -> None:
        """Attach the scan's resource-attribution view (per-stage
        cpu-seconds, bytes, peak arena — obs/attribution.py) to the
        exported frames, so ``parquet-tool top`` shows the same
        numbers the ledger accounts.  Updated at unit boundaries by
        the scan drivers."""
        with self._lock:
            self.attribution = d

    def set_profile(self, d: dict | None) -> None:
        """Attach the armed sampling profiler's brief (samples/s,
        off-CPU share, top frame — obs/profiler.py) to the exported
        frames for the ``top``/``watch`` PROFILE line.  Updated at
        unit boundaries like :meth:`set_attribution`."""
        with self._lock:
            self.profile = d

    def finish(self, state: str = "done") -> None:
        with self._lock:
            self.state = state
            self._inflight.clear()
        self._export(force=True)
        self._gauges()

    # -- views ------------------------------------------------------------

    def elapsed_s(self) -> float:
        with self._lock:
            return 0.0 if self._t0 is None \
                else time.monotonic() - self._t0

    def stragglers(self) -> list[dict]:
        """In-flight units running past the rolling p95 of completed
        unit walls (scaled; a fresh window flags nothing — no samples,
        no verdict)."""
        now = time.monotonic()
        with self._lock:
            inflight = dict(self._inflight)
        p95 = self._tracker.quantile(0.95)
        if p95 is None or len(self._tracker) < 4:
            return []
        bound = max(p95 * _STRAGGLER_FACTOR, _STRAGGLER_FLOOR_S)
        return [
            {"unit": u, "elapsed_s": round(now - t0, 3),
             "p95_s": round(p95, 3)}
            for u, t0 in sorted(inflight.items())
            if now - t0 > bound
        ]

    def snapshot(self) -> dict:
        """One JSON-serializable frame: everything ``parquet-tool
        top`` renders."""
        elapsed = self.elapsed_s()
        with self._lock:
            done = self.units_done
            total = self.total_units
            rows = self.rows_done
            ewma = self._ewma_unit_s
            state = self.state
            quarantined = self.units_quarantined
            bytes_staged = self.bytes_staged
            inflight = len(self._inflight)
            attribution = self.attribution
            profile = self.profile
        remaining = max(total - done, 0)
        eta = (remaining * ewma
               if (ewma is not None and state == "running") else None)
        rows_per_s = rows / elapsed if elapsed > 0 else 0.0
        return {
            "format": "tpq-progress",
            "version": 1,
            "label": self.label,
            "state": state,
            "pid": os.getpid(),
            "ts": time.time(),
            "units_done": done,
            "units_total": total,
            "units_quarantined": quarantined,
            "units_inflight": inflight,
            "rows_done": rows,
            "bytes_staged": bytes_staged,
            "elapsed_s": round(elapsed, 3),
            "rows_per_s": round(rows_per_s, 1),
            "ewma_unit_s": (None if ewma is None else round(ewma, 4)),
            "eta_s": (None if eta is None else round(eta, 3)),
            "stragglers": self.stragglers(),
            "attribution": attribution,
            "profile": profile,
        }

    # -- export (cross-process channel) -----------------------------------

    def _export(self, force: bool = False) -> None:
        path = self.export_path
        if not path:
            return
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_export < self._min_export:
                return
            self._last_export = now
        from .live import atomic_write_text

        # best-effort: a missing directory or full disk must not fail
        # the scan its status file describes
        atomic_write_text(path, json.dumps(self.snapshot(),
                                           sort_keys=True))

    def _gauges(self) -> None:
        from .live import live_enabled, registry

        if not live_enabled():
            return
        reg = registry()
        slug = self._slug
        reg.gauge(f"{slug}_units_done", self.units_done)
        reg.gauge(f"{slug}_units_total", self.total_units)
        reg.gauge(f"{slug}_rows_done", self.rows_done)
        snap_elapsed = self.elapsed_s()
        if snap_elapsed > 0:
            reg.gauge(f"{slug}_rows_per_s",
                      round(self.rows_done / snap_elapsed, 1))


def read_progress_file(path: str) -> dict:
    """Read back an exported status frame, validating the envelope.
    Raises ``ValueError`` on anything that is not a progress frame
    (atomic writes mean a torn file here is damage, not a race)."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"progress file {path!r} is not valid JSON: {e}") from e
    if not isinstance(doc, dict) or doc.get("format") != "tpq-progress":
        raise ValueError(f"{path!r} is not a tpq progress file")
    return doc
