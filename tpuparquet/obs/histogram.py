"""Fixed log2-bucket histograms whose merges are exact.

Every collector records into buckets at the same fixed boundaries
(bucket ``i`` holds the integer values whose ``bit_length()`` is ``i``,
i.e. ``[2**(i-1), 2**i)``; bucket 0 holds exactly 0), so merging two
histograms is elementwise integer addition — no re-binning, no float
error, identical totals regardless of merge order or sharding.  That is
the property the cross-thread (``stats.worker_stats``) and cross-host
(``shard.distributed.allgather_stats``) folds rely on: the fleet
histogram equals the histogram of the fleet.

Values are non-negative integers by convention; callers quantize
up-front (times as microseconds, ratios as permille) and name the unit
in the histogram key (``stager_wave_us``, ``wire_ratio_permille``).
"""

from __future__ import annotations

__all__ = ["Histogram", "N_BUCKETS", "bucket_lo", "bucket_hi"]

# bucket 64 absorbs everything >= 2**63 (nothing we measure gets there)
N_BUCKETS = 65


def bucket_lo(i: int) -> int:
    """Inclusive lower bound of bucket ``i``."""
    return 0 if i == 0 else 1 << (i - 1)


def bucket_hi(i: int) -> int:
    """Exclusive upper bound of bucket ``i``."""
    return 1 << i


class Histogram:
    """Counts per log2 bucket plus the exact sum and sample count.

    ``counts`` is a plain list of ints — recording is two list ops and
    three int adds, cheap enough to run on every page while a collector
    is active.
    """

    __slots__ = ("counts", "n", "total")

    def __init__(self):
        self.counts = [0] * N_BUCKETS
        self.n = 0
        self.total = 0

    def record(self, value) -> None:
        v = int(value)
        if v < 0:
            v = 0
        self.counts[min(v.bit_length(), N_BUCKETS - 1)] += 1
        self.n += 1
        self.total += v

    def merge_from(self, other: "Histogram") -> None:
        """Exact fold of another collector's buckets into this one."""
        c, oc = self.counts, other.counts
        for i in range(N_BUCKETS):
            c[i] += oc[i]
        self.n += other.n
        self.total += other.total

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def quantile(self, q: float) -> int:
        """Upper bound of the bucket containing the q-quantile (0<=q<=1).
        Bucket-resolution only — exact enough to say 'p99 page is 1-2 MB'."""
        if self.n == 0:
            return 0
        target = q * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                return bucket_hi(i)
        return bucket_hi(N_BUCKETS - 1)

    def as_dict(self) -> dict:
        """Sparse JSON form: only non-empty buckets ship (page-size
        histograms touch a handful of the 65 buckets)."""
        return {
            "n": self.n,
            "total": self.total,
            "counts": {str(i): c for i, c in enumerate(self.counts) if c},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        h = cls()
        h.n = int(d.get("n", 0))
        h.total = int(d.get("total", 0))
        for k, c in (d.get("counts") or {}).items():
            h.counts[int(k)] = int(c)
        return h

    def __repr__(self):
        return (f"Histogram(n={self.n}, total={self.total}, "
                f"p50<{self.quantile(0.5)}, p99<{self.quantile(0.99)})")
