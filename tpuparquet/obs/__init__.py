"""Structured decode telemetry (SURVEY.md §5 "tracing / metrics").

Two regimes share this package:

**Post-hoc** (scoped, rich) — under the ``collect_stats()`` API:

* :mod:`~tpuparquet.obs.events` — one record per decoded page with the
  chosen transport and the wire-size numbers that chose it, plus
  host-side phase spans; JSON-lines out, queryable in-process.
* :mod:`~tpuparquet.obs.histogram` — fixed log2-bucket histograms
  (page sizes, wire ratios, stager wave times) whose merges are exact
  across threads and hosts.
* :mod:`~tpuparquet.obs.export` — Chrome-trace/Perfetto JSON and the
  ``parquet-tool profile`` column table.

**Always-on** (process-lifetime, low-overhead) — no scope required:

* :mod:`~tpuparquet.obs.live` — the process-wide
  :class:`~tpuparquet.obs.live.MetricsRegistry`
  (counters/gauges/histograms, per-thread shards, exact merges),
  Prometheus text + JSON export, optional background snapshot writer
  (``TPQ_METRICS_EXPORT`` / ``TPQ_METRICS_INTERVAL_S``).  Every
  outermost ``collect_stats()`` scope and every scan unit folds into
  it exactly.
* :mod:`~tpuparquet.obs.recorder` — the flight recorder: bounded
  per-thread rings of the last N span/fault/page records
  (``TPQ_FLIGHT_RECORDER``, default 256; 0 disables).
* :mod:`~tpuparquet.obs.progress` — live scan progress
  (units/rows/s/EWMA ETA/stragglers), exported as a JSON status file
  (``TPQ_PROGRESS_EXPORT``) the ``parquet-tool top`` view tails.
* :mod:`~tpuparquet.obs.profiler` — the background sampling profiler
  (``TPQ_PROFILE`` / ``TPQ_PROFILE_HZ``): grid-jittered
  ``sys._current_frames()`` walks tagged with the ambient trace/span,
  scan label, and stage, off-CPU classification (lock sites, IO
  waits), mergeable per-(label, stage) stack tries, collapsed-stack /
  Chrome-trace export (``TPQ_PROFILE_EXPORT``), and the
  ``parquet-tool flame`` / ``doctor --profile`` consumers.
* :mod:`~tpuparquet.obs.postmortem` — automatic ``.postmortem.json``
  dumps (trigger coordinates + flight-recorder tail + metrics
  snapshot) beside the durable cursor when quarantine/salvage/
  deadline events fire.

**Longitudinal** (the time dimension — SLOs, budgets, paging):

* :mod:`~tpuparquet.obs.timeseries` — the bounded on-disk ring of
  delta-aware metric snapshots (``TPQ_TIMESERIES_DIR``), fed by the
  snapshot writer's ticks and by scan-end flushes.
* :mod:`~tpuparquet.obs.digest` — mergeable latency quantile digests
  (``TPQ_LATENCY_DIGEST``): per-label/per-stage unit and scan walls
  in fixed sub-octave buckets (~6% relative), exact merges across
  threads and hosts, exemplars linking hot buckets to trace ids.
* :mod:`~tpuparquet.obs.slo` — declarative objectives
  (``TPQ_SLO_FILE``) evaluated over the ring into error budgets and
  multi-window burn rates.
* :mod:`~tpuparquet.obs.alerts` — threshold/absence/burn-rate rules
  with stdout/file/callback sinks and atomic capped alert records
  (``TPQ_ALERTS_EXPORT``); ``parquet-tool watch`` renders all of it
  live.

Entry points::

    with tpuparquet.collect_stats(events=True) as st:
        read_row_group_device(reader, 0)
    st.events.transport_counts()      # {"planes": 3, "raw": 1, ...}
    st.events.write_jsonl("pages.jsonl")
    obs.write_chrome_trace(st.events, "trace.json")  # Perfetto

    obs.registry().prometheus_text()   # always-on counters, any time
    obs.flight_recorder().snapshot()   # what just happened, per thread

Everything is zero-cost when no collector is active (the hot paths'
``current_stats() is None`` check short-circuits before any event or
histogram code runs), and event-log-free under a plain
``collect_stats()`` (``st.events is None``).  The always-on layer
keeps the same discipline: one global ``is None`` check when the
recorder is off, one ~40-field fold per scope/unit for the registry,
nothing per value.
"""

from .events import (  # noqa: F401
    EventLog,
    PageEvent,
    TRANSPORT_COUNTER,
    counter_counts,
    event_summary,
    fault_counts_by_column,
    load_jsonl,
    plan_cache_span_counts,
)
from .attribution import (  # noqa: F401
    ScanLedger,
    diagnose,
    format_diagnosis,
    ledger,
    ledgers_snapshot,
    reset_ledgers,
    stage_seconds,
)
from .export import (  # noqa: F401
    chrome_trace,
    column_table,
    format_column_table,
    load_trace_file,
    spans_chrome_trace,
    spans_otlp,
    write_chrome_trace,
    write_trace_file,
)
from .alerts import (  # noqa: F401
    AlertEngine,
    AlertRule,
    emit_alert,
    load_alerts,
    record_alert,
)
from .alerts import engine as alert_engine  # noqa: F401
from .digest import DigestRegistry, QuantileDigest, observe  # noqa: F401
from .digest import digests as latency_digests  # noqa: F401
from .histogram import Histogram, N_BUCKETS  # noqa: F401
from .live import (  # noqa: F401
    MetricsRegistry,
    export_now,
    fold_stats,
    live_enabled,
    registry,
)
from .slo import (  # noqa: F401
    evaluate as evaluate_slo,
    format_report as format_slo_report,
    load_objectives,
)
from .timeseries import (  # noqa: F401
    MetricRing,
    load_ring,
    tick,
)
from .timeseries import ring as metric_ring  # noqa: F401
from .postmortem import (  # noqa: F401
    load_postmortem,
    postmortem_path_for,
    record_incident,
)
from .profiler import (  # noqa: F401
    Profiler,
    collapsed_lines,
    diff_states,
    load_profile_file,
    merge_profile_states,
    profile_consistency,
    set_profiling,
    top_frames,
    write_profile_file,
)
from .profiler import profiler as sampling_profiler  # noqa: F401
from .progress import ScanProgress, read_progress_file  # noqa: F401
# the accessor is re-exported as `flight_recorder` so the package
# attribute `obs.recorder` stays the MODULE, not the function
from .recorder import FlightRecorder, flight, set_ring  # noqa: F401
from .recorder import recorder as flight_recorder  # noqa: F401
from .trace import (  # noqa: F401
    Tracer,
    emit_span,
    set_tracing,
    snapshot_spans,
    trace_scope,
)
from .trace import tracer as trace_tracer  # noqa: F401

__all__ = [
    "EventLog", "PageEvent", "TRANSPORT_COUNTER", "counter_counts",
    "event_summary", "fault_counts_by_column", "load_jsonl",
    "plan_cache_span_counts", "chrome_trace",
    "column_table", "format_column_table", "write_chrome_trace",
    "spans_chrome_trace", "spans_otlp", "write_trace_file",
    "load_trace_file",
    "Histogram", "N_BUCKETS",
    "MetricsRegistry", "registry", "fold_stats", "live_enabled",
    "export_now",
    "FlightRecorder", "flight", "flight_recorder", "set_ring",
    "Tracer", "emit_span", "set_tracing", "snapshot_spans",
    "trace_scope", "trace_tracer",
    "ScanLedger", "ledger", "ledgers_snapshot", "reset_ledgers",
    "stage_seconds", "diagnose", "format_diagnosis",
    "ScanProgress", "read_progress_file",
    "Profiler", "set_profiling", "sampling_profiler",
    "merge_profile_states", "write_profile_file",
    "load_profile_file", "collapsed_lines", "top_frames",
    "diff_states", "profile_consistency",
    "record_incident", "postmortem_path_for", "load_postmortem",
    "QuantileDigest", "DigestRegistry", "observe", "latency_digests",
    "MetricRing", "load_ring", "tick", "metric_ring",
    "AlertEngine", "AlertRule", "emit_alert", "alert_engine",
    "record_alert", "load_alerts",
    "evaluate_slo", "format_slo_report", "load_objectives",
]
