"""Structured decode telemetry (SURVEY.md §5 "tracing / metrics").

Three layers under the ``collect_stats()`` API:

* :mod:`~tpuparquet.obs.events` — one record per decoded page with the
  chosen transport and the wire-size numbers that chose it, plus
  host-side phase spans; JSON-lines out, queryable in-process.
* :mod:`~tpuparquet.obs.histogram` — fixed log2-bucket histograms
  (page sizes, wire ratios, stager wave times) whose merges are exact
  across threads and hosts.
* :mod:`~tpuparquet.obs.export` — Chrome-trace/Perfetto JSON and the
  ``parquet-tool profile`` column table.

Entry points::

    with tpuparquet.collect_stats(events=True) as st:
        read_row_group_device(reader, 0)
    st.events.transport_counts()      # {"planes": 3, "raw": 1, ...}
    st.events.write_jsonl("pages.jsonl")
    obs.write_chrome_trace(st.events, "trace.json")  # Perfetto

Everything is zero-cost when no collector is active (the hot paths'
``current_stats() is None`` check short-circuits before any event or
histogram code runs), and event-log-free under a plain
``collect_stats()`` (``st.events is None``).
"""

from .events import (  # noqa: F401
    EventLog,
    PageEvent,
    TRANSPORT_COUNTER,
    counter_counts,
    event_summary,
    fault_counts_by_column,
    plan_cache_span_counts,
)
from .export import (  # noqa: F401
    chrome_trace,
    column_table,
    format_column_table,
    write_chrome_trace,
)
from .histogram import Histogram, N_BUCKETS  # noqa: F401

__all__ = [
    "EventLog", "PageEvent", "TRANSPORT_COUNTER", "counter_counts",
    "event_summary", "fault_counts_by_column",
    "plan_cache_span_counts", "chrome_trace",
    "column_table", "format_column_table", "write_chrome_trace",
    "Histogram", "N_BUCKETS",
]
