"""Causal scan tracing: lightweight spans with parent/child structure.

The flight recorder (``obs/recorder.py``) answers *what just
happened*; the metrics registry (``obs/live.py``) answers *how much,
in total*.  Neither can answer the question a slow scan actually
poses: **which stage of which unit's read → plan → stage → dispatch →
gather chain bounds the wall**, across the column-parallel plan pool,
hedged replica reads, deadline workers and multiple hosts.  That is a
causality question, and this module is the Dapper-style answer: every
pipeline stage records a **span** — ``(trace_id, span_id, parent_id,
name, start, dur, status, coordinates, payload)`` — and the parent
relationship is propagated ambiently via :mod:`contextvars` (captured
at submit time and re-entered by pool/hedge/deadline workers), so the
spans of one scan form one connected tree no matter how many threads
executed them.  ``parquet-tool doctor`` walks that tree
(:mod:`~tpuparquet.obs.attribution`) and names the bounding stage.

Cost model — exactly the flight-recorder discipline:

* **off (default)**: one module-global load + ``is None`` check per
  hot site; hot call sites guard the call itself
  (``if _trace._active is not None: _trace.emit_span(...)``) so even
  the kwargs build is skipped — enforced structurally by the
  ``tools/analyze`` recorder-guard pass.
* **on** (``TPQ_TRACE=1``; an integer > 1 sets the per-thread ring
  depth): one bounded ``deque.append`` of a small dict per span.
  Spans are stage/chunk granularity — never per value.  Rings live in
  a :class:`~tpuparquet.obs.recorder.ThreadSlots` (per-thread
  registration, dead-owner retirement), so memory stays bounded under
  the deadline/hedge layers' disposable-worker churn.

Sampling (``TPQ_TRACE_SAMPLE``, default 1.0) decides per TRACE, not
per span: an unsampled scan records nothing at all (its root context
never arms), so every recorded trace is complete — a partial tree
would defeat the critical-path walk.  Spans emitted with no ambient
trace context are dropped for the same reason: no orphans, ever.

Timebase: ``time.perf_counter()`` throughout (monotonic,
high-resolution); the tracer keeps one ``(wall, perf)`` anchor pair so
exports (:func:`~tpuparquet.obs.export.spans_otlp`) can map span
starts back to epoch time.

Export: ``TPQ_TRACE_EXPORT`` names a file the scan drivers write at
scan end (atomic tmp + replace) — ``*.perfetto.json`` /
``*.chrome.json`` → Chrome trace-event JSON (load at
ui.perfetto.dev), ``*.otlp.json`` → OTLP-shaped ``resourceSpans``
JSON, anything else → the native ``tpq-trace`` envelope
``parquet-tool doctor`` reads.  Cross-host,
``shard.distributed.allgather_traces`` folds every host's spans
(annotated with their process index) into one fleet-wide list.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from collections import deque

from . import profiler as _profiler
from .recorder import ThreadSlots

__all__ = [
    "Tracer", "tracer", "set_tracing", "trace_default",
    "sample_default", "trace_export_default", "current_ctx", "adopt",
    "start_trace", "end_trace", "open_span", "close_span",
    "emit_span", "trace_scope", "snapshot_spans", "clear_spans",
]

#: Ambient (trace_id, span_id) of the innermost open span — the
#: parent every new span attaches to.  Per-thread by construction
#: (each thread has its own context); workers that run a caller's
#: work on another thread re-enter the caller's value via
#: :func:`adopt`.
_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "tpq_trace_ctx", default=None)

_DEFAULT_RING = 8192


def trace_default() -> int:
    """Ring depth from ``TPQ_TRACE``: ``0``/unset/invalid = tracing
    off, ``1`` = on at the default depth, > 1 = on at that per-thread
    ring depth."""
    try:
        v = int(os.environ.get("TPQ_TRACE", "0"))
    except ValueError:
        return 0
    if v <= 0:
        return 0
    return _DEFAULT_RING if v == 1 else v


def sample_default() -> float:
    """Trace sampling rate from ``TPQ_TRACE_SAMPLE`` (fraction of
    traces recorded, default 1.0; clamped to [0, 1])."""
    try:
        v = float(os.environ.get("TPQ_TRACE_SAMPLE", ""))
    except ValueError:
        return 1.0
    return min(max(v, 0.0), 1.0)


def trace_export_default() -> str | None:
    """Scan-end trace export path (``TPQ_TRACE_EXPORT``; None=off)."""
    return os.environ.get("TPQ_TRACE_EXPORT") or None


class Tracer:
    """Per-thread bounded rings of completed spans + the id wells.

    Span ids are process-unique monotone ints (``itertools.count`` —
    its ``__next__`` is atomic under the GIL, no lock on the span
    path); trace ids embed the pid so multi-host merges can't
    collide.  Deterministic sampling: trace N of rate r records iff
    ``int(N*r) > int((N-1)*r)`` — reproducible without a PRNG."""

    def __init__(self, ring: int = _DEFAULT_RING,
                 sample: float = 1.0):
        self.ring = ring
        self.sample = sample
        self.anchor_wall = time.time()
        self.anchor_perf = time.perf_counter()
        self._span_ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._slots = ThreadSlots(
            make=lambda: deque(maxlen=ring),
            fold=lambda retired, dead: retired.extend(dead))

    def _sampled(self, n: int) -> bool:
        r = self.sample
        if r >= 1.0:
            return True
        if r <= 0.0:
            return False
        return int(n * r) > int((n - 1) * r)

    def record(self, rec: dict) -> None:
        self._slots.get().append(rec)

    def snapshot(self, trace: str | None = None) -> list[dict]:
        """All completed spans (every thread's ring + the retired
        fold), start-sorted; ``trace`` filters to one trace id."""
        out: list[dict] = []
        for r in self._slots.all():
            out.extend(list(r))
        if trace is not None:
            out = [s for s in out if s.get("trace") == trace]
        out.sort(key=lambda s: s["t0"])
        return out

    def clear(self) -> None:
        for r in self._slots.all():
            r.clear()

    def anchor(self) -> dict:
        """The wall/perf pair exports use to map span starts to epoch
        seconds: ``epoch = wall + (t0 - perf)``."""
        return {"wall": self.anchor_wall, "perf": self.anchor_perf}


#: The active tracer, or None when tracing is off — the single gate
#: every hot-path hook checks (one global load + ``is None``, the
#: recorder._active discipline).  Initialized from the environment at
#: import; reconfigure at runtime with :func:`set_tracing`.
_active: Tracer | None = None


def _init_from_env() -> None:
    global _active
    n = trace_default()
    _active = Tracer(n, sample_default()) if n > 0 else None


_init_from_env()


def tracer() -> Tracer | None:
    """The active tracer (None when tracing is off)."""
    return _active


def set_tracing(enabled: bool = True, *, ring: int | None = None,
                sample: float | None = None) -> Tracer | None:
    """Reconfigure at runtime: ``True`` installs a FRESH tracer
    (dropping recorded spans), ``False`` disables.  Returns the new
    tracer (tests and A/B benches flip this without re-importing)."""
    global _active
    if not enabled:
        _active = None
        return None
    _active = Tracer(ring if ring is not None
                     else (trace_default() or _DEFAULT_RING),
                     sample if sample is not None else sample_default())
    return _active


# ----------------------------------------------------------------------
# Context propagation
# ----------------------------------------------------------------------

def current_ctx():
    """The ambient ``(trace_id, span_id)`` pair (None outside any
    sampled trace).  Capture this at submit time and hand it to a
    worker thread, which re-enters it with :func:`adopt` — the
    cross-thread half of causal propagation."""
    if _active is None:
        return None
    return _ctx.get()


@contextlib.contextmanager
def adopt(ctx):
    """Run a block under a captured trace context (no-op for None):
    the worker-side half of cross-thread propagation — every span the
    block emits parents under the capturing site's open span."""
    if ctx is None:
        yield
        return
    token = _ctx.set(ctx)
    # the profiler mirrors every context transition (contextvars are
    # unreadable cross-thread, so the sampler needs its own map)
    if _profiler._active is not None:
        _profiler.ctx_push(ctx[0], ctx[1], None)
    try:
        yield
    finally:
        _reset(token)
        if _profiler._active is not None:
            _profiler.ctx_pop(ctx[0], ctx[1])


def _reset(token) -> None:
    # a generator resumed from a different context activation cannot
    # reset the token it minted — fall back to clearing the var
    try:
        _ctx.reset(token)
    except ValueError:
        _ctx.set(None)


# ----------------------------------------------------------------------
# Span lifecycle
# ----------------------------------------------------------------------

def start_trace(label: str, **fields):
    """Begin a new trace (the scan drivers call this once per run):
    allocates a trace id, applies the sampling decision, opens the
    root span and pushes it as the ambient context.  Returns an
    opaque handle for :func:`end_trace`, or None when tracing is off
    or this trace was not sampled — in which case every child
    ``emit_span``/``open_span`` is dropped too (whole-trace
    sampling)."""
    tr = _active
    if tr is None:
        return None
    n = next(tr._trace_ids)
    if not tr._sampled(n):
        return None
    tid = f"{os.getpid():x}-{n}"
    sid = next(tr._span_ids)
    token = _ctx.set((tid, sid))
    if _profiler._active is not None:
        _profiler.ctx_push(tid, sid, "scan", label=label)
    return {"trace": tid, "span": sid, "parent": None, "name": "scan",
            "t0": time.perf_counter(), "token": token,
            "fields": {"label": label, **fields}}


def end_trace(handle, status: str = "ok", **fields) -> None:
    """Close a :func:`start_trace` root: emits the root span and pops
    the ambient context.  No-op for None handles."""
    if handle is None:
        return
    close_span(handle, status=status, **fields)


def open_span(name: str, *, push: bool = True, parent=None, **fields):
    """Open a span that children will attach to.

    Parent resolution: explicit ``parent`` ctx, else the ambient
    context.  Returns None — and records nothing — when tracing is
    off or there is no enclosing sampled trace (no orphan spans).
    ``push=True`` makes this span the ambient context until
    :func:`close_span` (same-thread nesting); ``push=False`` leaves
    the ambient context alone and the caller hands ``ctx_of(handle)``
    to workers explicitly (the pipelined reader's unit spans, whose
    open/close straddle generator yields)."""
    tr = _active
    if tr is None:
        return None
    ctx = parent if parent is not None else _ctx.get()
    if ctx is None:
        return None
    sid = next(tr._span_ids)
    token = _ctx.set((ctx[0], sid)) if push else None
    if _profiler._active is not None:
        if push:
            _profiler.ctx_push(ctx[0], sid, name)
        else:
            _profiler.span_note(ctx[0], sid, name)
    return {"trace": ctx[0], "span": sid, "parent": ctx[1],
            "name": name, "t0": time.perf_counter(), "token": token,
            "fields": fields}


def ctx_of(handle):
    """The ``(trace_id, span_id)`` of an open span handle (None for
    None) — what a submitting site captures for its workers."""
    if handle is None:
        return None
    return (handle["trace"], handle["span"])


def close_span(handle, status: str = "ok", **fields) -> None:
    """Emit an open span with its measured duration; pops the ambient
    context when the span pushed one.  No-op for None handles (the
    disabled path), and safe when tracing was disabled mid-span.

    The context pop is conditional on the ambient context still being
    THIS span's: an abandoned scan generator finalized later (GC) must
    not clobber the context of whatever trace the thread has since
    started — a non-LIFO token reset would restore the pre-span value
    over the newer trace's root and silently drop all its spans."""
    if handle is None:
        return
    if handle["token"] is not None:
        cur = _ctx.get()
        if cur is not None and cur[0] == handle["trace"] \
                and cur[1] == handle["span"]:
            _reset(handle["token"])
            if _profiler._active is not None:
                _profiler.ctx_pop(handle["trace"], handle["span"])
    tr = _active
    if tr is None:
        return
    t1 = time.perf_counter()
    rec = {"trace": handle["trace"], "span": handle["span"],
           "parent": handle["parent"], "name": handle["name"],
           "t0": handle["t0"], "dur": t1 - handle["t0"],
           "tid": threading.get_ident(), "status": status}
    if handle["fields"]:
        rec.update(handle["fields"])
    if fields:
        rec.update(fields)
    tr.record(rec)


def emit_span(name: str, t0: float, dur: float, *, status: str = "ok",
              parent=None, **fields) -> None:
    """Record one COMPLETED span (the hot-site form: the call site
    measured ``t0``/``dur`` itself, usually for a counter it was
    already feeding).  Parents to the ambient context (or an explicit
    ``parent`` ctx); dropped when tracing is off or no sampled trace
    encloses the call.

    Hot per-chunk/per-stage sites guard the CALL itself with
    ``_trace._active is not None`` so the disabled path skips even
    the kwargs construction — the recorder-guard analyze pass holds
    ``emit_span`` call sites to the same rule as ``flight``."""
    tr = _active
    if tr is None:
        return
    ctx = parent if parent is not None else _ctx.get()
    if ctx is None:
        return
    rec = {"trace": ctx[0], "span": next(tr._span_ids),
           "parent": ctx[1], "name": name, "t0": t0, "dur": dur,
           "tid": threading.get_ident(), "status": status}
    if fields:
        rec.update(fields)
    tr.record(rec)


@contextlib.contextmanager
def trace_scope(label: str = "work", **fields):
    """Trace an arbitrary block as its own root trace (the
    tools/tests entry point: ``parquet-tool profile`` wraps its decode
    in one so the doctor can walk it).  Yields the root handle (None
    when tracing is off/unsampled)."""
    h = start_trace(label, **fields)
    try:
        yield h
    except BaseException:
        end_trace(h, status="error")
        raise
    end_trace(h)


def snapshot_spans(trace: str | None = None) -> list[dict]:
    """Completed spans of the active tracer ([] when off)."""
    tr = _active
    return [] if tr is None else tr.snapshot(trace)


def clear_spans() -> None:
    tr = _active
    if tr is not None:
        tr.clear()
