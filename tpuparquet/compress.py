"""Block compression registry + codecs (UNCOMPRESSED, SNAPPY, GZIP,
ZSTD, LZ4_RAW).

API parity with the reference's ``compress.go``: a process-wide registry of
:class:`BlockCompressor` objects keyed by ``CompressionCodec``, with
``register_block_compressor`` as the public extension hook
(``compress.go:130``) and built-ins registered at import
(``compress.go:152-156``).  ``decompress_block`` validates the decoded size
like ``newBlockReader`` (``compress.go:102-122``).

Snappy and LZ4_RAW are implemented from scratch (the Python image has
neither library): both pair a C fast path (``native/snappy.c``,
``native/lz4raw.c``) with a pure-Python mirror of the same algorithm.
GZIP and ZSTD bind the system libraries via ctypes
(``native/syslibs.py``) with the stdlib ``zlib`` module and the
optional ``zstandard`` wheel as fallbacks; ``TPQ_NATIVE_CODECS=0``
forces every codec onto its fallback for parity legs.

Write-side page compression exposes a zero-copy ``compress_into``
context per codec (:func:`page_codec_settings`) plus block-splitting
for the concatenation-safe frame formats (GZIP multi-member, ZSTD
multi-frame): :func:`page_compress_into` splits bodies >= 2×
``TPQ_COMPRESS_BLOCK_KB`` into independently compressed frames when
the caller holds more than one worker — same decoded bytes, parallel
wall-clock.  The read side reverses it in :func:`decompress_block_into`
(ZSTD frames decode concurrently; gzip members stream through one
inflate loop).
"""

from __future__ import annotations

import os
import threading
import zlib

import numpy as np

from .format.metadata import CompressionCodec
from .varint import read_uvarint, write_uvarint

__all__ = [
    "BlockCompressor",
    "register_block_compressor",
    "get_block_compressor",
    "registered_codecs",
    "compress_block",
    "decompress_block",
    "snappy_compress",
    "snappy_decompress",
    "snappy_parse_tokens",
    "snappy_single_literal_view",
    "lz4_compress",
    "lz4_decompress",
    "page_codec_settings",
    "page_compress_bound",
    "page_compress_into",
    "CompressionError",
]


def native_codecs_enabled() -> bool:
    """``TPQ_NATIVE_CODECS=0`` pins every codec to its pure-Python /
    stdlib / wheel fallback (and disables the native page-compression
    contexts) — the ci.sh parity leg.  Read per call: tests flip it
    mid-process."""
    return os.environ.get("TPQ_NATIVE_CODECS", "1") != "0"


class CompressionError(ValueError):
    pass


class BlockCompressor:
    """One whole-block codec; subclasses implement both directions."""

    def compress_block(self, block: bytes) -> bytes:
        raise NotImplementedError

    def decompress_block(self, block: bytes, decompressed_size: int) -> bytes:
        raise NotImplementedError


_registry: dict[int, BlockCompressor] = {}
_registry_lock = threading.Lock()


def register_block_compressor(codec: CompressionCodec, c: BlockCompressor) -> None:
    with _registry_lock:
        _registry[int(codec)] = c


def get_block_compressor(codec: CompressionCodec) -> BlockCompressor:
    with _registry_lock:
        c = _registry.get(int(codec))
    if c is None:
        raise CompressionError(
            f"compression codec {CompressionCodec(codec).name} is not "
            "registered (register_block_compressor to plug one in)"
        )
    return c


def registered_codecs() -> list[CompressionCodec]:
    with _registry_lock:
        return [CompressionCodec(k) for k in sorted(_registry)]


def compress_block(codec: CompressionCodec, block: bytes) -> bytes:
    return get_block_compressor(codec).compress_block(bytes(block))


def decompress_block(
    codec: CompressionCodec, block, decompressed_size: int
) -> bytes:
    out = get_block_compressor(codec).decompress_block(
        bytes(block), decompressed_size
    )
    if len(out) != decompressed_size:
        raise CompressionError(
            f"decompressed size {len(out)} != expected {decompressed_size}"
        )
    return out


def snappy_single_literal_view(block) -> "np.ndarray | None":
    """Zero-copy view of a snappy block that is one literal token.

    Incompressible pages — PLAIN numeric columns of high-entropy data —
    compress to ``[uvarint total][literal tag][payload]``; the payload
    IS the decompressed block, sitting inside the file bytes already.
    Returns that view, or None when the stream is anything else.  The
    single-core host this runs on makes the skipped memcpy a first-order
    win (decompression was ~60% of the device path's plan phase)."""
    buf = block if isinstance(block, np.ndarray) else np.frombuffer(
        block, dtype=np.uint8)
    try:
        total, pos = read_uvarint(buf, 0)
    except Exception:
        return None
    if pos >= buf.size:
        return None
    tag = int(buf[pos])
    pos += 1
    if tag & 3:
        return None  # first token is a copy
    ln = tag >> 2
    if ln >= 60:
        extra = ln - 59
        if pos + extra > buf.size:
            return None
        ln = 0
        for i in range(extra):
            ln |= int(buf[pos + i]) << (8 * i)
        pos += extra
    ln += 1
    if ln != total or pos + ln != buf.size:
        return None  # not a single literal covering the whole block
    return buf[pos : pos + ln]


def _zstd_decompress_frames(nat, block, decompressed_size, out,
                            workers: int):
    """Decode a multi-frame zstd stream with one worker per frame when
    the caller holds spare budget — the read-side mirror of the write
    path's block split.  Returns the produced length, or None when the
    stream is single-frame / unsplittable (caller one-shots it)."""
    if workers <= 1:
        return None
    try:
        spans = nat.frame_spans(block)
    except ValueError as e:
        raise CompressionError(str(e)) from None
    if spans is None or len(spans) < 2:
        return None
    total = sum(s[2] for s in spans)
    if total != decompressed_size:
        raise CompressionError(
            f"decompressed size {total} != expected {decompressed_size}")
    src = block if isinstance(block, np.ndarray) else np.frombuffer(
        block, dtype=np.uint8)
    dst_offs = []
    pos = 0
    for _, _, ulen in spans:
        dst_offs.append(pos)
        pos += ulen

    def one(i):
        off, clen, ulen = spans[i]
        return nat.decompress_into(
            src[off:off + clen], out[dst_offs[i]:dst_offs[i] + ulen], ulen)

    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(workers, len(spans))) as ex:
        try:
            list(ex.map(one, range(len(spans))))
        except ValueError as e:
            raise CompressionError(str(e)) from None
    from .stats import current_stats

    st = current_stats()
    if st is not None:
        st.codec_split_frames += len(spans)
    return total


_affinity_workers: int | None = None


def _shared_decode_budget() -> int:
    """Worker budget for frame-parallel decode: the arbiter's plan
    budget when a scan server is arbitrating, else the process CPU
    affinity — the same shared-budget rule the write side follows."""
    global _affinity_workers
    try:
        from .serve.arbiter import plan_budget

        b = plan_budget()
        if b:
            return max(1, int(b))
    except Exception:
        pass
    if _affinity_workers is None:
        try:
            _affinity_workers = len(os.sched_getaffinity(0))
        except (AttributeError, OSError):
            _affinity_workers = os.cpu_count() or 1
    return _affinity_workers


def decompress_block_into(codec: CompressionCodec, block,
                          decompressed_size: int, arena,
                          workers: int | None = None):
    """Device-path decompress: zero input copy and a recycled output
    slab when a native codec is available; otherwise falls back to
    :func:`decompress_block`.  Returns a u8 numpy view either way —
    arena-backed outputs are only valid until ``arena.release_all()``
    (single-literal snappy blocks come back as views of ``block``
    itself, valid as long as the caller's buffer).  ``workers > 1``
    lets multi-frame ZSTD bodies (the write-side block split) decode
    frame-parallel; None resolves the shared arbiter/affinity budget."""
    import numpy as np

    if decompressed_size is None or decompressed_size < 0:
        raise CompressionError("missing decompressed size")
    if codec == CompressionCodec.SNAPPY:
        view = snappy_single_literal_view(block)
        if view is not None:
            if view.size != decompressed_size:
                raise CompressionError(
                    f"decompressed size {view.size} != expected "
                    f"{decompressed_size}"
                )
            return view
    if codec == CompressionCodec.UNCOMPRESSED:
        out = np.frombuffer(block, dtype=np.uint8) if not isinstance(
            block, np.ndarray) else block
        if out.size != decompressed_size:
            raise CompressionError(
                f"decompressed size {out.size} != expected "
                f"{decompressed_size}"
            )
        return out
    if codec == CompressionCodec.SNAPPY:
        from .native import snappy_native

        nat = snappy_native()
        if nat is not None:
            out = arena.borrow(decompressed_size + 16)
            try:
                got = nat.decompress_np(block, decompressed_size, out=out)
            except ValueError as e:
                raise CompressionError(str(e)) from None
            if got.size != decompressed_size:
                raise CompressionError(
                    f"decompressed size {got.size} != expected "
                    f"{decompressed_size}"
                )
            return got
    elif codec == CompressionCodec.LZ4_RAW and native_codecs_enabled():
        from .native import lz4_native

        nat = lz4_native()
        if nat is not None:
            out = arena.borrow(decompressed_size + 16)
            try:
                return nat.decompress_np(block, decompressed_size, out=out)
            except ValueError as e:
                raise CompressionError(str(e)) from None
    elif codec == CompressionCodec.GZIP and native_codecs_enabled():
        from .native.syslibs import zlib_native

        nat = zlib_native()
        if nat is not None:
            out = arena.borrow(decompressed_size + 16)
            try:
                got = nat.decompress_into(block, out, decompressed_size)
            except ValueError as e:
                raise CompressionError(str(e)) from None
            if got != decompressed_size:
                raise CompressionError(
                    f"decompressed size {got} != expected "
                    f"{decompressed_size}"
                )
            return out[:got]
    elif codec == CompressionCodec.ZSTD and native_codecs_enabled():
        from .native.syslibs import zstd_native

        nat = zstd_native()
        if nat is not None:
            out = arena.borrow(decompressed_size + 16)
            got = _zstd_decompress_frames(
                nat, block, decompressed_size, out,
                workers if workers is not None
                else _shared_decode_budget())
            if got is None:
                try:
                    got = nat.decompress_into(block, out, decompressed_size)
                except ValueError as e:
                    raise CompressionError(str(e)) from None
            if got != decompressed_size:
                raise CompressionError(
                    f"decompressed size {got} != expected "
                    f"{decompressed_size}"
                )
            return out[:got]
    return np.frombuffer(
        decompress_block(codec, block, decompressed_size), dtype=np.uint8
    )


# --------------------------------------------------------------------------
# Built-in codecs
# --------------------------------------------------------------------------

class _Uncompressed(BlockCompressor):
    def compress_block(self, block):
        return block

    def decompress_block(self, block, decompressed_size):
        return block


class _Gzip(BlockCompressor):
    """GZIP through the ctypes libz binding when loadable, else the
    stdlib ``zlib`` module.  Both call the same system libz with the
    same parameters (default level, memLevel 8, wbits 31), so the two
    paths produce the SAME bytes on a normal install — the gzip
    byte-parity anchor in ci.sh.  Decompression accepts multi-member
    streams either way (the write-side block split concatenates
    members per RFC 1952)."""

    def compress_block(self, block):
        if native_codecs_enabled():
            from .native.syslibs import zlib_native

            nat = zlib_native()
            if nat is not None:
                return nat.compress(block)
        co = zlib.compressobj(wbits=16 + zlib.MAX_WBITS)  # gzip framing
        return co.compress(block) + co.flush()

    def decompress_block(self, block, decompressed_size):
        if native_codecs_enabled():
            from .native.syslibs import zlib_native

            nat = zlib_native()
            if nat is not None:
                try:
                    return nat.decompress(block, decompressed_size)
                except ValueError as e:
                    raise CompressionError(str(e)) from None
        # stdlib fallback: loop decompressobj over trailing members
        out = []
        buf = bytes(block)
        try:
            while buf:
                d = zlib.decompressobj(wbits=16 + zlib.MAX_WBITS)
                out.append(d.decompress(buf))
                if not d.eof:
                    raise CompressionError("gzip: truncated member")
                buf = d.unused_data
        except zlib.error as e:
            raise CompressionError(f"gzip: {e}") from e
        return b"".join(out)


def _zstd_level() -> int:
    try:
        return int(os.environ.get("TPQ_ZSTD_LEVEL", "1"))
    except ValueError:
        return 1


class _Zstd(BlockCompressor):
    """ZSTD through the ctypes libzstd binding when loadable, else the
    optional ``zstandard`` wheel.  Registered only when at least one
    backend exists; with ``TPQ_NATIVE_CODECS=0`` and no wheel, calls
    raise (the parity leg must then skip zstd, loudly).
    ``TPQ_ZSTD_LEVEL`` sets the compression level for both backends
    (default 1, Arrow's write-side default — the write bench is
    anchored against pyarrow, so the default must race the same
    speed/ratio point; raise it when file size matters more)."""

    def __init__(self):
        try:
            import zstandard
        except ImportError:
            zstandard = None
        self._zstd = zstandard
        # ZstdCompressor/ZstdDecompressor contexts are documented as not
        # shareable across concurrent calls; keep them thread-local.
        self._local = threading.local()

    def _nat(self):
        if not native_codecs_enabled():
            return None
        from .native.syslibs import zstd_native

        return zstd_native()

    def _ctx(self, level):
        if self._zstd is None:
            raise CompressionError(
                "zstd: native codecs disabled and the zstandard wheel "
                "is not installed")
        if getattr(self._local, "level", None) != level:
            self._local.c = self._zstd.ZstdCompressor(level=level)
            self._local.d = self._zstd.ZstdDecompressor()
            self._local.level = level
        return self._local

    def compress_block(self, block):
        level = _zstd_level()
        nat = self._nat()
        if nat is not None:
            return nat.compress(block, level)
        return self._ctx(level).c.compress(block)

    def decompress_block(self, block, decompressed_size):
        nat = self._nat()
        if nat is not None:
            try:
                return nat.decompress(block, decompressed_size)
            except ValueError as e:
                raise CompressionError(str(e)) from None
        ctx = self._ctx(_zstd_level())
        try:
            return ctx.d.decompress(block, max_output_size=decompressed_size)
        except CompressionError:
            raise
        except Exception as e:
            # the wheel's one-shot API stops at the first frame; a
            # block-split body is concatenated frames — stream across
            import io

            try:
                with ctx.d.stream_reader(io.BytesIO(bytes(block)),
                                         read_across_frames=True) as r:
                    return r.read(decompressed_size + 1)
            except Exception:
                raise CompressionError(f"zstd: {e}") from e


# --------------------------------------------------------------------------
# Snappy (from scratch)
# --------------------------------------------------------------------------

def snappy_parse_tokens(block: bytes):
    """Parse a snappy block into ``(total_len, ops)``.

    ``ops`` is a list of ``(dst, length, src)`` triples: ``src >= 0`` is a
    copy from absolute output offset ``src``; ``src == -1`` is a literal
    whose bytes start at ``dst_literal_pos`` (stored in a parallel slot as
    ``(dst, length, -1 - input_pos)``).  This op list is exactly what the
    device copy-resolution kernel consumes.
    """
    try:
        total, pos = read_uvarint(block, pos=0)
    except ValueError as e:
        raise CompressionError(f"snappy: bad size header: {e}") from None
    n = len(block)
    ops = []
    out_pos = 0
    while pos < n:
        tag = block[pos]
        kind = tag & 3
        pos += 1
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                extra = ln - 59
                if pos + extra > n:
                    raise CompressionError("snappy: truncated literal length")
                ln = int.from_bytes(block[pos : pos + extra], "little")
                pos += extra
            ln += 1
            if pos + ln > n:
                raise CompressionError("snappy: literal overruns input")
            ops.append((out_pos, ln, -1 - pos))
            pos += ln
            out_pos += ln
            continue
        if kind == 1:  # copy with 1-byte offset extension
            if pos >= n:
                raise CompressionError("snappy: truncated copy-1")
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | block[pos]
            pos += 1
        elif kind == 2:  # 2-byte offset
            if pos + 2 > n:
                raise CompressionError("snappy: truncated copy-2")
            ln = (tag >> 2) + 1
            off = int.from_bytes(block[pos : pos + 2], "little")
            pos += 2
        else:  # 4-byte offset
            if pos + 4 > n:
                raise CompressionError("snappy: truncated copy-4")
            ln = (tag >> 2) + 1
            off = int.from_bytes(block[pos : pos + 4], "little")
            pos += 4
        if off == 0 or off > out_pos:
            raise CompressionError(
                f"snappy: copy offset {off} out of range at output {out_pos}"
            )
        ops.append((out_pos, ln, out_pos - off))
        out_pos += ln
    if out_pos != total:
        raise CompressionError(
            f"snappy: stream produced {out_pos} bytes, header says {total}"
        )
    return total, ops


def snappy_decompress(block: bytes, expected_size: int | None = None) -> bytes:
    total, ops = snappy_parse_tokens(block)
    if expected_size is not None and total != expected_size:
        raise CompressionError(
            f"snappy: header size {total} != expected {expected_size}"
        )
    out = np.empty(total, dtype=np.uint8)
    src_buf = np.frombuffer(block, dtype=np.uint8)
    for dst, ln, src in ops:
        if src < 0:  # literal from input
            ip = -1 - src
            out[dst : dst + ln] = src_buf[ip : ip + ln]
        elif src + ln <= dst:  # non-overlapping copy
            out[dst : dst + ln] = out[src : src + ln]
        else:
            # Overlapping copy: byte-sequential semantics make it a periodic
            # extension of the bytes between src and dst, so tile the period.
            period = dst - src
            reps = -(-ln // period)
            out[dst : dst + ln] = np.tile(out[src:dst], reps)[:ln]
    return out.tobytes()


def _emit_literal(out: bytearray, data, lo: int, hi: int) -> None:
    # One token per literal stretch, however long (the tag format takes
    # up to 4 length bytes): an incompressible block then compresses to
    # exactly [uvarint][tag][payload], which the decode path serves as a
    # zero-copy view (``snappy_single_literal_view``) — same shape the
    # native C encoder emits.
    n = hi - lo
    if n <= 0:
        return
    ln = n - 1
    if ln < 60:
        out.append(ln << 2)
    elif ln < 256:
        out.append(60 << 2)
        out.append(ln)
    elif ln < 65536:
        out.append(61 << 2)
        out += ln.to_bytes(2, "little")
    elif ln < 1 << 24:
        out.append(62 << 2)
        out += ln.to_bytes(3, "little")
    else:
        out.append(63 << 2)
        out += ln.to_bytes(4, "little")
    out += data[lo:hi]


def _emit_copy(out: bytearray, offset: int, ln: int) -> None:
    # 2-byte-offset copies (tag 0b10) cover offset <= 65535, len 1..64.
    off = offset.to_bytes(2, "little")
    while ln > 64:
        out.append((63 << 2) | 2)
        out += off
        ln -= 64
    out.append(((ln - 1) << 2) | 2)
    out += off


def snappy_compress(data: bytes, min_match: int = 4) -> bytes:
    """Greedy hash-match snappy encoder (golang-snappy style, with the
    standard miss-skip acceleration).  Output is valid snappy that any
    implementation (incl. pyarrow's) decodes back to ``data``.

    ``min_match`` is the shortest back-reference worth emitting (>= 4);
    raising it trades ratio on text data for decode throughput."""
    data = bytes(data)
    n = len(data)
    min_match = max(min_match, 4)
    out = bytearray()
    write_uvarint(out, n)
    if n < 4:
        if n:
            _emit_literal(out, data, 0, n)
        return bytes(out)
    table: dict[int, int] = {}
    pos = 0
    lit_start = 0
    misses = 0
    while pos + 4 <= n:
        key = int.from_bytes(data[pos : pos + 4], "little")
        cand = table.get(key)
        table[key] = pos
        if cand is not None and pos - cand <= 65535:
            # hash hit is exact (key is the literal 4 bytes)
            length = 4
            limit = n - pos
            while (
                length < limit
                and data[cand + length] == data[pos + length]
            ):
                length += 1
            if length < min_match:
                misses += 1
                pos += 1 + (misses >> 5)
                continue
            _emit_literal(out, data, lit_start, pos)
            _emit_copy(out, pos - cand, length)
            pos += length
            lit_start = pos
            misses = 0
        else:
            misses += 1
            pos += 1 + (misses >> 5)  # skip faster through incompressible data
    _emit_literal(out, data, lit_start, n)
    return bytes(out)


class _Snappy(BlockCompressor):
    """Snappy with the native C fast path and a pure-Python fallback.

    The native codec (tpuparquet/native/snappy.c) is loaded lazily on
    first use; both implement the same wire format, so files are
    interchangeable either way.

    ``min_match`` sets the shortest back-reference the encoder emits.
    The default (4) matches the format's reference encoders (the Go
    implementation the reference vendors emits 4-byte matches): numeric
    column data's redundancy lives almost entirely in 4..7-byte matches
    at lag ``sizeof(value)`` — timestamp-like int64 streams measure
    1.00 at ``min_match=8`` vs 0.76 at 4 — and smaller blocks are what
    the device decompressor turns into less wire time.  Register
    ``_Snappy(min_match=8)`` via ``register_block_compressor`` to trade
    ratio back for encode throughput."""

    def __init__(self, min_match: int = 4):
        self._native = False  # not resolved yet
        self.min_match = min_match

    def _nat(self):
        if not native_codecs_enabled():
            return None
        if self._native is False:
            from .native import snappy_native

            self._native = snappy_native()
        return self._native

    def compress_block(self, block):
        nat = self._nat()
        if nat is not None:
            return nat.compress(bytes(block), min_match=self.min_match)
        return snappy_compress(block, min_match=self.min_match)

    def decompress_block(self, block, decompressed_size):
        nat = self._nat()
        if nat is not None:
            try:
                # memoryview over a numpy buffer: bytes-like (compares
                # equal to bytes, slices, unpacks) and the decode path
                # avoids two whole-buffer copies per page
                return memoryview(
                    nat.decompress_np(bytes(block), decompressed_size)
                )
            except ValueError as e:
                raise CompressionError(str(e)) from None
        return snappy_decompress(block, decompressed_size)


# --------------------------------------------------------------------------
# LZ4 raw block format (Parquet's LZ4_RAW, from scratch)
# --------------------------------------------------------------------------

def _lz4_emit_literals(out: bytearray, data, lo: int, lit: int,
                       mcode: int) -> None:
    if lit >= 15:
        out.append((15 << 4) | mcode)
        rem = lit - 15
        while rem >= 255:
            out.append(255)
            rem -= 255
        out.append(rem)
    else:
        out.append((lit << 4) | mcode)
    out += data[lo:lo + lit]


def lz4_compress(data) -> bytes:
    """Greedy hash-match LZ4 block encoder — a step-for-step Python
    mirror of ``native/lz4raw.c`` (same 64 KiB blocks, same
    zero-initialized table semantics, same skip acceleration, same end
    rules), so pure and native output are byte-identical and the ci.sh
    parity leg can pin file equality for LZ4_RAW."""
    data = bytes(data)
    n = len(data)
    if n == 0:
        return b"\x00"  # canonical empty block: one zero token
    out = bytearray()
    lit_start = 0  # absolute: pending literals span blocks
    for base in range(0, n, 65536):
        blen = min(n - base, 65536)
        # matches may neither start past blen-4 nor within the input's
        # last 12 bytes (format end rule)
        if n < 13 or base + 12 > n:
            continue
        if blen < 4:
            continue  # tail rides the final literal flush
        limit = min(blen - 4, n - 12 - base)
        table = [0] * 16384  # zero-init: position-0 candidates resolve
        # through the 4-byte compare, exactly like the C uint16 table
        pos = 0
        skip = 32
        while pos <= limit:
            key = data[base + pos:base + pos + 4]
            h = ((int.from_bytes(key, "little") * 2654435761)
                 & 0xFFFFFFFF) >> 18
            cand = table[h]
            table[h] = pos
            if cand < pos and data[base + cand:base + cand + 4] == key:
                length = 4
                # extend to block end; matches stop 5 bytes before the
                # end of the whole input
                maxlen = min(blen - pos, (n - 5) - (base + pos))
                while (length < maxlen
                       and data[base + cand + length]
                       == data[base + pos + length]):
                    length += 1
                if length < 4:  # end-rule clamp ate the match
                    step = skip >> 5
                    pos += step
                    skip += step
                    continue
                lit = base + pos - lit_start
                mext = length - 4
                off = pos - cand
                _lz4_emit_literals(out, data, lit_start, lit,
                                   15 if mext >= 15 else mext)
                out.append(off & 0xFF)
                out.append(off >> 8)
                if mext >= 15:
                    rem = mext - 15
                    while rem >= 255:
                        out.append(255)
                        rem -= 255
                    out.append(rem)
                end = pos + length
                if end <= limit and end >= 1:
                    seed = end - 1
                    table[((int.from_bytes(
                        data[base + seed:base + seed + 4], "little")
                        * 2654435761) & 0xFFFFFFFF) >> 18] = seed
                pos = end
                lit_start = base + pos
                skip = 32
            else:
                step = skip >> 5
                pos += step
                skip += step
    out2 = bytearray()
    _lz4_emit_literals(out2, data, lit_start, n - lit_start, 0)
    return bytes(out + out2)


def lz4_decompress(block, expected_size: int) -> bytes:
    """Safe pure-Python LZ4 block decoder (token loop mirroring
    ``tpq_lz4_decompress``); raises :class:`CompressionError` on any
    malformed stream."""
    src = bytes(block)
    n = len(src)
    if n == 0:
        if expected_size:
            raise CompressionError("lz4: empty stream, nonzero expected")
        return b""
    out = bytearray()
    ip = 0
    while True:
        if ip >= n:
            raise CompressionError("lz4: stream ends between sequences")
        token = src[ip]
        ip += 1
        lit = token >> 4
        if lit == 15:
            while True:
                if ip >= n:
                    raise CompressionError("lz4: truncated literal length")
                b = src[ip]
                ip += 1
                lit += b
                if lit > expected_size:
                    raise CompressionError("lz4: literal length overflow")
                if b != 255:
                    break
        if ip + lit > n:
            raise CompressionError("lz4: literal overruns input")
        out += src[ip:ip + lit]
        ip += lit
        if ip == n:
            break  # final sequence: literals only
        if ip + 2 > n:
            raise CompressionError("lz4: truncated match offset")
        off = src[ip] | (src[ip + 1] << 8)
        ip += 2
        if off == 0 or off > len(out):
            raise CompressionError(
                f"lz4: match offset {off} out of range at {len(out)}")
        mlen = token & 0xF
        if mlen == 15:
            while True:
                if ip >= n:
                    raise CompressionError("lz4: truncated match length")
                b = src[ip]
                ip += 1
                mlen += b
                if mlen > expected_size:
                    raise CompressionError("lz4: match length overflow")
                if b != 255:
                    break
        mlen += 4
        if len(out) + mlen > expected_size:
            raise CompressionError("lz4: output overruns expected size")
        start = len(out) - off
        if off >= mlen:
            out += out[start:start + mlen]
        else:  # overlapping copy: periodic extension of the window
            for i in range(mlen):
                out.append(out[start + i])
    if len(out) != expected_size:
        raise CompressionError(
            f"lz4: decoded {len(out)} bytes, expected {expected_size}")
    return bytes(out)


class _Lz4Raw(BlockCompressor):
    """LZ4_RAW with the native C fast path (``native/lz4raw.c``) and the
    byte-identical pure-Python mirror as fallback — files are
    bit-interchangeable whichever side produced them."""

    def _nat(self):
        if not native_codecs_enabled():
            return None
        from .native import lz4_native

        return lz4_native()

    def compress_block(self, block):
        nat = self._nat()
        if nat is not None:
            return nat.compress(bytes(block))
        return lz4_compress(block)

    def decompress_block(self, block, decompressed_size):
        nat = self._nat()
        if nat is not None:
            try:
                return memoryview(
                    nat.decompress_np(bytes(block), decompressed_size)
                )
            except ValueError as e:
                raise CompressionError(str(e)) from None
        return lz4_decompress(block, decompressed_size)


def builtin_uncompressed_registered() -> bool:
    """True when the UNCOMPRESSED slot still holds the built-in
    pass-through — the condition for the native page pipeline to skip
    the compressor entirely.  A user-registered transform on the
    UNCOMPRESSED codec id (the registry allows it) must keep full
    control of the bytes, so callers take the pure page path then."""
    with _registry_lock:
        return type(
            _registry.get(int(CompressionCodec.UNCOMPRESSED))
        ) is _Uncompressed


def snappy_native_settings():
    """``(native_codec, min_match)`` when the REGISTERED snappy block
    compressor is the built-in :class:`_Snappy` backed by the native C
    codec — the condition under which the write-side native page
    pipeline (``io/pages.py``) produces exactly the bytes
    ``compress_block`` would.  None otherwise (a custom compressor was
    registered, or no compiler): callers must then take the pure page
    path so registered-codec semantics are honored."""
    with _registry_lock:
        c = _registry.get(int(CompressionCodec.SNAPPY))
    if type(c) is _Snappy:
        nat = c._nat()
        if nat is not None:
            return nat, c.min_match
    return None


# --------------------------------------------------------------------------
# Write-side page compression contexts + block-parallel split
# --------------------------------------------------------------------------

class PageCodecCtx:
    """Zero-copy page-compression handle for the native page pipeline
    (``io/pages.py``): a worst-case :meth:`bound` and a
    :meth:`compress_into` writing straight into an arena slab.  Only
    handed out (:func:`page_codec_settings`) when the REGISTERED block
    compressor is the builtin backed by the same native codec, so the
    native page path produces exactly the bytes ``compress_block``
    would.  ``splittable`` marks the concatenation-safe frame formats
    (GZIP multi-member, ZSTD multi-frame) eligible for the
    block-parallel split."""

    __slots__ = ("codec", "splittable", "_bound", "_into")

    def __init__(self, codec, bound, into, splittable=False):
        self.codec = codec
        self.splittable = splittable
        self._bound = bound
        self._into = into

    def bound(self, n: int) -> int:
        return self._bound(n)

    def compress_into(self, src, out) -> int:
        return self._into(src, out)


def page_codec_settings(codec: CompressionCodec) -> PageCodecCtx | None:
    """The write-side native compression context for ``codec``, or None
    when the native page pipeline must not compress this codec itself
    (user-registered compressor, native codec unavailable, or
    ``TPQ_NATIVE_CODECS=0``) — callers then take the pure page path."""
    if not native_codecs_enabled():
        return None
    with _registry_lock:
        c = _registry.get(int(codec))
    if codec == CompressionCodec.SNAPPY:
        if type(c) is not _Snappy:
            return None
        nat = c._nat()
        if nat is None:
            return None
        mm = c.min_match
        return PageCodecCtx(
            codec, lambda n: 32 + n + n // 6,
            lambda src, out: nat.compress_into(src, out, mm))
    if codec == CompressionCodec.LZ4_RAW:
        if type(c) is not _Lz4Raw:
            return None
        from .native import lz4_native

        nat = lz4_native()
        if nat is None:
            return None
        return PageCodecCtx(codec, nat.max_compressed_length,
                            nat.compress_into)
    if codec == CompressionCodec.GZIP:
        if type(c) is not _Gzip:
            return None
        from .native.syslibs import zlib_native

        nat = zlib_native()
        if nat is None:
            return None
        return PageCodecCtx(codec, nat.compress_bound, nat.compress_into,
                            splittable=True)
    if codec == CompressionCodec.ZSTD:
        if type(c) is not _Zstd:
            return None
        from .native.syslibs import zstd_native

        nat = zstd_native()
        if nat is None:
            return None
        level = _zstd_level()
        return PageCodecCtx(
            codec, nat.compress_bound,
            lambda src, out: nat.compress_into(src, out, level),
            splittable=True)
    return None


def _split_block_bytes() -> int:
    """Sub-block size for block-parallel compression
    (``TPQ_COMPRESS_BLOCK_KB``, default 1 MiB; floored at 64 KiB —
    smaller frames are all header overhead)."""
    try:
        kb = int(os.environ.get("TPQ_COMPRESS_BLOCK_KB", "1024"))
    except ValueError:
        kb = 1024
    return max(64, kb) * 1024


def page_compress_bound(ctx: PageCodecCtx, n: int,
                        workers: int = 1) -> int:
    """Output capacity needed by :func:`page_compress_into` — the
    per-frame worst cases when the split engages, the plain codec bound
    otherwise."""
    block = _split_block_bytes()
    if not (ctx.splittable and workers > 1 and n >= 2 * block):
        return ctx.bound(n)
    nb = -(-n // block)
    return (nb - 1) * ctx.bound(block) + ctx.bound(n - (nb - 1) * block)


def page_compress_into(ctx: PageCodecCtx, src, out,
                       workers: int = 1) -> int:
    """Compress ``src`` into ``out`` (sized by
    :func:`page_compress_bound`), splitting into independently
    compressed frames when the codec is concatenation-safe, the caller
    holds more than one worker, and the body spans at least two split
    blocks.  Frame boundaries depend only on ``TPQ_COMPRESS_BLOCK_KB``
    — every multi-worker width emits the same bytes; one worker emits
    the single frame the serial path always wrote.  Returns the
    produced length."""
    n = src.size
    block = _split_block_bytes()
    if not (ctx.splittable and workers > 1 and n >= 2 * block):
        return ctx.compress_into(src, out)
    nb = -(-n // block)
    offs = [0]
    for i in range(nb):
        offs.append(offs[-1] + ctx.bound(min(block, n - i * block)))

    def one(i):
        a = i * block
        b = min(n, a + block)
        return ctx.compress_into(src[a:b], out[offs[i]:offs[i + 1]])

    from concurrent.futures import ThreadPoolExecutor

    # scoped executor: split compression is rare enough (large pages
    # only) that pool spin-up noise loses to lifecycle simplicity
    with ThreadPoolExecutor(max_workers=min(workers, nb)) as ex:
        lens = list(ex.map(one, range(nb)))
    pos = lens[0]
    for i in range(1, nb):  # compact frames down to one stream
        li = lens[i]
        if offs[i] != pos:
            out[pos:pos + li] = out[offs[i]:offs[i] + li].copy()
        pos += li
    from .stats import current_stats

    st = current_stats()
    if st is not None:
        st.codec_split_blocks += nb
    return pos


register_block_compressor(CompressionCodec.UNCOMPRESSED, _Uncompressed())
register_block_compressor(CompressionCodec.GZIP, _Gzip())
register_block_compressor(CompressionCodec.SNAPPY, _Snappy())
register_block_compressor(CompressionCodec.LZ4_RAW, _Lz4Raw())


def _zstd_backend_available() -> bool:
    from .native.syslibs import zstd_native

    if zstd_native() is not None:
        return True
    try:
        import zstandard  # noqa: F401
    except ImportError:
        return False
    return True


if _zstd_backend_available():
    register_block_compressor(CompressionCodec.ZSTD, _Zstd())
