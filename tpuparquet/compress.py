"""Block compression registry + codecs (UNCOMPRESSED, SNAPPY, GZIP, ZSTD).

API parity with the reference's ``compress.go``: a process-wide registry of
:class:`BlockCompressor` objects keyed by ``CompressionCodec``, with
``register_block_compressor`` as the public extension hook
(``compress.go:130``) and built-ins registered at import
(``compress.go:152-156``).  ``decompress_block`` validates the decoded size
like ``newBlockReader`` (``compress.go:102-122``).

Snappy is implemented from scratch (the Python image has no snappy
library): the format is a varint uncompressed-length header followed by
literal/copy tokens.  The decoder parses the token stream into (literal,
copy) operations and resolves copies — the same two-pass structure the
TPU-side decompressor uses (token parse on host, copy resolution on
device), per SURVEY.md §7 stage 5d.
"""

from __future__ import annotations

import threading
import zlib

import numpy as np

from .format.metadata import CompressionCodec
from .varint import read_uvarint, write_uvarint

__all__ = [
    "BlockCompressor",
    "register_block_compressor",
    "get_block_compressor",
    "registered_codecs",
    "compress_block",
    "decompress_block",
    "snappy_compress",
    "snappy_decompress",
    "snappy_parse_tokens",
    "snappy_single_literal_view",
    "CompressionError",
]


class CompressionError(ValueError):
    pass


class BlockCompressor:
    """One whole-block codec; subclasses implement both directions."""

    def compress_block(self, block: bytes) -> bytes:
        raise NotImplementedError

    def decompress_block(self, block: bytes, decompressed_size: int) -> bytes:
        raise NotImplementedError


_registry: dict[int, BlockCompressor] = {}
_registry_lock = threading.Lock()


def register_block_compressor(codec: CompressionCodec, c: BlockCompressor) -> None:
    with _registry_lock:
        _registry[int(codec)] = c


def get_block_compressor(codec: CompressionCodec) -> BlockCompressor:
    with _registry_lock:
        c = _registry.get(int(codec))
    if c is None:
        raise CompressionError(
            f"compression codec {CompressionCodec(codec).name} is not "
            "registered (register_block_compressor to plug one in)"
        )
    return c


def registered_codecs() -> list[CompressionCodec]:
    with _registry_lock:
        return [CompressionCodec(k) for k in sorted(_registry)]


def compress_block(codec: CompressionCodec, block: bytes) -> bytes:
    return get_block_compressor(codec).compress_block(bytes(block))


def decompress_block(
    codec: CompressionCodec, block, decompressed_size: int
) -> bytes:
    out = get_block_compressor(codec).decompress_block(
        bytes(block), decompressed_size
    )
    if len(out) != decompressed_size:
        raise CompressionError(
            f"decompressed size {len(out)} != expected {decompressed_size}"
        )
    return out


def snappy_single_literal_view(block) -> "np.ndarray | None":
    """Zero-copy view of a snappy block that is one literal token.

    Incompressible pages — PLAIN numeric columns of high-entropy data —
    compress to ``[uvarint total][literal tag][payload]``; the payload
    IS the decompressed block, sitting inside the file bytes already.
    Returns that view, or None when the stream is anything else.  The
    single-core host this runs on makes the skipped memcpy a first-order
    win (decompression was ~60% of the device path's plan phase)."""
    buf = block if isinstance(block, np.ndarray) else np.frombuffer(
        block, dtype=np.uint8)
    try:
        total, pos = read_uvarint(buf, 0)
    except Exception:
        return None
    if pos >= buf.size:
        return None
    tag = int(buf[pos])
    pos += 1
    if tag & 3:
        return None  # first token is a copy
    ln = tag >> 2
    if ln >= 60:
        extra = ln - 59
        if pos + extra > buf.size:
            return None
        ln = 0
        for i in range(extra):
            ln |= int(buf[pos + i]) << (8 * i)
        pos += extra
    ln += 1
    if ln != total or pos + ln != buf.size:
        return None  # not a single literal covering the whole block
    return buf[pos : pos + ln]


def decompress_block_into(codec: CompressionCodec, block,
                          decompressed_size: int, arena):
    """Device-path decompress: zero input copy and a recycled output
    slab when the native snappy codec is available; otherwise falls back
    to :func:`decompress_block`.  Returns a u8 numpy view either way —
    arena-backed outputs are only valid until ``arena.release_all()``
    (single-literal snappy blocks come back as views of ``block``
    itself, valid as long as the caller's buffer)."""
    import numpy as np

    if decompressed_size is None or decompressed_size < 0:
        raise CompressionError("missing decompressed size")
    if codec == CompressionCodec.SNAPPY:
        view = snappy_single_literal_view(block)
        if view is not None:
            if view.size != decompressed_size:
                raise CompressionError(
                    f"decompressed size {view.size} != expected "
                    f"{decompressed_size}"
                )
            return view
    if codec == CompressionCodec.UNCOMPRESSED:
        out = np.frombuffer(block, dtype=np.uint8) if not isinstance(
            block, np.ndarray) else block
        if out.size != decompressed_size:
            raise CompressionError(
                f"decompressed size {out.size} != expected "
                f"{decompressed_size}"
            )
        return out
    if codec == CompressionCodec.SNAPPY:
        from .native import snappy_native

        nat = snappy_native()
        if nat is not None:
            out = arena.borrow(decompressed_size + 16)
            try:
                got = nat.decompress_np(block, decompressed_size, out=out)
            except ValueError as e:
                raise CompressionError(str(e)) from None
            if got.size != decompressed_size:
                raise CompressionError(
                    f"decompressed size {got.size} != expected "
                    f"{decompressed_size}"
                )
            return got
    return np.frombuffer(
        decompress_block(codec, block, decompressed_size), dtype=np.uint8
    )


# --------------------------------------------------------------------------
# Built-in codecs
# --------------------------------------------------------------------------

class _Uncompressed(BlockCompressor):
    def compress_block(self, block):
        return block

    def decompress_block(self, block, decompressed_size):
        return block


class _Gzip(BlockCompressor):
    def compress_block(self, block):
        co = zlib.compressobj(wbits=16 + zlib.MAX_WBITS)  # gzip framing
        return co.compress(block) + co.flush()

    def decompress_block(self, block, decompressed_size):
        try:
            return zlib.decompress(block, wbits=16 + zlib.MAX_WBITS)
        except zlib.error as e:
            raise CompressionError(f"gzip: {e}") from e


class _Zstd(BlockCompressor):
    def __init__(self):
        import zstandard

        self._zstd = zstandard
        # ZstdCompressor/ZstdDecompressor contexts are documented as not
        # shareable across concurrent calls; keep them thread-local.
        self._local = threading.local()

    def _ctx(self):
        if not hasattr(self._local, "c"):
            self._local.c = self._zstd.ZstdCompressor()
            self._local.d = self._zstd.ZstdDecompressor()
        return self._local

    def compress_block(self, block):
        return self._ctx().c.compress(block)

    def decompress_block(self, block, decompressed_size):
        try:
            return self._ctx().d.decompress(
                block, max_output_size=decompressed_size
            )
        except Exception as e:
            raise CompressionError(f"zstd: {e}") from e


# --------------------------------------------------------------------------
# Snappy (from scratch)
# --------------------------------------------------------------------------

def snappy_parse_tokens(block: bytes):
    """Parse a snappy block into ``(total_len, ops)``.

    ``ops`` is a list of ``(dst, length, src)`` triples: ``src >= 0`` is a
    copy from absolute output offset ``src``; ``src == -1`` is a literal
    whose bytes start at ``dst_literal_pos`` (stored in a parallel slot as
    ``(dst, length, -1 - input_pos)``).  This op list is exactly what the
    device copy-resolution kernel consumes.
    """
    try:
        total, pos = read_uvarint(block, pos=0)
    except ValueError as e:
        raise CompressionError(f"snappy: bad size header: {e}") from None
    n = len(block)
    ops = []
    out_pos = 0
    while pos < n:
        tag = block[pos]
        kind = tag & 3
        pos += 1
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                extra = ln - 59
                if pos + extra > n:
                    raise CompressionError("snappy: truncated literal length")
                ln = int.from_bytes(block[pos : pos + extra], "little")
                pos += extra
            ln += 1
            if pos + ln > n:
                raise CompressionError("snappy: literal overruns input")
            ops.append((out_pos, ln, -1 - pos))
            pos += ln
            out_pos += ln
            continue
        if kind == 1:  # copy with 1-byte offset extension
            if pos >= n:
                raise CompressionError("snappy: truncated copy-1")
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | block[pos]
            pos += 1
        elif kind == 2:  # 2-byte offset
            if pos + 2 > n:
                raise CompressionError("snappy: truncated copy-2")
            ln = (tag >> 2) + 1
            off = int.from_bytes(block[pos : pos + 2], "little")
            pos += 2
        else:  # 4-byte offset
            if pos + 4 > n:
                raise CompressionError("snappy: truncated copy-4")
            ln = (tag >> 2) + 1
            off = int.from_bytes(block[pos : pos + 4], "little")
            pos += 4
        if off == 0 or off > out_pos:
            raise CompressionError(
                f"snappy: copy offset {off} out of range at output {out_pos}"
            )
        ops.append((out_pos, ln, out_pos - off))
        out_pos += ln
    if out_pos != total:
        raise CompressionError(
            f"snappy: stream produced {out_pos} bytes, header says {total}"
        )
    return total, ops


def snappy_decompress(block: bytes, expected_size: int | None = None) -> bytes:
    total, ops = snappy_parse_tokens(block)
    if expected_size is not None and total != expected_size:
        raise CompressionError(
            f"snappy: header size {total} != expected {expected_size}"
        )
    out = np.empty(total, dtype=np.uint8)
    src_buf = np.frombuffer(block, dtype=np.uint8)
    for dst, ln, src in ops:
        if src < 0:  # literal from input
            ip = -1 - src
            out[dst : dst + ln] = src_buf[ip : ip + ln]
        elif src + ln <= dst:  # non-overlapping copy
            out[dst : dst + ln] = out[src : src + ln]
        else:
            # Overlapping copy: byte-sequential semantics make it a periodic
            # extension of the bytes between src and dst, so tile the period.
            period = dst - src
            reps = -(-ln // period)
            out[dst : dst + ln] = np.tile(out[src:dst], reps)[:ln]
    return out.tobytes()


def _emit_literal(out: bytearray, data, lo: int, hi: int) -> None:
    # One token per literal stretch, however long (the tag format takes
    # up to 4 length bytes): an incompressible block then compresses to
    # exactly [uvarint][tag][payload], which the decode path serves as a
    # zero-copy view (``snappy_single_literal_view``) — same shape the
    # native C encoder emits.
    n = hi - lo
    if n <= 0:
        return
    ln = n - 1
    if ln < 60:
        out.append(ln << 2)
    elif ln < 256:
        out.append(60 << 2)
        out.append(ln)
    elif ln < 65536:
        out.append(61 << 2)
        out += ln.to_bytes(2, "little")
    elif ln < 1 << 24:
        out.append(62 << 2)
        out += ln.to_bytes(3, "little")
    else:
        out.append(63 << 2)
        out += ln.to_bytes(4, "little")
    out += data[lo:hi]


def _emit_copy(out: bytearray, offset: int, ln: int) -> None:
    # 2-byte-offset copies (tag 0b10) cover offset <= 65535, len 1..64.
    off = offset.to_bytes(2, "little")
    while ln > 64:
        out.append((63 << 2) | 2)
        out += off
        ln -= 64
    out.append(((ln - 1) << 2) | 2)
    out += off


def snappy_compress(data: bytes, min_match: int = 4) -> bytes:
    """Greedy hash-match snappy encoder (golang-snappy style, with the
    standard miss-skip acceleration).  Output is valid snappy that any
    implementation (incl. pyarrow's) decodes back to ``data``.

    ``min_match`` is the shortest back-reference worth emitting (>= 4);
    raising it trades ratio on text data for decode throughput."""
    data = bytes(data)
    n = len(data)
    min_match = max(min_match, 4)
    out = bytearray()
    write_uvarint(out, n)
    if n < 4:
        if n:
            _emit_literal(out, data, 0, n)
        return bytes(out)
    table: dict[int, int] = {}
    pos = 0
    lit_start = 0
    misses = 0
    while pos + 4 <= n:
        key = int.from_bytes(data[pos : pos + 4], "little")
        cand = table.get(key)
        table[key] = pos
        if cand is not None and pos - cand <= 65535:
            # hash hit is exact (key is the literal 4 bytes)
            length = 4
            limit = n - pos
            while (
                length < limit
                and data[cand + length] == data[pos + length]
            ):
                length += 1
            if length < min_match:
                misses += 1
                pos += 1 + (misses >> 5)
                continue
            _emit_literal(out, data, lit_start, pos)
            _emit_copy(out, pos - cand, length)
            pos += length
            lit_start = pos
            misses = 0
        else:
            misses += 1
            pos += 1 + (misses >> 5)  # skip faster through incompressible data
    _emit_literal(out, data, lit_start, n)
    return bytes(out)


class _Snappy(BlockCompressor):
    """Snappy with the native C fast path and a pure-Python fallback.

    The native codec (tpuparquet/native/snappy.c) is loaded lazily on
    first use; both implement the same wire format, so files are
    interchangeable either way.

    ``min_match`` sets the shortest back-reference the encoder emits.
    The default (4) matches the format's reference encoders (the Go
    implementation the reference vendors emits 4-byte matches): numeric
    column data's redundancy lives almost entirely in 4..7-byte matches
    at lag ``sizeof(value)`` — timestamp-like int64 streams measure
    1.00 at ``min_match=8`` vs 0.76 at 4 — and smaller blocks are what
    the device decompressor turns into less wire time.  Register
    ``_Snappy(min_match=8)`` via ``register_block_compressor`` to trade
    ratio back for encode throughput."""

    def __init__(self, min_match: int = 4):
        self._native = False  # not resolved yet
        self.min_match = min_match

    def _nat(self):
        if self._native is False:
            from .native import snappy_native

            self._native = snappy_native()
        return self._native

    def compress_block(self, block):
        nat = self._nat()
        if nat is not None:
            return nat.compress(bytes(block), min_match=self.min_match)
        return snappy_compress(block, min_match=self.min_match)

    def decompress_block(self, block, decompressed_size):
        nat = self._nat()
        if nat is not None:
            try:
                # memoryview over a numpy buffer: bytes-like (compares
                # equal to bytes, slices, unpacks) and the decode path
                # avoids two whole-buffer copies per page
                return memoryview(
                    nat.decompress_np(bytes(block), decompressed_size)
                )
            except ValueError as e:
                raise CompressionError(str(e)) from None
        return snappy_decompress(block, decompressed_size)


def builtin_uncompressed_registered() -> bool:
    """True when the UNCOMPRESSED slot still holds the built-in
    pass-through — the condition for the native page pipeline to skip
    the compressor entirely.  A user-registered transform on the
    UNCOMPRESSED codec id (the registry allows it) must keep full
    control of the bytes, so callers take the pure page path then."""
    with _registry_lock:
        return type(
            _registry.get(int(CompressionCodec.UNCOMPRESSED))
        ) is _Uncompressed


def snappy_native_settings():
    """``(native_codec, min_match)`` when the REGISTERED snappy block
    compressor is the built-in :class:`_Snappy` backed by the native C
    codec — the condition under which the write-side native page
    pipeline (``io/pages.py``) produces exactly the bytes
    ``compress_block`` would.  None otherwise (a custom compressor was
    registered, or no compiler): callers must then take the pure page
    path so registered-codec semantics are honored."""
    with _registry_lock:
        c = _registry.get(int(CompressionCodec.SNAPPY))
    if type(c) is _Snappy:
        nat = c._nat()
        if nat is not None:
            return nat, c.min_match
    return None


register_block_compressor(CompressionCodec.UNCOMPRESSED, _Uncompressed())
register_block_compressor(CompressionCodec.GZIP, _Gzip())
register_block_compressor(CompressionCodec.SNAPPY, _Snappy())
try:
    register_block_compressor(CompressionCodec.ZSTD, _Zstd())
except ImportError:  # zstandard not in this environment: stay pluggable
    pass
