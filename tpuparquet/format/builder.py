"""Typed programmatic schema construction.

The constructor counterpart of the DSL: build ``ColumnDefinition``
subtrees without writing schema text, with the three-level LIST/MAP
group shapes assembled for you (API parity with the reference's
``NewDataColumn``/``NewListColumn``/``NewMapColumn``/``AddGroup``,
``/root/reference/schema.go:491-583``).  The results feed
``Schema.add_node`` / ``SchemaDefinition`` and pass
``validate_strict`` — the same shapes ``parse_schema_definition``
produces from the equivalent text.

Logical types are passed as a ``LogicalType`` instance (or the
``decimal``/``timestamp``/... helpers below); the matching converted
type is populated automatically for format-v1 forward compatibility,
exactly as the DSL parser does (``dsl.py:400-473``).
"""

from __future__ import annotations

from .dsl import ColumnDefinition, SchemaDefinition, SchemaValidationError
from .metadata import (
    BsonType,
    ConvertedType,
    DateType,
    DecimalType,
    EnumType,
    FieldRepetitionType,
    IntType,
    JsonType,
    ListType,
    LogicalType,
    MapType,
    MicroSeconds,
    MilliSeconds,
    NanoSeconds,
    SchemaElement,
    StringType,
    TimestampType,
    TimeType,
    TimeUnit,
    Type,
    UUIDType,
)

__all__ = [
    "new_data_column",
    "new_group",
    "new_list_column",
    "new_map_column",
    "new_root",
    "logical_string",
    "logical_date",
    "logical_uuid",
    "logical_enum",
    "logical_json",
    "logical_bson",
    "logical_int",
    "logical_decimal",
    "logical_time",
    "logical_timestamp",
]

REQUIRED = FieldRepetitionType.REQUIRED
OPTIONAL = FieldRepetitionType.OPTIONAL
REPEATED = FieldRepetitionType.REPEATED


# -- logical-type helpers --------------------------------------------------

def logical_string() -> LogicalType:
    return LogicalType(STRING=StringType())


def logical_date() -> LogicalType:
    return LogicalType(DATE=DateType())


def logical_uuid() -> LogicalType:
    return LogicalType(UUID=UUIDType())


def logical_enum() -> LogicalType:
    return LogicalType(ENUM=EnumType())


def logical_json() -> LogicalType:
    return LogicalType(JSON=JsonType())


def logical_bson() -> LogicalType:
    return LogicalType(BSON=BsonType())


def logical_int(bit_width: int, signed: bool = True) -> LogicalType:
    if bit_width not in (8, 16, 32, 64):
        raise SchemaValidationError(f"INT: unsupported bitwidth {bit_width}")
    return LogicalType(INTEGER=IntType(bitWidth=bit_width, isSigned=signed))


def logical_decimal(precision: int, scale: int) -> LogicalType:
    return LogicalType(DECIMAL=DecimalType(scale=scale, precision=precision))


def _time_unit(unit: str) -> TimeUnit:
    u = unit.upper()
    if u == "MILLIS":
        return TimeUnit(MILLIS=MilliSeconds())
    if u == "MICROS":
        return TimeUnit(MICROS=MicroSeconds())
    if u == "NANOS":
        return TimeUnit(NANOS=NanoSeconds())
    raise SchemaValidationError(f"unsupported time unit {unit!r}")


def logical_time(unit: str = "MILLIS", utc: bool = True) -> LogicalType:
    return LogicalType(TIME=TimeType(isAdjustedToUTC=utc,
                                     unit=_time_unit(unit)))


def logical_timestamp(unit: str = "MILLIS", utc: bool = True) -> LogicalType:
    return LogicalType(TIMESTAMP=TimestampType(isAdjustedToUTC=utc,
                                               unit=_time_unit(unit)))


def _converted_for(lt: LogicalType, se: SchemaElement) -> None:
    """Populate the legacy converted type (and DECIMAL scale/precision)
    matching a new-style logical type — the same v1 forward-compat
    mapping the DSL parser applies (``dsl.py:408-472``).  UUID and
    NANOS-unit types have no legacy equivalent and set nothing."""
    if lt.STRING is not None:
        se.converted_type = ConvertedType.UTF8
    elif lt.DATE is not None:
        se.converted_type = ConvertedType.DATE
    elif lt.ENUM is not None:
        se.converted_type = ConvertedType.ENUM
    elif lt.JSON is not None:
        se.converted_type = ConvertedType.JSON
    elif lt.BSON is not None:
        se.converted_type = ConvertedType.BSON
    elif lt.INTEGER is not None:
        it = lt.INTEGER
        se.converted_type = ConvertedType[
            ("INT_" if it.isSigned else "UINT_") + str(it.bitWidth)]
    elif lt.DECIMAL is not None:
        se.scale = lt.DECIMAL.scale
        se.precision = lt.DECIMAL.precision
        se.converted_type = ConvertedType.DECIMAL
    elif lt.TIME is not None:
        if lt.TIME.unit.MILLIS is not None:
            se.converted_type = ConvertedType.TIME_MILLIS
        elif lt.TIME.unit.MICROS is not None:
            se.converted_type = ConvertedType.TIME_MICROS
    elif lt.TIMESTAMP is not None:
        if lt.TIMESTAMP.unit.MILLIS is not None:
            se.converted_type = ConvertedType.TIMESTAMP_MILLIS
        elif lt.TIMESTAMP.unit.MICROS is not None:
            se.converted_type = ConvertedType.TIMESTAMP_MICROS
    elif lt.LIST is not None:
        se.converted_type = ConvertedType.LIST
    elif lt.MAP is not None:
        se.converted_type = ConvertedType.MAP


# -- constructors ----------------------------------------------------------

def new_data_column(
    name: str,
    ptype: Type,
    repetition: FieldRepetitionType = REQUIRED,
    *,
    logical_type: LogicalType | None = None,
    converted_type: ConvertedType | None = None,
    type_length: int | None = None,
    field_id: int | None = None,
) -> ColumnDefinition:
    """A leaf data column (≙ ``NewDataColumn``, ``schema.go:493-499``).

    ``logical_type`` auto-fills the matching converted type (and
    DECIMAL scale/precision); pass ``converted_type`` alone for a
    legacy-only annotation."""
    ptype = Type(ptype)
    if ptype == Type.FIXED_LEN_BYTE_ARRAY and not type_length:
        raise SchemaValidationError(
            f"column {name!r}: FIXED_LEN_BYTE_ARRAY needs type_length")
    se = SchemaElement(
        name=name, type=ptype,
        repetition_type=FieldRepetitionType(repetition),
        type_length=type_length, field_id=field_id,
    )
    if logical_type is not None:
        se.logicalType = logical_type
        _converted_for(logical_type, se)
    if converted_type is not None:
        se.converted_type = ConvertedType(converted_type)
    return ColumnDefinition(se)


def new_group(
    name: str,
    repetition: FieldRepetitionType = REQUIRED,
    children: list[ColumnDefinition] | tuple = (),
    *,
    field_id: int | None = None,
) -> ColumnDefinition:
    """A plain (unannotated) group node (≙ ``AddGroup``,
    ``schema.go:569-577``); attach children here or later via
    ``Schema.add_node``."""
    se = SchemaElement(name=name,
                       repetition_type=FieldRepetitionType(repetition),
                       field_id=field_id)
    return ColumnDefinition(se, list(children))


def new_list_column(
    name: str,
    element: ColumnDefinition,
    repetition: FieldRepetitionType = OPTIONAL,
) -> ColumnDefinition:
    """The canonical three-level LIST shape (≙ ``NewListColumn``,
    ``schema.go:502-526``)::

        <repetition> group <name> (LIST) {
          repeated group list {
            <element renamed "element">;
          }
        }

    The element keeps its own repetition (required/optional) and may
    itself be a group, another list, or a map."""
    repetition = FieldRepetitionType(repetition)
    if repetition == REPEATED:
        raise SchemaValidationError(
            f"LIST column {name!r} cannot itself be repeated")
    if element.element.repetition_type == REPEATED:
        raise SchemaValidationError(
            f"LIST element of {name!r} cannot be repeated "
            "(the repeated level is the generated 'list' group)")
    element.element.name = "element"
    se = SchemaElement(name=name, repetition_type=repetition,
                       logicalType=LogicalType(LIST=ListType()),
                       converted_type=ConvertedType.LIST)
    inner = SchemaElement(name="list", repetition_type=REPEATED)
    return ColumnDefinition(se, [ColumnDefinition(inner, [element])])


def new_map_column(
    name: str,
    key: ColumnDefinition,
    value: ColumnDefinition,
    repetition: FieldRepetitionType = OPTIONAL,
) -> ColumnDefinition:
    """The canonical MAP shape (≙ ``NewMapColumn``,
    ``schema.go:529-566``)::

        <repetition> group <name> (MAP) {
          repeated group key_value (MAP_KEY_VALUE) {
            required <key renamed "key">;
            <value renamed "value">;
          }
        }

    The key must be REQUIRED (spec rule, enforced like the reference
    does); the value may be optional, a group, a list, or a map."""
    repetition = FieldRepetitionType(repetition)
    if repetition == REPEATED:
        raise SchemaValidationError(
            f"MAP column {name!r} cannot itself be repeated")
    if key.element.repetition_type != REQUIRED:
        raise SchemaValidationError(
            "the key repetition type should be REQUIRED")
    if value.element.repetition_type == REPEATED:
        raise SchemaValidationError(
            f"MAP value of {name!r} cannot be repeated")
    key.element.name = "key"
    value.element.name = "value"
    se = SchemaElement(name=name, repetition_type=repetition,
                       logicalType=LogicalType(MAP=MapType()),
                       converted_type=ConvertedType.MAP)
    kv = SchemaElement(name="key_value", repetition_type=REPEATED,
                       converted_type=ConvertedType.MAP_KEY_VALUE)
    return ColumnDefinition(se, [ColumnDefinition(kv, [key, value])])


def new_root(name: str = "msg",
             children: list[ColumnDefinition] | tuple = ()
             ) -> SchemaDefinition:
    """Assemble a whole ``SchemaDefinition`` from constructed columns —
    ``FileWriter(..., schema=new_root("m", [...]))`` without DSL text."""
    root = SchemaElement(name=name)
    return SchemaDefinition(ColumnDefinition(root, list(children)))
