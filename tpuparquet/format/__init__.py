"""Host-side Parquet format layer: thrift metadata, footer framing,
schema — plus the untrusted-metadata tools: strict validation
(``validate``) and torn-file salvage (``recover``)."""

from .compact import CompactReader, CompactWriter, ThriftError  # noqa: F401
from .footer import MAGIC, FormatError, read_file_metadata, write_footer  # noqa: F401
from .metadata import *  # noqa: F401,F403
from .validate import Finding, validate_metadata  # noqa: F401
from .recover import (  # noqa: F401
    forward_scan,
    read_salvage_hint,
    recover_file_metadata,
)
