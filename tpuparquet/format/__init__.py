"""Host-side Parquet format layer: thrift metadata, footer framing, schema."""

from .compact import CompactReader, CompactWriter, ThriftError  # noqa: F401
from .footer import MAGIC, FormatError, read_file_metadata, write_footer  # noqa: F401
from .metadata import *  # noqa: F401,F403
