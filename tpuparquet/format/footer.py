"""Parquet file framing: magic bytes + footer read/write.

Layout (same checks as the reference's ``/root/reference/file_meta.go:14-62``):

    "PAR1" | row groups ... | thrift(FileMetaData) | footer_len:int32 LE | "PAR1"

``read_file_metadata`` validates the magic at both ends, reads the 4-byte
little-endian footer length at EOF-8, then compact-thrift-decodes
``FileMetaData``.  Every framing failure raises
:class:`~tpuparquet.errors.CorruptFooterError` (the error taxonomy's
file-level class, carrying the rejecting byte offset); ``FormatError``
remains as a backwards-compatible alias.  Deeper semantic validation
(offset bounds, schema cross-checks) lives in ``format/validate.py``;
salvage of files this module rejects lives in ``format/recover.py``.
"""

from __future__ import annotations

import os
import struct

from ..errors import CorruptFooterError
from .compact import CompactWriter, ThriftError
from .metadata import FileMetaData, encode_struct

MAGIC = b"PAR1"

__all__ = ["MAGIC", "read_file_metadata", "write_footer", "FormatError"]

# Folded into the taxonomy (tpuparquet/errors.py): framing errors are
# file-level corruption with coordinates, so quarantining scan drivers
# can catch one class for both torn footers and bad chunks.  The old
# name stays importable — tests and external callers use it.
FormatError = CorruptFooterError


def _file_size(f) -> int:
    pos = f.tell()
    size = f.seek(0, os.SEEK_END)
    f.seek(pos)
    return size


def read_file_metadata(f) -> FileMetaData:
    """Read and validate the footer of a seekable binary file object."""
    from ..faults import filter_bytes

    size = _file_size(f)
    if size < len(MAGIC) * 2 + 4:
        raise FormatError(
            f"file too small to be parquet ({size} bytes)", offset=0)

    f.seek(0)
    if f.read(4) != MAGIC:
        raise FormatError("invalid magic at file head", offset=0)

    f.seek(size - 8)
    tail = filter_bytes("format.footer.tail", f.read(8))
    if len(tail) < 8 or tail[4:] != MAGIC:
        raise FormatError(
            f"invalid magic at file tail (offset {size - 4})",
            offset=size - 4)
    (footer_len,) = struct.unpack("<I", tail[:4])
    footer_start = size - 8 - footer_len
    # cap against the file: the footer cannot reach past the head magic
    # (a corrupt length field would otherwise send the seek anywhere)
    if footer_len <= 0 or footer_start < 4:
        raise FormatError(
            f"invalid footer length {footer_len} (footer would start at "
            f"{footer_start} in a {size}-byte file)", offset=size - 8)

    f.seek(footer_start)
    buf = filter_bytes("format.footer.blob", f.read(footer_len))
    if len(buf) != footer_len:
        raise FormatError(
            f"short read of footer: {len(buf)}/{footer_len} bytes at "
            f"offset {footer_start}", offset=footer_start)
    try:
        meta = FileMetaData.from_bytes(buf)
    except ThriftError as e:
        raise FormatError(f"corrupt footer thrift: {e}",
                          offset=footer_start) from e
    # Required-field validation: compact thrift is permissive enough that
    # corrupt bytes can decode to an empty struct, so enforce the fields
    # parquet.thrift marks `required` before trusting the result.
    if (
        meta.version is None
        or not meta.schema
        or meta.num_rows is None
        or meta.row_groups is None
    ):
        raise FormatError("footer missing required FileMetaData fields",
                          offset=footer_start)
    for rg in meta.row_groups:
        if rg.columns is None or rg.num_rows is None:
            raise FormatError("row group missing required fields",
                              offset=footer_start)
        for cc in rg.columns:
            cm = cc.meta_data
            if cm is None:
                raise FormatError("column chunk missing metadata",
                                  offset=footer_start)
            if (
                cm.type is None
                or cm.codec is None
                or not cm.path_in_schema
                or cm.num_values is None
                or cm.data_page_offset is None
                or cm.total_compressed_size is None
            ):
                raise FormatError(
                    "column metadata missing required fields",
                    offset=footer_start)
            if cm.num_values < 0 or cm.total_compressed_size < 0 \
                    or cm.data_page_offset < 0:
                raise FormatError("negative sizes in column metadata",
                                  offset=footer_start)
    return meta


def write_footer(f, meta: FileMetaData) -> int:
    """Append thrift(FileMetaData) + length + magic; returns bytes written.

    The caller is responsible for having written the leading magic already
    (the writer does so on the first row-group flush, mirroring
    ``/root/reference/file_writer.go:184``)."""
    w = CompactWriter()
    encode_struct(meta, w)
    blob = w.getvalue()
    f.write(blob)
    f.write(struct.pack("<I", len(blob)))
    f.write(MAGIC)
    return len(blob) + 8
