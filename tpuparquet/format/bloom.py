"""Split-block bloom filters (parquet-format BloomFilter.md).

The format's bloom filter is a *split-block* bloom filter (SBBF):
the bitset is an array of 32-byte blocks (8 u32 words); a value's
XXH64 hash picks one block (top 32 bits scaled by the block count)
and sets/checks one bit per word, chosen by 8 fixed odd salt
constants multiplied against the low 32 bits.  Membership tests have
no false negatives — a "definitely absent" answer licenses pruning a
whole column chunk for ``==`` / ``IN`` predicates.

Hash input is the value's PLAIN encoding without a length prefix
(little-endian bytes for numerics, the raw bytes for BYTE_ARRAY /
FIXED_LEN_BYTE_ARRAY) — exactly what
:meth:`~tpuparquet.io.values.ValueHandler.encode_stat_value` emits, so
the statistics and bloom layers share one value-encoding contract.

Serialization (``format/metadata.py`` structs): a compact-thrift
:class:`~tpuparquet.format.metadata.BloomFilterHeader` (numBytes +
algorithm/hash/compression unions) immediately followed by the raw
bitset, at ``ColumnMetaData.bloom_filter_offset``.  XXH64 is
implemented here in pure Python (the container has no xxhash module);
bloom columns are opt-in and dictionary-ish, so the handful of
thousands of hashes per chunk cost milliseconds.
"""

from __future__ import annotations

import struct

import numpy as np

from .metadata import (
    BloomFilterAlgorithm,
    BloomFilterCompression,
    BloomFilterHash,
    BloomFilterHeader,
    SplitBlockAlgorithm,
    Uncompressed,
    XxHash,
)
from .compact import CompactReader, ThriftError

__all__ = ["xxh64", "xxh64_py", "SplitBlockBloom", "optimal_bytes",
           "MAX_BLOOM_BYTES"]

_M64 = (1 << 64) - 1
_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5

# the 8 salt constants of the split-block algorithm (BloomFilter.md)
_SALT = (0x47b6137b, 0x44974d91, 0x8824ad5b, 0xa2b7289d,
         0x705495c7, 0x2df1424b, 0x9efc4947, 0x5c6bfb31)

# refuse to read absurd bitsets from untrusted metadata (a corrupt
# numBytes must degrade to "no bloom", not an allocation bomb)
MAX_BLOOM_BYTES = 64 << 20


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def _round(acc: int, inp: int) -> int:
    acc = (acc + inp * _P2) & _M64
    return (_rotl(acc, 31) * _P1) & _M64


try:  # the C library when present (pure-Python fallback below is
    # bit-identical — pinned by tests — just slower)
    import xxhash as _xxhash_mod
except ImportError:
    _xxhash_mod = None


def xxh64(data: bytes, seed: int = 0) -> int:
    """XXH64 of ``data`` (C library when installed, else the pure-Python
    fallback :func:`xxh64_py`; both match the reference vectors)."""
    if _xxhash_mod is not None:
        return _xxhash_mod.xxh64(data, seed=seed).intdigest()
    return xxh64_py(data, seed)


def xxh64_py(data: bytes, seed: int = 0) -> int:
    """Pure-Python XXH64 (the no-dependency fallback)."""
    n = len(data)
    pos = 0
    if n >= 32:
        v1 = (seed + _P1 + _P2) & _M64
        v2 = (seed + _P2) & _M64
        v3 = seed & _M64
        v4 = (seed - _P1) & _M64
        limit = n - 32
        while pos <= limit:
            lanes = struct.unpack_from("<4Q", data, pos)
            v1 = _round(v1, lanes[0])
            v2 = _round(v2, lanes[1])
            v3 = _round(v3, lanes[2])
            v4 = _round(v4, lanes[3])
            pos += 32
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12)
             + _rotl(v4, 18)) & _M64
        for v in (v1, v2, v3, v4):
            h = ((h ^ _round(0, v)) * _P1 + _P4) & _M64
    else:
        h = (seed + _P5) & _M64
    h = (h + n) & _M64
    while pos + 8 <= n:
        (k,) = struct.unpack_from("<Q", data, pos)
        h = ((_rotl(h ^ _round(0, k), 27) * _P1) + _P4) & _M64
        pos += 8
    if pos + 4 <= n:
        (k,) = struct.unpack_from("<I", data, pos)
        h = ((_rotl(h ^ (k * _P1) & _M64, 23) * _P2) + _P3) & _M64
        pos += 4
    while pos < n:
        h = (_rotl(h ^ (data[pos] * _P5) & _M64, 11) * _P1) & _M64
        pos += 1
    h ^= h >> 33
    h = (h * _P2) & _M64
    h ^= h >> 29
    h = (h * _P3) & _M64
    h ^= h >> 32
    return h


def optimal_bytes(ndv: int, fpp: float = 0.01) -> int:
    """Bitset size for ``ndv`` distinct values at ~``fpp`` false-positive
    rate, rounded up to a power-of-two number of 32-byte blocks
    (BloomFilter.md sizing: c = -8 / log(1 - fpp^(1/8)) bits/value)."""
    import math

    if ndv <= 0:
        return 32
    c = -8.0 / math.log(1.0 - fpp ** 0.125)
    bits = int(ndv * c)
    nbytes = max((bits + 7) // 8, 32)
    blocks = 1 << max((nbytes + 31) // 32 - 1, 0).bit_length()
    return min(blocks * 32, MAX_BLOOM_BYTES)


class SplitBlockBloom:
    """One column chunk's split-block bloom filter."""

    __slots__ = ("bitset",)

    def __init__(self, num_bytes: int = 32, bitset=None):
        if bitset is not None:
            self.bitset = np.asarray(bitset, dtype=np.uint32)
            if self.bitset.size % 8:
                raise ValueError("bloom bitset must be whole 32B blocks")
        else:
            if num_bytes < 32 or num_bytes % 32:
                raise ValueError(
                    f"bloom bitset bytes must be a positive multiple "
                    f"of 32, not {num_bytes}")
            self.bitset = np.zeros(num_bytes // 4, dtype=np.uint32)

    @property
    def num_bytes(self) -> int:
        return int(self.bitset.nbytes)

    @property
    def _num_blocks(self) -> int:
        return self.bitset.size // 8

    def _block_and_mask(self, h: int):
        block = ((h >> 32) * self._num_blocks) >> 32
        lo = h & 0xFFFFFFFF
        mask = [np.uint32((lo * s) & 0xFFFFFFFF) >> np.uint32(27)
                for s in _SALT]
        return block, mask

    def insert_hash(self, h: int) -> None:
        block, mask = self._block_and_mask(h)
        base = block * 8
        for i, bit in enumerate(mask):
            self.bitset[base + i] |= np.uint32(1) << bit

    def check_hash(self, h: int) -> bool:
        """False = definitely absent; True = possibly present."""
        block, mask = self._block_and_mask(h)
        base = block * 8
        for i, bit in enumerate(mask):
            if not (int(self.bitset[base + i]) >> int(bit)) & 1:
                return False
        return True

    def insert(self, encoded: bytes) -> None:
        self.insert_hash(xxh64(encoded))

    def check(self, encoded: bytes) -> bool:
        return self.check_hash(xxh64(encoded))

    # -- wire form (BloomFilterHeader thrift + raw bitset) ---------------

    def to_bytes(self) -> bytes:
        header = BloomFilterHeader(
            numBytes=self.num_bytes,
            algorithm=BloomFilterAlgorithm(BLOCK=SplitBlockAlgorithm()),
            hash=BloomFilterHash(XXHASH=XxHash()),
            compression=BloomFilterCompression(
                UNCOMPRESSED=Uncompressed()),
        )
        return header.to_bytes() + self.bitset.astype("<u4").tobytes()

    @classmethod
    def from_bytes(cls, buf, pos: int = 0) -> "SplitBlockBloom":
        """Parse header + bitset at ``pos``; raises ``ValueError`` on
        anything that is not a well-formed uncompressed XXH64 SBBF (the
        callers degrade to "no bloom")."""
        r = CompactReader(buf, pos)
        try:
            from .metadata import decode_struct

            header = decode_struct(BloomFilterHeader, r)
        except (ThriftError, IndexError, struct.error) as e:
            raise ValueError(f"corrupt bloom filter header: {e}") from e
        nb = header.numBytes
        if nb is None or nb < 32 or nb % 32 or nb > MAX_BLOOM_BYTES:
            raise ValueError(f"bloom filter numBytes {nb} out of range")
        if header.algorithm is None or header.algorithm.BLOCK is None:
            raise ValueError("bloom filter algorithm is not split-block")
        if header.hash is None or header.hash.XXHASH is None:
            raise ValueError("bloom filter hash is not XXH64")
        if (header.compression is None
                or header.compression.UNCOMPRESSED is None):
            raise ValueError("bloom filter compression unsupported")
        end = r.pos + nb
        if end > len(buf):
            raise ValueError("bloom filter bitset overruns the buffer")
        bits = np.frombuffer(bytes(buf[r.pos:end]), dtype="<u4")
        return cls(bitset=bits.astype(np.uint32))
