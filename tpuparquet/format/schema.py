"""Runtime schema tree: Dremel repetition/definition levels, projection.

The reader/writer-facing counterpart of the reference's ``Column`` tree
(``/root/reference/schema.go:23-135`` accessors, ``recursiveFix`` :585 for
level assignment, ``setSelectedColumns``/``isSelected`` :292-312 for column
projection).  A ``SchemaNode`` wraps one thrift ``SchemaElement``; levels
follow the Dremel rules:

* ``max_def_level`` = count of non-REQUIRED ancestors including self
  (root excluded),
* ``max_rep_level`` = count of REPEATED ancestors including self.
"""

from __future__ import annotations

from .dsl import (
    ColumnDefinition,
    SchemaDefinition,
    SchemaValidationError,
    parse_schema_definition,
)
from .metadata import FieldRepetitionType, SchemaElement, Type

__all__ = ["SchemaNode", "Schema"]


def _build_node(cd: ColumnDefinition, parent: "SchemaNode | None") -> "SchemaNode":
    node = SchemaNode(cd.element, parent)
    for child in cd.children:
        node.children.append(_build_node(child, node))
    return node


class SchemaNode:
    """One node of the runtime schema tree."""

    __slots__ = (
        "element", "children", "parent", "path",
        "max_rep_level", "max_def_level", "store",
    )

    def __init__(self, element: SchemaElement, parent: "SchemaNode | None" = None):
        self.element = element
        self.children: list[SchemaNode] = []
        self.parent = parent
        self.path: tuple[str, ...] = ()
        self.max_rep_level = 0
        self.max_def_level = 0
        # Attached by the I/O layer: per-leaf column store (None on groups).
        self.store = None

    # -- accessors (Column accessor parity, schema.go:23-135) --------------

    @property
    def name(self) -> str:
        return self.element.name

    @property
    def flat_name(self) -> str:
        return ".".join(self.path)

    @property
    def type(self) -> Type | None:
        return self.element.type

    @property
    def repetition_type(self) -> FieldRepetitionType | None:
        return self.element.repetition_type

    @property
    def is_leaf(self) -> bool:
        return self.element.type is not None

    @property
    def is_repeated(self) -> bool:
        return self.element.repetition_type == FieldRepetitionType.REPEATED

    @property
    def is_required(self) -> bool:
        return self.element.repetition_type == FieldRepetitionType.REQUIRED

    def __repr__(self):
        kind = "leaf" if self.is_leaf else "group"
        return (
            f"SchemaNode({self.flat_name or '<root>'}, {kind}, "
            f"maxR={self.max_rep_level}, maxD={self.max_def_level})"
        )


class Schema:
    """Schema tree + column projection.

    Construction from a footer's flat element list, from a parsed DSL
    definition, or programmatically by adding nodes.  ``leaves`` lists data
    columns in depth-first order — the same order column chunks appear in a
    row group.
    """

    def __init__(self, root: SchemaNode):
        self.root = root
        self.leaves: list[SchemaNode] = []
        self.selected: list[tuple[str, ...]] = []  # empty = all selected
        self._refresh()

    # -- construction ------------------------------------------------------

    @classmethod
    def from_elements(cls, elems: list[SchemaElement]) -> "Schema":
        sd = SchemaDefinition.from_schema_elements(elems)
        return cls.from_definition(sd)

    @classmethod
    def from_definition(cls, sd: SchemaDefinition) -> "Schema":
        return cls(_build_node(sd.root, None))

    @classmethod
    def from_string(cls, text: str) -> "Schema":
        return cls.from_definition(parse_schema_definition(text))

    @classmethod
    def empty(cls, name: str = "msg") -> "Schema":
        return cls(SchemaNode(SchemaElement(name=name)))

    def add_node(self, parent_path: str, cd: ColumnDefinition) -> SchemaNode:
        """Programmatic schema building (≙ AddGroup/AddColumn,
        ``schema.go:569-583``): attach a column definition subtree under the
        group identified by dotted ``parent_path`` ('' = root)."""
        parent = self.root if not parent_path else self._node_at(parent_path)
        if parent is None:
            raise SchemaValidationError(f"no such group: {parent_path!r}")
        if parent.is_leaf:
            raise SchemaValidationError(
                f"{parent_path!r} is a data column, cannot add children"
            )
        node = _build_node(cd, parent)
        parent.children.append(node)
        self._refresh()
        return node

    # -- maintenance -------------------------------------------------------

    def _refresh(self) -> None:
        """Recompute paths, levels and the leaf list (≙ recursiveFix)."""
        self.leaves = []

        def walk(node: SchemaNode, path: tuple, d: int, r: int):
            node.path = path
            node.max_def_level = d
            node.max_rep_level = r
            if node.is_leaf:
                self.leaves.append(node)
            num = len(node.children)
            node.element.num_children = num if num else None
            for child in node.children:
                cd = d + (0 if child.is_required else 1)
                cr = r + (1 if child.is_repeated else 0)
                walk(child, path + (child.name,), cd, cr)

        if self.root.is_leaf:
            raise SchemaValidationError("schema root cannot be a data column")
        walk(self.root, (), 0, 0)

    # -- navigation --------------------------------------------------------

    def _node_at(self, flat_name: str) -> SchemaNode | None:
        parts = flat_name.split(".")
        node = self.root
        for p in parts:
            for c in node.children:
                if c.name == p:
                    node = c
                    break
            else:
                return None
        return node

    def leaf(self, flat_name: str) -> SchemaNode | None:
        node = self._node_at(flat_name)
        return node if node is not None and node.is_leaf else None

    def to_elements(self) -> list[SchemaElement]:
        out: list[SchemaElement] = []

        def walk(node: SchemaNode):
            out.append(node.element)
            for c in node.children:
                walk(c)

        walk(self.root)
        return out

    def definition(self) -> SchemaDefinition:
        """Return the DSL view (≙ GetSchemaDefinition)."""
        def build(node: SchemaNode) -> ColumnDefinition:
            return ColumnDefinition(node.element, [build(c) for c in node.children])

        return SchemaDefinition(build(self.root))

    # -- projection (≙ setSelectedColumns/isSelected) ----------------------

    def set_selected_columns(self, *flat_names: str) -> None:
        """Restrict reading to the given dotted paths (and their subtrees).
        No arguments = select everything."""
        sel = []
        for fn in flat_names:
            if self._node_at(fn) is None:
                raise SchemaValidationError(f"column {fn!r} is not in the schema")
            sel.append(tuple(fn.split(".")))
        self.selected = sel

    def is_selected(self, node_or_path) -> bool:
        """A node is selected if the selection is empty, or if any selected
        path is a prefix of the node's path (subtree selection) or the node's
        path is a prefix of a selected path (ancestors stay for structure)."""
        if not self.selected:
            return True
        path = (
            node_or_path.path
            if isinstance(node_or_path, SchemaNode)
            else tuple(node_or_path.split("."))
        )
        for sel in self.selected:
            n = min(len(sel), len(path))
            if sel[:n] == path[:n]:
                return True
        return False

    def __str__(self) -> str:
        return str(self.definition())
