"""Parquet metadata structures (thrift ``parquet.thrift``, format 2.8.0).

The reference vendors 11k lines of thrift-generated Go
(``/root/reference/parquet/parquet.go``); here the same wire structs are
*declared* — each class lists ``(field_id, name, type)`` tuples mirroring
``parquet.thrift`` — and a single generic compact-protocol encoder/decoder in
this module walks the declarations.  Unknown fields are skipped on read
(forward compatibility), absent optional fields are omitted on write.

Enums carry the exact numeric values from the spec (``parquet.thrift``:
``Type`` block at :32, ``ConvertedType`` :48, ``FieldRepetitionType`` :182,
``Encoding`` :407, ``CompressionCodec`` :479, ``PageType`` :489).
"""

from __future__ import annotations

import enum

from .compact import CT, CompactReader, CompactWriter, ThriftError

__all__ = [
    "Type", "ConvertedType", "FieldRepetitionType", "Encoding",
    "CompressionCodec", "PageType", "BoundaryOrder",
    "Statistics", "StringType", "UUIDType", "MapType", "ListType", "EnumType",
    "DateType", "NullType", "DecimalType", "MilliSeconds", "MicroSeconds",
    "NanoSeconds", "TimeUnit", "TimestampType", "TimeType", "IntType",
    "JsonType", "BsonType", "LogicalType", "SchemaElement", "DataPageHeader",
    "IndexPageHeader", "DictionaryPageHeader", "DataPageHeaderV2",
    "SplitBlockAlgorithm", "BloomFilterAlgorithm", "XxHash", "BloomFilterHash",
    "Uncompressed", "BloomFilterCompression", "BloomFilterHeader",
    "PageHeader", "KeyValue", "SortingColumn", "PageEncodingStats",
    "ColumnMetaData", "EncryptionWithFooterKey", "EncryptionWithColumnKey",
    "ColumnCryptoMetaData", "ColumnChunk", "RowGroup", "TypeDefinedOrder",
    "ColumnOrder", "PageLocation", "OffsetIndex", "ColumnIndex",
    "AesGcmV1", "AesGcmCtrV1", "EncryptionAlgorithm", "FileMetaData",
    "FileCryptoMetaData",
    "decode_struct", "encode_struct",
]


# --------------------------------------------------------------------------
# Enums
# --------------------------------------------------------------------------

class Type(enum.IntEnum):
    BOOLEAN = 0
    INT32 = 1
    INT64 = 2
    INT96 = 3
    FLOAT = 4
    DOUBLE = 5
    BYTE_ARRAY = 6
    FIXED_LEN_BYTE_ARRAY = 7


class ConvertedType(enum.IntEnum):
    UTF8 = 0
    MAP = 1
    MAP_KEY_VALUE = 2
    LIST = 3
    ENUM = 4
    DECIMAL = 5
    DATE = 6
    TIME_MILLIS = 7
    TIME_MICROS = 8
    TIMESTAMP_MILLIS = 9
    TIMESTAMP_MICROS = 10
    UINT_8 = 11
    UINT_16 = 12
    UINT_32 = 13
    UINT_64 = 14
    INT_8 = 15
    INT_16 = 16
    INT_32 = 17
    INT_64 = 18
    JSON = 19
    BSON = 20
    INTERVAL = 21


class FieldRepetitionType(enum.IntEnum):
    REQUIRED = 0
    OPTIONAL = 1
    REPEATED = 2


class Encoding(enum.IntEnum):
    PLAIN = 0
    PLAIN_DICTIONARY = 2
    RLE = 3
    BIT_PACKED = 4
    DELTA_BINARY_PACKED = 5
    DELTA_LENGTH_BYTE_ARRAY = 6
    DELTA_BYTE_ARRAY = 7
    RLE_DICTIONARY = 8
    BYTE_STREAM_SPLIT = 9


class CompressionCodec(enum.IntEnum):
    UNCOMPRESSED = 0
    SNAPPY = 1
    GZIP = 2
    LZO = 3
    BROTLI = 4
    LZ4 = 5  # deprecated Hadoop-framed LZ4 (undocumented framing)
    ZSTD = 6
    LZ4_RAW = 7  # raw LZ4 block format (what modern writers emit)


class PageType(enum.IntEnum):
    DATA_PAGE = 0
    INDEX_PAGE = 1
    DICTIONARY_PAGE = 2
    DATA_PAGE_V2 = 3


class BoundaryOrder(enum.IntEnum):
    UNORDERED = 0
    ASCENDING = 1
    DESCENDING = 2


# --------------------------------------------------------------------------
# Type descriptors
# --------------------------------------------------------------------------

class _TD:
    """Base type descriptor: knows its compact type id and how to read/write
    a value of that type *outside* a field header (i.e. as a container
    element or after the header was consumed)."""

    ct: int

    def read(self, r: CompactReader):
        raise NotImplementedError

    def write(self, w: CompactWriter, v) -> None:
        raise NotImplementedError


class _TBool(_TD):
    ct = CT.TRUE  # placeholder; bool fields are special-cased

    def read(self, r):
        return r.read_byte() == CT.TRUE

    def write(self, w, v):
        w.write_byte(CT.TRUE if v else CT.FALSE)


class _TI8(_TD):
    ct = CT.I8

    def read(self, r):
        b = r.read_byte()
        return b - 256 if b >= 128 else b

    def write(self, w, v):
        w.write_byte(v & 0xFF)


class _TVarint(_TD):
    def read(self, r):
        return r.read_zigzag()

    def write(self, w, v):
        w.write_zigzag(int(v))


class _TI16(_TVarint):
    ct = CT.I16


class _TI32(_TVarint):
    ct = CT.I32


class _TI64(_TVarint):
    ct = CT.I64


class _TDouble(_TD):
    ct = CT.DOUBLE

    def read(self, r):
        return r.read_double()

    def write(self, w, v):
        w.write_double(float(v))


class _TBinary(_TD):
    ct = CT.BINARY

    def read(self, r):
        return r.read_binary()

    def write(self, w, v):
        w.write_binary(bytes(v))


class _TString(_TD):
    ct = CT.BINARY

    def read(self, r):
        return r.read_binary().decode("utf-8", errors="replace")

    def write(self, w, v):
        w.write_binary(v.encode("utf-8"))


class _TEnum(_TD):
    ct = CT.I32

    def __init__(self, enum_cls):
        self.enum_cls = enum_cls

    def read(self, r):
        v = r.read_zigzag()
        try:
            return self.enum_cls(v)
        except ValueError:
            return v  # tolerate unknown enum values from future writers

    def write(self, w, v):
        w.write_zigzag(int(v))


class _TList(_TD):
    ct = CT.LIST

    def __init__(self, elem: _TD):
        self.elem = elem

    def read(self, r):
        etype, size = r.read_list_header()
        elem = self.elem
        if isinstance(elem, _TBool):
            return [r.read_byte() == CT.TRUE for _ in range(size)]
        return [elem.read(r) for _ in range(size)]

    def write(self, w, v):
        elem = self.elem
        ect = CT.TRUE if isinstance(elem, _TBool) else elem.ct
        w.write_list_header(ect, len(v))
        for x in v:
            elem.write(w, x)


class _TStruct(_TD):
    ct = CT.STRUCT

    def __init__(self, cls):
        self.cls = cls

    def read(self, r):
        return decode_struct(self.cls, r)

    def write(self, w, v):
        encode_struct(v, w)


BOOL = _TBool()
I8 = _TI8()
I16 = _TI16()
I32 = _TI32()
I64 = _TI64()
DOUBLE = _TDouble()
BINARY = _TBinary()
STRING = _TString()


# --------------------------------------------------------------------------
# Declarative struct machinery
# --------------------------------------------------------------------------

class ThriftStruct:
    """Base for declarative thrift structs.

    Subclasses set ``FIELDS = [(fid, name, type_descriptor), ...]`` in
    ``parquet.thrift`` order.  Instances hold each field as an attribute
    (``None`` = absent).  Equality compares all fields (handy in tests).
    """

    FIELDS: list = []
    # filled by __init_subclass__
    _BY_ID: dict = {}
    _NAMES: tuple = ()

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        cls._BY_ID = {fid: (name, td) for fid, name, td in cls.FIELDS}
        cls._NAMES = tuple(name for _, name, _td in cls.FIELDS)

    def __init__(self, **kwargs):
        for name in self._NAMES:
            setattr(self, name, kwargs.pop(name, None))
        if kwargs:
            raise TypeError(
                f"{type(self).__name__}: unknown fields {sorted(kwargs)}"
            )

    def __eq__(self, other):
        if type(other) is not type(self):
            return NotImplemented
        return all(
            getattr(self, n) == getattr(other, n) for n in self._NAMES
        )

    def __repr__(self):
        parts = [
            f"{n}={getattr(self, n)!r}"
            for n in self._NAMES
            if getattr(self, n) is not None
        ]
        return f"{type(self).__name__}({', '.join(parts)})"

    # Convenience serialization entry points -------------------------------

    def to_bytes(self) -> bytes:
        w = CompactWriter()
        encode_struct(self, w)
        return w.getvalue()

    @classmethod
    def from_bytes(cls, buf, pos: int = 0):
        r = CompactReader(buf, pos)
        return decode_struct(cls, r)


def decode_struct(cls, r: CompactReader):
    obj = cls.__new__(cls)
    for name in cls._NAMES:
        setattr(obj, name, None)
    last_fid = 0
    by_id = cls._BY_ID
    while True:
        ctype, fid = r.read_field_header(last_fid)
        if ctype == CT.STOP:
            return obj
        entry = by_id.get(fid)
        if entry is None:
            # Unknown field: skip (bools carry their value in the header).
            r.skip(ctype)
        else:
            name, td = entry
            if isinstance(td, _TBool):
                if ctype in (CT.TRUE, CT.FALSE):
                    setattr(obj, name, ctype == CT.TRUE)
                else:  # declared/wire mismatch: skip by wire type
                    r.skip(ctype)
            elif ctype == td.ct:
                setattr(obj, name, td.read(r))
            else:
                # Wire type disagrees with the declaration (corrupt input or
                # schema evolution): always consume by the *wire* type so the
                # stream stays in sync, and leave the field absent.
                r.skip(ctype)
        last_fid = fid


def encode_struct(obj, w: CompactWriter) -> None:
    last_fid = 0
    for fid, name, td in obj.FIELDS:
        v = getattr(obj, name)
        if v is None:
            continue
        if isinstance(td, _TBool):
            w.write_field_header(CT.TRUE if v else CT.FALSE, fid, last_fid)
        else:
            w.write_field_header(td.ct, fid, last_fid)
            td.write(w, v)
        last_fid = fid
    w.write_stop()


def _S(cls) -> _TStruct:
    return _TStruct(cls)


# --------------------------------------------------------------------------
# The structs (field ids match parquet.thrift, apache-parquet-format-2.8.0)
# --------------------------------------------------------------------------

class Statistics(ThriftStruct):
    FIELDS = [
        (1, "max", BINARY),
        (2, "min", BINARY),
        (3, "null_count", I64),
        (4, "distinct_count", I64),
        (5, "max_value", BINARY),
        (6, "min_value", BINARY),
    ]


class StringType(ThriftStruct):
    FIELDS = []


class UUIDType(ThriftStruct):
    FIELDS = []


class Float16Type(ThriftStruct):
    FIELDS = []


class MapType(ThriftStruct):
    FIELDS = []


class ListType(ThriftStruct):
    FIELDS = []


class EnumType(ThriftStruct):
    FIELDS = []


class DateType(ThriftStruct):
    FIELDS = []


class NullType(ThriftStruct):
    FIELDS = []


class DecimalType(ThriftStruct):
    FIELDS = [(1, "scale", I32), (2, "precision", I32)]


class MilliSeconds(ThriftStruct):
    FIELDS = []


class MicroSeconds(ThriftStruct):
    FIELDS = []


class NanoSeconds(ThriftStruct):
    FIELDS = []


class TimeUnit(ThriftStruct):
    """Union: exactly one of MILLIS/MICROS/NANOS is set."""

    FIELDS = [
        (1, "MILLIS", _S(MilliSeconds)),
        (2, "MICROS", _S(MicroSeconds)),
        (3, "NANOS", _S(NanoSeconds)),
    ]


class TimestampType(ThriftStruct):
    FIELDS = [(1, "isAdjustedToUTC", BOOL), (2, "unit", _S(TimeUnit))]


class TimeType(ThriftStruct):
    FIELDS = [(1, "isAdjustedToUTC", BOOL), (2, "unit", _S(TimeUnit))]


class IntType(ThriftStruct):
    FIELDS = [(1, "bitWidth", I8), (2, "isSigned", BOOL)]


class JsonType(ThriftStruct):
    FIELDS = []


class BsonType(ThriftStruct):
    FIELDS = []


class LogicalType(ThriftStruct):
    """Union: exactly one member set (parquet.thrift:322-344)."""

    FIELDS = [
        (1, "STRING", _S(StringType)),
        (2, "MAP", _S(MapType)),
        (3, "LIST", _S(ListType)),
        (4, "ENUM", _S(EnumType)),
        (5, "DECIMAL", _S(DecimalType)),
        (6, "DATE", _S(DateType)),
        (7, "TIME", _S(TimeType)),
        (8, "TIMESTAMP", _S(TimestampType)),
        (10, "INTEGER", _S(IntType)),
        (11, "UNKNOWN", _S(NullType)),
        (12, "JSON", _S(JsonType)),
        (13, "BSON", _S(BsonType)),
        (14, "UUID", _S(UUIDType)),
        (15, "FLOAT16", _S(Float16Type)),
    ]

    def set_member(self):
        """Return ``(name, value)`` of the single set union member."""
        for name in self._NAMES:
            v = getattr(self, name)
            if v is not None:
                return name, v
        return None, None


class SchemaElement(ThriftStruct):
    FIELDS = [
        (1, "type", _TEnum(Type)),
        (2, "type_length", I32),
        (3, "repetition_type", _TEnum(FieldRepetitionType)),
        (4, "name", STRING),
        (5, "num_children", I32),
        (6, "converted_type", _TEnum(ConvertedType)),
        (7, "scale", I32),
        (8, "precision", I32),
        (9, "field_id", I32),
        (10, "logicalType", _S(LogicalType)),
    ]


class DataPageHeader(ThriftStruct):
    FIELDS = [
        (1, "num_values", I32),
        (2, "encoding", _TEnum(Encoding)),
        (3, "definition_level_encoding", _TEnum(Encoding)),
        (4, "repetition_level_encoding", _TEnum(Encoding)),
        (5, "statistics", _S(Statistics)),
    ]


class IndexPageHeader(ThriftStruct):
    FIELDS = []


class DictionaryPageHeader(ThriftStruct):
    FIELDS = [
        (1, "num_values", I32),
        (2, "encoding", _TEnum(Encoding)),
        (3, "is_sorted", BOOL),
    ]


class DataPageHeaderV2(ThriftStruct):
    FIELDS = [
        (1, "num_values", I32),
        (2, "num_nulls", I32),
        (3, "num_rows", I32),
        (4, "encoding", _TEnum(Encoding)),
        (5, "definition_levels_byte_length", I32),
        (6, "repetition_levels_byte_length", I32),
        (7, "is_compressed", BOOL),  # default true when absent
        (8, "statistics", _S(Statistics)),
    ]


class SplitBlockAlgorithm(ThriftStruct):
    FIELDS = []


class BloomFilterAlgorithm(ThriftStruct):
    FIELDS = [(1, "BLOCK", _S(SplitBlockAlgorithm))]


class XxHash(ThriftStruct):
    FIELDS = []


class BloomFilterHash(ThriftStruct):
    FIELDS = [(1, "XXHASH", _S(XxHash))]


class Uncompressed(ThriftStruct):
    FIELDS = []


class BloomFilterCompression(ThriftStruct):
    FIELDS = [(1, "UNCOMPRESSED", _S(Uncompressed))]


class BloomFilterHeader(ThriftStruct):
    FIELDS = [
        (1, "numBytes", I32),
        (2, "algorithm", _S(BloomFilterAlgorithm)),
        (3, "hash", _S(BloomFilterHash)),
        (4, "compression", _S(BloomFilterCompression)),
    ]


class PageHeader(ThriftStruct):
    FIELDS = [
        (1, "type", _TEnum(PageType)),
        (2, "uncompressed_page_size", I32),
        (3, "compressed_page_size", I32),
        (4, "crc", I32),
        (5, "data_page_header", _S(DataPageHeader)),
        (6, "index_page_header", _S(IndexPageHeader)),
        (7, "dictionary_page_header", _S(DictionaryPageHeader)),
        (8, "data_page_header_v2", _S(DataPageHeaderV2)),
    ]


class KeyValue(ThriftStruct):
    FIELDS = [(1, "key", STRING), (2, "value", STRING)]


class SortingColumn(ThriftStruct):
    FIELDS = [
        (1, "column_idx", I32),
        (2, "descending", BOOL),
        (3, "nulls_first", BOOL),
    ]


class PageEncodingStats(ThriftStruct):
    FIELDS = [
        (1, "page_type", _TEnum(PageType)),
        (2, "encoding", _TEnum(Encoding)),
        (3, "count", I32),
    ]


class ColumnMetaData(ThriftStruct):
    FIELDS = [
        (1, "type", _TEnum(Type)),
        (2, "encodings", _TList(_TEnum(Encoding))),
        (3, "path_in_schema", _TList(STRING)),
        (4, "codec", _TEnum(CompressionCodec)),
        (5, "num_values", I64),
        (6, "total_uncompressed_size", I64),
        (7, "total_compressed_size", I64),
        (8, "key_value_metadata", _TList(_S(KeyValue))),
        (9, "data_page_offset", I64),
        (10, "index_page_offset", I64),
        (11, "dictionary_page_offset", I64),
        (12, "statistics", _S(Statistics)),
        (13, "encoding_stats", _TList(_S(PageEncodingStats))),
        (14, "bloom_filter_offset", I64),
        (15, "bloom_filter_length", I32),
    ]


class EncryptionWithFooterKey(ThriftStruct):
    FIELDS = []


class EncryptionWithColumnKey(ThriftStruct):
    FIELDS = [
        (1, "path_in_schema", _TList(STRING)),
        (2, "key_metadata", BINARY),
    ]


class ColumnCryptoMetaData(ThriftStruct):
    FIELDS = [
        (1, "ENCRYPTION_WITH_FOOTER_KEY", _S(EncryptionWithFooterKey)),
        (2, "ENCRYPTION_WITH_COLUMN_KEY", _S(EncryptionWithColumnKey)),
    ]


class ColumnChunk(ThriftStruct):
    FIELDS = [
        (1, "file_path", STRING),
        (2, "file_offset", I64),
        (3, "meta_data", _S(ColumnMetaData)),
        (4, "offset_index_offset", I64),
        (5, "offset_index_length", I32),
        (6, "column_index_offset", I64),
        (7, "column_index_length", I32),
        (8, "crypto_metadata", _S(ColumnCryptoMetaData)),
        (9, "encrypted_column_metadata", BINARY),
    ]


class RowGroup(ThriftStruct):
    FIELDS = [
        (1, "columns", _TList(_S(ColumnChunk))),
        (2, "total_byte_size", I64),
        (3, "num_rows", I64),
        (4, "sorting_columns", _TList(_S(SortingColumn))),
        (5, "file_offset", I64),
        (6, "total_compressed_size", I64),
        (7, "ordinal", I16),
    ]


class TypeDefinedOrder(ThriftStruct):
    FIELDS = []


class ColumnOrder(ThriftStruct):
    FIELDS = [(1, "TYPE_ORDER", _S(TypeDefinedOrder))]


class PageLocation(ThriftStruct):
    FIELDS = [
        (1, "offset", I64),
        (2, "compressed_page_size", I32),
        (3, "first_row_index", I64),
    ]


class OffsetIndex(ThriftStruct):
    FIELDS = [(1, "page_locations", _TList(_S(PageLocation)))]


class ColumnIndex(ThriftStruct):
    FIELDS = [
        (1, "null_pages", _TList(BOOL)),
        (2, "min_values", _TList(BINARY)),
        (3, "max_values", _TList(BINARY)),
        (4, "boundary_order", _TEnum(BoundaryOrder)),
        (5, "null_counts", _TList(I64)),
    ]


class AesGcmV1(ThriftStruct):
    FIELDS = [
        (1, "aad_prefix", BINARY),
        (2, "aad_file_unique", BINARY),
        (3, "supply_aad_prefix", BOOL),
    ]


class AesGcmCtrV1(ThriftStruct):
    FIELDS = [
        (1, "aad_prefix", BINARY),
        (2, "aad_file_unique", BINARY),
        (3, "supply_aad_prefix", BOOL),
    ]


class EncryptionAlgorithm(ThriftStruct):
    FIELDS = [
        (1, "AES_GCM_V1", _S(AesGcmV1)),
        (2, "AES_GCM_CTR_V1", _S(AesGcmCtrV1)),
    ]


class FileMetaData(ThriftStruct):
    FIELDS = [
        (1, "version", I32),
        (2, "schema", _TList(_S(SchemaElement))),
        (3, "num_rows", I64),
        (4, "row_groups", _TList(_S(RowGroup))),
        (5, "key_value_metadata", _TList(_S(KeyValue))),
        (6, "created_by", STRING),
        (7, "column_orders", _TList(_S(ColumnOrder))),
        (8, "encryption_algorithm", _S(EncryptionAlgorithm)),
        (9, "footer_signing_key_metadata", BINARY),
    ]


class FileCryptoMetaData(ThriftStruct):
    FIELDS = [
        (1, "encryption_algorithm", _S(EncryptionAlgorithm)),
        (2, "key_metadata", BINARY),
    ]
