"""Strict ``FileMetaData`` validation: treat the footer as untrusted.

Compact thrift is permissive — corrupt bytes can decode into a struct
whose *shape* is fine but whose numbers point anywhere.  The decode
path bounds-checks lazily (each chunk as it is read), which means a bad
footer aborts a scan halfway through, after work was done.  This module
front-loads the whole check: :func:`validate_metadata` cross-checks
every ``RowGroup``/``ColumnChunk`` against the file size and the schema
tree and returns structured :class:`Finding`\\ s, so callers can reject
a file at open time (``FileReader(strict_metadata=True)``, env
``TPQ_STRICT_METADATA``), report findings (``parquet-tool meta
--strict``), or salvage the valid row-group prefix
(``FileReader(salvage=True)``).

The bar is the SURVEY's "bit-exact or absent, never wrong", applied to
metadata: an offset that escapes the file, a value count that disagrees
with the row count, or a path that is not in the schema is an ``error``
finding; oddities that decode fine but smell (unknown codec enum from a
future writer, zero-byte chunk with values) are ``warn``.
"""

from __future__ import annotations

import os

from .metadata import CompressionCodec, FileMetaData, Type
from .schema import Schema

__all__ = [
    "Finding",
    "validate_metadata",
    "validate_page_index",
    "raise_on_errors",
    "strict_metadata_default",
]


def strict_metadata_default() -> bool:
    """Reader-side gate: validate the footer before trusting it?
    Default OFF (validation walks every chunk's metadata; scans that
    open thousands of known-good shards shouldn't pay it twice);
    enable with ``TPQ_STRICT_METADATA=1`` or per-reader via
    ``FileReader(strict_metadata=True)``."""
    return os.environ.get("TPQ_STRICT_METADATA", "0") != "0"


class Finding:
    """One validator observation: ``level`` is ``"error"`` (metadata is
    wrong — a strict reader must reject) or ``"warn"`` (legal but
    suspicious).  ``code`` is a stable machine-readable slug; the
    coordinate fields pinpoint the row group / column / byte offset
    when known."""

    __slots__ = ("level", "code", "message", "row_group", "column",
                 "offset")

    def __init__(self, level: str, code: str, message: str, *,
                 row_group=None, column=None, offset=None):
        self.level = level
        self.code = code
        self.message = message
        self.row_group = row_group
        self.column = column
        self.offset = offset

    @property
    def is_error(self) -> bool:
        return self.level == "error"

    def as_dict(self) -> dict:
        d = {"level": self.level, "code": self.code,
             "message": self.message}
        for k in ("row_group", "column", "offset"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    def __str__(self) -> str:
        at = ", ".join(
            f"{k}={getattr(self, k)}"
            for k in ("row_group", "column", "offset")
            if getattr(self, k) is not None)
        return (f"{self.level}[{self.code}] {self.message}"
                + (f" [{at}]" if at else ""))

    def __repr__(self) -> str:
        return f"Finding({self})"


def _err(findings, code, msg, **at):
    findings.append(Finding("error", code, msg, **at))


def _warn(findings, code, msg, **at):
    findings.append(Finding("warn", code, msg, **at))


def validate_metadata(meta: FileMetaData, file_size: int) -> list[Finding]:
    """Bounds- and cross-check a decoded footer against the file.

    Pure function of ``(meta, file_size)`` — no I/O.  Returns every
    finding (it does not stop at the first), so ``parquet-tool meta
    --strict`` can report the full damage and the salvage path can tell
    exactly which row-group prefix is clean.
    """
    findings: list[Finding] = []

    # -- required file-level fields --------------------------------------
    if meta.version is None:
        _err(findings, "missing-version",
             "FileMetaData.version is required but absent")
    if not meta.schema:
        _err(findings, "missing-schema",
             "FileMetaData.schema is required but empty")
    if meta.num_rows is None:
        _err(findings, "missing-num-rows",
             "FileMetaData.num_rows is required but absent")
    elif meta.num_rows < 0:
        _err(findings, "negative-num-rows",
             f"FileMetaData.num_rows is {meta.num_rows}")
    if meta.row_groups is None:
        _err(findings, "missing-row-groups",
             "FileMetaData.row_groups is required but absent")
    if not meta.schema or meta.row_groups is None:
        return findings  # nothing below is checkable

    # -- schema tree -----------------------------------------------------
    # Build the leaf map (dotted path -> node) via the same tree walk
    # the reader uses; a tree that does not walk (num_children that
    # overruns the element list, a leaf with no type) is one error.
    try:
        schema = Schema.from_elements(meta.schema)
        leaves = {leaf.flat_name: leaf for leaf in schema.leaves}
    except Exception as e:  # malformed tree: IndexError, ValueError, ...
        _err(findings, "schema-tree",
             f"schema element list does not form a tree: "
             f"{type(e).__name__}: {e}")
        return findings
    if not leaves:
        _err(findings, "schema-no-leaves", "schema has no leaf columns")
        return findings

    # -- row groups ------------------------------------------------------
    total_rows = 0
    seen_ranges: list[tuple[int, int, int, str]] = []
    for rgi, rg in enumerate(meta.row_groups):
        if rg.num_rows is None:
            _err(findings, "rg-missing-num-rows",
                 "row group missing required num_rows", row_group=rgi)
            continue
        if rg.num_rows < 0:
            _err(findings, "rg-negative-num-rows",
                 f"row group num_rows is {rg.num_rows}", row_group=rgi)
            continue
        total_rows += rg.num_rows
        if not rg.columns:
            _err(findings, "rg-missing-columns",
                 "row group has no column chunks", row_group=rgi)
            continue
        if len(rg.columns) != len(leaves):
            _err(findings, "rg-column-count",
                 f"row group has {len(rg.columns)} column chunks, "
                 f"schema has {len(leaves)} leaves", row_group=rgi)
        for cc in rg.columns:
            _validate_chunk(findings, cc, rgi, rg, leaves, file_size,
                            seen_ranges)

    if meta.num_rows is not None and meta.num_rows >= 0 \
            and total_rows != meta.num_rows:
        _err(findings, "num-rows-sum",
             f"FileMetaData.num_rows {meta.num_rows} != sum of row-group "
             f"rows {total_rows}")

    # -- chunk byte ranges must not overlap ------------------------------
    # sweep with a RUNNING max end, not adjacent-pair compares: a chunk
    # whose lying size swallows several successors must conflict with
    # every one of them, not just its immediate neighbor.  The finding
    # anchors at the EARLIER row group of the pair — either member may
    # be the liar, so a prefix trim must stop before both.
    seen_ranges.sort()
    cur = None  # (start, end, rgi, column) with the furthest end so far
    for rng in seen_ranges:
        s1, e1, rg1, c1 = rng
        if cur is not None and s1 < cur[1]:
            s0, e0, rg0, c0 = cur
            _err(findings, "chunk-overlap",
                 f"column chunk [{s1}, {e1}) (rg {rg1}, {c1}) overlaps "
                 f"[{s0}, {e0}) (rg {rg0}, {c0})",
                 row_group=min(rg0, rg1), column=c1, offset=s1)
        if cur is None or e1 > cur[1]:
            cur = rng
    return findings


def _validate_chunk(findings, cc, rgi, rg, leaves, file_size,
                    seen_ranges) -> None:
    cm = cc.meta_data
    if cm is None:
        _err(findings, "chunk-missing-metadata",
             "column chunk missing meta_data", row_group=rgi)
        return
    path = ".".join(cm.path_in_schema) if cm.path_in_schema else None
    at = {"row_group": rgi, "column": path}

    # required fields
    if not cm.path_in_schema:
        _err(findings, "chunk-missing-path",
             "column metadata missing path_in_schema", row_group=rgi)
        return
    missing = [name for name in ("type", "codec", "num_values",
                                 "data_page_offset",
                                 "total_compressed_size")
               if getattr(cm, name) is None]
    if missing:
        _err(findings, "chunk-missing-fields",
             f"column metadata missing required {', '.join(missing)}",
             **at)
        return

    # schema cross-checks
    leaf = leaves.get(path)
    if leaf is None:
        _err(findings, "chunk-unknown-column",
             f"path_in_schema {path!r} is not a schema leaf", **at)
        return
    try:
        ptype = Type(cm.type)
    except ValueError:
        _err(findings, "chunk-bad-type",
             f"unknown physical type {cm.type}", **at)
        return
    if leaf.type is not None and ptype != leaf.type:
        _err(findings, "chunk-type-mismatch",
             f"chunk type {ptype.name} disagrees with schema leaf type "
             f"{Type(leaf.type).name}", **at)
    if not isinstance(cm.codec, CompressionCodec):
        _warn(findings, "chunk-unknown-codec",
              f"unknown compression codec enum {cm.codec}", **at)

    # counts
    if cm.num_values < 0:
        _err(findings, "chunk-negative-values",
             f"num_values is {cm.num_values}", **at)
        return
    if cm.total_compressed_size < 0:
        _err(findings, "chunk-negative-size",
             f"total_compressed_size is {cm.total_compressed_size}", **at)
        return
    if cm.total_uncompressed_size is not None \
            and cm.total_uncompressed_size < 0:
        _err(findings, "chunk-negative-size",
             f"total_uncompressed_size is {cm.total_uncompressed_size}",
             **at)
    if rg.num_rows is not None:
        # cross-check values against rows: a non-repeated leaf stores
        # exactly one (possibly null) value slot per record
        if leaf.max_rep_level == 0 and cm.num_values != rg.num_rows:
            _err(findings, "chunk-values-vs-rows",
                 f"num_values {cm.num_values} != row group num_rows "
                 f"{rg.num_rows} for non-repeated column", **at)
        if leaf.max_rep_level > 0 and rg.num_rows > 0 \
                and cm.num_values == 0:
            _warn(findings, "chunk-repeated-empty",
                  "repeated column has 0 values in a non-empty row group",
                  **at)
    if cm.num_values > 0 and cm.total_compressed_size == 0:
        _err(findings, "chunk-zero-bytes",
             f"{cm.num_values} values in 0 compressed bytes", **at)
    if cm.num_values == 0:
        # empty chunk: the page loop never dereferences its offsets
        # (pyarrow writes data_page_offset=0 with a dictionary-only
        # chunk for empty row groups), so there is nothing to bound
        return

    # byte ranges against the file
    start = cm.data_page_offset
    if cm.dictionary_page_offset is not None:
        if cm.dictionary_page_offset < 0:
            _err(findings, "chunk-offset-oob",
                 f"dictionary_page_offset {cm.dictionary_page_offset} "
                 "is negative", offset=cm.dictionary_page_offset, **at)
            return
        if cm.dictionary_page_offset > cm.data_page_offset:
            _err(findings, "chunk-dict-after-data",
                 f"dictionary_page_offset {cm.dictionary_page_offset} > "
                 f"data_page_offset {cm.data_page_offset}", **at)
        start = min(start, cm.dictionary_page_offset)
    if start < 4:
        _err(findings, "chunk-offset-oob",
             f"chunk starts at {start}, before the 4-byte magic",
             offset=start, **at)
        return
    end = start + cm.total_compressed_size
    if end > file_size:
        _err(findings, "chunk-offset-oob",
             f"chunk byte range [{start}, {end}) overruns the file "
             f"({file_size} bytes)", offset=start, **at)
        return
    seen_ranges.append((start, end, rgi, path))

    # statistics self-consistency: decoded min must not exceed max
    # under the column's own order, and null_count must fit the chunk
    # (predicate pushdown trusts these bounds to prune — a lying
    # summary must be a structured finding, not a wrong result)
    st = cm.statistics
    if st is not None:
        if st.null_count is not None and (
                st.null_count < 0 or st.null_count > cm.num_values):
            _err(findings, "stats-null-count",
                 f"statistics null_count {st.null_count} outside "
                 f"[0, {cm.num_values}]", **at)
        if st.min_value is not None and st.max_value is not None:
            try:
                from ..io.values import handler_for
                h = handler_for(leaf.element)
                if not h.stats_bytewise_comparable():
                    mn = mx = None  # order not bytewise: uncheckable
                else:
                    mn = h.decode_stat_logical(st.min_value)
                    mx = h.decode_stat_logical(st.max_value)
            except Exception:
                mn = mx = None  # undecodable bounds: bounded below
            if mn is not None and mx is not None:
                try:
                    bad = mn > mx
                except TypeError:
                    bad = False
                if bad:
                    _err(findings, "stats-min-gt-max",
                         f"statistics min {mn!r} > max {mx!r}", **at)

    # page-index / bloom pointers must land inside the file.  WARN,
    # not error: an unreadable index only costs pruning efficiency
    # (reads degrade to "no pruning"), and a truncated-but-salvageable
    # file has every row group's index pointer dangling — error-level
    # findings here would wreck the salvage valid-prefix trim for
    # row groups whose DATA is intact.
    for off_name, len_name in (
            ("column_index_offset", "column_index_length"),
            ("offset_index_offset", "offset_index_length")):
        off = getattr(cc, off_name)
        ln = getattr(cc, len_name)
        if off is None and ln is None:
            continue
        if off is None or ln is None or off < 4 or ln <= 0 \
                or off + ln > file_size:
            _warn(findings, "pageindex-oob",
                  f"{off_name}/{len_name} [{off}, "
                  f"{off if off is None or ln is None else off + ln}) "
                  f"outside the file ({file_size} bytes)", **at)
    boff, blen = cm.bloom_filter_offset, cm.bloom_filter_length
    if boff is not None and (
            boff < 4 or boff >= file_size
            or (blen is not None
                and (blen <= 0 or boff + blen > file_size))):
        _warn(findings, "bloom-oob",
              f"bloom_filter_offset/length [{boff}, "
              f"{boff if blen is None else boff + blen}) outside the "
              f"file ({file_size} bytes)", **at)


def validate_page_index(ci, oi, cm, num_rows: int, file_size: int, *,
                        element=None, row_group=None) -> list[Finding]:
    """Cross-check one column's decoded ``ColumnIndex``/``OffsetIndex``
    pair against its chunk metadata — the read-side guard that turns a
    lying page index into structured findings so pruning degrades to
    "decode everything" instead of skipping rows it shouldn't.

    Checks: the two structs agree on the page count, per-page bounds
    decode with min ≤ max (column order), page locations stay inside
    the chunk's byte range, and ``first_row_index`` is 0-based, strictly
    increasing and within the row group.  Pure function — the caller
    already read and thrift-decoded the structs."""
    findings: list[Finding] = []
    path = ".".join(cm.path_in_schema) if cm.path_in_schema else None
    at = {"row_group": row_group, "column": path}

    locs = oi.page_locations if oi is not None else None
    if not locs:
        _err(findings, "pageindex-empty",
             "OffsetIndex has no page locations", **at)
        return findings
    n = len(locs)
    for name, lst in (("null_pages", ci.null_pages),
                      ("min_values", ci.min_values),
                      ("max_values", ci.max_values)):
        if lst is None or len(lst) != n:
            _err(findings, "pageindex-count",
                 f"ColumnIndex.{name} has "
                 f"{0 if lst is None else len(lst)} entries, OffsetIndex "
                 f"has {n} pages", **at)
            return findings
    if ci.null_counts is not None and len(ci.null_counts) != n:
        _err(findings, "pageindex-count",
             f"ColumnIndex.null_counts has {len(ci.null_counts)} "
             f"entries, OffsetIndex has {n} pages", **at)

    start = cm.data_page_offset
    if cm.dictionary_page_offset is not None:
        start = min(start, cm.dictionary_page_offset)
    chunk_end = start + cm.total_compressed_size
    prev_row = -1
    for i, loc in enumerate(locs):
        if loc.offset is None or loc.compressed_page_size is None \
                or loc.first_row_index is None:
            _err(findings, "pageindex-missing-fields",
                 f"page location {i} missing required fields", **at)
            return findings
        if loc.offset < start or loc.compressed_page_size <= 0 \
                or loc.offset + loc.compressed_page_size > chunk_end \
                or loc.offset + loc.compressed_page_size > file_size:
            _err(findings, "pageindex-loc-oob",
                 f"page {i} byte range [{loc.offset}, "
                 f"{loc.offset + loc.compressed_page_size}) escapes the "
                 f"chunk [{start}, {chunk_end})",
                 offset=loc.offset, **at)
        fr = loc.first_row_index
        if fr <= prev_row or fr >= max(num_rows, 1) \
                or (i == 0 and fr != 0):
            _err(findings, "pageindex-rows",
                 f"page {i} first_row_index {fr} is not strictly "
                 f"increasing from 0 within {num_rows} rows", **at)
            return findings
        prev_row = fr

    handler = None
    if element is not None:
        try:
            from ..io.values import handler_for

            handler = handler_for(element)
            if not handler.stats_bytewise_comparable():
                handler = None  # order not bytewise: bounds uncheckable
        except Exception:
            handler = None
    for i in range(n):
        if ci.null_pages[i]:
            continue
        mn_b, mx_b = ci.min_values[i], ci.max_values[i]
        if mn_b is None or mx_b is None or mn_b == b"" or mx_b == b"":
            _err(findings, "pageindex-bounds",
                 f"non-null page {i} carries empty min/max", **at)
            continue
        if handler is None:
            continue
        try:
            mn = handler.decode_stat_logical(mn_b)
            mx = handler.decode_stat_logical(mx_b)
            bad = mn is not None and mx is not None and mn > mx
        except Exception:
            bad = True  # bounds that don't decode cannot be trusted
        if bad:
            _err(findings, "pageindex-min-gt-max",
                 f"page {i} min > max under the column's order", **at)
    return findings


def raise_on_errors(findings: list[Finding], *, file=None) -> None:
    """Raise :class:`~tpuparquet.errors.CorruptFooterError` summarizing
    the error-level findings (no-op when there are none)."""
    errors = [f for f in findings if f.is_error]
    if not errors:
        return
    from ..errors import CorruptFooterError

    head = errors[0]
    more = f" (+{len(errors) - 1} more)" if len(errors) > 1 else ""
    raise CorruptFooterError(
        f"metadata failed strict validation: {head}{more}",
        file=file, offset=head.offset, findings=findings,
        row_group=head.row_group, column=head.column)
