"""Textual schema DSL: parser, printer, validator.

Capability parity with the reference's ``parquetschema`` package
(``/root/reference/parquetschema/schema_parser.go`` and
``schema_def.go:31-94`` define the grammar):

    message ::= 'message' <identifier> '{' <column-definition>* '}'
    column-definition ::= ('required'|'optional'|'repeated')
                          ( 'group' <name> [ '(' <converted-type> ')' ] '{' ... '}'
                          | <type> <name> [ '(' <annotation> ')' ] [ '=' <field-id> ] ';' )

Annotations on fields are either new-style logical types (STRING, DATE,
TIMESTAMP(unit, utc), TIME(unit, utc), INT(width, signed),
DECIMAL(precision, scale), UUID, ENUM, JSON, BSON) — which also set the
backward-compatible converted type where one exists — or bare converted-type
names (UTF8, MAP, LIST, TIME_MILLIS, INT_8, ...).

Validation implements the LIST/MAP shape rules (incl. the four
backward-compatibility LIST forms accepted by non-strict mode) and the
physical-type checks for every logical/converted annotation, mirroring
``schema_parser.go:715-1044``.
"""

from __future__ import annotations

import math
import re

from .metadata import (
    BsonType,
    ConvertedType,
    DateType,
    DecimalType,
    EnumType,
    FieldRepetitionType,
    IntType,
    JsonType,
    ListType,
    LogicalType,
    MapType,
    MicroSeconds,
    MilliSeconds,
    NanoSeconds,
    NullType,
    SchemaElement,
    StringType,
    TimestampType,
    TimeType,
    TimeUnit,
    Type,
    UUIDType,
)

__all__ = [
    "ColumnDefinition",
    "SchemaDefinition",
    "SchemaParseError",
    "SchemaValidationError",
    "parse_schema_definition",
]


class SchemaParseError(ValueError):
    def __init__(self, msg, line=None):
        super().__init__(f"line {line}: {msg}" if line else msg)
        self.line = line


class SchemaValidationError(ValueError):
    pass


_TYPE_NAMES = {
    "binary": Type.BYTE_ARRAY,
    "float": Type.FLOAT,
    "double": Type.DOUBLE,
    "boolean": Type.BOOLEAN,
    "int32": Type.INT32,
    "int64": Type.INT64,
    "int96": Type.INT96,
    "fixed_len_byte_array": Type.FIXED_LEN_BYTE_ARRAY,
}
_TYPE_PRINT = {v: k for k, v in _TYPE_NAMES.items()}


# --------------------------------------------------------------------------
# Definition model
# --------------------------------------------------------------------------

class ColumnDefinition:
    """One node of a schema definition: a SchemaElement + children."""

    __slots__ = ("element", "children")

    def __init__(self, element: SchemaElement, children=None):
        self.element = element
        self.children = children or []

    @property
    def name(self) -> str:
        return self.element.name

    def __eq__(self, other):
        if not isinstance(other, ColumnDefinition):
            return NotImplemented
        return self.element == other.element and self.children == other.children

    def __repr__(self):
        return f"ColumnDefinition({self.element!r}, children={len(self.children)})"


class SchemaDefinition:
    """A parsed schema: wraps the root ColumnDefinition.

    API parity with the reference's ``SchemaDefinition`` (``schema_def.go``):
    ``__str__`` prints the DSL back out (parse->print->parse is a fixpoint),
    ``sub_schema`` returns a direct child as its own definition, ``validate``
    and ``validate_strict`` check structural rules.
    """

    __slots__ = ("root",)

    def __init__(self, root: ColumnDefinition):
        self.root = root

    # -- construction ------------------------------------------------------

    @classmethod
    def from_schema_elements(cls, elems: list[SchemaElement]) -> "SchemaDefinition":
        """Build from the flat depth-first SchemaElement list of a footer."""
        if not elems:
            raise SchemaValidationError("empty schema element list")
        pos = 0

        def build() -> ColumnDefinition:
            nonlocal pos
            if pos >= len(elems):
                raise SchemaValidationError("schema element list truncated")
            se = elems[pos]
            pos += 1
            col = ColumnDefinition(se)
            n = se.num_children or 0
            for _ in range(n):
                col.children.append(build())
            return col

        root = build()
        if pos != len(elems):
            raise SchemaValidationError(
                f"schema element list has {len(elems) - pos} trailing elements"
            )
        return cls(root)

    def to_schema_elements(self) -> list[SchemaElement]:
        """Flatten back to the depth-first list stored in the footer."""
        out: list[SchemaElement] = []

        def walk(col: ColumnDefinition):
            se = col.element
            se.num_children = len(col.children) if col.children else None
            out.append(se)
            for c in col.children:
                walk(c)

        walk(self.root)
        return out

    # -- navigation --------------------------------------------------------

    def sub_schema(self, name: str) -> "SchemaDefinition | None":
        for c in self.root.children:
            if c.name == name:
                return SchemaDefinition(c)
        return None

    def schema_element(self) -> SchemaElement | None:
        return self.root.element if self.root else None

    # -- validation --------------------------------------------------------

    def validate(self) -> None:
        _validate_column(self.root, is_root=True, strict=False)

    def validate_strict(self) -> None:
        _validate_column(self.root, is_root=True, strict=True)

    # -- printing ----------------------------------------------------------

    def __str__(self) -> str:
        if self.root is None:
            return "message empty {\n}\n"
        out = [f"message {self.root.name} {{\n"]
        _print_cols(out, self.root.children, 2)
        out.append("}\n")
        return "".join(out)

    def __eq__(self, other):
        if not isinstance(other, SchemaDefinition):
            return NotImplemented
        return self.root == other.root


def _print_cols(out: list, cols: list, indent: int) -> None:
    for col in cols:
        se = col.element
        pad = " " * indent
        rep = FieldRepetitionType(se.repetition_type).name.lower()
        if se.type is None:
            out.append(f"{pad}{rep} group {se.name}")
            if se.converted_type is not None:
                out.append(f" ({ConvertedType(se.converted_type).name})")
            out.append(" {\n")
            _print_cols(out, col.children, indent + 2)
            out.append(f"{pad}}}\n")
        else:
            tname = _TYPE_PRINT[Type(se.type)]
            if se.type == Type.FIXED_LEN_BYTE_ARRAY:
                tname = f"fixed_len_byte_array({se.type_length})"
            out.append(f"{pad}{rep} {tname} {se.name}")
            if se.logicalType is not None:
                out.append(f" ({_print_logical(se.logicalType)})")
            elif se.converted_type is not None:
                out.append(f" ({ConvertedType(se.converted_type).name})")
            if se.field_id is not None:
                out.append(f" = {se.field_id}")
            out.append(";\n")


def _unit_name(unit: TimeUnit) -> str:
    if unit.NANOS is not None:
        return "NANOS"
    if unit.MICROS is not None:
        return "MICROS"
    return "MILLIS"


def _print_logical(lt: LogicalType) -> str:
    name, val = lt.set_member()
    if name == "STRING":
        return "STRING"
    if name == "DATE":
        return "DATE"
    if name == "TIMESTAMP":
        utc = "true" if val.isAdjustedToUTC else "false"
        return f"TIMESTAMP({_unit_name(val.unit)}, {utc})"
    if name == "TIME":
        utc = "true" if val.isAdjustedToUTC else "false"
        return f"TIME({_unit_name(val.unit)}, {utc})"
    if name == "UUID":
        return "UUID"
    if name == "ENUM":
        return "ENUM"
    if name == "JSON":
        return "JSON"
    if name == "BSON":
        return "BSON"
    if name == "DECIMAL":
        return f"DECIMAL({val.precision}, {val.scale})"
    if name == "INTEGER":
        signed = "true" if val.isSigned else "false"
        return f"INT({val.bitWidth}, {signed})"
    return name or "UNKNOWN"


# --------------------------------------------------------------------------
# Tokenizer + recursive-descent parser
# --------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<number>\d+)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.\-]*)
  | (?P<punct>[(){}=;,])
    """,
    re.VERBOSE,
)


class _Tok:
    __slots__ = ("kind", "val", "line")

    def __init__(self, kind, val, line):
        self.kind = kind  # 'number' | 'ident' | the punct char | 'eof'
        self.val = val
        self.line = line


def _tokenize(text: str) -> list[_Tok]:
    toks = []
    pos = 0
    line = 1
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise SchemaParseError(f"unexpected character {text[pos]!r}", line)
        if m.lastgroup == "ws":
            line += m.group().count("\n")
        elif m.lastgroup == "punct":
            toks.append(_Tok(m.group(), m.group(), line))
        else:
            toks.append(_Tok(m.lastgroup, m.group(), line))
        pos = m.end()
    toks.append(_Tok("eof", "", line))
    return toks


class _Parser:
    def __init__(self, text: str):
        self.toks = _tokenize(text)
        self.i = 0

    @property
    def tok(self) -> _Tok:
        return self.toks[self.i]

    def advance(self) -> _Tok:
        t = self.tok
        if t.kind != "eof":
            self.i += 1
        return t

    def error(self, msg: str):
        raise SchemaParseError(msg, self.tok.line)

    def expect(self, kind: str, what: str = "") -> _Tok:
        if self.tok.kind != kind:
            self.error(f"expected {what or kind}, got {self.tok.val!r}")
        return self.advance()

    def expect_keyword(self, word: str) -> None:
        if not (self.tok.kind == "ident" and self.tok.val == word):
            self.error(f"expected {word!r}, got {self.tok.val!r}")
        self.advance()

    # grammar --------------------------------------------------------------

    def parse_message(self) -> SchemaDefinition:
        self.expect_keyword("message")
        name = self.expect("ident", "message name").val
        root = ColumnDefinition(SchemaElement(name=name))
        self.expect("{")
        while self.tok.kind != "}":
            root.children.append(self.parse_column())
        self.expect("}")
        if self.tok.kind != "eof":
            self.error(f"trailing content after schema: {self.tok.val!r}")
        _fix_num_children(root)
        return SchemaDefinition(root)

    def parse_column(self) -> ColumnDefinition:
        rep_tok = self.expect("ident", "repetition type")
        try:
            rep = FieldRepetitionType[rep_tok.val.upper()]
        except KeyError:
            raise SchemaParseError(
                f"invalid field repetition type {rep_tok.val!r}", rep_tok.line
            )
        if self.tok.kind == "ident" and self.tok.val == "group":
            self.advance()
            name = self.expect("ident", "group name").val
            se = SchemaElement(name=name, repetition_type=rep)
            col = ColumnDefinition(se)
            if self.tok.kind == "(":
                self.advance()
                ct_tok = self.expect("ident", "converted type")
                try:
                    se.converted_type = ConvertedType[ct_tok.val]
                except KeyError:
                    raise SchemaParseError(
                        f"invalid converted type {ct_tok.val!r}", ct_tok.line
                    )
                self.expect(")")
            self.expect("{")
            while self.tok.kind != "}":
                col.children.append(self.parse_column())
            self.expect("}")
            return col

        # primitive field
        type_tok = self.expect("ident", "type")
        ptype = _TYPE_NAMES.get(type_tok.val)
        if ptype is None:
            raise SchemaParseError(f"invalid type {type_tok.val!r}", type_tok.line)
        se = SchemaElement(type=ptype, repetition_type=rep)
        if ptype == Type.FIXED_LEN_BYTE_ARRAY:
            self.expect("(")
            se.type_length = int(self.expect("number", "byte length").val)
            self.expect(")")
        se.name = self.expect("ident", "field name").val
        if self.tok.kind == "(":
            self.parse_annotation(se)
        if self.tok.kind == "=":
            self.advance()
            se.field_id = int(self.expect("number", "field id").val)
        self.expect(";")
        return ColumnDefinition(se)

    def parse_annotation(self, se: SchemaElement) -> None:
        """Parse ``( ... )`` after a field: logical or converted type.

        New-style logical types also populate the matching converted type
        (format v1 forward compatibility), exactly as the reference does
        (``schema_parser.go:483-698``)."""
        self.expect("(")
        name_tok = self.expect("ident", "annotation")
        name = name_tok.val.upper()
        lt = LogicalType()
        ct = None
        if name == "STRING":
            lt.STRING = StringType()
            ct = ConvertedType.UTF8
        elif name == "DATE":
            lt.DATE = DateType()
            ct = ConvertedType.DATE
        elif name == "UUID":
            lt.UUID = UUIDType()
        elif name == "ENUM":
            lt.ENUM = EnumType()
            ct = ConvertedType.ENUM
        elif name == "JSON":
            lt.JSON = JsonType()
            ct = ConvertedType.JSON
        elif name == "BSON":
            lt.BSON = BsonType()
            ct = ConvertedType.BSON
        elif name == "TIMESTAMP":
            unit, utc = self.parse_unit_bool("TIMESTAMP")
            lt.TIMESTAMP = TimestampType(isAdjustedToUTC=utc, unit=unit)
            if unit.MILLIS is not None:
                ct = ConvertedType.TIMESTAMP_MILLIS
            elif unit.MICROS is not None:
                ct = ConvertedType.TIMESTAMP_MICROS
        elif name == "TIME":
            unit, utc = self.parse_unit_bool("TIME")
            lt.TIME = TimeType(isAdjustedToUTC=utc, unit=unit)
            if unit.MILLIS is not None:
                ct = ConvertedType.TIME_MILLIS
            elif unit.MICROS is not None:
                ct = ConvertedType.TIME_MICROS
        elif name == "INT":
            self.expect("(")
            width = int(self.expect("number", "bit width").val)
            if width not in (8, 16, 32, 64):
                self.error(f"INT: unsupported bitwidth {width}")
            self.expect(",")
            signed = self.parse_bool("INT")
            self.expect(")")
            lt.INTEGER = IntType(bitWidth=width, isSigned=signed)
            ct = ConvertedType[("INT_" if signed else "UINT_") + str(width)]
        elif name == "DECIMAL":
            self.expect("(")
            precision = int(self.expect("number", "precision").val)
            self.expect(",")
            scale = int(self.expect("number", "scale").val)
            self.expect(")")
            lt.DECIMAL = DecimalType(scale=scale, precision=precision)
            se.scale = scale
            se.precision = precision
            ct = ConvertedType.DECIMAL
        else:
            # Bare converted-type annotation (UTF8, LIST, TIME_MILLIS, ...)
            try:
                se.converted_type = ConvertedType[name]
            except KeyError:
                self.error(
                    f"unsupported logical type or converted type {name_tok.val!r}"
                )
            self.expect(")")
            return
        se.logicalType = lt
        if ct is not None:
            se.converted_type = ct
        self.expect(")")

    def parse_unit_bool(self, what: str) -> tuple[TimeUnit, bool]:
        self.expect("(")
        unit_tok = self.expect("ident", "time unit")
        unit = TimeUnit()
        if unit_tok.val == "MILLIS":
            unit.MILLIS = MilliSeconds()
        elif unit_tok.val == "MICROS":
            unit.MICROS = MicroSeconds()
        elif unit_tok.val == "NANOS":
            unit.NANOS = NanoSeconds()
        else:
            raise SchemaParseError(
                f"unknown unit annotation {unit_tok.val!r} for {what}",
                unit_tok.line,
            )
        self.expect(",")
        utc = self.parse_bool(what)
        self.expect(")")
        return unit, utc

    def parse_bool(self, what: str) -> bool:
        tok = self.expect("ident", "boolean")
        if tok.val == "true":
            return True
        if tok.val == "false":
            return False
        raise SchemaParseError(
            f"invalid boolean {tok.val!r} for {what}", tok.line
        )


def _fix_num_children(col: ColumnDefinition) -> None:
    if col.children:
        col.element.num_children = len(col.children)
    for c in col.children:
        _fix_num_children(c)


def parse_schema_definition(text: str) -> SchemaDefinition:
    """Parse the textual DSL; raises SchemaParseError with a line number."""
    sd = _Parser(text).parse_message()
    sd.validate()
    return sd


# --------------------------------------------------------------------------
# Validation (shape rules for LIST/MAP + type checks per annotation)
# --------------------------------------------------------------------------

def _lt_member(se: SchemaElement) -> str | None:
    if se.logicalType is None:
        return None
    return se.logicalType.set_member()[0]


def _validate_column(col: ColumnDefinition, is_root: bool, strict: bool) -> None:
    se = col.element
    if se is None:
        raise SchemaValidationError("column has no schema element")
    if not se.name:
        raise SchemaValidationError("column has no name")
    if not is_root and not col.children and se.type is None:
        raise SchemaValidationError(
            f"field {se.name} has neither children nor a type"
        )
    if se.type is not None and col.children:
        raise SchemaValidationError(
            f"field {se.name} has a type but also children"
        )

    lt = _lt_member(se)
    ct = se.converted_type
    ptype = se.type

    def type_check(cond: bool, msg: str):
        if not cond:
            raise SchemaValidationError(f"field {se.name} {msg}")

    if lt == "LIST" or ct == ConvertedType.LIST:
        _validate_list(col, strict)
    elif lt == "MAP" or ct in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE):
        _validate_map(col, strict)
    elif lt == "DATE" or ct == ConvertedType.DATE:
        type_check(ptype == Type.INT32, "is annotated as DATE but is not an int32")
    elif lt == "TIMESTAMP":
        type_check(
            ptype in (Type.INT64, Type.INT96),
            "is annotated as TIMESTAMP but is not an int64/int96",
        )
    elif lt == "TIME":
        t = se.logicalType.TIME
        if t.unit.MILLIS is not None:
            type_check(ptype == Type.INT32,
                       "is annotated as TIME(MILLIS) but is not an int32")
        else:
            type_check(ptype == Type.INT64,
                       "is annotated as TIME(MICROS|NANOS) but is not an int64")
    elif lt == "UUID":
        type_check(
            ptype == Type.FIXED_LEN_BYTE_ARRAY and se.type_length == 16,
            "is annotated as UUID but is not a fixed_len_byte_array(16)",
        )
    elif lt == "ENUM":
        type_check(ptype == Type.BYTE_ARRAY,
                   "is annotated as ENUM but is not a binary")
    elif lt == "JSON":
        type_check(ptype == Type.BYTE_ARRAY,
                   "is annotated as JSON but is not a binary")
    elif lt == "BSON":
        type_check(ptype == Type.BYTE_ARRAY,
                   "is annotated as BSON but is not a binary")
    elif lt == "DECIMAL":
        _validate_decimal(col)
    elif lt == "INTEGER":
        it = se.logicalType.INTEGER
        want = Type.INT64 if it.bitWidth == 64 else Type.INT32
        type_check(
            ptype == want,
            f"is annotated as INT({it.bitWidth}, ...) but element type is "
            f"{ptype}",
        )
    elif ct == ConvertedType.UTF8:
        type_check(ptype == Type.BYTE_ARRAY,
                   "is annotated as UTF8 but is not binary")
    elif ct == ConvertedType.TIME_MILLIS:
        type_check(ptype == Type.INT32,
                   "is annotated as TIME_MILLIS but is not int32")
    elif ct == ConvertedType.TIME_MICROS:
        type_check(ptype == Type.INT64,
                   "is annotated as TIME_MICROS but is not int64")
    elif ct == ConvertedType.TIMESTAMP_MILLIS:
        type_check(ptype == Type.INT64,
                   "is annotated as TIMESTAMP_MILLIS but is not int64")
    elif ct == ConvertedType.TIMESTAMP_MICROS:
        type_check(ptype == Type.INT64,
                   "is annotated as TIMESTAMP_MICROS but is not int64")
    elif ct in (
        ConvertedType.UINT_8, ConvertedType.UINT_16, ConvertedType.UINT_32,
        ConvertedType.INT_8, ConvertedType.INT_16, ConvertedType.INT_32,
    ):
        type_check(
            ptype == Type.INT32,
            f"is annotated as {ConvertedType(ct).name} but is not int32",
        )
    elif ct in (ConvertedType.UINT_64, ConvertedType.INT_64):
        type_check(
            ptype == Type.INT64,
            f"is annotated as {ConvertedType(ct).name} but is not int64",
        )
    elif ct == ConvertedType.INTERVAL:
        type_check(
            ptype == Type.FIXED_LEN_BYTE_ARRAY and se.type_length == 12,
            "is annotated as INTERVAL but is not a fixed_len_byte_array(12)",
        )
    else:
        for c in col.children:
            _validate_column(c, is_root=False, strict=strict)


def _validate_list(col: ColumnDefinition, strict: bool) -> None:
    se = col.element
    if se.type is not None:
        raise SchemaValidationError(
            f"field {se.name} is not a group but annotated as LIST"
        )
    rep = se.repetition_type
    if rep not in (FieldRepetitionType.OPTIONAL, FieldRepetitionType.REQUIRED):
        raise SchemaValidationError(
            f"field {se.name} is a LIST but has repetition type {rep}"
        )
    if len(col.children) != 1:
        raise SchemaValidationError(
            f"field {se.name} is a LIST but has {len(col.children)} children"
        )
    child = col.children[0]
    if child.name != "list":
        if strict:
            raise SchemaValidationError(
                f'field {se.name} is a LIST but its child is not named "list"'
            )
        # Backward-compatibility forms (LogicalTypes.md rules 1-4):
        #  1. repeated primitive field     -> field type is the element type
        #  2. repeated group, >1 children  -> the group is the element type
        #  3. repeated group named "array"/"<name>_tuple"/"bag", 1 child
        #  4. otherwise, repeated group with 1 child is the element itself
        if child.element.type is None and not child.children:
            raise SchemaValidationError(
                f"field {se.name} is a LIST but the repeated group inside it "
                'is not called "list" and contains no fields'
            )
    else:
        if (child.element.type is not None
                or child.element.repetition_type != FieldRepetitionType.REPEATED):
            raise SchemaValidationError(
                f"field {se.name} is a LIST but its child is not a repeated group"
            )
        if len(child.children) != 1:
            raise SchemaValidationError(
                f"field {se.name}.list has {len(child.children)} children"
            )
        elem = child.children[0]
        if elem.name != "element":
            raise SchemaValidationError(
                f'{se.name}.list has a child but it\'s called '
                f'{elem.name!r}, not "element"'
            )
        erep = elem.element.repetition_type
        if erep not in (FieldRepetitionType.OPTIONAL, FieldRepetitionType.REQUIRED):
            raise SchemaValidationError(
                f"{se.name}.list.element has disallowed repetition type {erep}"
            )
    # Validate the repeated child itself (covers backward-compat form 1,
    # where the element is a repeated primitive and has no children of its
    # own) — annotations on it must still type-check.
    _validate_column(child, is_root=False, strict=strict)


def _validate_map(col: ColumnDefinition, strict: bool) -> None:
    se = col.element
    if strict and se.converted_type == ConvertedType.MAP_KEY_VALUE:
        raise SchemaValidationError(
            f"field {se.name} is incorrectly annotated as MAP_KEY_VALUE"
        )
    if se.type is not None:
        raise SchemaValidationError(
            f"field {se.name} is not a group but annotated as MAP"
        )
    if len(col.children) != 1:
        raise SchemaValidationError(
            f"field {se.name} is a MAP but has {len(col.children)} children"
        )
    kv = col.children[0]
    if (kv.element.type is not None
            or kv.element.repetition_type != FieldRepetitionType.REPEATED):
        raise SchemaValidationError(
            f"field {se.name} is a MAP but its child is not a repeated group"
        )
    if strict:
        if kv.name != "key_value":
            raise SchemaValidationError(
                f'field {se.name} is a MAP but its child is not named "key_value"'
            )
        found_key = found_value = False
        for c in kv.children:
            if c.name == "key":
                if c.element.repetition_type != FieldRepetitionType.REQUIRED:
                    raise SchemaValidationError(
                        f'field {se.name}.key_value.key is not of repetition '
                        'type "required"'
                    )
                found_key = True
            elif c.name == "value":
                found_value = True
            else:
                raise SchemaValidationError(
                    f"field {se.name} is a MAP so {se.name}.key_value.{c.name} "
                    "is not allowed"
                )
        if not found_key:
            raise SchemaValidationError(
                f"field {se.name} is missing {se.name}.key_value.key"
            )
        if not found_value:
            raise SchemaValidationError(
                f"field {se.name} is missing {se.name}.key_value.value"
            )
    else:
        if len(kv.children) != 2:
            raise SchemaValidationError(
                f"field {se.name} is a MAP but {se.name}.{kv.name} contains "
                f"{len(kv.children)} children (expected 2)"
            )
    for c in kv.children:
        _validate_column(c, is_root=False, strict=strict)


def _validate_decimal(col: ColumnDefinition) -> None:
    se = col.element
    dec = se.logicalType.DECIMAL
    ptype = se.type
    if ptype == Type.INT32:
        lo, hi = 1, 9
    elif ptype == Type.INT64:
        lo, hi = 1, 18
    elif ptype == Type.FIXED_LEN_BYTE_ARRAY:
        # Spec: precision <= floor(log10(2^(8n-1) - 1)); for n=16 that is 38
        # (decimal128, as pyarrow/Spark emit).  floor(log10(x)) == digits-1.
        n = se.type_length or 0
        lo, hi = 1, len(str(2 ** (8 * n - 1) - 1)) - 1 if n else 0
    elif ptype == Type.BYTE_ARRAY:
        lo, hi = 1, None
    else:
        raise SchemaValidationError(
            f"field {se.name} is annotated as DECIMAL but type {ptype} is "
            "unsupported"
        )
    if dec.precision < lo or (hi is not None and dec.precision > hi):
        raise SchemaValidationError(
            f"field {se.name} is annotated as DECIMAL but precision "
            f"{dec.precision} is out of bounds"
        )
