"""Torn-file salvage: rebuild metadata for files with no usable footer.

A Parquet writer that dies mid-write leaves the readable data pages on
disk but no footer — and the footer is the only map.  This module
rebuilds the map from the pages themselves (parquet-mr's footer
recovery, cuDF's untrusted-metadata stance): forward-scan from the head
magic decoding ``PageHeader`` structs back-to-back, reject garbage with
the same sanity checks + page-CRC verification the decode path uses,
group the surviving pages into column chunks and row groups, and emit a
synthesized ``FileMetaData`` covering exactly the complete row-group
prefix.  Decoded output is bit-exact or absent — never wrong: a page
that fails any check ends the scan, and a row group missing any chunk
is dropped.

Page headers carry sizes and encodings but NOT the schema or codec, so
recovery needs one of:

* a **salvage hint** — ``FileWriter`` (``salvage_hint=``, env
  ``TPQ_SALVAGE_HINT``, default on) frames a tiny thrift blob of the
  schema + codec right after the leading magic (``TPQS`` + u32 length +
  thrift ``FileMetaData``).  Spec-compatible: footers address pages by
  absolute offset, so foreign readers (pyarrow, parquet-mr) skip the
  frame without noticing it; torn files become self-salvaging.
* a **sibling** — ``like=`` any ``FileMetaData``/path/reader with the
  same schema (the usual case for a sharded dataset: every healthy
  shard is a donor).

Chunk grouping assumes the layout this library's writer emits — one
data page per chunk, optionally preceded by its dictionary page.
Multi-data-page chunks (some foreign writers) have no recoverable chunk
boundary without a footer; the scan stops at the first page that
doesn't fit the pattern and salvages the prefix before it.
"""

from __future__ import annotations

import struct

from ..errors import CorruptFooterError
from .compact import CompactReader, CompactWriter, ThriftError
from .footer import MAGIC, _file_size
from .metadata import (
    ColumnChunk,
    ColumnMetaData,
    CompressionCodec,
    Encoding,
    FileMetaData,
    KeyValue,
    PageHeader,
    PageType,
    RowGroup,
    decode_struct,
    encode_struct,
)
from .schema import Schema

__all__ = [
    "SALVAGE_MAGIC",
    "PageRec",
    "encode_salvage_hint",
    "read_salvage_hint",
    "forward_scan",
    "rebuild_row_groups",
    "recover_file_metadata",
    "salvage_valid_prefix",
    "SALVAGED_KEY",
]

SALVAGE_MAGIC = b"TPQS"
SALVAGED_KEY = "tpq.salvaged"       # kv marker on synthesized metadata
_CODEC_KEY = "tpq.codec"            # kv slot in the hint frame
_MAX_HINT = 1 << 24                 # 16 MiB: no real schema is bigger
_MAX_HEADER = 1 << 16               # page headers are tens of bytes

# data-page types a recovered chunk may contain
_DATA_TYPES = (PageType.DATA_PAGE, PageType.DATA_PAGE_V2)


# ----------------------------------------------------------------------
# Salvage hint frame
# ----------------------------------------------------------------------

def encode_salvage_hint(schema: Schema, codec: CompressionCodec,
                        created_by: str | None = None) -> bytes:
    """The writer-side frame: schema + codec as a row-group-less
    ``FileMetaData``, length-prefixed behind :data:`SALVAGE_MAGIC`."""
    hint = FileMetaData(
        version=1,
        schema=schema.to_elements(),
        num_rows=0,
        row_groups=[],
        key_value_metadata=[
            KeyValue(key=_CODEC_KEY, value=CompressionCodec(codec).name)],
        created_by=created_by,
    )
    w = CompactWriter()
    encode_struct(hint, w)
    blob = w.getvalue()
    return SALVAGE_MAGIC + struct.pack("<I", len(blob)) + blob


def read_salvage_hint(f) -> "tuple[FileMetaData, int] | None":
    """Read the hint frame after the head magic; returns ``(hint_meta,
    end_offset)`` — the offset where pages begin — or None when the
    file has no (valid) hint.  Never raises: a corrupt hint is just an
    absent hint (the frame sits in the torn region like everything
    else)."""
    size = _file_size(f)
    if size < 4 + 8:
        return None
    f.seek(4)
    head = f.read(8)
    if head[:4] != SALVAGE_MAGIC:
        return None
    (n,) = struct.unpack("<I", head[4:])
    if n <= 0 or n > _MAX_HINT or 12 + n > size:
        return None
    blob = f.read(n)
    if len(blob) != n:
        return None
    try:
        hint = FileMetaData.from_bytes(blob)
    except ThriftError:
        return None
    if not hint.schema:
        return None
    return hint, 12 + n


def hint_codec(hint: FileMetaData) -> "CompressionCodec | None":
    for kv in hint.key_value_metadata or []:
        if kv.key == _CODEC_KEY:
            try:
                return CompressionCodec[kv.value]
            except KeyError:
                return None
    return None


# ----------------------------------------------------------------------
# Forward page scan
# ----------------------------------------------------------------------

class PageRec:
    """One page found by the forward scan: absolute file coordinates."""

    __slots__ = ("offset", "header", "header_len", "data_start",
                 "data_end")

    def __init__(self, offset, header, header_len, data_start, data_end):
        self.offset = offset
        self.header = header
        self.header_len = header_len
        self.data_start = data_start
        self.data_end = data_end

    def __repr__(self):
        return (f"PageRec({PageType(self.header.type).name} "
                f"@{self.offset}, {self.data_end - self.offset}B)")


def _header_sane(ph: PageHeader, remaining: int) -> bool:
    """The garbage rejector: does this decode look like a real page
    header?  Thrift's permissiveness means random bytes sometimes
    decode without error — but they essentially never produce a known
    page type WITH its matching sub-header and sane sizes."""
    try:
        ptype = PageType(ph.type)
    except (ValueError, TypeError):
        return False
    if ph.compressed_page_size is None or ph.compressed_page_size < 0 \
            or ph.compressed_page_size > remaining:
        return False
    if ph.uncompressed_page_size is None or ph.uncompressed_page_size < 0:
        return False
    if ptype == PageType.DATA_PAGE:
        h = ph.data_page_header
        return h is not None and h.num_values is not None \
            and h.num_values >= 0 and h.encoding is not None
    if ptype == PageType.DATA_PAGE_V2:
        h = ph.data_page_header_v2
        return h is not None and h.num_values is not None \
            and h.num_values >= 0 and h.encoding is not None
    if ptype == PageType.DICTIONARY_PAGE:
        h = ph.dictionary_page_header
        return h is not None and h.num_values is not None \
            and h.num_values >= 0
    return ptype == PageType.INDEX_PAGE


def forward_scan(buf, start: int = 4, end: int | None = None,
                 verify_crc: bool = True) -> tuple[list[PageRec], dict]:
    """Walk ``buf`` from ``start`` decoding page headers back-to-back.

    Returns ``(pages, stop)`` where ``stop`` records why and where the
    walk ended: ``reason`` is ``"end"`` (clean stop exactly at ``end``),
    ``"bad-header"`` (bytes that are not a page header — in an intact
    file this is simply the footer thrift), ``"truncated-page"`` (a
    header whose payload overruns the bytes we have — the torn write),
    or ``"crc-mismatch"`` (a page the PR-2 integrity check rejects).
    Pages before the stop are trustworthy; nothing after is touched.
    """
    from ..io.pages import verify_page_crc

    mv = memoryview(buf)
    if end is None:
        end = len(mv)
    if start == 4 and bytes(mv[4:8]) == SALVAGE_MAGIC and end >= 12:
        # default start on a hinted file: step over the hint frame
        (n,) = struct.unpack("<I", mv[8:12])
        if 0 < n <= _MAX_HINT and 12 + n <= end:
            start = 12 + n
    pages: list[PageRec] = []
    pos = start
    while pos < end:
        r = CompactReader(mv, pos, min(pos + _MAX_HEADER, end))
        try:
            ph = decode_struct(PageHeader, r)
        except ThriftError:
            return pages, {"reason": "bad-header", "offset": pos}
        if not _header_sane(ph, remaining=end - r.pos):
            # distinguish "the payload would overrun" (torn tail) from
            # "this never was a page header" (footer bytes / garbage)
            if _header_sane(ph, remaining=1 << 62):
                return pages, {"reason": "truncated-page", "offset": pos}
            return pages, {"reason": "bad-header", "offset": pos}
        data_start = r.pos
        data_end = data_start + ph.compressed_page_size
        if verify_crc and ph.crc is not None:
            try:
                verify_page_crc(ph, mv[data_start:data_end],
                                enabled=True)
            except ValueError:
                return pages, {"reason": "crc-mismatch", "offset": pos}
        pages.append(PageRec(pos, ph, data_start - pos, data_start,
                             data_end))
        pos = data_end
    return pages, {"reason": "end", "offset": pos}


# ----------------------------------------------------------------------
# Metadata rebuild
# ----------------------------------------------------------------------

def rebuild_row_groups(pages: list[PageRec], schema: Schema,
                       codec: CompressionCodec) -> tuple[list[RowGroup],
                                                         dict]:
    """Group scanned pages into chunks (leaf-order cycling: one data
    page per chunk, optional leading dictionary page) and chunks into
    complete row groups.  Returns ``(row_groups, info)`` where ``info``
    counts what the incomplete tail lost."""
    leaves = schema.leaves
    L = len(leaves)
    chunks: list[ColumnChunk] = []
    rows_per_chunk: list[int] = []
    i = 0
    stop = None
    while i < len(pages):
        leaf = leaves[len(chunks) % L]
        first = pages[i]
        dict_page = None
        if first.header.type == PageType.DICTIONARY_PAGE:
            dict_page = first
            i += 1
            if i >= len(pages):
                stop = "chunk-cut-mid"
                break
        data_page = pages[i]
        if data_page.header.type not in _DATA_TYPES:
            # two dictionary pages in a row / an index page where a
            # data page belongs: not the layout we can rebuild
            stop = "unrecognized-layout"
            break
        i += 1
        v2 = data_page.header.type == PageType.DATA_PAGE_V2
        h = data_page.header.data_page_header_v2 if v2 \
            else data_page.header.data_page_header
        start = dict_page.offset if dict_page is not None \
            else data_page.offset
        encodings = [Encoding.RLE]
        try:
            encodings.append(Encoding(h.encoding))
        except ValueError:
            pass
        if dict_page is not None \
                and Encoding.RLE_DICTIONARY not in encodings:
            encodings.append(Encoding.RLE_DICTIONARY)
        total_uncomp = data_page.header_len \
            + data_page.header.uncompressed_page_size
        if dict_page is not None:
            total_uncomp += dict_page.header_len \
                + dict_page.header.uncompressed_page_size
        cm = ColumnMetaData(
            type=leaf.type,
            encodings=encodings,
            path_in_schema=list(leaf.path),
            codec=codec,
            num_values=h.num_values,
            total_uncompressed_size=total_uncomp,
            total_compressed_size=data_page.data_end - start,
            data_page_offset=data_page.offset,
            dictionary_page_offset=(
                dict_page.offset if dict_page is not None else None),
        )
        chunks.append(ColumnChunk(file_offset=start, meta_data=cm))
        rows = None
        if leaf.max_rep_level == 0:
            rows = h.num_values
        elif v2 and h.num_rows is not None:
            rows = h.num_rows
        rows_per_chunk.append(rows)

    row_groups: list[RowGroup] = []
    n_complete = len(chunks) // L
    for rgi in range(n_complete):
        cc = chunks[rgi * L : (rgi + 1) * L]
        rows = [r for r in rows_per_chunk[rgi * L : (rgi + 1) * L]
                if r is not None]
        # every chunk that knows its row count must agree — a
        # disagreement means the grouping drifted; trust ends here
        if rows and any(r != rows[0] for r in rows):
            stop = "row-count-disagreement"
            n_complete = rgi
            break
        if not rows:
            # no chunk knows its row count (all leaves repeated, V1
            # pages): num_values counts elements, not records, and
            # guessing would be WRONG, not absent — stop salvage here
            stop = "unknown-row-count"
            n_complete = rgi
            break
        num_rows = rows[0]
        row_groups.append(RowGroup(
            columns=cc,
            total_byte_size=sum(
                c.meta_data.total_uncompressed_size for c in cc),
            total_compressed_size=sum(
                c.meta_data.total_compressed_size for c in cc),
            num_rows=num_rows,
            ordinal=rgi,
        ))
    row_groups = row_groups[:n_complete]
    info = {
        "chunks_recovered": n_complete * L,
        "chunks_dropped": len(chunks) - n_complete * L,
        "pages_dropped": len(pages) - i,
    }
    if stop:
        info["grouping_stop"] = stop
    return row_groups, info


def recover_file_metadata(f, *, like=None,
                          verify_crc: bool = True
                          ) -> tuple[FileMetaData, dict]:
    """Synthesize ``FileMetaData`` for a file whose footer is unusable.

    ``like`` donates the schema and codec: a ``FileMetaData``, a path,
    or an open reader with ``.meta``.  When absent, the file's own
    salvage hint is used; a file with neither raises
    :class:`CorruptFooterError` (page headers alone cannot name columns
    or types, and guessing would violate "never wrong").

    Returns ``(meta, report)``; ``meta`` carries a
    ``tpq.salvaged = "1"`` key-value marker so downstream consumers can
    tell partial metadata from a real footer.
    """
    size = _file_size(f)
    f.seek(0)
    if size < 4 or f.read(4) != MAGIC:
        raise CorruptFooterError(
            f"invalid magic at file head: not a parquet file "
            f"({size} bytes)", offset=0)

    start = 4
    hint = read_salvage_hint(f)
    if hint is not None:
        start = hint[1]
    donor, codec, created_by = _donor_schema(like, hint)
    schema = Schema.from_elements(donor)

    # scan without materializing a copy of the file: BytesIO exposes
    # its buffer zero-copy, real files mmap; only unseekable oddballs
    # pay the full read.  forward_scan keeps no references into the
    # buffer (PageRecs hold decoded structs + integer offsets), so the
    # view/map is released as soon as the walk ends.
    import io as _io
    import mmap as _mmap

    buf = close = None
    if isinstance(f, _io.BytesIO):
        buf = f.getbuffer().toreadonly()
        close = buf.release
    else:
        try:
            buf = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
            close = buf.close
        except (OSError, ValueError, AttributeError,
                _io.UnsupportedOperation):
            f.seek(0)
            buf = f.read()
    try:
        pages, stop = forward_scan(buf, start=start,
                                   verify_crc=verify_crc)
    finally:
        if close is not None:
            close()
    row_groups, info = rebuild_row_groups(pages, schema, codec)
    num_rows = sum(rg.num_rows for rg in row_groups)
    if row_groups:
        last = row_groups[-1].columns[-1]
        recovered_end = (last.file_offset
                         + last.meta_data.total_compressed_size)
    else:
        recovered_end = start
    meta = FileMetaData(
        version=1,
        schema=donor,
        num_rows=num_rows,
        row_groups=row_groups,
        key_value_metadata=[KeyValue(key=SALVAGED_KEY, value="1")],
        created_by=created_by or "tpuparquet salvage",
    )
    report = {
        "schema_source": ("like" if like is not None else "hint"),
        "pages_scanned": len(pages),
        "row_groups_recovered": len(row_groups),
        "rows_recovered": num_rows,
        "stop_reason": stop["reason"],
        "stop_offset": stop["offset"],
        "bytes_recovered": recovered_end,
        "bytes_lost": max(size - recovered_end, 0),
        "file_size": size,
    }
    report.update(info)
    return meta, report


def _donor_schema(like, hint):
    """Resolve (schema elements, codec, created_by) from ``like`` or
    the hint frame."""
    if like is None:
        if hint is None:
            raise CorruptFooterError(
                "cannot salvage: footer unusable and the file has no "
                "salvage hint — pass salvage_like= a sibling file or "
                "metadata with the same schema")
        hm = hint[0]
        codec = hint_codec(hm)
        if codec is None:
            codec = CompressionCodec.UNCOMPRESSED
        return hm.schema, codec, hm.created_by
    meta = like
    if isinstance(like, (str, bytes)):
        from .footer import read_file_metadata

        with open(like, "rb") as df:
            meta = read_file_metadata(df)
    elif hasattr(like, "meta"):
        meta = like.meta
    if not isinstance(meta, FileMetaData) or not meta.schema:
        raise CorruptFooterError(
            f"salvage_like donor has no usable schema: {like!r}")
    codec = CompressionCodec.UNCOMPRESSED
    for rg in meta.row_groups or []:
        if rg.columns and rg.columns[0].meta_data is not None \
                and rg.columns[0].meta_data.codec is not None:
            try:
                codec = CompressionCodec(rg.columns[0].meta_data.codec)
            except ValueError:
                pass
            break
    return meta.schema, codec, meta.created_by


# ----------------------------------------------------------------------
# Valid-prefix salvage (footer readable, validation failed)
# ----------------------------------------------------------------------

def salvage_valid_prefix(meta: FileMetaData, file_size: int,
                         findings=None
                         ) -> "tuple[FileMetaData, dict] | None":
    """For a footer that *decodes* but fails strict validation: keep
    the longest row-group prefix with no error findings.  Returns
    ``(trimmed_meta, report)`` or None when the damage is file-level
    (schema missing/malformed) and nothing can be trusted.
    ``findings`` may pass in a precomputed ``validate_metadata(meta,
    file_size)`` result (it is a pure function of those inputs) to
    avoid walking wide metadata twice."""
    from .validate import validate_metadata

    if findings is None:
        findings = validate_metadata(meta, file_size)
    errors = [f for f in findings if f.is_error]
    if not errors:
        return None  # nothing to salvage — the metadata is fine
    # file-level errors that the trim itself repairs are tolerable;
    # anything else file-level poisons the schema and with it every rg
    repairable = {"num-rows-sum", "missing-num-rows",
                  "negative-num-rows", "missing-version"}
    for fd in errors:
        if fd.row_group is None and fd.code not in repairable:
            return None
    # only repairable file-level errors -> every row group is clean and
    # the trim itself repairs the file-level numbers: keep them ALL
    rg_errors = [fd.row_group for fd in errors
                 if fd.row_group is not None]
    first_bad = min(rg_errors) if rg_errors else len(meta.row_groups)
    kept = list(meta.row_groups[:first_bad])
    kv = list(meta.key_value_metadata or [])
    kv.append(KeyValue(key=SALVAGED_KEY, value="1"))
    trimmed = FileMetaData(
        version=meta.version if meta.version is not None else 1,
        schema=meta.schema,
        num_rows=sum(rg.num_rows for rg in kept),
        row_groups=kept,
        key_value_metadata=kv,
        created_by=meta.created_by,
        column_orders=meta.column_orders,
    )
    report = {
        "schema_source": "footer",
        "row_groups_recovered": len(kept),
        "row_groups_rejected": len(meta.row_groups) - len(kept),
        "rows_recovered": trimmed.num_rows,
        "stop_reason": "metadata-invalid",
        "findings": [f.as_dict() for f in findings],
    }
    return trimmed, report
