"""Thrift compact-protocol primitives.

Parquet metadata (file footer ``FileMetaData`` and per-page ``PageHeader``)
is serialized with the Thrift *compact* protocol.  The reference implementation
uses the generated apache/thrift Go runtime (see ``/root/reference/parquet/``
and ``/root/reference/helpers.go:101-117`` which selects ``TCompactProtocol``);
we instead hand-roll the protocol: it is small, and a declarative schema system
(see :mod:`tpuparquet.format.metadata`) keeps the struct definitions readable
and auditable against ``parquet.thrift``.

Wire format summary (Thrift compact protocol spec):

* varint: unsigned LEB128 (7 bits per byte, MSB = continuation).
* zigzag: signed -> unsigned mapping ``(n << 1) ^ (n >> 63)``.
* i16/i32/i64: zigzag varint.  i8: single byte.  double: 8-byte LE IEEE754.
* binary/string: varint byte-length + raw bytes.
* struct: sequence of field headers, terminated by a 0x00 STOP byte.  A field
  header is one byte ``(delta << 4) | compact_type`` when the field-id delta
  from the previous field is in 1..15, otherwise ``compact_type`` alone
  followed by the zigzag-varint field id.
* bool fields encode the value *in the type nibble* (1 = true, 2 = false);
  bool list elements are one byte each.
* list/set: one byte ``(size << 4) | elem_type`` when size < 15, else
  ``0xF0 | elem_type`` followed by varint size.
* map: varint size (a single 0x00 for the empty map) then one byte
  ``(key_type << 4) | value_type`` and alternating key/value payloads.
"""

from __future__ import annotations

import struct as _struct

from ..varint import read_uvarint, write_uvarint, zigzag_decode, zigzag_encode

__all__ = [
    "CT",
    "CompactReader",
    "CompactWriter",
    "ThriftError",
]


class ThriftError(ValueError):
    """Raised on malformed compact-protocol input."""


class CT:
    """Compact-protocol type ids (the low nibble of a field header)."""

    STOP = 0
    TRUE = 1
    FALSE = 2
    I8 = 3
    I16 = 4
    I32 = 5
    I64 = 6
    DOUBLE = 7
    BINARY = 8
    LIST = 9
    SET = 10
    MAP = 11
    STRUCT = 12


class CompactReader:
    """Pull-parser over a bytes-like object.

    Tracks its own offset so callers can parse a thrift struct embedded in a
    larger buffer (page headers inside a column chunk) and learn how many
    bytes the struct consumed — the reference does this with a byte-counting
    reader (``offsetReader``, ``/root/reference/helpers.go:37-62``).
    """

    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf, pos: int = 0, end: int | None = None):
        self.buf = memoryview(buf)
        self.pos = pos
        self.end = len(self.buf) if end is None else end

    def _need(self, n: int) -> None:
        if self.pos + n > self.end:
            raise ThriftError(
                f"truncated thrift data: need {n} bytes at offset {self.pos}, "
                f"have {self.end - self.pos}"
            )

    def read_byte(self) -> int:
        self._need(1)
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def read_varint(self) -> int:
        try:
            v, self.pos = read_uvarint(self.buf[: self.end], self.pos)
        except ValueError as e:
            raise ThriftError(str(e)) from None
        return v

    def read_zigzag(self) -> int:
        return zigzag_decode(self.read_varint())

    def read_double(self) -> float:
        self._need(8)
        (v,) = _struct.unpack_from("<d", self.buf, self.pos)
        self.pos += 8
        return v

    def read_binary(self) -> bytes:
        n = self.read_varint()
        if n < 0 or self.pos + n > self.end:
            raise ThriftError(f"binary length {n} out of bounds")
        v = bytes(self.buf[self.pos : self.pos + n])
        self.pos += n
        return v

    # -- struct scaffolding ------------------------------------------------

    def read_field_header(self, last_fid: int) -> tuple[int, int]:
        """Return ``(compact_type, field_id)``; type STOP ends the struct."""
        b = self.read_byte()
        if b == CT.STOP:
            return CT.STOP, 0
        ctype = b & 0x0F
        delta = (b & 0xF0) >> 4
        if delta:
            fid = last_fid + delta
        else:
            fid = self.read_zigzag()
        return ctype, fid

    def read_list_header(self) -> tuple[int, int]:
        b = self.read_byte()
        etype = b & 0x0F
        size = (b & 0xF0) >> 4
        if size == 15:
            size = self.read_varint()
        return etype, size

    def read_map_header(self) -> tuple[int, int, int]:
        size = self.read_varint()
        if size == 0:
            return 0, 0, 0
        b = self.read_byte()
        return (b & 0xF0) >> 4, b & 0x0F, size

    def skip(self, ctype: int) -> None:
        """Skip a value of the given compact type (unknown-field tolerance)."""
        if ctype in (CT.TRUE, CT.FALSE):
            return  # value lived in the field header
        if ctype == CT.I8:
            self.read_byte()
        elif ctype in (CT.I16, CT.I32, CT.I64):
            self.read_varint()
        elif ctype == CT.DOUBLE:
            self._need(8)
            self.pos += 8
        elif ctype == CT.BINARY:
            n = self.read_varint()
            self._need(n)
            self.pos += n
        elif ctype in (CT.LIST, CT.SET):
            etype, size = self.read_list_header()
            for _ in range(size):
                self._skip_elem(etype)
        elif ctype == CT.MAP:
            ktype, vtype, size = self.read_map_header()
            for _ in range(size):
                self._skip_elem(ktype)
                self._skip_elem(vtype)
        elif ctype == CT.STRUCT:
            last = 0
            while True:
                ft, fid = self.read_field_header(last)
                if ft == CT.STOP:
                    return
                self.skip(ft)
                last = fid
        else:
            raise ThriftError(f"cannot skip unknown compact type {ctype}")

    def _skip_elem(self, etype: int) -> None:
        """Skip a container element; bools occupy one byte inside containers
        (unlike struct fields, where the value lives in the header nibble)."""
        if etype in (CT.TRUE, CT.FALSE):
            self.read_byte()
        else:
            self.skip(etype)


class CompactWriter:
    """Append-only compact-protocol emitter into a ``bytearray``."""

    __slots__ = ("out",)

    def __init__(self):
        self.out = bytearray()

    def getvalue(self) -> bytes:
        return bytes(self.out)

    def write_byte(self, b: int) -> None:
        self.out.append(b & 0xFF)

    def write_varint(self, n: int) -> None:
        write_uvarint(self.out, n)

    def write_zigzag(self, n: int) -> None:
        self.write_varint(zigzag_encode(n))

    def write_double(self, v: float) -> None:
        self.out += _struct.pack("<d", v)

    def write_binary(self, v: bytes) -> None:
        self.write_varint(len(v))
        self.out += v

    def write_field_header(self, ctype: int, fid: int, last_fid: int) -> None:
        delta = fid - last_fid
        if 0 < delta <= 15:
            self.write_byte((delta << 4) | ctype)
        else:
            self.write_byte(ctype)
            self.write_zigzag(fid)

    def write_stop(self) -> None:
        self.write_byte(CT.STOP)

    def write_list_header(self, etype: int, size: int) -> None:
        if size < 15:
            self.write_byte((size << 4) | etype)
        else:
            self.write_byte(0xF0 | etype)
            self.write_varint(size)
