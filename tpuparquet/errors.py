"""Structured error taxonomy for the scan/decode path.

The reference (and the seed) raised bare ``ValueError`` everywhere on
the decode path, which gives a scan driver no way to tell *corruption*
(permanent — quarantine the unit) from a *transient* I/O hiccup
(retry) from a *device* failure (degrade to the CPU path).  This
module is the taxonomy that makes those policies implementable:

* :class:`CorruptPageError` / :class:`CorruptChunkError` — the bytes
  are wrong (CRC mismatch, truncation, malformed header, impossible
  counts).  Permanent for this file; a fault-tolerant scan quarantines
  the unit and continues.  Both subclass ``ValueError`` so every
  existing ``except ValueError`` caller (and the crash-corpus "clean
  failure" contract in ``tests/test_corpus.py``) keeps working.
* :class:`CorruptFooterError` — the file-level analogue: torn or
  truncated footer, metadata that fails bounds validation.  A sharded
  scan quarantines the whole *file* (or salvages its readable prefix,
  ``format/recover.py``) and continues.
* :class:`TransientIOError` — the read *might* succeed if repeated
  (flaky NFS, throttled object store).  Subclasses ``OSError``;
  :func:`tpuparquet.faults.retry_transient` retries these with bounded
  exponential backoff.
* :class:`DeviceDispatchError` — staging or kernel dispatch to the
  accelerator failed.  The data is fine; the resilient read path
  retries and then degrades to the bit-exact CPU decode
  (``kernels.device.read_row_group_device_resilient``).
* :class:`DeadlineExceededError` / :class:`DispatchDeadlineError` —
  the *time* domain (``tpuparquet/deadline.py``): a watched operation
  ran past its budget.  A hung chunk read becomes
  :class:`DeadlineExceededError` (a :class:`TransientIOError`, so the
  retry/hedge ladder handles it); a hung device dispatch becomes
  :class:`DispatchDeadlineError` (a :class:`DeviceDispatchError`, so
  the dispatch-retry → CPU-fallback ladder handles it).  Both carry
  ``elapsed`` and ``budget`` seconds next to the scan coordinates, so
  a quarantine entry says exactly how long the unit hung.

Every class carries scan coordinates (file / row group / column /
page).  Inner layers raise with what they know; outer layers
:meth:`~ScanError.annotate` the rest as the error propagates, so by
the time a quarantine report sees it the failing unit is pinpointed
exactly.
"""

from __future__ import annotations

__all__ = [
    "ScanError",
    "CorruptPageError",
    "CorruptChunkError",
    "CorruptFooterError",
    "CorruptManifestError",
    "TransientIOError",
    "DeviceDispatchError",
    "DeadlineExceededError",
    "DispatchDeadlineError",
    "ServeStateError",
    "AdmissionRejected",
    "QUARANTINE_ERRORS",
]

_COORD_FIELDS = ("file", "row_group", "column", "page")


class ScanError(Exception):
    """Base of the taxonomy: an error with scan coordinates.

    ``file`` is a path or file index (whatever the raising layer
    knows), ``row_group``/``page`` are ordinals, ``column`` is the
    dotted ``path_in_schema``.  All optional — :meth:`annotate` fills
    blanks as the error crosses layers without clobbering what an
    inner layer already pinned.
    """

    def __init__(self, message: str = "", *, file=None, row_group=None,
                 column=None, page=None):
        super().__init__(message)
        self.message = message
        self.file = file
        self.row_group = row_group
        self.column = column
        self.page = page

    def coordinates(self) -> dict:
        """The known coordinates, as a dict (omits unknowns)."""
        return {
            k: getattr(self, k)
            for k in _COORD_FIELDS
            if getattr(self, k) is not None
        }

    def annotate(self, **coords) -> "ScanError":
        """Fill in *missing* coordinates; returns self for re-raise."""
        for k, v in coords.items():
            if k not in _COORD_FIELDS:
                raise TypeError(f"unknown coordinate {k!r}")
            if getattr(self, k) is None and v is not None:
                setattr(self, k, v)
        return self

    def __str__(self) -> str:
        c = self.coordinates()
        if not c:
            return self.message
        at = ", ".join(f"{k}={v}" for k, v in c.items())
        return f"{self.message} [{at}]"


class CorruptPageError(ScanError, ValueError):
    """One page's bytes are wrong (CRC mismatch, malformed header,
    truncated payload, impossible value counts)."""


class CorruptChunkError(ScanError, ValueError):
    """A column chunk is structurally wrong beyond one page (byte
    range out of bounds, short read, value-count mismatch)."""


class CorruptFooterError(ScanError, ValueError):
    """The file's framing or ``FileMetaData`` is wrong: bad magic, torn
    or truncated footer, thrift that does not decode, or metadata whose
    offsets/counts fail validation against the file
    (``format/validate.py``).  Carries the byte ``offset`` of the
    rejecting check (when one layer knows it) next to the usual scan
    coordinates, and the structured validator ``findings`` when the
    strict-metadata path raised it.  The legacy name
    ``tpuparquet.format.footer.FormatError`` is an alias."""

    def __init__(self, message: str = "", *, offset=None, findings=None,
                 **coords):
        super().__init__(message, **coords)
        self.offset = offset
        self.findings = list(findings) if findings else []

    def coordinates(self) -> dict:
        c = super().coordinates()
        if self.offset is not None:
            c["offset"] = self.offset
        return c


class CorruptManifestError(ScanError, ValueError):
    """A partitioned dataset's manifest (or commit journal) failed its
    framing checks: not the envelope format, unknown version, CRC
    mismatch over the canonical body, or a body that fails structural
    validation.  The dataset-level analogue of
    :class:`CorruptFooterError` — ``file`` carries the manifest path,
    and the resolver degrades to the newest *older* snapshot that
    validates (quarantining this one) rather than failing the scan."""


class TransientIOError(ScanError, OSError):
    """An I/O failure that may succeed on retry."""


class DeviceDispatchError(ScanError, RuntimeError):
    """Staging/dispatching decode work to the accelerator failed; the
    input bytes are fine and the CPU path can still decode them."""


class _DeadlineInfo:
    """Shared elapsed/budget plumbing for the two deadline classes
    (they must subclass *different* taxonomy parents — OSError for the
    retry ladder, RuntimeError for the dispatch ladder — so the info
    rides as a mixin)."""

    def _set_deadline(self, elapsed, budget, site):
        self.elapsed = elapsed   # seconds the operation actually ran
        self.budget = budget     # seconds it was allowed
        self.site = site         # watched site name (deadline.py)

    def _deadline_coords(self, c: dict) -> dict:
        if self.elapsed is not None:
            c["elapsed_s"] = round(self.elapsed, 3)
        if self.budget is not None:
            c["budget_s"] = self.budget
        return c


class DeadlineExceededError(_DeadlineInfo, TransientIOError):
    """A watched read ran past its time budget (hung NFS mount,
    stalled object-store request).  Subclasses
    :class:`TransientIOError`, so :func:`tpuparquet.faults.
    retry_transient` retries it and a quarantining scan absorbs the
    exhausted ladder — a hang becomes a bounded, classified failure
    instead of a stalled fleet."""

    def __init__(self, message: str = "", *, elapsed=None, budget=None,
                 site=None, **coords):
        super().__init__(message, **coords)
        self._set_deadline(elapsed, budget, site)

    def coordinates(self) -> dict:
        return self._deadline_coords(super().coordinates())


class DispatchDeadlineError(_DeadlineInfo, DeviceDispatchError):
    """A watched device dispatch ran past its time budget (wedged
    accelerator, dead tunnel).  Subclasses
    :class:`DeviceDispatchError`, so the resilient read path's
    retry → CPU-fallback ladder handles it."""

    def __init__(self, message: str = "", *, elapsed=None, budget=None,
                 site=None, **coords):
        super().__init__(message, **coords)
        self._set_deadline(elapsed, budget, site)

    def coordinates(self) -> dict:
        return self._deadline_coords(super().coordinates())


class ServeStateError(RuntimeError):
    """Invalid scan-server lifecycle operation — e.g. activating a
    second process-wide :class:`~tpuparquet.serve.ResourceArbiter`
    while another is live.  A caller bug, not a scan failure: it
    never enters the quarantine/retry routing."""


class AdmissionRejected(ServeStateError):
    """Load-shed rejection from the scan server's admission control
    (:meth:`tpuparquet.serve.ResourceArbiter.admit`).

    Always RETRYABLE: the request was never queued, so resubmitting
    after ``retry_after_s`` is safe and duplicate-free.  Carries the
    machine-readable fields a client backoff loop needs: ``tenant``,
    ``reason`` (``"queue_full"`` / ``"byte_budget"`` /
    ``"deadline_budget"`` / ``"draining"``) and ``retry_after_s``."""

    def __init__(self, msg: str, *, tenant: str, reason: str,
                 retry_after_s: float):
        super().__init__(msg)
        self.tenant = tenant
        self.reason = reason
        self.retry_after_s = retry_after_s


# What a quarantining scan may absorb per unit: the library's clean
# failure taxonomy (ValueError covers Corrupt*/Thrift/codec errors,
# EOFError truncation, TypeError/NotImplementedError foreign shapes,
# OSError exhausted-retry I/O, RuntimeError exhausted device dispatch).
# Raw crash types (IndexError, KeyError, ...) are BUGS and always
# propagate — quarantine must never paper over them.  RecursionError
# subclasses RuntimeError, so catch sites pair this tuple with
# :func:`never_quarantine` to keep it (a crash, not a failure) loud.
QUARANTINE_ERRORS = (ValueError, EOFError, TypeError,
                     NotImplementedError, OSError, RuntimeError)


def never_quarantine(exc: BaseException) -> bool:
    """Crash types that must propagate even though they subclass a
    member of :data:`QUARANTINE_ERRORS`."""
    return isinstance(exc, RecursionError)
