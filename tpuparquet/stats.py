"""Decode statistics and tracing (SURVEY.md §5 "metrics / logging").

The reference exposes introspection only through footer metadata; the
TPU build adds first-class decode-throughput counters — the BASELINE
metric (values/sec/chip) as a library feature:

    with tpuparquet.collect_stats() as st:
        reader.read_row_group_arrays(0)
    print(st.summary())

Counters are plain Python ints collected only while a collector is
active (zero overhead otherwise).  ``trace()`` wraps a scope in a JAX
profiler trace for TensorBoard.

THREAD-LOCAL SEMANTICS: the active collector is per-thread, not
per-process.  ``collect_stats()`` registers its collector on the
calling thread only — decode work an external caller dispatches to its
OWN worker threads inside the scope is invisible to that collector
unless each worker wraps its slice in :func:`worker_stats` and the
coordinator folds the result with ``merge_from`` after joining (the
pattern the library's internal thread pools use — see
``kernels/device.pipelined_reads`` and ``io/writer._flush_prepared``).
A shared collector incremented from racing threads would lose counts;
the thread-local design makes that impossible rather than unlikely.

Structured telemetry (``tpuparquet/obs/``) rides the same collector:
``collect_stats(events=True)`` attaches a per-page
:class:`~tpuparquet.obs.events.EventLog`, and log2-bucket histograms
(:class:`~tpuparquet.obs.histogram.Histogram`) record whenever any
collector is active.  Both merge exactly across ``worker_stats``
collectors and across hosts (``shard.distributed.allgather_stats``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time

__all__ = ["DecodeStats", "collect_stats", "current_stats",
           "worker_stats", "merge_worker_stats", "adopt_stats",
           "trace"]


@dataclasses.dataclass
class DecodeStats:
    """Counters for one collection scope."""

    row_groups: int = 0
    chunks: int = 0
    pages: int = 0
    # pages whose values segment decompressed ON DEVICE (snappy token
    # kernel) rather than on host — evidence the device path engaged
    pages_device_snappy: int = 0
    # pages whose PLAIN values shipped as the byte-plane RLE transport
    # (upper planes as runs) instead of raw bytes
    pages_device_planes: int = 0
    # pages whose PLAIN int values shipped as packed delta offsets
    # (first + per-page min_delta + w-bit deltas), rebuilt by the delta
    # expand kernels — the sorted-column transport
    pages_device_delta_lanes: int = 0
    # write-side pages whose values encoded ON DEVICE (DeviceValues:
    # DELTA/BSS/PLAIN in kernels/encode.py) — evidence the writer TPU
    # path engaged rather than pulling raw values to host
    pages_device_encoded: int = 0
    # pages whose VALUES were decoded on host and staged as-is (the
    # catch-all else of the device dispatch, kernels/device.py) — the
    # fallback-matrix observable: tests/test_fallback_matrix.py pins
    # exactly which (encoding x type) land here, so a regression that
    # silently demotes a device path to host fails a test, not a profile
    pages_host_values: int = 0
    values: int = 0
    bytes_compressed: int = 0
    bytes_uncompressed: int = 0
    # chunk bytes fetched from the source (FileReader.chunk_blob —
    # in-memory views and file reads alike) and the wall spent
    # fetching them (retry/hedge/deadline wait included): the
    # read-side pair of plan_s/transfer_s, and the bytes_read half of
    # the per-scan attribution ledger (obs/attribution.py)
    bytes_read: int = 0
    read_s: float = 0.0
    # bytes shipped host->device THROUGH THE BATCHED STAGER (counted at
    # transfer time, split/padding included) — the transfer-wall
    # observable: compressed-wire shipping shows up as bytes_staged <
    # bytes_uncompressed.  A few fallback paths (CPU-decoded values,
    # FLBA/boolean staging inside finish()) transfer outside the
    # stager and are not counted here.
    bytes_staged: int = 0
    # slow-path executions that a healthy build would run natively (e.g.
    # a stale .so forcing the numpy bp-stats fallback): nonzero means
    # perf has quietly regressed with no functional symptom
    native_fallbacks: int = 0
    # -- fault-tolerance observables (tpuparquet/faults.py, errors.py) --
    # pages whose header carried a CRC that was checked and matched;
    # mismatches raise CorruptPageError AND count, so a fleet report
    # can say "N pages verified, M rejected"
    pages_crc_verified: int = 0
    crc_mismatches: int = 0
    # injected faults delivered by the harness (tests/chaos drills only;
    # nonzero in production means an injector leaked)
    faults_injected: int = 0
    # transient-I/O retry attempts (faults.retry_transient) and
    # device-dispatch retry attempts (read_row_group_device_resilient)
    io_retries: int = 0
    dispatch_retries: int = 0
    # graceful degradation: pages planned under the forced-host decode
    # (transport "host-degraded") and whole units that fell back to the
    # bit-exact CPU decode after device dispatch kept failing
    pages_degraded: int = 0
    units_degraded: int = 0
    # scan units isolated by on_error="quarantine" (coordinates live in
    # the scan's QuarantineReport; this is the fleet-foldable total)
    units_quarantined: int = 0
    # -- file-level salvage observables (format/validate.py, recover.py) --
    # whole files whose footer was torn/invalid and were opened through
    # the salvage path (readable row-group prefix only), and the row
    # groups those salvages recovered
    files_salvaged: int = 0
    row_groups_recovered: int = 0
    # whole files a sharded scan quarantined at open time (footer
    # unusable and salvage off/failed); per-file coordinates live in
    # the scan's QuarantineReport
    files_quarantined: int = 0
    # footers rejected by strict metadata validation
    # (FileReader(strict_metadata=True) / TPQ_STRICT_METADATA)
    metadata_rejects: int = 0
    # -- time-domain observables (tpuparquet/deadline.py) --
    # watched operations (chunk reads, device dispatches, whole units)
    # that ran past their budget and were converted into
    # DeadlineExceededError/DispatchDeadlineError by the watchdog path
    deadline_exceeded: int = 0
    # hedged reads: extra replica reads launched after the hedge
    # delay, and how many of those actually won the race (a healthy
    # store hedges rarely and wins rarely; a degraded primary shows
    # hedges_won ~ hedges_issued)
    hedges_issued: int = 0
    hedges_won: int = 0
    # durable cursor checkpoints written (shard.scan.save_cursor_file
    # via the auto-checkpoint path or an explicit cursor_save)
    checkpoints_written: int = 0
    # -- write pipeline (io/pages.py, io/chunk.py) --
    # every page this scope wrote (dictionary + data, native or pure
    # path) and the subset whose body was assembled by the native
    # one-pass pipeline (native/page.c): the conservation invariant is
    # pages_assembled_native <= pages_written, with equality on data
    # pages when TPQ_WRITE_NATIVE is on and the codec qualifies
    pages_written: int = 0
    pages_assembled_native: int = 0
    # where the native write wall went, accumulated per page: body
    # encode (levels + dict-index/value streams into the arena
    # buffer), block compress + page CRC, and header build + buffer
    # writes.  All zero on the pure path (its stages interleave through
    # Python bytes and can't be attributed exactly).
    write_encode_s: float = 0.0
    write_compress_s: float = 0.0
    write_assemble_s: float = 0.0
    # block-parallel codec split: sub-blocks compressed as independent
    # frames on write (compress.page_compress_into) and frames decoded
    # concurrently on read (multi-frame ZSTD bodies).  Zero whenever
    # pages stay single-frame — the 1-worker byte-parity mode.
    codec_split_blocks: int = 0
    codec_split_frames: int = 0
    # -- predicate pushdown / pruning (tpuparquet/filter.py) --
    # row groups skipped entirely by a filter verdict (chunk Statistics,
    # bloom filters, or the page index proving no row can match) — the
    # scan never forms/decodes a unit for them
    row_groups_pruned: int = 0
    # data pages skipped inside surviving row groups (not decompressed,
    # not decoded, not staged), summed over column chunks
    pages_pruned: int = 0
    # rows statically eliminated by pruning decisions: the rows of
    # pruned row groups plus, per surviving filtered row group, the
    # rows outside the page-index candidate set (counted once per row
    # group, NOT once per column)
    rows_pruned: int = 0
    # bloom-filter probes that answered "definitely absent" (each such
    # verdict licenses a prune; blooms have no false negatives)
    bloom_hits: int = 0
    # -- partitioned datasets (tpuparquet/dataset/) --
    # data files skipped entirely by partition-value pruning against
    # the manifest (the scan never opens them — this composes BEFORE
    # the per-file stats/bloom/page-index layers above)
    dataset_files_pruned: int = 0
    # orphaned staging files / stale journals moved to _quarantine/ by
    # the dataset orphan sweep (never deleted silently)
    dataset_orphans_swept: int = 0
    # exact-filter selectivity accounting: rows that entered exact
    # predicate evaluation vs rows that survived it (selectivity =
    # filter_rows_out / filter_rows_in); rows pruned statically never
    # enter these — rows_pruned covers them
    filter_rows_in: int = 0
    filter_rows_out: int = 0
    # -- gather / output placement (shard/scan.py gather_column et al.) --
    # bytes of assembled column globals that LANDED on destination
    # shards during the gather's reshard step: per-destination-shard
    # received bytes summed over the target's devices (padding
    # included).  Replicated out-sharding pays global_bytes x n_devices;
    # a 1:1 consumer-aligned placement pays ~global_bytes — flat in
    # mesh size.  The r05 "is the gather volume irreducible?" question
    # is answered by this counter, not conjecture.
    gather_bytes_moved: int = 0
    # the share of gather_bytes_moved that is pure replication (every
    # copy of a global byte beyond the first): replicated out-sharding
    # contributes global_bytes x (n_devices - 1); an evenly-sharded
    # consumer placement contributes 0.  True consumer fan-out (a spec
    # that replicates over some mesh axis) shows up here too —
    # proportional to the fan-out actually requested.
    gather_bytes_replicated: int = 0
    # wall spent in the gather's reshard/collective step (the
    # device-side half of gather time; host-side densify/pad/stack
    # assembly is the rest of the caller's gather wall)
    gather_reshard_s: float = 0.0
    # -- footer-keyed plan cache (kernels/plancache.py) --
    # per-(rg, column) lookups during device planning: hits skip the
    # transport competition (sample windows, token scans), misses run
    # it and store the verdicts; evictions are LRU drops under the
    # TPQ_PLAN_CACHE_MB byte budget.  All zero when the cache is off.
    plan_cache_hits: int = 0
    plan_cache_misses: int = 0
    plan_cache_evictions: int = 0
    # -- remote byte-range sources (io/source.py, io/rangecache.py) --
    # range requests actually issued to a remote source by the chunk
    # fetch path (after coalescing; cache hits never issue one) and the
    # requests *saved* by merging: a prefetch of R chunk ranges that
    # collapses to M fetches adds M to remote_ranges_fetched and R - M
    # to ranges_coalesced.  remote_bytes is the exact payload total of
    # issued fetches (gap bytes included — that's the trade the
    # coalescer makes); remote_retry counts retry-ladder re-issues
    # against remote sources (the remote twin of io_retries)
    remote_ranges_fetched: int = 0
    ranges_coalesced: int = 0
    remote_bytes: int = 0
    remote_retry: int = 0
    # tiered range cache: per-tier lookups split exactly into hits +
    # misses (conservation: hits + misses == lookups), evictions are
    # LRU drops, budget rejections and poison/invalidation removals
    cache_hits_mem: int = 0
    cache_misses_mem: int = 0
    cache_evictions_mem: int = 0
    cache_hits_disk: int = 0
    cache_misses_disk: int = 0
    cache_evictions_disk: int = 0
    # where the device-path wall went, accumulated per unit: host plan
    # phase (page walk, decompression, run-table scans — overlapped with
    # transfer by the pipelined reader, so plan_s can exceed the e2e
    # wall), stager transfer (put(), blocking to completion), and
    # dispatch+sync (finish ops + the batched block_until_ready).  On
    # the real chip these tell which side binds: transfer_s ~ wall means
    # the wire is the wall; plan_s ~ wall means the planner is.
    plan_s: float = 0.0
    transfer_s: float = 0.0
    dispatch_s: float = 0.0
    wall_s: float = 0.0
    _t0: float = dataclasses.field(default=0.0, repr=False)
    # structured telemetry (tpuparquet/obs/): named log2-bucket
    # histograms, recorded whenever this collector is active; and the
    # per-page event log, attached only by collect_stats(events=True)
    # (None otherwise — the hot paths check `st.events is not None`
    # before any per-page event work)
    hists: dict = dataclasses.field(default_factory=dict, repr=False)
    events: object = dataclasses.field(default=None, repr=False)

    # counter fields merged across worker collectors (everything
    # cumulative; wall_s/_t0 belong to the owning scope alone)
    _MERGE_FIELDS = (
        "row_groups", "chunks", "pages", "pages_device_snappy",
        "pages_device_planes", "pages_device_delta_lanes",
        "pages_device_encoded", "pages_host_values", "values",
        "bytes_compressed", "bytes_uncompressed", "bytes_staged",
        "bytes_read", "read_s",
        "native_fallbacks", "pages_crc_verified", "crc_mismatches",
        "faults_injected", "io_retries", "dispatch_retries",
        "pages_degraded", "units_degraded", "units_quarantined",
        "files_salvaged", "row_groups_recovered", "files_quarantined",
        "metadata_rejects",
        "deadline_exceeded", "hedges_issued", "hedges_won",
        "checkpoints_written",
        "pages_written", "pages_assembled_native",
        "write_encode_s", "write_compress_s", "write_assemble_s",
        "codec_split_blocks", "codec_split_frames",
        "row_groups_pruned", "pages_pruned", "rows_pruned",
        "bloom_hits", "filter_rows_in", "filter_rows_out",
        "dataset_files_pruned", "dataset_orphans_swept",
        "gather_bytes_moved", "gather_bytes_replicated",
        "gather_reshard_s",
        "plan_cache_hits", "plan_cache_misses", "plan_cache_evictions",
        "remote_ranges_fetched", "ranges_coalesced", "remote_bytes",
        "remote_retry",
        "cache_hits_mem", "cache_misses_mem", "cache_evictions_mem",
        "cache_hits_disk", "cache_misses_disk", "cache_evictions_disk",
        "plan_s", "transfer_s", "dispatch_s",
    )

    def merge_from(self, other: "DecodeStats") -> None:
        """Fold a worker collector's counts into this one (called on
        the coordinating thread after the worker is joined).  Histogram
        folds are exact (integer bucket adds); the worker's event log,
        if any, appends to this collector's."""
        for f in self._MERGE_FIELDS:
            setattr(self, f, getattr(self, f) + getattr(other, f))
        for name, h in other.hists.items():
            self.hist(name).merge_from(h)
        if other.events is not None and self.events is not None:
            self.events.merge_from(other.events)

    def hist(self, name: str):
        """Get-or-create the named histogram (obs.Histogram)."""
        h = self.hists.get(name)
        if h is None:
            from .obs.histogram import Histogram

            h = self.hists[name] = Histogram()
        return h

    @property
    def values_per_sec(self) -> float:
        return self.values / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def compression_ratio(self) -> float:
        if self.bytes_compressed == 0:
            return 1.0
        return self.bytes_uncompressed / self.bytes_compressed

    def as_dict(self) -> dict:
        return {
            "row_groups": self.row_groups,
            "chunks": self.chunks,
            "pages": self.pages,
            "pages_device_snappy": self.pages_device_snappy,
            "pages_device_planes": self.pages_device_planes,
            "pages_device_delta_lanes": self.pages_device_delta_lanes,
            "pages_device_encoded": self.pages_device_encoded,
            "pages_host_values": self.pages_host_values,
            "values": self.values,
            "bytes_compressed": self.bytes_compressed,
            "bytes_uncompressed": self.bytes_uncompressed,
            "bytes_staged": self.bytes_staged,
            "bytes_read": self.bytes_read,
            "read_s": round(self.read_s, 6),
            "native_fallbacks": self.native_fallbacks,
            "pages_crc_verified": self.pages_crc_verified,
            "crc_mismatches": self.crc_mismatches,
            "faults_injected": self.faults_injected,
            "io_retries": self.io_retries,
            "dispatch_retries": self.dispatch_retries,
            "pages_degraded": self.pages_degraded,
            "units_degraded": self.units_degraded,
            "units_quarantined": self.units_quarantined,
            "files_salvaged": self.files_salvaged,
            "row_groups_recovered": self.row_groups_recovered,
            "files_quarantined": self.files_quarantined,
            "metadata_rejects": self.metadata_rejects,
            "deadline_exceeded": self.deadline_exceeded,
            "hedges_issued": self.hedges_issued,
            "hedges_won": self.hedges_won,
            "checkpoints_written": self.checkpoints_written,
            "pages_written": self.pages_written,
            "pages_assembled_native": self.pages_assembled_native,
            "write_encode_s": round(self.write_encode_s, 6),
            "write_compress_s": round(self.write_compress_s, 6),
            "write_assemble_s": round(self.write_assemble_s, 6),
            "codec_split_blocks": self.codec_split_blocks,
            "codec_split_frames": self.codec_split_frames,
            "row_groups_pruned": self.row_groups_pruned,
            "pages_pruned": self.pages_pruned,
            "rows_pruned": self.rows_pruned,
            "bloom_hits": self.bloom_hits,
            "dataset_files_pruned": self.dataset_files_pruned,
            "dataset_orphans_swept": self.dataset_orphans_swept,
            "filter_rows_in": self.filter_rows_in,
            "filter_rows_out": self.filter_rows_out,
            "selectivity": round(
                self.filter_rows_out / self.filter_rows_in, 6)
            if self.filter_rows_in else None,
            "gather_bytes_moved": self.gather_bytes_moved,
            "gather_bytes_replicated": self.gather_bytes_replicated,
            "gather_reshard_s": round(self.gather_reshard_s, 6),
            "plan_cache_hits": self.plan_cache_hits,
            "plan_cache_misses": self.plan_cache_misses,
            "plan_cache_evictions": self.plan_cache_evictions,
            "remote_ranges_fetched": self.remote_ranges_fetched,
            "ranges_coalesced": self.ranges_coalesced,
            "remote_bytes": self.remote_bytes,
            "remote_retry": self.remote_retry,
            "cache_hits_mem": self.cache_hits_mem,
            "cache_misses_mem": self.cache_misses_mem,
            "cache_evictions_mem": self.cache_evictions_mem,
            "cache_hits_disk": self.cache_hits_disk,
            "cache_misses_disk": self.cache_misses_disk,
            "cache_evictions_disk": self.cache_evictions_disk,
            "plan_s": round(self.plan_s, 6),
            "transfer_s": round(self.transfer_s, 6),
            "dispatch_s": round(self.dispatch_s, 6),
            "wall_s": round(self.wall_s, 6),
            "values_per_sec": round(self.values_per_sec, 1),
            "compression_ratio": round(self.compression_ratio, 3),
        }

    def summary(self) -> str:
        d = self.as_dict()
        return (
            f"decoded {d['values']:,} values in {d['pages']} pages / "
            f"{d['chunks']} chunks / {d['row_groups']} row groups; "
            f"{d['bytes_compressed']:,}B -> {d['bytes_uncompressed']:,}B "
            f"(x{d['compression_ratio']}); "
            f"{d['wall_s']:.4f}s = {d['values_per_sec']:,.0f} values/s"
            + (f"; staged {d['bytes_staged']:,}B to device"
               if d["bytes_staged"] else "")
            + (f"; plan {d['plan_s']:.3f}s / transfer "
               f"{d['transfer_s']:.3f}s / dispatch {d['dispatch_s']:.3f}s"
               if d["transfer_s"] else "")
            + (f"; {d['native_fallbacks']} native fallbacks (stale .so?)"
               if d["native_fallbacks"] else "")
            + (f"; crc verified {d['pages_crc_verified']} pages"
               if d["pages_crc_verified"] else "")
            + (f"; FAULTS: {d['crc_mismatches']} crc mismatches, "
               f"{d['faults_injected']} injected, "
               f"{d['io_retries']} io retries, "
               f"{d['dispatch_retries']} dispatch retries, "
               f"{d['pages_degraded']}p/{d['units_degraded']}u degraded "
               f"to host, {d['units_quarantined']} quarantined"
               if (d["crc_mismatches"] or d["faults_injected"]
                   or d["io_retries"] or d["dispatch_retries"]
                   or d["pages_degraded"] or d["units_degraded"]
                   or d["units_quarantined"]) else "")
            + (f"; TIME: {d['deadline_exceeded']} deadlines exceeded, "
               f"{d['hedges_issued']} hedges issued "
               f"({d['hedges_won']} won), "
               f"{d['checkpoints_written']} checkpoints"
               if (d["deadline_exceeded"] or d["hedges_issued"]
                   or d["checkpoints_written"]) else "")
            + (f"; WRITE: {d['pages_written']} pages "
               f"({d['pages_assembled_native']} native), "
               f"encode {d['write_encode_s']:.3f}s / compress "
               f"{d['write_compress_s']:.3f}s / assemble "
               f"{d['write_assemble_s']:.3f}s"
               + (f", {d['codec_split_blocks']} split blocks"
                  if d["codec_split_blocks"] else "")
               if d["pages_written"] else "")
            + (f"; {d['codec_split_frames']} codec frames "
               f"decoded parallel" if d["codec_split_frames"] else "")
            + (f"; PRUNE: {d['row_groups_pruned']} row groups / "
               f"{d['pages_pruned']} pages / {d['rows_pruned']} rows "
               f"pruned, {d['bloom_hits']} bloom hits"
               + (f", selectivity {d['selectivity']:.4f} "
                  f"({d['filter_rows_out']:,}/{d['filter_rows_in']:,})"
                  if d["filter_rows_in"] else "")
               if (d["row_groups_pruned"] or d["pages_pruned"]
                   or d["rows_pruned"] or d["bloom_hits"]
                   or d["filter_rows_in"]) else "")
            + (f"; GATHER: {d['gather_bytes_moved']:,}B to consumers "
               f"({d['gather_bytes_replicated']:,}B replication), "
               f"reshard {d['gather_reshard_s']:.3f}s"
               if (d["gather_bytes_moved"] or d["gather_reshard_s"])
               else "")
            + (f"; PLAN CACHE: {d['plan_cache_hits']} hits / "
               f"{d['plan_cache_misses']} misses / "
               f"{d['plan_cache_evictions']} evictions"
               if (d["plan_cache_hits"] or d["plan_cache_misses"]
                   or d["plan_cache_evictions"]) else "")
            + (f"; REMOTE: {d['remote_ranges_fetched']} ranges "
               f"({d['ranges_coalesced']} coalesced away), "
               f"{d['remote_bytes']:,}B fetched, "
               f"{d['remote_retry']} retries; cache mem "
               f"{d['cache_hits_mem']}/{d['cache_misses_mem']}"
               f"/{d['cache_evictions_mem']} disk "
               f"{d['cache_hits_disk']}/{d['cache_misses_disk']}"
               f"/{d['cache_evictions_disk']} (hit/miss/evict)"
               if (d["remote_ranges_fetched"] or d["remote_retry"]
                   or d["cache_hits_mem"] or d["cache_misses_mem"]
                   or d["cache_hits_disk"] or d["cache_misses_disk"])
               else "")
            + (f"; SALVAGE: {d['files_salvaged']} files salvaged "
               f"({d['row_groups_recovered']} row groups recovered), "
               f"{d['files_quarantined']} files quarantined, "
               f"{d['metadata_rejects']} metadata rejects"
               if (d["files_salvaged"] or d["files_quarantined"]
                   or d["metadata_rejects"]) else "")
        )

    def histograms_dict(self) -> dict:
        """Sparse JSON form of every recorded histogram."""
        return {name: h.as_dict() for name, h in sorted(self.hists.items())}

    # -- exact wire form (cross-host aggregation) -----------------------

    def to_state(self) -> dict:
        """JSON-serializable EXACT state: unrounded counters + wall +
        histograms (``as_dict`` rounds for display; aggregation must
        not).  The event log does not ship — it is per-host detail."""
        d = {f: getattr(self, f) for f in self._MERGE_FIELDS}
        d["wall_s"] = self.wall_s
        if self.hists:
            d["hists"] = self.histograms_dict()
        return d

    @classmethod
    def from_state(cls, d: dict) -> "DecodeStats":
        from .obs.histogram import Histogram

        st = cls()
        for f in cls._MERGE_FIELDS:
            if f in d:
                setattr(st, f, d[f])
        st.wall_s = d.get("wall_s", 0.0)
        for name, h in (d.get("hists") or {}).items():
            st.hists[name] = Histogram.from_dict(h)
        return st


_tls = threading.local()


def current_stats() -> DecodeStats | None:
    """The active collector ON THIS THREAD, or None (the hot path
    checks this).  Thread-local: a worker thread planning or encoding
    on behalf of a scope uses :func:`worker_stats` and its coordinator
    merges — plain ``+=`` on a shared collector from racing threads
    loses increments, and ``values``/``bytes_*`` feed headline bench
    fields."""
    return getattr(_tls, "active", None)


@contextlib.contextmanager
def collect_stats(events: bool = False):
    """Collect decode counters for the enclosed scope (on THIS thread —
    see the module docstring for the worker-thread contract).

    ``events=True`` additionally attaches a per-page event log
    (``st.events``, an :class:`~tpuparquet.obs.events.EventLog`): one
    record per decoded page with the chosen transport and the gate's
    wire-size numbers, plus host-side phase spans for the Perfetto
    export.  Off by default — the event log allocates per page."""
    prev = getattr(_tls, "active", None)
    st = DecodeStats()
    if events:
        from .obs.events import EventLog

        st.events = EventLog()
    st._t0 = time.perf_counter()
    _tls.active = st
    try:
        yield st
    finally:
        st.wall_s = time.perf_counter() - st._t0
        _tls.active = prev
        # always-on regime bridge (obs/live.py): every collect_stats
        # scope folds into the process-wide metrics registry on exit,
        # exactly once per count — a nested scope SHADOWS the outer
        # (its counts never reach the outer collector), and worker
        # collectors merge into their coordinator instead of folding,
        # so no count lands twice.  One ~40-field pass per scope;
        # TPQ_LIVE_METRICS=0 disables.
        from .obs.live import fold_stats

        fold_stats(st)


@contextlib.contextmanager
def adopt_stats(st: "DecodeStats"):
    """Temporarily install an EXISTING collector as this thread's
    active one (no wall bookkeeping — the owner keeps its own clock).
    The scan drivers use this to meter unit decodes into a
    scan-lifetime collector when the caller has no collector of their
    own, so the always-on metrics registry sees scans nobody wrapped
    in ``collect_stats()``.  Same restore discipline as the scopes
    above; never nest around a scope you don't own."""
    prev = getattr(_tls, "active", None)
    _tls.active = st
    try:
        yield st
    finally:
        _tls.active = prev


@contextlib.contextmanager
def worker_stats(like: "DecodeStats | None" = None):
    """Fresh per-thread collector for a pool worker; yields it.  The
    coordinating thread merges the result into ITS active collector
    (``merge_from``) after joining the worker — no cross-thread
    increments, no lost counts.

    ``like`` is the coordinator's collector (or None): when it carries
    an event log, the worker gets its own log on the SAME clock
    (shared ``t0``), so merged span timestamps line up in one
    timeline."""
    prev = getattr(_tls, "active", None)
    st = DecodeStats()
    if like is not None and like.events is not None:
        from .obs.events import EventLog

        st.events = EventLog(t0=like.events.t0)
    _tls.active = st
    try:
        yield st
    finally:
        _tls.active = prev


# counters that carry fault-layer observability (injected faults, CRC
# rejects, retry attempts, deadline expiries, hedges): the only thing
# a FAILED worker attempt may contribute to its coordinator —
# everything else from a failed attempt would be a phantom count.
# These must cover every counter the fault EVENTS (which DO merge on
# failure) can record, or counters and events diverge.
_FAULT_OBSERVABILITY_FIELDS = ("faults_injected", "crc_mismatches",
                               "io_retries", "remote_retry",
                               "dispatch_retries",
                               "deadline_exceeded", "hedges_issued",
                               "hedges_won")


def merge_worker_stats(st: "DecodeStats | None",
                       ws: "DecodeStats | None", *,
                       failed: bool) -> None:
    """Fold a worker/attempt collector into the coordinator's with the
    resilient-attempt exactness policy: EVERYTHING on success;
    fault-layer observability only on failure (a unit that retried N
    times still counts its pages/values/bytes exactly once, and
    aborted attempts leave no phantom page events).  The single owner
    of this policy — used by the retry ladder
    (``kernels.device.read_row_group_device_resilient``) and the
    deadline/hedge worker threads (``tpuparquet/deadline.py``)."""
    if st is None or ws is None:
        return
    if not failed:
        st.merge_from(ws)
        return
    for f in _FAULT_OBSERVABILITY_FIELDS:
        setattr(st, f, getattr(st, f) + getattr(ws, f))
    if st.events is not None and ws.events is not None:
        st.events.faults.extend(ws.events.faults)


@contextlib.contextmanager
def trace(log_dir: str):
    """JAX profiler trace of the enclosed scope (view in TensorBoard /
    Perfetto).  Device-side kernel timings come from the profiler; the
    counters above stay host-side and cheap."""
    import jax

    with jax.profiler.trace(log_dir):
        yield
