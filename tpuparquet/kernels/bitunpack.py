"""Device bit-unpacking: the core decode primitive (jnp + Pallas).

Replaces the CPU `unpack8*` function tables for the device path.  The
formulation is chosen for TPU vector units: for a static width ``w``, a
block of 32 consecutive values occupies exactly ``w`` u32 words of the
packed stream, and the (word-index, bit-shift) pattern of the 32 values
within those words depends only on ``w`` — so the decode is

    words:  (n_blocks, w) u32
    lo    = words[:, WIDX[w]]            # static fancy index
    hi    = words[:, WIDX2[w]]
    out   = ((lo >> SHIFT[w]) | (hi << (32 - SHIFT[w]))) & mask

with zero data-dependent gathers — pure reshapes, static selects and
shifts, which XLA vectorizes onto the VPU and which is equally valid
inside a Pallas kernel.  Widths 1..32 are supported (dict indices, levels
and delta miniblocks never exceed 32; 64-bit lanes decode as two passes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["unpack_u32", "unpack_u64", "unpack_u32_pallas",
           "pad_to_words", "plan_tables"]


@functools.lru_cache(maxsize=None)
def plan_tables(width: int):
    """Static (word_idx, word_idx2, shift) tables for one width."""
    i = np.arange(32)
    bit = i * width
    widx = bit // 32
    shift = bit % 32
    # The value's high bits live in the next word when shift + width > 32.
    widx2 = np.minimum(widx + 1, width - 1)
    return (
        tuple(widx.tolist()),
        tuple(widx2.tolist()),
        tuple(shift.tolist()),
    )


def pad_to_words(data: bytes | np.ndarray, width: int, count: int) -> np.ndarray:
    """Host-side staging: pad the packed byte stream so it covers whole
    32-value blocks, and return it as little-endian u32 words."""
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(
        data, np.ndarray
    ) else data
    n_blocks = (count + 31) // 32
    need_bytes = n_blocks * width * 4
    if len(buf) < need_bytes:
        padded = np.zeros(need_bytes, dtype=np.uint8)
        padded[: len(buf)] = buf
        buf = padded
    else:
        buf = buf[:need_bytes]
    return buf.view("<u4").reshape(n_blocks, width)


def _unpack_block_math(words, width: int):
    """(n_blocks, width) u32 -> (n_blocks, 32) u32.  Shared by the jnp and
    Pallas implementations."""
    if width == 32:
        return words
    widx, widx2, shift = plan_tables(width)
    widx = jnp.asarray(widx, dtype=jnp.int32)
    widx2 = jnp.asarray(widx2, dtype=jnp.int32)
    shift = jnp.asarray(shift, dtype=jnp.uint32)
    lo = words[:, widx]
    hi = words[:, widx2]
    mask = jnp.uint32((1 << width) - 1)
    # hi contributes only when the value straddles a word boundary;
    # (32 - shift) == 32 is UB, so gate it with where().
    straddle = (shift + width) > 32
    hi_part = jnp.where(
        straddle,
        hi << jnp.where(straddle, 32 - shift.astype(jnp.int32), 0).astype(
            jnp.uint32
        ),
        jnp.uint32(0),
    )
    return ((lo >> shift) | hi_part) & mask


@functools.partial(jax.jit, static_argnames=("width", "count"))
def unpack_u32(words: jax.Array, width: int, count: int) -> jax.Array:
    """Unpack LSB-first ``width``-bit values (device, jnp path).

    ``words``: u32 words from :func:`pad_to_words` — either the 2-D
    (n_blocks, width) matrix or its FLAT 1-D form.  Ship flat: a 2-D
    u32 array with a <=32 minor dim tiles to 128 lanes on TPU (up to
    128/width x transient HBM); the reshape here happens inside the jit
    and fuses into the column gathers.  Returns (count,) u32."""
    if width == 0:
        return jnp.zeros((count,), dtype=jnp.uint32)
    if words.ndim == 1:
        words = words.reshape(-1, width)
    out = _unpack_block_math(words.astype(jnp.uint32), width)
    return out.reshape(-1)[:count]


@functools.lru_cache(maxsize=None)
def plan_tables64(width: int):
    """Static (widx, widx2, widx3, shift) tables for widths up to 64.

    A 32-value block of ``width``-bit values spans exactly ``width`` u32
    words; value i starts at bit i*width, so its 64 bits live in up to
    three consecutive words (two 32-bit chunks at a per-lane shift)."""
    i = np.arange(32)
    bit = i * width
    widx = bit // 32
    shift = bit % 32
    widx2 = np.minimum(widx + 1, width - 1)
    widx3 = np.minimum(widx + 2, width - 1)
    return (
        tuple(widx.tolist()),
        tuple(widx2.tolist()),
        tuple(widx3.tolist()),
        tuple(shift.tolist()),
    )


def _chunk32(w_lo, w_hi, shift):
    """32 bits starting ``shift`` bits into ``w_lo`` (vector shifts;
    shift==0 gated to avoid the undefined <<32)."""
    nonzero = shift > 0
    hi_part = jnp.where(
        nonzero,
        w_hi << jnp.where(nonzero, 32 - shift.astype(jnp.int32), 0).astype(
            jnp.uint32
        ),
        jnp.uint32(0),
    )
    return (w_lo >> shift) | hi_part


@functools.partial(jax.jit, static_argnames=("width", "count"))
def unpack_u64(words: jax.Array, width: int, count: int):
    """Unpack LSB-first ``width``-bit values (width 0..64) into two u32
    lanes: returns ``(lo, hi)`` arrays of shape (count,).

    The 64-bit twin of :func:`unpack_u32` — one formulation instead of
    the reference's generated per-width unpack tables
    (``bitpacking64.go``, 3383 generated LoC)."""
    if width == 0:
        z = jnp.zeros((count,), dtype=jnp.uint32)
        return z, z
    if width <= 32:
        lo = unpack_u32(words, width, count)
        return lo, jnp.zeros((count,), dtype=jnp.uint32)
    if words.ndim == 1:
        words = words.reshape(-1, width)
    words = words.astype(jnp.uint32)
    widx, widx2, widx3, shift = plan_tables64(width)
    shift = jnp.asarray(shift, dtype=jnp.uint32)
    w1 = words[:, jnp.asarray(widx, dtype=jnp.int32)]
    w2 = words[:, jnp.asarray(widx2, dtype=jnp.int32)]
    w3 = words[:, jnp.asarray(widx3, dtype=jnp.int32)]
    lo = _chunk32(w1, w2, shift)
    hi = _chunk32(w2, w3, shift)
    if width < 64:
        hi = hi & jnp.uint32((1 << (width - 32)) - 1)
    return lo.reshape(-1)[:count], hi.reshape(-1)[:count]


def _unpack_block_unrolled(words, width: int):
    """Same math as :func:`_unpack_block_math` but with the per-lane index
    tables unrolled into static Python ints — Pallas kernels may not
    capture array constants, and 32 static shift/or ops map straight onto
    the VPU anyway.

    The word-straddle contribution uses ``hi * 2^k`` instead of
    ``hi << k``: Mosaic (TPU v5e, measured on hardware 2026-07)
    miscompiles the ``(lo >> sh) | (hi << (32 - sh))`` pattern — every
    width >= 17 data-dependently corrupts high bits of the straddle
    contribution, while widths <= 16 (including their straddle lanes,
    e.g. sh=30 at width 3) decode clean and interpret mode is bit-exact
    at every width, so the precise codegen trigger lives in Mosaic.
    The u32-wraparound multiply is the same value for every straddle
    lane and compiles correctly at every width (verified by an on-chip
    sweep vs the CPU oracle, widths 1..32)."""
    if width == 32:
        return words
    widx, widx2, shift = plan_tables(width)
    mask = np.uint32((1 << width) - 1)
    cols = []
    for i in range(32):
        sh = shift[i]
        lo = words[:, widx[i]] >> np.uint32(sh)
        if sh + width > 32:
            lo = lo | (words[:, widx2[i]]
                       * np.uint32((1 << (32 - sh)) & 0xFFFFFFFF))
        cols.append(lo & mask)
    return jnp.stack(cols, axis=1)


def _unpack_kernel(words_ref, out_ref, *, width: int):
    out_ref[:] = _unpack_block_unrolled(words_ref[:], width)


@functools.partial(jax.jit, static_argnames=("width", "count",
                                             "block_rows", "interpret"))
def unpack_u32_pallas(words: jax.Array, width: int, count: int,
                      block_rows: int = 512, interpret: bool = False):
    """Pallas version: grid over row-blocks of the words matrix, VPU
    shift/mask math in VMEM.  Semantics identical to :func:`unpack_u32`.

    Jitted so eager callers (and the A/B harness) don't pay a re-trace
    + re-lower of the pallas_call per invocation; inside the fused page
    kernels the enclosing jit makes this a no-op."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if width == 0:
        return jnp.zeros((count,), dtype=jnp.uint32)
    if not interpret and jax.default_backend() != "tpu":
        interpret = True  # Mosaic only compiles for TPU
    if words.ndim == 1:
        words = words.reshape(-1, width)
    n_blocks = words.shape[0]
    rows = min(block_rows, max(n_blocks, 1))
    grid = (pl.cdiv(n_blocks, rows),)
    padded_blocks = grid[0] * rows
    if padded_blocks != n_blocks:
        words = jnp.pad(words, ((0, padded_blocks - n_blocks), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_unpack_kernel, width=width),
        out_shape=jax.ShapeDtypeStruct((padded_blocks, 32), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, width), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rows, 32), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(words.astype(jnp.uint32))
    return out.reshape(-1)[:count]
