"""Device encode kernels: the write-side twins of the decode set.

SURVEY.md §7 stage 7 ("writer TPU path — encode kernels mirror
decode").  The use case is columns that already live in HBM after TPU
compute: encoding on device ships *encoded* bytes over the narrow
host link instead of raw values (a sorted int64 timestamp column
delta-packs to ~1/3 of its PLAIN bytes; dict indices to width/64).

Same shape discipline as decode (``kernels/decode.py``): static
widths, flat 1-D u32 buffers at every jit boundary, all dynamic
decisions (per-miniblock widths) made on host between two device
phases.  Every kernel is byte-exact with its NumPy twin in
``cpu/bitpack.py`` / ``cpu/delta.py`` — the tests assert identical
wire bytes, not just round-trip equality.

Reference analogues (CPU-only, value-at-a-time there): the generated
pack tables ``bitbacking32.go``/``bitpacking64.go`` (one vectorized
formulation replaces ~4.6k generated LoC, as on the decode side), the
delta encoder ``deltabp_encoder.go`` (block 128 / 4 miniblocks per its
call sites, ``type_bytearray.go:176-180``), and the writer encode
dispatch ``chunk_writer.go:99-159``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# ``jax.enable_x64`` moved out of jax.experimental in newer releases;
# older jaxlibs only ship the experimental spelling.  Same context
# manager either way (both accept the bool flag).
if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:  # pragma: no cover - depends on installed jax
    from jax.experimental import enable_x64

__all__ = [
    "enable_x64",
    "pack_u32_device",
    "pack_u64_device",
    "bss_encode_device",
    "delta_encode_device",
    "DeviceValues",
]


@functools.lru_cache(maxsize=None)
def _pack_tables(width: int):
    """Static per-word contribution tables for one width.

    A 32-value block occupies exactly ``width`` u32 words; word j's 32
    bits [32j, 32j+32) overlap value i's bits [i*w, i*w+w).  Each entry
    is (value_lane, p) where p = 32j - i*w is the bit offset into the
    value whose 32-bit window lands in this word (p < 0: the value
    starts -p bits into the word)."""
    out = []
    for j in range(width):
        lo_bit, hi_bit = 32 * j, 32 * j + 32
        contribs = []
        for i in range(32):
            b = i * width
            if b < hi_bit and b + width > lo_bit:
                contribs.append((i, lo_bit - b))
        out.append(tuple(contribs))
    return tuple(out)


def _pack_block_math(vlo, vhi, width: int):
    """(n_blocks, 32) u32 lane pair -> (n_blocks, width) u32 words.

    ``vhi`` is None for the 32-bit case.  Values MUST already fit in
    ``width`` bits (the delta planner guarantees it; raw callers mask).
    Static shifts only; the straddle uses the same multiply-instead-of-
    shift trick as the decode side (Mosaic miscompiles the shift form
    for sh >= 16 — see bitunpack._unpack_block_unrolled)."""
    words = []
    for contribs in _pack_tables(width):
        acc = None
        for i, p in contribs:
            if p < 0:
                # value starts -p bits into this word: low bits shift up
                c = vlo[:, i] * np.uint32((1 << (-p)) & 0xFFFFFFFF)
            elif p == 0:
                c = vlo[:, i]
            elif p < 32:
                c = vlo[:, i] >> np.uint32(p)
                if vhi is not None:
                    c = c | (vhi[:, i]
                             * np.uint32((1 << (32 - p)) & 0xFFFFFFFF))
            else:
                if vhi is None:
                    continue
                c = vhi[:, i] >> np.uint32(p - 32)
            acc = c if acc is None else (acc | c)
        words.append(acc if acc is not None
                     else jnp.zeros_like(vlo[:, 0]))
    return jnp.stack(words, axis=1)


@functools.partial(jax.jit, static_argnames=("width", "count"))
def pack_u32_device(values: jax.Array, width: int, count: int) -> jax.Array:
    """LSB-first bit-pack of ``count`` u32 values (< 2^width) into flat
    u32 words — the inverse of :func:`bitunpack.unpack_u32`; byte-exact
    with ``cpu.bitpack.pack``.  Input may be longer (padded); the tail
    past ``count`` is zeroed so padding never leaks into the stream."""
    n_blocks = (count + 31) // 32
    if width == 0 or n_blocks == 0:
        return jnp.zeros((0,), dtype=jnp.uint32)
    v = values[: n_blocks * 32]
    if v.shape[0] < n_blocks * 32:
        v = jnp.pad(v, (0, n_blocks * 32 - v.shape[0]))
    idx = jnp.arange(n_blocks * 32, dtype=jnp.int32)
    v = jnp.where(idx < count, v, 0).reshape(n_blocks, 32)
    mask = jnp.uint32(((1 << width) - 1) & 0xFFFFFFFF)
    return _pack_block_math(v & mask, None, width).reshape(-1)


@functools.partial(jax.jit, static_argnames=("width", "count"))
def pack_u64_device(lo: jax.Array, hi: jax.Array, width: int,
                    count: int) -> jax.Array:
    """64-bit twin of :func:`pack_u32_device` for widths 33..64: values
    arrive as (lo, hi) u32 lanes, already < 2^width."""
    n_blocks = (count + 31) // 32
    if width == 0 or n_blocks == 0:
        return jnp.zeros((0,), dtype=jnp.uint32)

    def prep(x):
        x = x[: n_blocks * 32]
        if x.shape[0] < n_blocks * 32:
            x = jnp.pad(x, (0, n_blocks * 32 - x.shape[0]))
        idx = jnp.arange(n_blocks * 32, dtype=jnp.int32)
        return jnp.where(idx < count, x, 0).reshape(n_blocks, 32)

    vlo, vhi = prep(lo), prep(hi)
    if width <= 32:
        mask = jnp.uint32(((1 << width) - 1) & 0xFFFFFFFF)
        return _pack_block_math(vlo & mask, None, width).reshape(-1)
    himask = jnp.uint32(((1 << (width - 32)) - 1) & 0xFFFFFFFF)
    return _pack_block_math(vlo, vhi & himask, width).reshape(-1)


@functools.partial(jax.jit, static_argnames=("count", "k", "lanes"))
def bss_encode_device(flat: jax.Array, count: int, k: int,
                      lanes: int) -> jax.Array:
    """BYTE_STREAM_SPLIT encode: flat (count*lanes,) u32 lane words ->
    (k*count,) u8 stream bytes.  Inverse of ``decode.bss_to_lanes``;
    byte-exact with ``cpu.bss.encode_byte_stream_split``."""
    w = flat[: count * lanes].reshape(count, lanes)
    b = jnp.stack([(w >> (8 * s)) & 0xFF for s in range(4)], axis=2)
    rows = b.reshape(count, lanes * 4)[:, :k].astype(jnp.uint8)
    return rows.T.reshape(-1)


# ----------------------------------------------------------------------
# DELTA_BINARY_PACKED encode: two device phases around one host width
# decision, mirroring the decode planner's width-grouped miniblocks.
# ----------------------------------------------------------------------

_BLOCK = 128
_MINIBLOCKS = 4
_MB = _BLOCK // _MINIBLOCKS


def _sub64(alo, ahi, blo, bhi):
    """(a - b) on u32 lanes with borrow."""
    lo = alo - blo
    borrow = (alo < blo).astype(jnp.uint32)
    return lo, ahi - bhi - borrow


def _bucket_blocks(count: int) -> int:
    """Power-of-two block count covering ``count`` values (min 32).

    Phase-1/phase-2 jits key on SHAPES with the true count traced, so
    arbitrary per-page value counts compile O(log) kernel variants, not
    one per count (a writer streaming variable pages would otherwise
    recompile per page)."""
    from .decode import bucket

    need = max((max(count - 1, 1) + _BLOCK - 1) // _BLOCK, 1)
    return bucket(need)


def _pad_flat(flat, lanes: int, nb: int):
    want = (nb * _BLOCK + 1) * lanes
    if flat.shape[0] < want:
        flat = jnp.pad(flat, (0, want - flat.shape[0]))
    return flat[:want]


@jax.jit
def _delta_phase1_i64(flat: jax.Array, valid):
    """Flat (2*(NB*128+1),) u32 interleaved i64 lanes (bucket-padded,
    true count ``valid`` traced) -> per-block min_delta lanes,
    per-miniblock adjusted maxima lanes, and the adjusted delta stream
    (device-resident for phase 2)."""
    c = flat.shape[0] // 2
    v = flat.reshape(c, 2)
    lo, hi = v[:, 0], v[:, 1]
    dlo, dhi = _sub64(lo[1:], hi[1:], lo[:-1], hi[:-1])
    nd = c - 1                      # == NB * _BLOCK
    nb = nd // _BLOCK
    idx = jnp.arange(nd, dtype=jnp.int32)
    live = idx < (valid - 1)
    # dead lanes become i64 max so they never win the min
    dlo = jnp.where(live, dlo, jnp.uint32(0xFFFFFFFF))
    dhi = jnp.where(live, dhi, jnp.uint32(0x7FFFFFFF))
    blo = dlo.reshape(nb, _BLOCK)
    bhi = dhi.reshape(nb, _BLOCK)
    # signed i64 min per block via lexicographic (hi signed, lo unsigned)
    shi = bhi.astype(jnp.int32)

    def min_pair(a, b):
        alo, ahi = a
        blo_, bhi_ = b
        a_less = (ahi < bhi_) | ((ahi == bhi_) & (alo < blo_))
        return (jnp.where(a_less, alo, blo_),
                jnp.where(a_less, ahi, bhi_))

    mlo, mhi = blo, shi
    k = _BLOCK
    while k > 1:
        k //= 2
        mlo, mhi = min_pair(
            (mlo[:, :k], mhi[:, :k]), (mlo[:, k:2 * k], mhi[:, k:2 * k]))
    min_lo, min_hi = mlo[:, 0], mhi[:, 0].astype(jnp.uint32)
    # adjusted = delta - min_delta (u64 lanes), dead lanes forced to 0
    alo, ahi = _sub64(blo.reshape(-1), bhi.reshape(-1),
                      jnp.repeat(min_lo, _BLOCK),
                      jnp.repeat(min_hi, _BLOCK))
    alo = jnp.where(live, alo, 0)
    ahi = jnp.where(live, ahi, 0)
    # per-miniblock max (u64): lexicographic on (hi unsigned, lo)
    xlo = alo.reshape(nb * _MINIBLOCKS, _MB)
    xhi = ahi.reshape(nb * _MINIBLOCKS, _MB)

    def max_pair(a, b):
        alo_, ahi_ = a
        blo_, bhi_ = b
        a_more = (ahi_ > bhi_) | ((ahi_ == bhi_) & (alo_ > blo_))
        return (jnp.where(a_more, alo_, blo_),
                jnp.where(a_more, ahi_, bhi_))

    qlo, qhi = xlo, xhi
    k = _MB
    while k > 1:
        k //= 2
        qlo, qhi = max_pair(
            (qlo[:, :k], qhi[:, :k]), (qlo[:, k:2 * k], qhi[:, k:2 * k]))
    return (min_lo, min_hi, qlo[:, 0], qhi[:, 0], alo, ahi)


@jax.jit
def _delta_phase1_i32(flat: jax.Array, valid):
    """32-bit twin of :func:`_delta_phase1_i64`: single-lane u32 math
    (the host is32 path wraps deltas at 32 bits, cpu/delta.py)."""
    c = flat.shape[0]
    v = flat
    d = v[1:] - v[:-1]  # u32 wraparound == two's-complement i32 delta
    nd = c - 1
    nb = nd // _BLOCK
    idx = jnp.arange(nd, dtype=jnp.int32)
    live = idx < (valid - 1)
    # dead lanes become i32 max so they never win the signed min
    d = jnp.where(live, d, jnp.uint32(0x7FFFFFFF))
    b = d.reshape(nb, _BLOCK)
    mins = jnp.min(b.astype(jnp.int32), axis=1)
    # adjusted = delta - min in [0, 2^32): u32 wrap equals the host's
    # 64-bit subtraction of values within the i32 range
    adj = b - mins.astype(jnp.uint32)[:, None]
    adj = jnp.where(live.reshape(nb, _BLOCK), adj, 0)
    mx = jnp.max(adj.reshape(nb * _MINIBLOCKS, _MB), axis=1)
    return mins, mx, adj.reshape(-1)


@functools.partial(jax.jit, static_argnames=("width",))
def _pack_masked32(values: jax.Array, valid, width: int) -> jax.Array:
    """Bucket-shaped pack: ``values`` length is a padded multiple of 32
    (jit keys on the bucket shape), the true count ``valid`` is traced,
    dead lanes zeroed before packing."""
    idx = jnp.arange(values.shape[0], dtype=jnp.int32)
    v = jnp.where(idx < valid, values, 0).reshape(-1, 32)
    mask = jnp.uint32(((1 << width) - 1) & 0xFFFFFFFF)
    return _pack_block_math(v & mask, None, width).reshape(-1)


@functools.partial(jax.jit, static_argnames=("width",))
def _pack_masked64(lo: jax.Array, hi: jax.Array, valid,
                   width: int) -> jax.Array:
    idx = jnp.arange(lo.shape[0], dtype=jnp.int32)
    vlo = jnp.where(idx < valid, lo, 0).reshape(-1, 32)
    vhi = jnp.where(idx < valid, hi, 0).reshape(-1, 32)
    himask = jnp.uint32(((1 << (width - 32)) - 1) & 0xFFFFFFFF)
    return _pack_block_math(vlo, vhi & himask, width).reshape(-1)


def delta_encode_device(flat, count: int, is32: bool = False) -> bytes:
    """DELTA_BINARY_PACKED encode with the deltas, minima, maxima and
    miniblock packing computed ON DEVICE; byte-identical to
    ``cpu.delta.encode_delta_binary_packed`` (block 128, 4 miniblocks).

    ``flat``: device (or host) flat u32 lanes — (count*2,) interleaved
    (lo, hi) for int64, (count,) for int32 (``is32=True``, which wraps
    deltas at 32 bits exactly like the host encoder).  Only the packed
    miniblock words, per-block minima and per-miniblock maxima cross
    back to the host; for a sorted timestamp column that is ~1/3 of the
    PLAIN bytes."""
    from ..varint import write_uvarint, write_zigzag

    from ..cpu.delta import widths_from_max
    from .decode import bucket

    flat2 = jnp.asarray(flat)
    lanes = 1 if is32 else 2
    out = bytearray()
    write_uvarint(out, _BLOCK)
    write_uvarint(out, _MINIBLOCKS)
    write_uvarint(out, count)
    if count == 0:
        write_zigzag(out, 0)
        return bytes(out)
    first_lanes = np.asarray(flat2[:lanes])  # one transfer
    if is32:
        v0 = int(first_lanes[0])
        first = v0 - (1 << 32) if v0 >= (1 << 31) else v0
    else:
        v0 = int(first_lanes[0]) | (int(first_lanes[1]) << 32)
        first = v0 - (1 << 64) if v0 >= (1 << 63) else v0
    write_zigzag(out, first)
    if count == 1:
        return bytes(out)

    nb_bucket = _bucket_blocks(count)
    padded = _pad_flat(flat2, lanes, nb_bucket)
    nb = (count - 1 + _BLOCK - 1) // _BLOCK  # true block count
    if is32:
        mins, mx, alo = _delta_phase1_i32(padded, count)
        minima = np.asarray(mins)[:nb].astype(np.int64)
        mb_max = np.asarray(mx)[: nb * _MINIBLOCKS].astype(np.uint64)
        ahi = None
    else:
        min_lo, min_hi, mx_lo, mx_hi, alo, ahi = _delta_phase1_i64(
            padded, count)
        minima = (np.asarray(min_lo)[:nb].astype(np.uint64)
                  | (np.asarray(min_hi)[:nb].astype(np.uint64)
                     << np.uint64(32))).view(np.int64)
        mb_max = (np.asarray(mx_lo)[: nb * _MINIBLOCKS].astype(np.uint64)
                  | (np.asarray(mx_hi)[: nb * _MINIBLOCKS].astype(np.uint64)
                     << np.uint64(32)))
    widths = widths_from_max(mb_max)

    # phase 2: pack all miniblocks of one width in one device call.
    # The gather/pack shapes bucket so the jit cache stays O(widths x
    # log(size)), not one entry per data-dependent miniblock count.
    payloads: list[bytes] = [b""] * len(widths)
    for w in np.unique(widths):
        w = int(w)
        if w == 0:
            continue
        idx = np.nonzero(widths == w)[0]
        cnt = len(idx) * _MB
        cap = bucket(cnt)
        sel = np.zeros(cap, dtype=np.int32)
        sel[:cnt] = (idx[:, None] * _MB
                     + np.arange(_MB)[None, :]).reshape(-1)
        sel_dev = jnp.asarray(sel)
        if w <= 32:
            words = _pack_masked32(alo[sel_dev], cnt, w)
        else:
            words = _pack_masked64(alo[sel_dev], ahi[sel_dev], cnt, w)
        raw = np.asarray(words).tobytes()
        step = _MB * w // 8
        for j, i in enumerate(idx):
            payloads[i] = raw[j * step : (j + 1) * step]

    widths_b = widths.astype(np.uint8).tobytes()
    for b in range(nb):
        write_zigzag(out, int(minima[b]))
        out.extend(widths_b[b * _MINIBLOCKS : (b + 1) * _MINIBLOCKS])
        for p in payloads[b * _MINIBLOCKS : (b + 1) * _MINIBLOCKS]:
            out.extend(p)
    return bytes(out)


class DeviceValues:
    """Device-resident fixed-width column values for the columnar write
    path (``FileWriter.write_columns``): the values stay in HBM through
    validation and statistics, and DELTA_BINARY_PACKED (int64),
    BYTE_STREAM_SPLIT and PLAIN pages encode on device — only encoded
    bytes and two stat scalars cross the host link.  Small-range
    integer columns dictionary-encode via a DEVICE-side intern
    (:func:`device_dict_build`): the index stream crosses at 4 bytes
    per value instead of the unpacked column, and the file matches the
    host path byte for byte.

    ``flat``: flat u32 lane words (the DeviceColumn layout: lanes
    interleaved little-endian, ``itemsize//4`` words per value);
    ``dtype``: the logical dtype — int32/int64/float32/float64.
    Combine with ``column_encodings`` to force DELTA or BSS.
    """

    __slots__ = ("flat", "dtype")

    def __init__(self, flat, dtype):
        self.dtype = np.dtype(dtype)
        if self.dtype not in (np.dtype(np.int32), np.dtype(np.int64),
                              np.dtype(np.float32), np.dtype(np.float64)):
            raise TypeError(
                f"DeviceValues supports int32/int64/float32/float64, "
                f"got {self.dtype}")
        self.flat = jnp.asarray(flat)
        if self.flat.dtype != jnp.uint32 or self.flat.ndim != 1:
            raise TypeError("flat must be a 1-D uint32 lane array")
        if self.flat.shape[0] % self.lanes:
            raise ValueError(
                f"lane array length {self.flat.shape[0]} not a multiple "
                f"of {self.lanes}")

    @property
    def lanes(self) -> int:
        return self.dtype.itemsize // 4

    @property
    def count(self) -> int:
        """Derived from the lane buffer (never stored), so tree
        transforms that reshape the leaf can't desync it."""
        return self.flat.shape[0] // self.lanes

    def __len__(self) -> int:
        return self.count

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.flat).view(self.dtype)

    def min_max(self, unsigned: bool = False):
        """(min, max) as numpy scalars of the storage dtype — computed on
        device, only two scalars cross to host.  Mirrors
        ``io.values.Handler.min_max``: NaNs excluded, (None, None) when
        empty or all-NaN; ``unsigned`` orders integers as u32/u64 but
        returns signed-storage values."""
        if self.count == 0:
            return None, None
        with enable_x64(True):
            v = self.flat
            if self.lanes == 2:
                v = jax.lax.bitcast_convert_type(
                    v.reshape(-1, 2),
                    jnp.uint64 if unsigned else
                    (jnp.float64 if self.dtype.kind == "f" else jnp.int64))
            elif self.dtype.kind == "f":
                v = jax.lax.bitcast_convert_type(v, jnp.float32)
            elif unsigned:
                pass  # u32 order is the lane dtype's own
            else:
                v = jax.lax.bitcast_convert_type(v, jnp.int32)
            if self.dtype.kind == "f":
                mn, mx = jnp.nanmin(v), jnp.nanmax(v)
            else:
                mn, mx = jnp.min(v), jnp.max(v)
            mn, mx = np.asarray(mn)[()], np.asarray(mx)[()]
        if self.dtype.kind == "f":
            if np.isnan(mn):
                return None, None
            return self.dtype.type(mn), self.dtype.type(mx)
        if unsigned:
            store = np.int32 if self.dtype.itemsize == 4 else np.int64
            return (np.asarray(mn).view(store)[()],
                    np.asarray(mx).view(store)[()])
        return self.dtype.type(mn), self.dtype.type(mx)

    def encode(self, ptype, encoding) -> bytes:
        """Encode one page's values on device; returns the wire bytes."""
        from ..format.metadata import Encoding, Type
        from ..stats import current_stats

        st = current_stats()
        if st is not None:
            st.pages_device_encoded += 1
        if encoding == Encoding.PLAIN:
            # PLAIN little-endian value bytes == the LE lane words' bytes
            return np.asarray(self.flat).tobytes()
        if encoding == Encoding.DELTA_BINARY_PACKED:
            return delta_encode_device(self.flat, self.count,
                                       is32=(ptype == Type.INT32))
        if encoding == Encoding.BYTE_STREAM_SPLIT:
            out = bss_encode_device(self.flat, self.count,
                                    self.dtype.itemsize, self.lanes)
            return np.asarray(out).tobytes()
        raise ValueError(
            f"DeviceValues cannot encode {encoding!r}; supported: PLAIN, "
            "DELTA_BINARY_PACKED, BYTE_STREAM_SPLIT")


def device_dict_build(dv: "DeviceValues"):
    """Device-side dictionary interning for small-range integer
    ``DeviceValues`` columns: the range table, first-occurrence order
    and per-value indices all compute in HBM, and only the int32 index
    stream plus the tiny order table cross to the host (4 wire bytes
    per value instead of the unpacked column).

    Returns ``(dictionary ndarray, pull)`` where ``pull()`` fetches
    the int32 index stream — deferred so the caller's dictionary-size
    gates run BEFORE the only per-value transfer.  The order is
    EXACTLY the host interner's first-occurrence order
    (``cpu/dictionary._build_int_dictionary_smallrange``), so for
    small-RANGE columns the written file is byte-identical to encoding
    the same values from a numpy array.  None when the range gate
    rejects; a KNOWN divergence from the host path: wide-range but
    few-distinct columns (host np.unique still dict-encodes them)
    stay on the non-dict device encodes — interning them would need a
    device sort over 64-bit lanes."""
    if dv.dtype.kind != "i":
        return None
    n = dv.count
    if n == 0:
        return None
    lo, hi = dv.min_max()
    rng = int(hi) - int(lo) + 1  # Python ints: no wraparound
    if rng > 4 * n or rng > 1 << 24:
        return None  # same gate as the host interner
    # (value - lo) < 2**24 fits the LOW lane's u32 wraparound exactly,
    # so the high lane of int64 columns never participates
    lo_lane = np.uint32(int(lo) & 0xFFFFFFFF)
    vals_lo = dv.flat[:: dv.lanes] if dv.lanes > 1 else dv.flat
    off = (vals_lo - lo_lane).astype(jnp.int32)
    first = jnp.full(rng, n, dtype=jnp.int32).at[off].min(
        jnp.arange(n, dtype=jnp.int32))
    # present entries (first < n) sort before absent ones, in
    # first-occurrence order; ties are impossible
    order_full = jnp.argsort(first)
    dsize = int(jnp.sum(first < n))
    order = order_full[:dsize]
    rank = jnp.zeros(rng, dtype=jnp.int32).at[order].set(
        jnp.arange(dsize, dtype=jnp.int32))
    indices = rank[off]
    dict_np = (np.asarray(order).astype(np.int64) + int(lo)).astype(
        dv.dtype)
    return dict_np, lambda: np.asarray(indices)


def _devicevalues_unflatten(aux, leaves):
    # bypass __init__: pytree unflattening may pass dummy leaves while
    # manipulating tree structure, which must not be validated
    obj = DeviceValues.__new__(DeviceValues)
    (obj.dtype,) = aux
    obj.flat = leaves[0]
    return obj


# DeviceValues is a JAX pytree (lane buffer is the leaf; dtype static
# aux — count derives from the leaf, so leaf-reshaping transforms stay
# consistent): jitted producers can return one directly, and it feeds
# write_columns without leaving the device.
jax.tree_util.register_pytree_node(
    DeviceValues,
    lambda v: ((v.flat,), (v.dtype,)),
    _devicevalues_unflatten,
)
