"""Device decode orchestration: column chunks -> device-resident columns.

The cuDF-style batch-decode backend of BASELINE.json: raw page bytes are
staged to device memory and decoded by vectorized kernels; the host only
parses headers and builds plan tables.  Output is Arrow-layout
:class:`DeviceColumn` objects (packed values + validity + levels), which
``to_numpy()`` materializes in exactly the CPU oracle's representation for
bit-exact parity checks.

Current device coverage (the rest falls back to the CPU oracle per value
segment, still staged into the same DeviceColumn):

* PLAIN int32/int64/float/double/int96/FLBA (reinterpret staging)
* PLAIN boolean (width-1 unpack)
* RLE_DICTIONARY indices (run-table expand) + dictionary gather,
  fixed-width and variable-width (byte-level gather)
* definition/repetition levels (run-table expand) + validity fusion
* DELTA_BINARY_PACKED int32
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..compress import decompress_block
from ..cpu import decode_plain
from ..cpu.plain import ByteArrayColumn
from ..format.compact import CompactReader
from ..format.metadata import (
    ColumnMetaData,
    CompressionCodec,
    Encoding,
    PageHeader,
    PageType,
    Type,
    decode_struct,
)
from ..format.schema import SchemaNode
from .bitunpack import pad_to_words, unpack_u32
from .decode import (
    dict_gather_bytes,
    dict_gather_fixed,
    expand_delta_i32,
    levels_to_validity,
    plain_fixed_to_lanes,
    plan_delta_i32,
    stage_u32,
)
from .hybrid import decode_hybrid_device

__all__ = ["DeviceColumn", "decode_chunk_device", "read_row_group_device"]

_LANES = {
    Type.INT32: 1, Type.FLOAT: 1, Type.INT64: 2, Type.DOUBLE: 2,
    Type.INT96: 3,
}

_DICT_ENCODINGS = (Encoding.PLAIN_DICTIONARY, Encoding.RLE_DICTIONARY)


class DeviceColumn:
    """Device-resident decoded column (Arrow layout).

    ``data``: (n_non_null, lanes) u32 for fixed-width types, or u8 bytes
    with ``offsets`` for BYTE_ARRAY.  ``mask``/``positions`` map record
    slots to packed values; ``rep_levels``/``def_levels`` preserve nesting.
    """

    __slots__ = ("ptype", "type_length", "data", "offsets", "mask",
                 "positions", "rep_levels", "def_levels", "num_values")

    def __init__(self, ptype, type_length, data, offsets, mask, positions,
                 rep_levels, def_levels, num_values):
        self.ptype = ptype
        self.type_length = type_length
        self.data = data
        self.offsets = offsets
        self.mask = mask
        self.positions = positions
        self.rep_levels = rep_levels
        self.def_levels = def_levels
        self.num_values = num_values

    def block_until_ready(self):
        for x in (self.data, self.offsets, self.mask, self.rep_levels,
                  self.def_levels):
            if x is not None:
                x.block_until_ready()
        return self

    def to_numpy(self):
        """Materialize to the CPU oracle's chunk representation:
        (values, rep_levels, def_levels)."""
        rep = np.asarray(self.rep_levels, dtype=np.int32)
        dl = np.asarray(self.def_levels, dtype=np.int32)
        if self.offsets is not None:
            offs = np.asarray(self.offsets, dtype=np.int64)
            data = np.asarray(self.data, dtype=np.uint8)[: int(offs[-1])]
            return ByteArrayColumn(offs, data), rep, dl
        lanes = np.asarray(self.data, dtype=np.uint32)
        if self.ptype == Type.BOOLEAN:
            return lanes.reshape(-1).astype(bool), rep, dl
        if self.ptype == Type.INT32:
            return lanes.reshape(-1).view(np.int32), rep, dl
        if self.ptype == Type.FLOAT:
            return lanes.reshape(-1).view(np.float32), rep, dl
        if self.ptype == Type.INT64:
            return lanes.reshape(-1).view(np.uint8).view("<i8"), rep, dl
        if self.ptype == Type.DOUBLE:
            return lanes.reshape(-1).view(np.uint8).view("<f8"), rep, dl
        if self.ptype == Type.INT96:
            return lanes.reshape(-1, 3), rep, dl
        if self.ptype == Type.FIXED_LEN_BYTE_ARRAY:
            n = self.type_length
            return (
                lanes.reshape(-1).view(np.uint8).reshape(-1, 4 * lanes.shape[1])[:, :n],
                rep, dl,
            )
        raise TypeError(f"unsupported type {self.ptype}")


def _stage_fixed_plain(raw: bytes, count: int, ptype: Type,
                       type_length) -> jax.Array:
    if ptype == Type.BOOLEAN:
        words = pad_to_words(np.frombuffer(raw, np.uint8), 1, count)
        return unpack_u32(jnp.asarray(words), 1, count)[:, None]
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        return _stage_byte_rows(
            np.frombuffer(raw, np.uint8, count * type_length).reshape(
                count, type_length
            )
        )
    lanes = _LANES[ptype]
    words = stage_u32(raw, count * lanes)
    return plain_fixed_to_lanes(jnp.asarray(words), count, lanes)


def _flba_lanes(type_length: int) -> int:
    return (type_length + 3) // 4


def _stage_byte_rows(arr: np.ndarray) -> jax.Array:
    """(N, L) u8 rows -> (N, lanes) u32, zero-padding each row to whole
    little-endian u32 lanes (shared FLBA/int96 staging)."""
    rows = arr.view(np.uint8).reshape(arr.shape[0], -1)
    lanes = _flba_lanes(rows.shape[1])
    padded = np.zeros((rows.shape[0], lanes * 4), dtype=np.uint8)
    padded[:, : rows.shape[1]] = rows
    return jnp.asarray(padded.reshape(-1, lanes, 4).view("<u4")[..., 0])


def _levels_host(data, n: int, max_level: int, enc: str) -> np.ndarray:
    """Host-side def-level decode, used only to count non-nulls without a
    device->host sync.  Delegates to the CPU oracle's level decoders
    (incl. their level-range validation).  ``enc``: "v1_rle"
    (length-prefixed hybrid), "bit_packed" (legacy MSB-first), or
    "v2_raw" (unprefixed hybrid)."""
    from ..cpu.levels import (
        decode_levels_bitpacked,
        decode_levels_raw,
        decode_levels_v1,
    )

    if enc == "bit_packed":
        return decode_levels_bitpacked(data, n, max_level)
    if enc == "v1_rle":
        return decode_levels_v1(data, n, max_level)[0]
    return decode_levels_raw(data, n, max_level)


def decode_chunk_device(blob, cm: ColumnMetaData, node: SchemaNode,
                        base: int = 0) -> DeviceColumn:
    """Decode one column chunk to a DeviceColumn.

    ``blob`` holds the chunk's byte range; offsets in ``cm`` are absolute
    minus ``base``.  Host work: page-header walk, block decompression
    (until the device snappy path lands), plan building.
    """
    codec = CompressionCodec(cm.codec)
    ptype = Type(node.element.type)
    start = cm.data_page_offset
    if cm.dictionary_page_offset is not None:
        start = min(start, cm.dictionary_page_offset)
    start -= base
    end = start + cm.total_compressed_size
    r = CompactReader(blob, start, end)

    dict_fixed = None      # staged (D, lanes) u32
    dict_offsets = None    # staged byte-array dictionary
    dict_data = None
    dict_lens_np = None
    dict_np = None

    val_parts = []         # device arrays, (n, lanes) u32
    bytes_parts = []       # (lens_np, device u8 data) per page for BYTE_ARRAY
    rep_parts = []
    def_parts = []
    values_read = 0
    total = cm.num_values

    while values_read < total:
        ph = decode_struct(PageHeader, r)
        payload = bytes(blob[r.pos : r.pos + ph.compressed_page_size])
        r.pos += ph.compressed_page_size
        ptype_page = PageType(ph.type)

        if ptype_page == PageType.DICTIONARY_PAGE:
            raw = decompress_block(codec, payload, ph.uncompressed_page_size)
            dict_np = decode_plain(
                ptype, raw, ph.dictionary_page_header.num_values,
                node.element.type_length,
            )
            if isinstance(dict_np, ByteArrayColumn):
                dict_offsets = jnp.asarray(dict_np.offsets, dtype=jnp.int32)
                dict_data = jnp.asarray(dict_np.data)
                dict_lens_np = dict_np.lengths()
            else:
                arr = np.asarray(dict_np)
                if arr.dtype == np.bool_:
                    staged = arr.astype(np.uint32)[:, None]
                elif arr.dtype in (np.dtype("<i4"), np.dtype("<f4")):
                    staged = arr.view("<u4")[:, None]
                elif arr.dtype in (np.dtype("<i8"), np.dtype("<f8")):
                    staged = arr.view("<u4").reshape(-1, 2)
                elif ptype == Type.INT96:
                    staged = arr.astype("<u4")
                else:  # FLBA (D, L) u8
                    staged = _stage_byte_rows(arr)
                dict_fixed = jnp.asarray(staged)
            if r.pos != cm.data_page_offset - base:
                r.pos = cm.data_page_offset - base
            continue

        if ptype_page == PageType.DATA_PAGE:
            h = ph.data_page_header
            raw = decompress_block(codec, payload, ph.uncompressed_page_size)
            n = h.num_values
            pos = 0
            rep_dev, pos, _ = _levels_v1_device(
                raw, n, node.max_rep_level, pos,
                h.repetition_level_encoding,
            )
            dl_start = pos
            dl_dev, pos, dl_host = _levels_v1_device(
                raw, n, node.max_def_level, pos,
                h.definition_level_encoding,
            )
            level_bytes = raw[dl_start:pos]
            level_enc = "v1_rle"
            values_seg = raw[pos:]
            enc = h.encoding
        elif ptype_page == PageType.DATA_PAGE_V2:
            h = ph.data_page_header_v2
            n = h.num_values
            rl_len = h.repetition_levels_byte_length or 0
            dl_len = h.definition_levels_byte_length or 0
            rep_dev = _levels_raw_device(
                payload[:rl_len], n, node.max_rep_level
            )
            level_bytes = payload[rl_len : rl_len + dl_len]
            level_enc = "v2_raw"
            dl_host = None
            dl_dev = _levels_raw_device(level_bytes, n, node.max_def_level)
            values_seg = payload[rl_len + dl_len :]
            if h.is_compressed is not False:
                values_seg = decompress_block(
                    codec, values_seg,
                    ph.uncompressed_page_size - rl_len - dl_len,
                )
            enc = h.encoding
        else:
            continue

        if not node.max_def_level:
            non_null = n
        elif (ptype_page == PageType.DATA_PAGE_V2
              and h.num_nulls is not None):
            non_null = n - h.num_nulls
        else:
            # count non-nulls from the host-side level bytes (cheap,
            # vectorized) rather than syncing the device expansion back —
            # device->host round-trips serialize the page pipeline
            if dl_host is None:
                dl_host = _levels_host(level_bytes, n, node.max_def_level,
                                       level_enc)
            non_null = int((dl_host == node.max_def_level).sum())
        rep_parts.append(rep_dev)
        def_parts.append(dl_dev)
        values_read += n

        if enc in _DICT_ENCODINGS:
            width = values_seg[0] if len(values_seg) else 0
            if dict_fixed is not None:
                idx = decode_hybrid_device(
                    values_seg, non_null, width, pos=1
                ).astype(jnp.int32) if width else jnp.zeros(
                    (non_null,), jnp.int32
                )
                val_parts.append(dict_gather_fixed(dict_fixed, idx))
            elif dict_offsets is not None:
                # host-side index decode (vectorized, no device sync) just
                # to size the output; the gather uses the device indices
                from ..cpu.hybrid import decode_hybrid
                from .decode import bucket
                from .hybrid import decode_hybrid_device_padded

                idx_np = (
                    decode_hybrid(values_seg, non_null, width, pos=1)
                    .astype(np.int32)
                    if width else np.zeros(non_null, np.int32)
                )
                lens = dict_lens_np[idx_np]
                out_offsets = np.zeros(non_null + 1, dtype=np.int32)
                np.cumsum(lens, out=out_offsets[1:])
                total_b = int(out_offsets[-1])
                # every dynamic input stays at its bucket size so the jit
                # cache keys on buckets, not exact per-page counts
                cap = bucket(max(total_b, 1))
                idx_pad = decode_hybrid_device_padded(
                    values_seg, non_null, width, pos=1
                ).astype(jnp.int32) if width else jnp.zeros(
                    (bucket(max(non_null, 1)),), jnp.int32
                )
                offs_pad = np.full(idx_pad.shape[0] + 1, total_b,
                                   dtype=np.int32)
                offs_pad[: non_null + 1] = out_offsets
                data = dict_gather_bytes(
                    dict_offsets, dict_data, idx_pad,
                    jnp.asarray(offs_pad), cap,
                )
                bytes_parts.append((out_offsets, data, total_b))
            else:
                raise ValueError("dict-encoded page without dictionary")
        elif enc == Encoding.PLAIN:
            if ptype == Type.BYTE_ARRAY:
                col = decode_plain(ptype, values_seg, non_null)  # host scan
                offs = col.offsets.astype(np.int32)
                bytes_parts.append(
                    (offs, jnp.asarray(col.data), int(col.data.size))
                )
            else:
                val_parts.append(
                    _stage_fixed_plain(values_seg, non_null, ptype,
                                       node.element.type_length)
                )
        elif enc == Encoding.DELTA_BINARY_PACKED and ptype == Type.INT32:
            plan = plan_delta_i32(values_seg)
            val_parts.append(expand_delta_i32(plan)[:non_null, None])
        else:
            # CPU fallback for the remaining encodings; stage the result.
            col = decode_values_cpu(ptype, enc, values_seg, non_null,
                                    node.element.type_length)
            if isinstance(col, ByteArrayColumn):
                bytes_parts.append(
                    (col.offsets.astype(np.int32), jnp.asarray(col.data),
                     int(col.data.size))
                )
            else:
                val_parts.append(_stage_numpy_fixed(col, ptype))

    rep = jnp.concatenate(rep_parts) if rep_parts else jnp.zeros(0, jnp.int32)
    dl = jnp.concatenate(def_parts) if def_parts else jnp.zeros(0, jnp.int32)
    mask, positions = levels_to_validity(dl.astype(jnp.int32),
                                         node.max_def_level) \
        if node.max_def_level else (
            jnp.ones(total, dtype=bool),
            jnp.arange(total, dtype=jnp.int32),
        )

    if bytes_parts:
        # merge per-page byte columns: rebase offsets, concat data
        all_offs = [np.zeros(1, dtype=np.int64)]
        datas = []
        base_off = 0
        for offs, data, nbytes in bytes_parts:
            all_offs.append(np.asarray(offs[1:], dtype=np.int64) + base_off)
            datas.append(jnp.asarray(data)[:nbytes])
            base_off += nbytes
        offsets = jnp.asarray(np.concatenate(all_offs))
        data = jnp.concatenate(datas) if datas else jnp.zeros(0, jnp.uint8)
        return DeviceColumn(ptype, node.element.type_length, data, offsets,
                            mask, positions, rep, dl, total)

    if val_parts:
        data = jnp.concatenate(val_parts)
    else:
        data = jnp.zeros((0, 1), dtype=jnp.uint32)
    return DeviceColumn(ptype, node.element.type_length, data, None, mask,
                        positions, rep, dl, total)


def read_row_group_device(reader, rg_index: int) -> dict[str, DeviceColumn]:
    """Decode the selected columns of one row group onto the device.

    The device-path sibling of ``FileReader.read_row_group_arrays``: same
    selection semantics, device-resident results."""
    rg = reader.meta.row_groups[rg_index]
    out = {}
    for path, node, cm, blob, start in reader.iter_selected_chunks(rg):
        out[path] = decode_chunk_device(memoryview(blob), cm, node,
                                        base=start)
    return out


def decode_values_cpu(ptype, enc, data, count, type_length):
    from ..io.pages import decode_values

    return decode_values(ptype, enc, data, count, type_length)


def _stage_numpy_fixed(col, ptype: Type) -> jax.Array:
    arr = np.asarray(col)
    if arr.dtype == np.bool_:
        return jnp.asarray(arr.astype(np.uint32)[:, None])
    if arr.dtype.itemsize == 4:
        return jnp.asarray(arr.view("<u4").reshape(-1, 1))
    if arr.dtype.itemsize == 8:
        return jnp.asarray(arr.view("<u4").reshape(-1, 2))
    if arr.ndim == 2:  # FLBA / int96 byte matrices
        return _stage_byte_rows(arr)
    raise TypeError(f"cannot stage {arr.dtype} for {ptype}")


def _levels_v1_device(raw, n, max_level, pos, encoding=Encoding.RLE):
    """Returns (device levels, end pos, host levels | None).  Host levels
    are populated when the decode already happened on host (BIT_PACKED),
    so callers never decode the same bytes twice."""
    if max_level == 0:
        return jnp.zeros((n,), dtype=jnp.int32), pos, None
    width = max_level.bit_length()
    if encoding == Encoding.BIT_PACKED:
        # Legacy MSB-first levels (old parquet-mr writers): decode on host
        # via the oracle and stage — rare enough not to warrant a kernel.
        from ..cpu import decode_levels_bitpacked

        nbytes = (n * width + 7) // 8
        vals = decode_levels_bitpacked(raw[pos : pos + nbytes], n, max_level)
        return jnp.asarray(vals, dtype=jnp.int32), pos + nbytes, vals
    import struct

    (size,) = struct.unpack_from("<I", raw, pos)
    body = raw[pos + 4 : pos + 4 + size]
    vals = decode_hybrid_device(body, n, width)
    return vals.astype(jnp.int32), pos + 4 + size, None


def _levels_raw_device(raw, n, max_level):
    if max_level == 0:
        return jnp.zeros((n,), dtype=jnp.int32)
    width = max_level.bit_length()
    return decode_hybrid_device(raw, n, width).astype(jnp.int32)
