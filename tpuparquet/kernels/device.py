"""Device decode orchestration: column chunks -> device-resident columns.

The cuDF-style batch-decode backend of BASELINE.json: raw page bytes are
staged to device memory and decoded by vectorized kernels; the host only
parses headers and builds plan tables.  Output is Arrow-layout
:class:`DeviceColumn` objects (packed values + validity + levels), which
``to_numpy()`` materializes in exactly the CPU oracle's representation for
bit-exact parity checks.

Device coverage — every value encoding the format defines:

* PLAIN int32/int64/float/double/int96/FLBA (reinterpret staging)
* PLAIN boolean (width-1 unpack) and RLE boolean (run-table expand)
* RLE_DICTIONARY indices (run-table expand) + dictionary gather,
  fixed-width and variable-width (byte-level gather)
* definition/repetition levels (run-table expand) + validity fusion
* DELTA_BINARY_PACKED int32 and int64 (two-u32-lane arithmetic)
* BYTE_STREAM_SPLIT int32/int64/float/double/FLBA (device transpose)
* DELTA_LENGTH_BYTE_ARRAY (host length scan, zero-copy payload staging)
* DELTA_BYTE_ARRAY (front coding = the snappy kernel's copy graph;
  non-expanding pages assemble on host, chosen per page because it
  ships STRICTLY fewer bytes, not for lack of a kernel — wire-neutral
  pages take the device kernel.  The golden exception list
  ``HOST_ASSEMBLY_EXCEPTIONS`` in ``tests/test_fallback_matrix.py``
  pins exactly which (type, encoding) combinations may do this, and
  its wire-number pin asserts every host-assembled page really
  shipped fewer bytes than the compact wire form)
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..compress import decompress_block, decompress_block_into
from ..cpu import decode_plain
from ..errors import CorruptChunkError, CorruptPageError, \
    DeviceDispatchError, ScanError
from ..faults import backoff_delays, fault_point, filter_bytes
from ..native import plane_native
from ..obs import profiler as _profiler
from ..obs import recorder as _flightrec
from ..obs import trace as _trace
from ..obs.recorder import flight
from ..obs.trace import emit_span
from .arena import HostArena, discard_thread_arena, lease_arena, \
    return_arena, thread_arena, trim_arena_pool
from ..cpu.plain import ByteArrayColumn
from ..format.compact import CompactReader
from ..format.metadata import (
    ColumnMetaData,
    CompressionCodec,
    Encoding,
    PageHeader,
    PageType,
    Type,
    decode_struct,
)
from ..format.schema import SchemaNode
from .bitunpack import pad_to_words, unpack_u32
from .decode import (
    bucket,
    dict_gather_bytes,
    dict_gather_fixed,
    expand_delta_i32,
    expand_delta_i64,
    levels_to_validity,
    plain_fixed_to_lanes,
    plan_delta_i32,
    plan_delta_i64,
    stage_u32,
    u8_to_u32_words,
)

__all__ = ["DeviceColumn", "decode_chunk_device", "read_row_group_device",
           "read_row_groups_device", "read_row_group_device_resilient",
           "cpu_fallback_values"]


# ----------------------------------------------------------------------
# Graceful degradation: forced-host value decode.
#
# When device dispatch fails (simulated via the fault harness, or a
# real accelerator error surfacing as DeviceDispatchError /
# RuntimeError), the resilient read path re-plans the unit under this
# thread-local flag: every page's VALUES decode on the bit-exact CPU
# oracle and only finished buffers cross to the device — no device
# decode kernels, no wire transports.  Pages planned this way report
# transport "host-degraded" and count DecodeStats.pages_degraded.
# ----------------------------------------------------------------------

_degrade_tls = threading.local()


def _host_values_only() -> bool:
    return getattr(_degrade_tls, "host_only", False)


@contextlib.contextmanager
def cpu_fallback_values():
    """Scope (this thread) forcing every page's values onto the CPU
    oracle decode — the device→host graceful-degradation mode."""
    prev = getattr(_degrade_tls, "host_only", False)
    _degrade_tls.host_only = True
    try:
        yield
    finally:
        _degrade_tls.host_only = prev

_LANES = {
    Type.INT32: 1, Type.FLOAT: 1, Type.INT64: 2, Type.DOUBLE: 2,
    Type.INT96: 3,
}


def _lanes_for(ptype: Type, type_length) -> int:
    """u32 words per value in the flat device layout.

    Value buffers are FLAT 1-D u32 at every jit boundary: a 2-D
    ``u32[n, lanes]`` TPU output is tiled T(8,128) with the minor dim
    padded to 128 — 64x HBM waste for int64 (measured: a 50M-value
    int64 chunk would allocate 25.6 GB and OOM)."""
    if ptype == Type.BOOLEAN:
        return 1
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        return _flba_lanes(type_length)
    return _LANES[ptype]

_DICT_ENCODINGS = (Encoding.PLAIN_DICTIONARY, Encoding.RLE_DICTIONARY)

# competition winner -> event-log transport label (obs.TRANSPORT_COUNTER
# maps these back to the DecodeStats counter each increments)
_CHOSEN_TRANSPORT = {"planes": "planes", "delta": "delta-lanes",
                     "snappy": "snappy-tokens"}

# Device-side snappy decompression of PLAIN fixed-width value segments
# (tokens + literals ship instead of the decompressed bytes).  Engages
# only for genuinely-compressed blocks — single-literal blocks keep the
# zero-copy host view, which is strictly cheaper.
def _DEVICE_SNAPPY() -> bool:
    """Read per plan (not import) so same-process A/B runs can flip it."""
    if _host_values_only():
        return False
    return os.environ.get("TPQ_DEVICE_SNAPPY", "1") != "0"

# Byte-plane RLE wire transport for PLAIN fixed-width segments (any
# codec, including UNCOMPRESSED): upper byte planes of numeric data are
# nearly constant and ship as runs.  Gated per page by measured wire
# size — pages whose planes are all random ship raw as before.
def _DEVICE_DELTA_LANES() -> bool:
    if _host_values_only():
        return False
    return os.environ.get("TPQ_DEVICE_DELTA", "1") != "0"


def _padded_u32_bytes(n_words: int) -> int:
    """POST-split staged bytes of an (n_words,) u32 array — the pure
    arithmetic of ``_split_rows``' decomposition (16 MB pieces, then
    descending powers of two down to ``_MIN_PIECE_BYTES``, then one
    bucketed tail), so wire estimates don't materialize throwaway
    arrays."""
    from .decode import bucket

    max_rows = 1 << ((_PIECE_BYTES // 4).bit_length() - 1)
    min_rows = 1 << ((_MIN_PIECE_BYTES // 4).bit_length() - 1)
    total = (n_words // max_rows) * max_rows
    left = n_words - total
    while left >= min_rows:
        p = 1 << (left.bit_length() - 1)
        total += p
        left -= p
    if left:
        total += bucket(left)
    return total * 4


def _plan_delta_lane_words(seg, count: int, ptype: Type, params=None):
    """Plan the delta-lane transport for one PLAIN int32/int64 values
    segment: re-encode values as (first, per-page min_delta, packed
    delta offsets) on the host and rebuild them with the EXISTING
    delta expand kernels on device.

    ``params`` is the plan cache's remembered ``(min_delta, width)``
    for this page: the O(window) entropy rejection and the full
    min/max pass are skipped, and a single max-reduce re-validates the
    cached width against the actual deltas (a stale hint falls back to
    the full computation rather than corrupting — hints stay
    performance-only).

    Sorted/clustered columns (timestamps, counters, row ids) pack their
    deltas into a few bits per value where even the byte planes ship
    half the raw words — the round-4 notes measured lanes at 0.505x of
    raw vs 0.35x for deltas on a pyarrow timestamp file, but rejected
    the transport because numpy pack cost 680 ms per 10M values.  The
    C word-writer pack (native/pack.c, 54 ms) changes that math; this
    planner only engages when the native is present.

    All arithmetic is modular (uint64/uint32 wrap), matching the
    expand kernels' lane adds and prefix scan — random pages reject on
    width, never corrupt.  Returns (exact_wire_bytes, commit) or None;
    ``commit(stager)`` stages the plan and returns ``get_words(staged)``
    producing the flat u32 lane layout PLAIN consumers slice."""
    from ..native import pack_native

    if count < 1024 or pack_native() is None:
        return None
    lanes = _LANES[ptype]
    nbytes = count * lanes * 4
    buf = (seg.reshape(-1) if isinstance(seg, np.ndarray)
           else np.frombuffer(seg, dtype=np.uint8))
    if buf.size < nbytes:
        raise ValueError("PLAIN: input too short")
    if lanes == 2:
        v = np.ascontiguousarray(buf[:nbytes]).view("<u8")
    else:
        v = np.ascontiguousarray(buf[:nbytes]).view("<u4")
    n_deltas = count - 1

    def _width(dd):
        lo = int(dd.min())
        hi = int(dd.max())
        span = int(np.uint64(hi - lo)) if lanes == 2 \
            else int(np.uint32(hi - lo))
        return lo, span.bit_length()

    if params is None:
        # O(window) entropy rejection before any full pass (the adjacent
        # plane planner samples for the same reason): the sample's delta
        # span lower-bounds the full span, so a window that already needs
        # full width proves the page rejects
        win = 16384
        if count > win:
            _, w_s = _width((v[1 : win + 1] - v[:win]).view(
                np.int64 if lanes == 2 else np.int32))
            if w_s >= 32 * lanes:
                return None
    # wrap-consistent deltas: the device rebuild adds mod 2^(32*lanes)
    d = (v[1:] - v[:-1]).view(np.int64 if lanes == 2 else np.int32)
    if params is not None:
        # cached (min_delta, width): re-validate with ONE reduce over
        # the offsets instead of the two-pass min/max — a stale hint
        # (changed bytes under an unchanged footer) recomputes honestly
        md, w = params
        mask = (1 << (32 * lanes)) - 1
        off_c = ((d.astype(np.int64) - md).astype(np.uint64)
                 & np.uint64(mask)) if lanes == 1 \
            else (d - np.int64(md)).view(np.uint64)
        fits = (w < 32 * lanes
                and (off_c.size == 0
                     or int(off_c.max()).bit_length() <= w))
        if not fits:
            md, w = _width(d)
            off_c = None
    else:
        md, w = _width(d)
        off_c = None
    if w >= 32 * lanes:
        return None
    # Advertise the POST-SPLIT staged cost, not the packed byte count:
    # the stager pads the words array's tail piece to a power-of-two
    # (_split_rows), and a first cut of this planner that compared
    # pre-pad wire flipped pages to delta that staged MORE after
    # padding than the planes they displaced.  (Competitors advertise
    # pre-pad wire, so this pessimizes delta — it engages only when
    # clearly better.)
    # Quantize the padded delta count to 32k multiples: the expand jit
    # compiles per (n_vals, w) shape, and exact per-page sizes would
    # recompile on every distinct page length for <3% wire savings.
    n_pad32 = (n_deltas + 32767) // 32768 * 32768
    n_words = n_pad32 // 32 * w
    wire = _padded_u32_bytes(n_words) + 32 if w else 32
    if wire + 4096 >= nbytes:
        return None  # must clear the same savings floor as the planes

    def commit(stager, _i64=(lanes == 2)):
        # pack deferred to here: the planner only charged the cheap
        # diff/min/max pass while the plane transport could still win
        from .bitunpack import pad_to_words
        from .decode import DeltaPlan

        mask = (1 << (32 * lanes)) - 1
        if off_c is not None:  # hint path already built the offsets
            off = off_c
        else:
            off = ((d.astype(np.int64) - md).astype(np.uint64)
                   & np.uint64(mask)) if lanes == 1 \
                else (d - md).view(np.uint64)
        n_pad = n_pad32
        if n_pad != n_deltas:
            off = np.concatenate(
                [off, np.zeros(n_pad - n_deltas, dtype=np.uint64)])
        packed = pack_native().pack(off, w) if w \
            else np.empty(0, np.uint8)
        words = pad_to_words(packed, w, n_pad).reshape(-1) if w else None
        md_u = np.uint64(md & mask)
        md_lo = np.asarray([md_u & np.uint64(0xFFFFFFFF)],
                           dtype=np.uint32)
        md_hi = np.asarray([md_u >> np.uint64(32)], dtype=np.uint32)
        groups = ([(w, words, None, None, n_pad, 0, n_deltas)]
                  if w else [])
        plan = DeltaPlan(groups, md_lo, md_hi, n_deltas, int(v[0]),
                         count)
        build = _stage_delta_plan(plan, stager, need_hi=_i64)

        def get_words(s, _b=build):
            from .decode import expand_delta_i32, expand_delta_i64

            return (expand_delta_i64(_b(s)) if _i64
                    else expand_delta_i32(_b(s)))

        return get_words

    return wire, commit, (md, w)


def _DEVICE_PLANES() -> bool:
    if _host_values_only():
        return False
    return os.environ.get("TPQ_DEVICE_PLANES", "1") != "0"


def _plan_token_expansion(payload, expected_size: int):
    """Shared prologue of the token-shipping planners: single-literal /
    no-native-scanner / int32-overflow checks, then the token plan.
    Returns ``(te, ts, lp, out_cap, steps, out_len, wire)`` or None;
    ``wire`` is what the token tables cost on the wire (padded sizes —
    the padding ships)."""
    from ..compress import snappy_single_literal_view

    if snappy_single_literal_view(payload) is not None:
        return None
    from ..native import snappy_native

    nat = snappy_native()
    if nat is None or getattr(nat, "_scan_tokens_fn", None) is None:
        return None
    from .snappy import plan_tokens

    plan = plan_tokens(payload, expected_size)
    if plan is None:
        return None  # int32 token table would wrap
    te, ts, lp = plan[:3]
    return (*plan, te.nbytes + ts.nbytes + lp.nbytes)


def _stage_token_expansion(plan, stager: "_Stager"):
    """Stage a token plan; returns ``blob(staged) -> u8[out_cap]``."""
    te, ts, lp, out_cap, steps = plan[:5]
    hs = stager.add_many([te, ts, lp], pad=False)

    def blob(staged, _hs=hs, _cap=out_cap, _steps=steps):
        from .snappy import expand_tokens

        return expand_tokens(staged[_hs[0]], staged[_hs[1]],
                             staged[_hs[2]], _cap, _steps)

    return blob


def _plan_device_snappy_blob(payload, expected_size: int,
                             wire_budget: float, stager: "_Stager"):
    """Like :func:`_plan_device_snappy_words` but returning
    ``(wire, blob)`` with the raw u8 page expansion (for byte-granular
    consumers), engaged only when the token tables fit ``wire_budget``
    bytes."""
    plan = _plan_token_expansion(payload, expected_size)
    if plan is None or plan[6] > wire_budget:
        return None
    return plan[6], _stage_token_expansion(plan, stager)


def _rle_table(plane: np.ndarray, count: int, val_dtype, bucket,
               max_runs: int | None = None):
    """(bucket-padded ends, vals, cap) run tables for one plane/lane, or
    None when the plane has more than ``max_runs`` runs (the table could
    not beat shipping the plane raw, so don't finish building it).

    ``plane`` may be a strided view — the native path scans it in one C
    pass with no bool temp or materialized copy."""
    nat = plane_native()
    if nat is not None and plane.ndim == 1:
        res = nat.run_scan(
            plane, count if max_runs is None else min(max_runs, count))
        if res is None:
            return None
        ends_r, vals_r = res
        cap = bucket(len(ends_r))
        ends = np.full(cap, count, dtype=np.int32)
        ends[: len(ends_r)] = ends_r
        vals = np.zeros(cap, dtype=val_dtype)
        vals[: len(vals_r)] = vals_r
        return ends, vals, cap
    change = np.flatnonzero(plane[1:] != plane[:-1]).astype(np.int32) + 1
    if max_runs is not None and len(change) + 1 > max_runs:
        return None
    cap = bucket(len(change) + 1)
    ends = np.full(cap, count, dtype=np.int32)
    ends[: len(change)] = change
    ends[len(change)] = count
    vals = np.zeros(cap, dtype=val_dtype)
    vals[: len(change) + 1] = plane[np.concatenate(
        ([0], change)).astype(np.int64)]
    return ends, vals, cap


def _lane_contig(plane: np.ndarray) -> np.ndarray:
    """Contiguous copy of a (possibly strided) lane/plane view."""
    nat = plane_native()
    if nat is not None and plane.ndim == 1 and not plane.flags.c_contiguous:
        return nat.gather(plane)
    return np.ascontiguousarray(plane)


def _plan_plane_words(seg, count: int, lanes: int, stager: "_Stager",
                      budget: int | None = None, lane_plans=None):
    """Plan the lane/byte-plane RLE transport for one PLAIN fixed-width
    values segment (``count`` values of ``lanes`` u32 words each).

    Decisions are made PER U32 LANE from a contiguous sample window, so
    a full-entropy page rejects in O(window) and an engaged page only
    ever touches the lanes/planes that pay:

    * ``rle32`` — the lane is runs as a whole (high words of
      timestamps/counters; zero high lanes of small-range values);
      one strided compare + flatnonzero, 8 wire bytes per run.
    * ``bytes`` — the lane is random as a word but has constant upper
      byte planes (e.g. int32s < 2^16); only the random byte planes
      ship raw.
    * ``raw32`` — genuinely random lane: one contiguous u32 slab.

    Host cost matters as much as wire here (the planner runs on the
    pipeline's plan thread): everything below is one strided-view pass
    per engaged lane, no full-page 2-D materialization.

    ``budget``, when given, is a competing transport's exact wire cost
    (snappy tokens): the planes engage only if they beat it.
    ``lane_plans`` is the plan cache's remembered per-lane verdict list
    for this page: the sample windows and the estimate pre-gate are
    skipped and the tables build directly — the actual-cost gate below
    still re-checks what the BUILT tables weigh, so a stale hint ships
    raw rather than a losing transport.

    Returns ``(wire, words_closure, lane_plans)`` — the wire cost
    recomputed from the BUILT tables (what the gate actually accepted;
    the event log reports it) — or None when the page rejects."""
    from .decode import bucket

    if count < 1024:
        return None  # can't clear the 4 KiB savings gate
    nbytes = count * lanes * 4
    buf = (seg.reshape(-1) if isinstance(seg, np.ndarray)
           else np.frombuffer(seg, dtype=np.uint8, count=nbytes))
    if buf.size < nbytes:
        raise ValueError("PLAIN values segment shorter than value count")
    words_v = buf[:nbytes].view("<u4")  # value-interleaved lanes
    wire_cap = (0.75 * nbytes if budget is None
                else min(0.75 * nbytes, budget))
    if lane_plans is not None and len(lane_plans) == lanes:
        plans = lane_plans
    else:
        win_n = min(count, 1 << 14)
        mid = (count - win_n) // 2

        plans = []  # per lane: ("raw32",) | ("rle32", est) | ("bytes", keep)
        wire = 0
        for lane in range(lanes):
            lw = np.ascontiguousarray(
                words_v[mid * lanes + lane
                        : (mid + win_n) * lanes : lanes])
            r32 = float((lw[1:] != lw[:-1]).mean()) if win_n > 1 else 1.0
            est32 = 8 * bucket(int(r32 * count) + 1)
            if est32 < 4 * count:  # beats the 4-bytes-per-value raw lane
                plans.append(("rle32", est32))
                wire += est32
                continue
            wb = lw.view(np.uint8).reshape(win_n, 4)
            r8 = (wb[1:] != wb[:-1]).mean(axis=0)
            cost8 = np.minimum(5 * np.array(
                [bucket(int(r * count) + 1) for r in r8]), count)
            if cost8.sum() < 0.75 * 4 * count:
                plans.append(("bytes", cost8))
                wire += int(cost8.sum())
            else:
                plans.append(("raw32",))
                wire += 4 * count
        # engage only on a solid win: the plan thread pays real host
        # time per engaged lane, so marginal pages keep the raw path
        if wire > wire_cap or nbytes - wire < 4096:
            return None

    raw32_parts, raw8_parts = [], []
    e32_parts, v32_parts, e8_parts, v8_parts = [], [], [], []
    s32 = s8 = 0
    spec = []
    actual = 0  # wire recomputed from BUILT tables (samples can lie)

    def raw32(lane_v):
        nonlocal actual
        spec.append(("raw32", len(raw32_parts)))
        raw32_parts.append(_lane_contig(lane_v))
        actual += 4 * count

    def raw8(col):
        nonlocal actual
        raw8_parts.append(col)
        actual += count
        return ("raw8", len(raw8_parts) - 1)

    for lane, plan in enumerate(plans):
        lane_v = words_v[lane::lanes]  # strided view, len == count
        if plan[0] == "rle32":
            # beyond count/2 runs the 8 B/run table cannot beat the raw
            # 4 B/value lane, so the scan aborts there (tab is None)
            tab = _rle_table(lane_v, count, np.uint32, bucket,
                             max_runs=count // 2 + 1)
            if tab is None or 8 * tab[2] >= 4 * count:
                # the sample under-estimated (heterogeneous page):
                # the built table would out-weigh the raw lane
                raw32(lane_v)
                continue
            ends, vals, cap = tab
            e32_parts.append(ends)
            v32_parts.append(vals)
            spec.append(("rle32", s32, cap))
            s32 += cap
            actual += 8 * cap
        elif plan[0] == "raw32":
            raw32(lane_v)
        else:
            cost8 = plan[1]
            subs = []
            for t in range(4):
                # strided view of byte plane t of this lane (LE words:
                # byte t of value i lives at i*4*lanes + 4*lane + t)
                col = buf[4 * lane + t : nbytes : 4 * lanes]
                if cost8[t] >= count:
                    subs.append(raw8(_lane_contig(col)))
                    continue
                tab = _rle_table(col, count, np.uint8, bucket,
                                 max_runs=count // 5 + 1)
                if tab is None or 5 * tab[2] >= count:
                    # sample under-estimated
                    subs.append(raw8(_lane_contig(col)))
                    continue
                ends, vals, cap = tab
                e8_parts.append(ends)
                v8_parts.append(vals)
                subs.append(("rle8", s8, cap))
                s8 += cap
                actual += 5 * cap
            spec.append(("bytes", *subs))
    # re-apply the gate on what the tables actually cost: a page whose
    # sample window misrepresented it should ship raw, not an engaged
    # transport that saves nothing (nothing is staged until below, so
    # bailing here is free)
    if actual > wire_cap or nbytes - actual < 4096:
        return None

    def cat(parts, dtype):
        if not parts:
            return np.zeros(1, dtype=dtype)
        if len(parts) == 1:  # already contiguous: don't re-copy 10s of MB
            return parts[0]
        return np.concatenate(parts)

    hs = stager.add_many(
        [cat(raw32_parts, np.uint32), cat(e32_parts, np.int32),
         cat(v32_parts, np.uint32), cat(raw8_parts, np.uint8),
         cat(e8_parts, np.int32), cat(v8_parts, np.uint8)],
        pad=False)
    spec = tuple(spec)

    def words(staged, _hs=hs, _spec=spec, _count=count, _lanes=lanes):
        from .decode import planes_to_words

        return planes_to_words(
            staged[_hs[0]], staged[_hs[1]], staged[_hs[2]],
            staged[_hs[3]], staged[_hs[4]], staged[_hs[5]],
            _spec, _count, _lanes)

    return actual, words, plans


def _stage_delta_plan(plan, stager: "_Stager", need_hi: bool):
    """Route a DeltaPlan's device buffers through the batched stager
    (wave-chunked transfer + bytes_staged accounting — these previously
    shipped as implicit device_puts at dispatch, uncounted).

    The packed width-class words ride the padded path (the build slices
    them back to exact length before unpack's reshape); per-miniblock
    scatter starts/takes and the per-block min_delta lanes ship exact —
    padding would corrupt scatter targets and the repeat length.
    ``need_hi`` is False for i32 plans: ``expand_delta_i32`` never
    reads the hi lane, so it stays host-side."""
    from .decode import DeltaPlan

    specs = []
    for w, words, starts, takes, n_vals, start, n_take in plan.groups:
        wh = stager.add(words)
        if starts is None:
            specs.append((w, wh, words.size, None, None,
                          n_vals, start, n_take))
        else:
            sh = stager.add(starts, pad=False)
            th = stager.add(takes, pad=False)
            specs.append((w, wh, words.size, sh, th, n_vals, 0, 0))
    has_md = plan.md_lo.size > 0
    lo_h = stager.add(plan.md_lo, pad=False) if has_md else None
    hi_h = stager.add(plan.md_hi, pad=False) if has_md and need_hi \
        else None
    # captured by value: holding the plan object itself would keep the
    # just-staged host words/starts/takes arrays alive through dispatch
    lo_host = None if has_md else plan.md_lo
    hi_host = plan.md_hi if hi_h is None else None
    meta = (plan.block_size, plan.first, plan.total)

    def build(s, _specs=tuple(specs), _lo=lo_h, _hi=hi_h,
              _lo_host=lo_host, _hi_host=hi_host, _meta=meta):
        groups = []
        for w, wh, nw, sh, th, n_vals, start, n_take in _specs:
            groups.append((
                w, s[wh][:nw],
                None if sh is None else s[sh],
                None if th is None else s[th],
                n_vals, start, n_take,
            ))
        return DeltaPlan(
            groups,
            _lo_host if _lo is None else s[_lo],
            _hi_host if _hi is None else s[_hi],
            *_meta,
        )

    return build


def _plan_device_snappy_words(payload, expected_size: int, n_words: int,
                              offset: int = 0):
    """Plan device-side snappy decompression of one values segment.

    Returns ``(wire, commit)`` when the segment could decompress on
    device (multi-token block, native scanner available): ``wire`` is
    the exact transfer cost, and ``commit(stager)`` stages the plan and
    returns ``words(staged) -> (n_words,) u32``.  Returns None when the
    host path applies (single literal -> zero-copy view; no native
    scanner; int32 overflow risk; tokens would not shrink the
    transfer).  Staging is deferred so the dispatcher can pit the token
    wire against the lane/byte-plane transport and ship the cheaper.
    Wire format work happens in ``native/snappy.c
    tpq_snappy_scan_tokens``; copy resolution is
    :func:`tpuparquet.kernels.snappy.expand_tokens` (pointer doubling).
    Reference analogue of the block being replaced:
    ``compress.go:102-122`` (the hot decompress in the read loop).

    ``offset`` (bytes into the decompressed block) serves V1 pages whose
    level streams precede the values: the host scans levels from its own
    decompressed copy, but the WIRE ships the compressed tokens and the
    device slices the values segment out of its own expansion — level
    run tables are tiny; the values bytes are the transfer wall."""
    plan = _plan_token_expansion(payload, expected_size)
    if plan is None:
        return None
    out_len, wire = plan[5], plan[6]
    if out_len < offset + n_words * 4:
        raise ValueError("PLAIN values segment shorter than value count")
    # the wire gate: short-match-heavy blocks (numeric data under
    # min_match=4) cost more as 8-byte-per-token tables than as raw
    # bytes — ship tokens only when they actually shrink the transfer
    if wire >= 0.9 * (n_words * 4):
        return None

    def commit(stager, _plan=plan, _nw=n_words, _off=offset):
        blob = _stage_token_expansion(_plan, stager)

        def words(staged, _blob=blob, _nw=_nw, _off=_off):
            from .decode import u8_to_u32_words_at

            out = _blob(staged)
            if _off == 0:
                return u8_to_u32_words(out, _nw)
            return u8_to_u32_words_at(out, jnp.int32(_off), _nw)

        return words

    return wire, commit


class DeviceColumn:
    """Device-resident decoded column (Arrow layout).

    ``data``: flat (n_non_null * lanes,) u32 for fixed-width types
    (``lanes`` little-endian words per value — see :func:`_lanes_for`
    for why the buffer is 1-D), or u8 bytes with ``offsets`` for
    BYTE_ARRAY.  ``mask``/``positions`` map record
    slots to packed values; ``rep_levels``/``def_levels`` preserve nesting.

    Buffers are stored *bucket-padded* (the shape the fused page kernels
    emit) with logical lengths ``num_values`` (record slots) and
    ``n_packed`` (non-null values); the public accessors slice lazily and
    materialize implicit streams (all-zero levels, all-valid masks) on
    demand, so the common flat-required case costs zero extra dispatches.
    """

    __slots__ = ("ptype", "type_length", "offsets", "num_values",
                 "n_packed", "n_bytes", "_data_p", "_mask_p", "_pos_p",
                 "_rep_p", "_def_p", "_cache")

    def __init__(self, ptype, type_length, data, offsets, mask, positions,
                 rep_levels, def_levels, num_values, n_packed=None,
                 n_bytes=None):
        self.ptype = ptype
        self.type_length = type_length
        self._data_p = data
        self.offsets = offsets
        self._mask_p = mask
        self._pos_p = positions
        self._rep_p = rep_levels
        self._def_p = def_levels
        self.num_values = num_values
        self.n_packed = (
            n_packed if n_packed is not None
            else (None if data is None
                  else data.shape[0] // (self.lanes or 1))
        )
        self.n_bytes = n_bytes  # BYTE_ARRAY only: logical data length
        self._cache = {}

    @property
    def lanes(self):
        """u32 words per value (fixed-width types; None for BYTE_ARRAY)."""
        if self.offsets is not None:
            return None
        return _lanes_for(self.ptype, self.type_length)

    # -- lazy exact-shape accessors ---------------------------------------

    def _sliced(self, key, padded, n, fill):
        got = self._cache.get(key)
        if got is None:
            if padded is None:
                got = fill()
            elif padded.shape[0] == n:
                got = padded
            else:
                got = padded[:n]
            self._cache[key] = got
        return got

    @property
    def data(self):
        if self.offsets is not None:
            # BYTE_ARRAY: the buffer axis is bytes, not values
            return self._sliced(
                "data", self._data_p, self.n_bytes,
                lambda: jnp.zeros((0,), dtype=jnp.uint8))
        return self._sliced(
            "data", self._data_p, (self.n_packed or 0) * self.lanes,
            lambda: jnp.zeros((0,), dtype=jnp.uint32))

    @property
    def mask(self):
        return self._sliced(
            "mask", self._mask_p, self.num_values,
            lambda: jnp.ones((self.num_values,), dtype=bool))

    @property
    def positions(self):
        return self._sliced(
            "pos", self._pos_p, self.num_values,
            lambda: jnp.arange(self.num_values, dtype=jnp.int32))

    @property
    def rep_levels(self):
        return self._sliced(
            "rep", self._rep_p, self.num_values,
            lambda: jnp.zeros((self.num_values,), dtype=jnp.int32))

    @property
    def def_levels(self):
        return self._sliced(
            "def", self._def_p, self.num_values,
            lambda: jnp.zeros((self.num_values,), dtype=jnp.int32))

    def _buffers(self):
        """Every live device buffer (the single source of truth for
        batched syncs — block_until_ready AND _finish_row_group fence
        through this, so a new slot added here is fenced everywhere)."""
        return [
            x for x in (self._data_p, self.offsets, self._mask_p,
                        self._pos_p, self._rep_p, self._def_p)
            if x is not None
        ]

    def block_until_ready(self):
        # one batched sync: each individual block_until_ready is a
        # round trip over a remote-attached device
        jax.block_until_ready(self._buffers())
        return self

    def to_numpy(self, limit: int | None = None):
        """Materialize to the CPU oracle's chunk representation:
        (values, rep_levels, def_levels).  Slices padding host-side.

        ``limit`` bounds the materialization to the first ``limit``
        record slots (values keep their packed non-null order) —
        device buffers are sliced BEFORE the pull, so a bounded check
        of a huge chunk never streams the whole buffer over a narrow
        host link."""
        n = self.num_values
        if limit is not None and limit < n:
            n = max(limit, 0)
            rep = (np.zeros(n, dtype=np.int32) if self._rep_p is None
                   else np.asarray(self._rep_p[:n], dtype=np.int32))
            dl = (np.zeros(n, dtype=np.int32) if self._def_p is None
                  else np.asarray(self._def_p[:n], dtype=np.int32))
            nn = (n if self._mask_p is None
                  else int(np.asarray(self.mask[:n]).sum()))
            if self.offsets is not None:
                offs = np.asarray(self.offsets[: nn + 1], dtype=np.int64)
                data = np.asarray(self.data[: int(offs[-1])],
                                  dtype=np.uint8)
                return ByteArrayColumn(offs, data), rep, dl
            lanes = self.lanes
            flat = np.asarray(self.data[: nn * lanes],
                              dtype=np.uint32)
            return self._flat_to_typed(flat, lanes), rep, dl
        rep = (np.zeros(n, dtype=np.int32) if self._rep_p is None
               else np.asarray(self._rep_p, dtype=np.int32)[:n])
        dl = (np.zeros(n, dtype=np.int32) if self._def_p is None
              else np.asarray(self._def_p, dtype=np.int32)[:n])
        if self.offsets is not None:
            offs = np.asarray(self.offsets, dtype=np.int64)
            data = np.asarray(self.data, dtype=np.uint8)[: int(offs[-1])]
            return ByteArrayColumn(offs, data), rep, dl
        lanes = self.lanes
        flat = np.asarray(self.data, dtype=np.uint32)
        return self._flat_to_typed(flat, lanes), rep, dl

    def as_values(self):
        """Repackage the packed values for ``FileWriter.write_columns``
        (a :class:`tpuparquet.kernels.encode.DeviceValues` — it shares
        this column's flat u32 lane layout, so no data moves).

        Fixed-width int32/int64/float/double columns only, and the
        column must be all-non-null (``write_columns`` takes validity
        separately via ``masks=``; the packed buffer is exactly the
        non-null stream either way)."""
        from ..cpu.plain import PHYSICAL_DTYPES
        from .encode import DeviceValues

        dt = (None if self.offsets is not None or self.ptype == Type.BOOLEAN
              else PHYSICAL_DTYPES.get(self.ptype))
        if dt is None:
            raise TypeError(
                f"as_values supports int32/int64/float/double columns, "
                f"not {self.ptype.name}")
        return DeviceValues(self.data, dt)

    def _flat_to_typed(self, flat: np.ndarray, lanes: int):
        """Flat little-endian u32 lane words -> the oracle's value
        array (the single home of the lane-layout contract)."""
        if self.ptype == Type.BOOLEAN:
            return flat.astype(bool)
        if self.ptype == Type.INT32:
            return flat.view(np.int32)
        if self.ptype == Type.FLOAT:
            return flat.view(np.float32)
        if self.ptype == Type.INT64:
            return flat.view(np.uint8).view("<i8")
        if self.ptype == Type.DOUBLE:
            return flat.view(np.uint8).view("<f8")
        if self.ptype == Type.INT96:
            return flat.reshape(-1, 3)
        if self.ptype == Type.FIXED_LEN_BYTE_ARRAY:
            n = self.type_length
            return flat.view(np.uint8).reshape(-1, 4 * lanes)[:, :n]
        raise TypeError(f"unsupported type {self.ptype}")


def _devicecolumn_flatten(col: DeviceColumn):
    leaves = (col._data_p, col.offsets, col._mask_p, col._pos_p,
              col._rep_p, col._def_p)
    aux = (col.ptype, col.type_length, col.num_values, col.n_packed,
           col.n_bytes)
    return leaves, aux


def _devicecolumn_unflatten(aux, leaves):
    data, offsets, mask, positions, rep, dl = leaves
    ptype, type_length, num_values, n_packed, n_bytes = aux
    return DeviceColumn(ptype, type_length, data, offsets, mask,
                        positions, rep, dl, num_values,
                        n_packed=n_packed, n_bytes=n_bytes)


# DeviceColumn is a JAX pytree: decoded columns pass straight through
# jit/vmap/transform boundaries (buffers are the leaves; shape metadata
# is static aux), so `jax.jit(fn)(read_row_group_device(...)['x'])`
# just works — the decode output is a first-class device value.
jax.tree_util.register_pytree_node(
    DeviceColumn, _devicecolumn_flatten, _devicecolumn_unflatten)


def _stage_fixed_plain(raw: bytes, count: int, ptype: Type,
                       type_length) -> jax.Array:
    if ptype == Type.BOOLEAN:
        words = pad_to_words(np.frombuffer(raw, np.uint8), 1, count)
        return unpack_u32(jnp.asarray(words.reshape(-1)), 1, count)
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        return _stage_byte_rows(
            np.frombuffer(raw, np.uint8, count * type_length).reshape(
                count, type_length
            )
        )
    lanes = _LANES[ptype]
    words = stage_u32(raw, count * lanes)
    return plain_fixed_to_lanes(jnp.asarray(words), count, lanes)


def _flba_lanes(type_length: int) -> int:
    return (type_length + 3) // 4


def _stage_byte_rows_np(arr: np.ndarray) -> np.ndarray:
    """(N, L) u8 rows -> flat (N*lanes,) u32, zero-padding each row to
    whole little-endian u32 lanes (shared FLBA/int96 staging)."""
    if arr.shape[0] == 0:  # all-null page: zero rows, width still known
        return np.zeros((0,), dtype=np.uint32)
    rows = arr.view(np.uint8).reshape(arr.shape[0], -1)
    lanes = _flba_lanes(rows.shape[1])
    padded = np.zeros((rows.shape[0], lanes * 4), dtype=np.uint8)
    padded[:, : rows.shape[1]] = rows
    return padded.view("<u4").reshape(-1)


def _stage_byte_rows(arr: np.ndarray) -> jax.Array:
    return jnp.asarray(_stage_byte_rows_np(arr))


def _check_dict_indices(i_sc, width: int, non_null: int, dict_len: int,
                        idx_np=None) -> None:
    """Reject out-of-range dictionary indices host-side.

    The device gather clamps indices (its padding lanes must stay in
    range), so a corrupt file's oversized index would silently decode to
    the last dictionary entry; the CPU oracle raises instead.  Precise
    scan maxing is only needed when the bit width can express an index
    beyond the dictionary — the writer-aligned case costs nothing."""
    if non_null == 0:
        return
    if dict_len <= 0:
        raise ValueError("dict-encoded page with empty dictionary")
    if idx_np is not None:
        mx = int(idx_np.max()) if idx_np.size else -1
    elif i_sc is None:
        mx = 0  # width 0: every index decodes to 0
    elif (1 << width) <= dict_len:
        return
    else:
        from .hybrid import max_scan_value

        mx = max_scan_value(i_sc, width)
    if mx >= dict_len:
        raise ValueError(
            f"dictionary index {mx} out of range "
            f"(dictionary has {dict_len} entries)"
        )


# Transfer geometry, measured on the remote-attached TPU tunnel:
# a single device_put runs ~1.7 GB/s up to ~96 MB and collapses to
# ~115 MB/s above ~128 MB, while a list of <=16 MB pieces in one call
# sustains 4-6 GB/s — provided no more than ~128 MB is in flight at
# once (beyond that the tunnel congests).  So staging splits large
# arrays into power-of-two-row pieces and ships them in bounded waves,
# blocking between waves.
_PIECE_BYTES = 16 << 20   # split unit for large arrays
# Below this, pieces zero-pad to a power-of-two bucket.  The floor
# trades padding waste (tail bucket up to 2x a sub-floor array) against
# transfer-program compiles (~65-80 ms per distinct shape on the
# tunnel, one-time): the round-4 1 MB floor cost config-3/4 staged
# wire 10-22% in tail padding across their many mid-sized level/word
# arrays; 128 KB adds at most three more power-of-two shapes per dtype.
_MIN_PIECE_BYTES = 128 << 10
_WAVE_BYTES = 96 << 20    # max bytes in flight per wave


def _split_rows(a: np.ndarray):
    """Decompose an array into leading-dim pieces with power-of-two row
    counts (descending), zero-padding only the final piece.  Keeps the
    universe of transferred shapes small — the tunnel compiles a
    transfer program per distinct (shape, dtype) at ~65-80 ms each —
    without bucket-padding whole multi-hundred-MB buffers."""
    if a.ndim == 0 or a.shape[0] == 0:
        return [a]
    from .decode import bucket

    row_bytes = a.itemsize
    for d in a.shape[1:]:
        row_bytes *= d
    max_rows = max(1, 1 << max(0, (_PIECE_BYTES // row_bytes)
                               .bit_length() - 1))
    min_rows = max(1, 1 << max(0, (_MIN_PIECE_BYTES // row_bytes)
                               .bit_length() - 1))
    # Zero-copy slices with power-of-two row counts: 16 MB pieces, then
    # descending powers of two down to _MIN_PIECE_BYTES, then one
    # zero-padded tail of at most _MIN_PIECE_BYTES.  Transfer-program
    # shapes stay a small power-of-two universe, the host copies at
    # most _MIN_PIECE_BYTES per array, and the reassembled total is
    # deterministic in n (bounded jit keys).
    n = a.shape[0]
    pieces = []
    pos = 0
    while n - pos >= max_rows:
        pieces.append(a[pos : pos + max_rows])
        pos += max_rows
    left = n - pos
    while left >= min_rows:
        p = 1 << (left.bit_length() - 1)
        pieces.append(a[pos : pos + p])
        pos += p
        left -= p
    if left:
        b = bucket(left)  # <= min_rows (bucket() floors at 32)
        tail = np.zeros((b,) + a.shape[1:], a.dtype)
        tail[:left] = a[pos:]
        pieces.append(tail)
    return pieces


class _Stager:
    """Collects host arrays across chunks for batched wave transfers.

    ``put()`` decomposes padded arrays into pieces (``_split_rows``),
    ships them in waves of at most ``_WAVE_BYTES`` — blocking between
    waves, which is what keeps the tunnel at full throughput — and
    reassembles split arrays with a device-side concatenate.  It returns
    only after every transfer has completed, so host buffers (arena
    slabs included) are immediately reusable; all padding is zeros.

    ``pad=False`` arrays ship with their exact shape, unsplit — for
    buffers whose tail padding would corrupt device semantics (e.g. the
    monotonic offset arrays fed to searchsorted)."""

    __slots__ = ("arrays", "no_pad")

    def __init__(self):
        self.arrays = []
        self.no_pad = set()

    def add(self, arr, pad: bool = True) -> int:
        a = arr if isinstance(arr, np.ndarray) else np.asarray(arr)
        self.arrays.append(np.ascontiguousarray(a))
        if not pad:
            self.no_pad.add(len(self.arrays) - 1)
        return len(self.arrays) - 1

    def add_many(self, arrs, pad: bool = True) -> list[int]:
        return [self.add(a, pad=pad) for a in arrs]

    def put(self):
        return _put_all([self])[0]


def _put_all(stagers):
    """One batched wave transfer across SEVERAL stagers (the per-column
    stagers of one unit); returns each stager's staged list.

    Pieces ship in column order, so the wave composition is identical
    to the pre-column-parallel single-stager path (and independent of
    how many plan threads built the stagers) — the parity pin's
    staged-bytes guarantee."""
    specs = []
    pieces = []
    for stg in stagers:
        sp = []
        for i, a in enumerate(stg.arrays):
            ps = [a] if i in stg.no_pad else _split_rows(a)
            sp.append((len(pieces), len(ps)))
            pieces.extend(ps)
        specs.append(sp)
    if not pieces:
        return [[] for _ in stagers]
    from ..stats import current_stats

    _cs = current_stats()
    _whist = None
    if _cs is not None:
        # counted at transfer time, post-split/padding: the pieces
        # ARE the wire
        _cs.bytes_staged += sum(p.nbytes for p in pieces)
        # per-wave transfer wall (put -> the block that fences it):
        # the tunnel-health observable — a congested link shows as
        # the wave histogram's tail exploding while bytes_staged
        # stays flat
        _whist = _cs.hist("stager_wave_us")
    dev = [None] * len(pieces)
    prev = None
    t_wave = 0.0
    i = 0
    while i < len(pieces):
        wave, wave_bytes = [], 0
        while i < len(pieces) and (
            not wave or wave_bytes + pieces[i].nbytes <= _WAVE_BYTES
        ):
            wave.append(i)
            wave_bytes += pieces[i].nbytes
            i += 1
        if prev is not None:
            jax.block_until_ready(prev)
            if _whist is not None:
                _whist.record((time.perf_counter() - t_wave) * 1e6)
        if _whist is not None:
            t_wave = time.perf_counter()
        out = jax.device_put([pieces[j] for j in wave])
        for j, d in zip(wave, out):
            dev[j] = d
        prev = out
    jax.block_until_ready(prev)
    if _whist is not None and prev is not None:
        _whist.record((time.perf_counter() - t_wave) * 1e6)
    return [
        [dev[s] if n == 1 else jnp.concatenate(dev[s : s + n])
         for s, n in sp]
        for sp in specs
    ]


def decode_chunk_device(blob, cm: ColumnMetaData, node: SchemaNode,
                        base: int = 0) -> DeviceColumn:
    """Decode one column chunk to a DeviceColumn (standalone wrapper; the
    row-group path batches staging across chunks)."""
    arena = thread_arena()
    try:
        st = _Stager()
        finish = plan_chunk_device(blob, cm, node, base, st, arena)
        col = finish(st.put())  # put() blocks until transfers complete
        # finish() itself stages some paths (CPU fallbacks, delta,
        # FLBA/boolean) straight from arena-backed views, outside the
        # stager — those transfers must land before slabs recycle
        col.block_until_ready()
    except BaseException:
        discard_thread_arena()  # in-flight transfers may read the slabs
        raise
    arena.release_all()
    return col


def plan_chunk_device(blob, cm: ColumnMetaData, node: SchemaNode,
                      base: int, stager: _Stager,
                      arena: HostArena | None = None,
                      verify_crc: bool | None = None,
                      cache_key=None, cache_state=None):
    """Phase 1 (host): page-header walk, block decompression, run-table
    scans, staging-plan registration.  Returns ``finish(staged)`` which
    issues the fused device dispatches and assembles the DeviceColumn.

    ``blob`` holds the chunk's byte range; offsets in ``cm`` are absolute
    minus ``base``.  ``verify_crc`` gates page CRC32 verification when
    headers carry one (None = env default) — same semantics as the CPU
    path in ``io/chunk.py``.

    ``cache_key`` is this chunk's plan-cache identity
    (``(footer fingerprint, rg, column)``, see ``kernels/plancache.py``):
    on a hit the per-page transport competition is skipped and only the
    remembered winner's planner runs; on a miss the verdicts are stored.
    Hints are ROUTING-ONLY — they choose which lossless transport plans,
    never what the decoded bytes are, so a stale hint degrades wire
    choice at worst.  ``cache_state`` (a list, out-param) receives
    "hit" / "miss" / "off" for span annotation.
    """
    from ..io.pages import crc_verify_default, verify_page_crc
    from ..stats import current_stats

    if arena is None:
        arena = HostArena()  # throwaway: no recycling, plain lifetime
    if verify_crc is None:
        verify_crc = crc_verify_default()
    codec = CompressionCodec(cm.codec)
    ptype = Type(node.element.type)
    _st = current_stats()
    # per-page event log (obs/): only on when the active collector was
    # opened with collect_stats(events=True) — the emission sites below
    # all gate on `_ev is not None`, so a plain collector (or none)
    # pays nothing per page
    _ev = None if _st is None else _st.events
    _col_path = ".".join(cm.path_in_schema)
    _degraded = _host_values_only()
    # footer-keyed plan cache: hints index by DATA-page ordinal
    _pc = _hints = _record = None
    if cache_key is not None and not _degraded:
        from .plancache import plan_cache

        _pc = plan_cache()
        if _pc is not None:
            _hints = _pc.lookup(cache_key)
            if _hints is None:
                _record = []
    if cache_state is not None:
        cache_state.append(
            "off" if _pc is None
            else ("hit" if _hints is not None else "miss"))
    _page_i = 0
    _walk_i = 0  # all-page ordinal (dict pages included): error coords
    if _st is not None:
        _st.chunks += 1
        _st.bytes_compressed += cm.total_compressed_size
        _st.bytes_uncompressed += cm.total_uncompressed_size or 0
        _st.values += cm.num_values
    start = cm.data_page_offset
    if cm.dictionary_page_offset is not None:
        start = min(start, cm.dictionary_page_offset)
    start -= base
    end = start + cm.total_compressed_size
    r = CompactReader(blob, start, end)

    dict_fixed_h = None    # stager handle: flat (D*lanes,) u32
    dict_offsets_h = None  # stager handles: byte-array dictionary
    dict_data_h = None
    dict_lens_np = None
    dict_len = 0
    dict_host = None       # host copy, kept only for the degraded path

    # Deferred device work: each op is a closure (staged, parts) -> None
    # appended during the host walk and executed by finish() after the
    # one batched transfer.  parts keys: "val", "bytes", "rep", "def".
    ops = []
    values_read = 0
    total = cm.num_values
    max_def = node.max_def_level
    dwidth = max_def.bit_length()
    vlanes = (None if ptype == Type.BYTE_ARRAY
              else _lanes_for(ptype, node.element.type_length))

    while values_read < total:
        if r.pos >= end:
            raise CorruptChunkError(
                f"column chunk exhausted at {values_read}/{total} values",
                column=_col_path,
            )
        _t_pg = time.perf_counter() if _ev is not None else 0.0
        ph = decode_struct(PageHeader, r)
        # same malformed-header checks as the CPU path (io/chunk.py,
        # io/pages.py) — thrift-optional fields may arrive as None
        if ph.compressed_page_size is None or ph.compressed_page_size < 0:
            raise CorruptPageError("page header missing compressed size",
                                   column=_col_path, page=_walk_i)
        if ph.uncompressed_page_size is None or ph.uncompressed_page_size < 0:
            raise CorruptPageError("page header missing uncompressed size",
                                   column=_col_path, page=_walk_i)
        if r.pos + ph.compressed_page_size > end:
            raise CorruptPageError("page payload overruns column chunk",
                                   column=_col_path, page=_walk_i)
        # zero-copy view of the compressed bytes (the decompressors take
        # any buffer; a bytes() here would copy every page)
        payload = np.frombuffer(
            filter_bytes("kernels.device.page_payload",
                         blob[r.pos : r.pos + ph.compressed_page_size],
                         column=_col_path, page=_walk_i),
            dtype=np.uint8,
        )
        if payload.size != ph.compressed_page_size:
            raise CorruptPageError("page payload truncated",
                                   column=_col_path, page=_walk_i)
        if verify_page_crc(ph, payload, enabled=verify_crc,
                           column=_col_path, page=_walk_i):
            if _st is not None:
                _st.pages_crc_verified += 1
        r.pos += ph.compressed_page_size
        _walk_i += 1
        ptype_page = PageType(ph.type)
        if ptype_page in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2) \
                and not _degraded:
            # simulated device failures land here (harness site); the
            # degraded re-plan skips it — the CPU decode it models
            # doesn't touch the device kernels
            fault_point("kernels.device.page_dispatch",
                        column=_col_path, page=_page_i)

        if ptype_page == PageType.DICTIONARY_PAGE:
            dph = ph.dictionary_page_header
            if dph is None or dph.num_values is None or dph.num_values < 0:
                raise ValueError(
                    "DICTIONARY_PAGE header missing its struct"
                )
            raw = decompress_block_into(codec, payload,
                                        ph.uncompressed_page_size, arena)
            dict_np = decode_plain(
                ptype, raw, dph.num_values,
                node.element.type_length,
            )
            if _degraded:
                # the host gather below needs the dictionary ON HOST;
                # own the bytes — `raw` is an arena view that recycles
                dict_host = (dict_np if isinstance(dict_np,
                                                   ByteArrayColumn)
                             else np.array(dict_np, copy=True))
            if isinstance(dict_np, ByteArrayColumn):
                dict_offsets_h = stager.add(
                    dict_np.offsets.astype(np.int32))
                dict_data_h = stager.add(dict_np.data)
                dict_lens_np = dict_np.lengths()
                dict_len = len(dict_lens_np)
            else:
                arr = np.asarray(dict_np)
                dict_len = arr.shape[0]
                if arr.dtype == np.bool_:
                    staged = arr.astype(np.uint32).reshape(-1)
                elif arr.dtype in (np.dtype("<i4"), np.dtype("<f4")):
                    staged = arr.view("<u4").reshape(-1)
                elif arr.dtype in (np.dtype("<i8"), np.dtype("<f8")):
                    staged = arr.view("<u4").reshape(-1)
                elif ptype == Type.INT96:
                    staged = arr.astype("<u4").reshape(-1)
                else:  # FLBA (D, L) u8
                    staged = _stage_byte_rows_np(arr)
                dict_fixed_h = stager.add(staged)
            if r.pos != cm.data_page_offset - base:
                r.pos = cm.data_page_offset - base
            continue

        bytes_comp = None  # BYTE_ARRAY PLAIN: compressed source for the
        # device page-blob gather (src, uncompressed_size, values_offset)
        if ptype_page == PageType.DATA_PAGE:
            h = ph.data_page_header
            if h is None or h.num_values is None or h.num_values < 0:
                raise ValueError("DATA_PAGE header missing data_page_header")
            n = h.num_values
            device_plain = (_DEVICE_SNAPPY()
                            and codec == CompressionCodec.SNAPPY
                            and h.encoding == Encoding.PLAIN
                            and ptype in _LANES)
            if device_plain and not node.max_rep_level and not max_def:
                # flat-required PLAIN page: the block holds no level
                # bytes, so planning needs nothing from the payload —
                # defer decompression (device tokens, or zero-copy host
                # view for single-literal blocks, decided at dispatch)
                values_comp = (payload, ph.uncompressed_page_size, 0)
                values_seg = None
                dl_scan = dl_host = None
            else:
                values_comp = None
                raw = decompress_block_into(codec, payload,
                                            ph.uncompressed_page_size, arena)
                pos = 0
                if node.max_rep_level:
                    r_scan, r_host, pos = _scan_levels_v1(
                        raw, n, node.max_rep_level, pos,
                        h.repetition_level_encoding,
                    )
                    _defer_levels(ops, stager, "rep", r_scan, r_host, n,
                                  node.max_rep_level.bit_length(),
                                  max_level=node.max_rep_level)
                dl_scan, dl_host, pos = _scan_levels_v1(
                    raw, n, max_def, pos, h.definition_level_encoding
                )
                values_seg = raw[pos:]
                if device_plain:
                    # V1 page WITH levels: host scanned them from its
                    # own copy; the wire can still ship tokens, with the
                    # device slicing values out of its expansion at
                    # ``pos`` (values_seg stays the host fallback for
                    # single-literal / no-scanner blocks)
                    values_comp = (payload, ph.uncompressed_page_size,
                                   pos)
                elif (_DEVICE_SNAPPY() and codec == CompressionCodec.SNAPPY
                        and h.encoding == Encoding.PLAIN
                        and ptype == Type.BYTE_ARRAY):
                    # BYTE_ARRAY twin: host scans lengths from its copy;
                    # the device can gather value bytes out of its own
                    # expansion (length prefixes skipped arithmetically)
                    bytes_comp = (payload, ph.uncompressed_page_size, pos)
            enc = h.encoding
        elif ptype_page == PageType.DATA_PAGE_V2:
            from ..cpu.hybrid import scan_hybrid

            h = ph.data_page_header_v2
            if h is None or h.num_values is None or h.num_values < 0:
                raise ValueError(
                    "DATA_PAGE_V2 header missing data_page_header_v2"
                )
            n = h.num_values
            rl_len = h.repetition_levels_byte_length or 0
            dl_len = h.definition_levels_byte_length or 0
            if rl_len < 0 or dl_len < 0 or rl_len + dl_len > len(payload):
                raise ValueError("V2 level lengths exceed page size")
            if node.max_rep_level:
                r_scan = scan_hybrid(
                    payload[:rl_len], n, node.max_rep_level.bit_length()
                )
                _defer_levels(ops, stager, "rep", r_scan, None, n,
                              node.max_rep_level.bit_length(),
                              max_level=node.max_rep_level)
            dl_scan, dl_host = (None, None)
            if max_def:
                dl_scan = scan_hybrid(
                    payload[rl_len : rl_len + dl_len], n, dwidth
                )
            values_seg = payload[rl_len + dl_len :]
            values_comp = None
            if h.is_compressed is not False:
                vals_size = ph.uncompressed_page_size - rl_len - dl_len
                if (_DEVICE_SNAPPY() and codec == CompressionCodec.SNAPPY
                        and h.encoding == Encoding.PLAIN
                        and ptype in _LANES):
                    # V2 keeps levels outside compression: planning only
                    # needs the level bytes, so the values block can
                    # decompress on device
                    values_comp = (values_seg, vals_size, 0)
                    values_seg = None
                else:
                    if (_DEVICE_SNAPPY()
                            and codec == CompressionCodec.SNAPPY
                            and h.encoding == Encoding.PLAIN
                            and ptype == Type.BYTE_ARRAY):
                        bytes_comp = (values_seg, vals_size, 0)
                    values_seg = decompress_block_into(
                        codec, values_seg, vals_size, arena,
                    )
            enc = h.encoding
        else:
            continue
        # flight recorder: page coordinates ride the ring even with no
        # collector active (guarded so the disabled path skips the
        # kwargs build too — this is the per-page hot loop)
        if _flightrec._active is not None:
            _flightrec.flight("page", site="kernels.device",
                              column=_col_path, page=_page_i, values=n)
        if _st is not None:
            _st.pages += 1
            _st.hist("page_comp_bytes").record(ph.compressed_page_size)
            _st.hist("page_uncomp_bytes").record(
                ph.uncompressed_page_size)

        if not max_def:
            non_null = n
        elif dl_scan is not None:
            # count non-nulls from the run table (RLE arithmetic + one
            # vectorized unpack) rather than syncing the device expansion
            # back — device->host round-trips serialize the page pipeline
            from .hybrid import count_eq_scan

            non_null = count_eq_scan(dl_scan, dwidth, max_def,
                                     validate_max=True)
            if (ptype_page == PageType.DATA_PAGE_V2
                    and h.num_nulls is not None
                    and n - h.num_nulls != non_null):
                # same cross-check as the CPU path (io/pages.py)
                raise ValueError(
                    f"V2 num_nulls {h.num_nulls} disagrees with def "
                    f"levels ({n - non_null} nulls)"
                )
        else:
            non_null = int((dl_host == max_def).sum())
        values_read += n

        # plan-cache hint for THIS data page (routing-only: which
        # transport planner to run; None entry = page had no cacheable
        # decision).  _rec_entry collects the miss-path verdict; every
        # data page appends exactly one entry so hint indices stay
        # aligned with the data-page ordinal across re-reads.
        _hint = (_hints[_page_i]
                 if _hints is not None and _page_i < len(_hints)
                 else None)
        _rec_entry = None

        # Resolve deferred value-segment decompression.  The device
        # transports COMPETE on wire cost: snappy tokens (no host
        # decompress) vs byte planes vs delta lanes (both need the
        # decompressed bytes — native snappy makes that cheap).  A
        # timestamp page whose tokens cost 0.76x of raw but whose lanes
        # cost 0.50x must ship lanes, not whichever planner ran first.
        # The token SCAN is itself a third of the plan wall, so it runs
        # LAZILY: the compressed payload size approximates the token
        # transport's wire (tokens re-encode the block as table +
        # literals), and a competitor already under that bound skips
        # the scan outright — trading a few percent of wire precision
        # in the crossover region for ~30% of the plan phase.
        plan_words = None
        payload_bound = None
        # cached verdict for a PLAIN fixed-width page: run ONLY the
        # remembered winner's planner (or none, for a raw page) — the
        # losers' sample windows and above all the token SCAN are what a
        # warm re-read skips
        _use_hint = (isinstance(_hint, tuple) and len(_hint) >= 2
                     and _hint[0] == "plain")
        _hchoice = _hint[1] if _use_hint else None
        _hparams = (_hint[2] if _use_hint and len(_hint) > 2 else None)
        if values_comp is not None:
            payload_bound = len(values_comp[0])
            competitors = ((_DEVICE_PLANES()
                            or (_DEVICE_DELTA_LANES()
                                and ptype in (Type.INT32, Type.INT64)))
                           and non_null >= 1024)
            if _use_hint:
                competitors = _hchoice in ("planes", "delta")
            if values_seg is None and competitors:
                values_seg = decompress_block_into(
                    codec, values_comp[0], values_comp[1], arena)
        delta_cand = None
        if ((not _use_hint or _hchoice == "delta")
                and _DEVICE_DELTA_LANES() and enc == Encoding.PLAIN
                and ptype in (Type.INT32, Type.INT64)
                and values_seg is not None):
            delta_cand = _plan_delta_lane_words(
                values_seg, non_null, ptype,
                params=(_hparams if _use_hint and _hchoice == "delta"
                        else None))
        delta_wire = delta_cand[0] if delta_cand is not None else None

        planes_spec = None

        def _try_planes(budget):
            if ((not _use_hint or _hchoice == "planes")
                    and _DEVICE_PLANES() and non_null
                    and enc == Encoding.PLAIN and ptype in _LANES
                    and values_seg is not None):
                return _plan_plane_words(
                    values_seg, non_null, _LANES[ptype], stager,
                    budget=budget,
                    lane_plans=(_hparams
                                if _use_hint and _hchoice == "planes"
                                else None))
            return None

        budgets = [c for c in (delta_wire, payload_bound)
                   if c is not None]
        planes_wire = None
        _pl = _try_planes(min(budgets) if budgets else None)
        if _pl is not None:
            planes_wire, plan_words, planes_spec = _pl
        chosen = "planes" if plan_words is not None else None
        tok = None
        tok_scanned = False
        if plan_words is None:
            run_tok = payload_bound is not None and not (
                delta_wire is not None and delta_wire < payload_bound)
            if _use_hint:
                run_tok = (_hchoice == "snappy"
                           and payload_bound is not None)
            if run_tok:
                # no competitor beats the token bound: pay the scan
                tok_scanned = True
                tok = _plan_device_snappy_words(
                    values_comp[0], values_comp[1],
                    non_null * _LANES[ptype], offset=values_comp[2],
                )
                if tok is None and not _use_hint:
                    # token transport unreachable after all: re-contest
                    # the planes without its payload bound (they may
                    # have been pruned ONLY by it)
                    _pl = _try_planes(delta_wire)
                    if _pl is not None:
                        planes_wire, plan_words, planes_spec = _pl
                    chosen = "planes" if plan_words is not None else None
            if plan_words is None:
                if delta_cand is not None and (
                        tok is None or delta_cand[0] < tok[0]):
                    plan_words = delta_cand[1](stager)
                    chosen = "delta"
                elif tok is not None:
                    plan_words = tok[1](stager)
                    chosen = "snappy"
                elif values_seg is None and values_comp is not None:
                    # no device transport reachable (or a cached "raw"
                    # verdict skipped the competition): the PLAIN
                    # fallback below needs the decompressed bytes
                    values_seg = decompress_block_into(
                        codec, values_comp[0], values_comp[1], arena)
        if _record is not None and enc == Encoding.PLAIN \
                and ptype in _LANES:
            _params = (planes_spec if chosen == "planes"
                       else delta_cand[2] if chosen == "delta"
                       else None)
            _rec_entry = ("plain", chosen, _params)
        chosen_wire = (planes_wire if chosen == "planes"
                       else delta_wire if chosen == "delta"
                       else tok[0] if chosen == "snappy" else None)
        if _st is not None and chosen is not None:
            if chosen == "planes":
                _st.pages_device_planes += 1
            elif chosen == "delta":
                _st.pages_device_delta_lanes += 1
            else:
                _st.pages_device_snappy += 1

        # event-log fields for this page (filled by the dispatch chain
        # below; emitted once at the end of the loop body).  The PLAIN
        # fixed-width transports are decided right here, so their
        # transport label, wire numbers and gate verdict resolve now.
        _tr = _wire_ev = _raw_ev = _gate = _reason = None
        if enc == Encoding.PLAIN and ptype in _LANES:
            _raw_ev = non_null * _LANES[ptype] * 4
            _tr = _CHOSEN_TRANSPORT.get(chosen, "raw")
            _wire_ev = chosen_wire if chosen is not None else _raw_ev
            if _st is not None and chosen is not None and _raw_ev:
                _st.hist("wire_ratio_permille").record(
                    chosen_wire * 1000 // _raw_ev)
            if _ev is not None:
                # "declined" = competed on wire cost (or in-planner
                # gates) and lost; "n/a" = never eligible for this
                # page — the distinction an operator needs when a
                # transport they expected is absent
                _gate = {"raw": _raw_ev}
                _gate["delta-lanes"] = (
                    delta_wire if delta_wire is not None
                    else "declined" if delta_cand is not None
                    or (_DEVICE_DELTA_LANES()
                        and ptype in (Type.INT32, Type.INT64)
                        and values_seg is not None)
                    else "n/a (type/flag/compressed)")
                _gate["planes"] = (
                    planes_wire if planes_wire is not None
                    else "declined" if (_DEVICE_PLANES() and non_null
                                        and values_seg is not None)
                    else "n/a (flag/empty/compressed)")
                if tok is not None:
                    _gate["snappy-tokens"] = tok[0]
                elif payload_bound is None:
                    _gate["snappy-tokens"] = "n/a (not device-snappy)"
                elif tok_scanned:
                    _gate["snappy-tokens"] = "declined"
                else:
                    _gate["snappy-tokens"] = (
                        f"not-scanned (competitor under payload bound "
                        f"{payload_bound}B)")
                if chosen is not None:
                    _reason = (f"{_tr} {chosen_wire}B beat raw "
                               f"{_raw_ev}B")
                else:
                    _reason = "no transport beat raw staging"
                if _use_hint:
                    _reason += " (plan-cache hit)"

        # Def-level plan, padded for the fused page kernels.  A page
        # whose value path can't fuse expands it standalone via
        # _defer_levels below.
        dl_ref = None  # (handles, cnt, nbp, single) when fusable
        if dl_scan is not None:
            from .hybrid import plan_stream_args

            dl_args, dl_cnt, dl_nbp, dl_sg = plan_stream_args(
                dl_scan, n, dwidth)
            dl_ref = (stager.add_many(dl_args, pad=False), dl_cnt, dl_nbp,
                      dl_sg)
        elif dl_host is not None:
            hh = stager.add(np.asarray(dl_host, dtype=np.int32))
            ops.append(lambda s, p, _h=hh, _n=n:
                       p["def"].append((s[_h], _n)))

        def _def_standalone():
            """Expand the def plan on its own (non-fused value paths)."""
            if dl_ref is not None:
                from .decode import expand_tbl

                hs, cnt, nbp = dl_ref[:3]

                def op(s, p, _hs=hs, _cnt=cnt, _nbp=nbp, _n=n,
                       _sg=dl_ref[3]):
                    dl_dev = expand_tbl(
                        s[_hs[0]], s[_hs[1]], _cnt, dwidth, _nbp,
                        single=_sg,
                    ).astype(jnp.int32)
                    p["def"].append((dl_dev, _n))

                ops.append(op)

        if _degraded:
            # Graceful degradation (cpu_fallback_values): this page's
            # VALUES decode on the bit-exact CPU oracle — the exact
            # code path `read_row_group_arrays` runs — and only the
            # finished buffers stage to the device.  No decode kernels,
            # no wire transports; level expansion still rides the
            # shared machinery above.
            _tr = "host-degraded"
            _wire_ev = _raw_ev = _gate = None
            _reason = "device dispatch degraded: CPU oracle decode"
            _def_standalone()
            if _st is not None:
                _st.pages_degraded += 1
            if enc in _DICT_ENCODINGS:
                from ..cpu import decode_dict_indices, gather

                if dict_host is None:
                    raise CorruptChunkError(
                        "dictionary-encoded page but no dictionary "
                        "page seen", column=_col_path)
                # bytes(): the oracle decoder indexes scalars out of
                # its input, and numpy-u8 scalars overflow its width
                # arithmetic
                idx = decode_dict_indices(bytes(memoryview(values_seg)),
                                          non_null)
                if idx.size and int(idx.max()) >= dict_len:
                    raise CorruptPageError(
                        f"dictionary index {int(idx.max())} out of "
                        f"range (dictionary has {dict_len})",
                        column=_col_path, page=_page_i)
                col = gather(dict_host, idx)
            else:
                col = decode_values_cpu(ptype, enc, values_seg,
                                        non_null,
                                        node.element.type_length)
            # own the bytes: the oracle decoders return VIEWS of the
            # arena-backed page buffer, and on the CPU backend staging
            # can be zero-copy — a recycled slab would silently rewrite
            # this column under a later unit's decode
            if isinstance(col, ByteArrayColumn):
                col = ByteArrayColumn(np.array(col.offsets, copy=True),
                                      np.array(col.data, copy=True))
            else:
                col = np.array(col, copy=True)
            if isinstance(col, ByteArrayColumn):
                dh = stager.add(col.data)
                ops.append(
                    lambda s, p, _dh=dh,
                    _o=col.offsets.astype(np.int32),
                    _nb=int(col.data.size):
                    p["bytes"].append((_o, s[_dh], _nb))
                )
            else:
                ops.append(
                    lambda s, p, _c=col, _nn=non_null:
                    p["val"].append((_stage_numpy_fixed(_c, ptype), _nn))
                )
        elif enc in _DICT_ENCODINGS:
            _tr = "dict"
            width = int(values_seg[0]) if len(values_seg) else 0
            if dict_fixed_h is not None:
                from ..cpu.hybrid import scan_hybrid
                from .hybrid import plan_stream_args

                i_sc = scan_hybrid(values_seg, non_null, width, pos=1) \
                    if width else None
                _check_dict_indices(i_sc, width, non_null, dict_len)
                idx_ref = None
                if i_sc is not None:
                    idx_args, i_cnt, i_nbp, i_sg = plan_stream_args(
                        i_sc, non_null, width)
                    idx_ref = (stager.add_many(idx_args, pad=False),
                               i_cnt, i_nbp, i_sg)
                if dl_ref is not None and idx_ref is not None:
                    from .decode import page_dict_fixed_levels_tbl

                    def op(s, p, _d=dl_ref, _i=idx_ref, _n=n,
                           _nn=non_null, _w=width, _dh=dict_fixed_h,
                           _vl=vlanes):
                        vals, dl_dev = page_dict_fixed_levels_tbl(
                            s[_dh],
                            s[_d[0][0]], s[_d[0][1]],
                            s[_i[0][0]], s[_i[0][1]],
                            _d[1], dwidth, _d[2], _i[1], _w, _i[2],
                            lanes=_vl, dsingle=_d[3], isingle=_i[3],
                        )
                        p["def"].append((dl_dev, _n))
                        p["val"].append((vals, _nn))

                    ops.append(op)
                else:
                    _def_standalone()
                    if idx_ref is None:
                        def op(s, p, _nn=non_null, _dh=dict_fixed_h,
                               _vl=vlanes):
                            idx = jnp.zeros((_nn,), jnp.int32)
                            p["val"].append(
                                (dict_gather_fixed(s[_dh], idx,
                                                   lanes=_vl), _nn)
                            )

                        ops.append(op)
                    else:
                        from .decode import page_dict_fixed_tbl

                        def op(s, p, _i=idx_ref, _nn=non_null, _w=width,
                               _dh=dict_fixed_h, _vl=vlanes):
                            vals = page_dict_fixed_tbl(
                                s[_dh], s[_i[0][0]], s[_i[0][1]],
                                _i[1], _w, _i[2], lanes=_vl,
                                isingle=_i[3],
                            )
                            p["val"].append((vals, _nn))

                        ops.append(op)
            elif dict_offsets_h is not None:
                # host-side index decode (vectorized, no device sync) just
                # to size the output; the gather uses the device indices.
                # One scan serves both the host expand and the device plan.
                from ..cpu.hybrid import expand_scan, scan_hybrid
                from .decode import bucket
                from .hybrid import plan_stream_args

                _def_standalone()
                if width:
                    i_sc = scan_hybrid(values_seg, non_null, width, pos=1)
                    idx_u = expand_scan(*i_sc[:6], non_null, width)
                    # validate BEFORE the int32 cast: a width-32 index
                    # like 0xFFFFFFFF would wrap negative and pass
                    _check_dict_indices(None, width, non_null, dict_len,
                                        idx_np=idx_u)
                    idx_np = idx_u.astype(np.int32)
                else:
                    i_sc = None
                    idx_np = np.zeros(non_null, np.int32)
                    _check_dict_indices(None, width, non_null, dict_len,
                                        idx_np=idx_np)
                lens = dict_lens_np[idx_np]
                out_offsets = np.zeros(non_null + 1, dtype=np.int32)
                np.cumsum(lens, out=out_offsets[1:])
                total_b = int(out_offsets[-1])
                # every dynamic input stays at its bucket size so the jit
                # cache keys on buckets, not exact per-page counts
                cap = bucket(max(total_b, 1))
                if i_sc is not None:
                    i_args, i_cnt, i_nbp, i_single = plan_stream_args(
                        i_sc, non_null, width, expanded=idx_u)
                    idx_hs = stager.add_many(i_args, pad=False)
                else:
                    idx_hs = None
                    i_cnt = bucket(max(non_null, 1))
                    i_single = False
                def op(s, p, _ih=idx_hs, _icnt=i_cnt,
                       _inbp=(i_nbp if width else 0), _w=width,
                       _isg=i_single,
                       _cap=cap, _oo=out_offsets, _nn=non_null,
                       _tb=total_b, _doh=dict_offsets_h,
                       _ddh=dict_data_h):
                    from .decode import page_dict_bytes_tbl

                    if _ih is None:
                        dummy = jnp.zeros((1,), jnp.uint32)
                        data = page_dict_bytes_tbl(
                            s[_doh], s[_ddh], dummy, dummy,
                            np.int32(_nn), _icnt, _w, _inbp, _cap,
                            has_idx=False,
                        )
                    else:
                        data = page_dict_bytes_tbl(
                            s[_doh], s[_ddh], s[_ih[0]], s[_ih[1]],
                            np.int32(_nn), _icnt, _w, _inbp, _cap,
                            isingle=_isg,
                        )
                    p["bytes"].append((_oo, data, _tb))

                ops.append(op)
            else:
                raise ValueError("dict-encoded page without dictionary")
        elif enc == Encoding.PLAIN:
            if ptype == Type.BYTE_ARRAY:
                _def_standalone()
                col = decode_plain(ptype, values_seg, non_null)  # host scan
                offs = col.offsets.astype(np.int32)
                from .decode import bucket as _bucket

                blob_plan = None
                budget = None
                # cached "raw" verdict skips the token scan outright; a
                # cached "tokens" verdict (or no hint) pays it — the
                # tables it builds ARE the staged content
                _ba_skip = (isinstance(_hint, tuple) and len(_hint) == 2
                            and _hint[0] == "ba" and _hint[1] is False)
                if bytes_comp is not None and not _ba_skip:
                    budget = (0.9 * int(col.data.size)
                              - 4 * _bucket(non_null + 1))
                    if budget > 0:
                        blob_plan = _plan_device_snappy_blob(
                            bytes_comp[0], bytes_comp[1], budget, stager)
                if _record is not None:
                    _rec_entry = ("ba", blob_plan is not None)
                _raw_ev = int(col.data.size)
                if _ev is not None:
                    _gate = {"raw": _raw_ev,
                             "snappy-tokens": (
                                 blob_plan[0] if blob_plan is not None
                                 else "declined" if budget is not None
                                 else "n/a (not device-snappy)")}
                if blob_plan is not None:
                    # compressed tokens + padded offsets ship; the
                    # device expands the page and gathers value bytes
                    # (length prefixes skipped arithmetically)
                    from .decode import bucket, plain_bytes_from_blob

                    blob_wire, blob_plan = blob_plan
                    _tr = "snappy-tokens"
                    _wire_ev = blob_wire
                    if _st is not None:
                        _st.pages_device_snappy += 1
                        if _raw_ev:
                            _st.hist("wire_ratio_permille").record(
                                blob_wire * 1000 // _raw_ev)
                    if _ev is not None:
                        _reason = (f"tokens {blob_wire}B under budget "
                                   f"{int(budget)}B (raw {_raw_ev}B)")
                    nb = int(col.data.size)
                    cap = bucket(max(nb, 1))
                    ocap = bucket(non_null + 1)
                    offs_pad = np.full(ocap, nb, dtype=np.int32)
                    offs_pad[: non_null + 1] = offs
                    oh = stager.add(offs_pad, pad=False)

                    def op(s, p, _bp=blob_plan, _oh=oh, _o=offs,
                           _cap=cap, _nb=nb, _pos=bytes_comp[2]):
                        data = plain_bytes_from_blob(
                            _bp(s), s[_oh], jnp.int32(_pos), _cap)
                        p["bytes"].append((_o, data, _nb))

                    ops.append(op)
                else:
                    _tr = "raw"
                    _wire_ev = _raw_ev
                    dh = stager.add(col.data)
                    ops.append(
                        lambda s, p, _dh=dh, _o=offs,
                        _nb=int(col.data.size):
                        p["bytes"].append((_o, s[_dh], _nb))
                    )
            elif (dl_ref is not None
                  and ptype not in (Type.BOOLEAN,
                                    Type.FIXED_LEN_BYTE_ARRAY)):
                from .decode import page_plain_fixed_levels_tbl

                lanes = _LANES[ptype]
                if plan_words is not None:
                    get_words = plan_words
                else:
                    wh = stager.add(stage_u32(values_seg, non_null * lanes))
                    get_words = lambda s, _wh=wh: s[_wh]

                def op(s, p, _gw=get_words, _d=dl_ref, _nn=non_null, _n=n,
                       _lanes=lanes):
                    vals, dl_dev = page_plain_fixed_levels_tbl(
                        _gw(s), s[_d[0][0]], s[_d[0][1]], _nn, _lanes,
                        _d[1], dwidth, _d[2], dsingle=_d[3],
                                            )
                    p["def"].append((dl_dev, _n))
                    p["val"].append((vals, _nn))

                ops.append(op)
            elif ptype in _LANES:
                # zero-copy u32 view of the decompressed values rides the
                # one batched transfer (or the words come straight from
                # the device snappy kernel); 'decode' is a device reshape
                _def_standalone()
                lanes = _LANES[ptype]
                if plan_words is not None:
                    get_words = plan_words
                else:
                    wh = stager.add(stage_u32(values_seg, non_null * lanes))
                    get_words = lambda s, _wh=wh: s[_wh]
                ops.append(
                    lambda s, p, _gw=get_words, _nn=non_null, _lanes=lanes:
                    p["val"].append(
                        (plain_fixed_to_lanes(_gw(s), _nn, _lanes), _nn)
                    )
                )
            else:
                _tr = "raw"
                _def_standalone()
                # values_seg stays a zero-copy view (arena lifetime runs
                # until the caller's release, after transfers complete)
                ops.append(
                    lambda s, p, _seg=values_seg, _nn=non_null:
                    p["val"].append((
                        _stage_fixed_plain(_seg, _nn, ptype,
                                           node.element.type_length),
                        _nn,
                    ))
                )
        elif enc == Encoding.BYTE_STREAM_SPLIT and ptype in (
                Type.INT32, Type.INT64, Type.FLOAT, Type.DOUBLE,
                Type.FIXED_LEN_BYTE_ARRAY):
            from .decode import bss_to_lanes

            _tr = "bss"
            _def_standalone()
            k = (node.element.type_length
                 if ptype == Type.FIXED_LEN_BYTE_ARRAY
                 else 4 * _LANES[ptype])
            raw_np = (values_seg.reshape(-1)
                      if isinstance(values_seg, np.ndarray)
                      else np.frombuffer(values_seg, dtype=np.uint8))
            if raw_np.size < non_null * k:
                raise ValueError("BYTE_STREAM_SPLIT: input too short")
            if non_null:
                rh = stager.add(raw_np[: non_null * k])
                ops.append(
                    lambda s, p, _rh=rh, _nn=non_null, _k=k, _vl=vlanes:
                    p["val"].append(
                        (bss_to_lanes(s[_rh], _nn, _k, _vl), _nn)
                    )
                )
        elif enc == Encoding.RLE and ptype == Type.BOOLEAN:
            # boolean RLE data values: a length-prefixed width-1 hybrid
            # stream — the same prefix parse and run-table deferral as
            # the V1 levels
            import struct

            _tr = "rle"
            _def_standalone()
            if len(values_seg) < 4:
                raise ValueError("boolean RLE stream missing length")
            (bsz,) = struct.unpack_from("<I", values_seg, 0)
            if 4 + bsz > len(values_seg):
                # the shared level scanner would silently truncate the
                # slice; a declared length beyond the page is corrupt
                raise ValueError("boolean RLE length exceeds page")
            if non_null:
                b_sc, _, _ = _scan_levels_v1(values_seg, non_null, 1, 0)
                _defer_levels(ops, stager, "val", b_sc, None, non_null, 1,
                              cast=None)
        elif enc == Encoding.DELTA_LENGTH_BYTE_ARRAY \
                and ptype == Type.BYTE_ARRAY:
            # lengths decode host-side (small delta stream, validation
            # shared with the CPU decoder); the byte payload ships as a
            # zero-copy view — the CPU fallback would memcpy the whole
            # string payload before staging
            from ..cpu.delta import scan_delta_length_byte_array

            _tr = "dlba"
            _def_standalone()
            offs, dpos = scan_delta_length_byte_array(values_seg,
                                                      non_null)
            dlba_bytes = int(offs[-1])
            view = np.frombuffer(values_seg, np.uint8, dlba_bytes, dpos)
            dh = stager.add(view)
            ops.append(
                lambda s, p, _dh=dh, _o=offs, _nb=dlba_bytes:
                p["bytes"].append((_o, s[_dh], _nb))
            )
        elif enc == Encoding.DELTA_BYTE_ARRAY and ptype in (
                Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
            # front coding IS the LZ copy-resolution problem the snappy
            # kernel solves: each value = one copy token (its prefix,
            # read from the previous value's output start) + one literal
            # token (its suffix).  Ship compact prefixes+suffixes, expand
            # on device by pointer doubling (kernels/snappy.py).  FLBA
            # rides the same expansion; its flat output converts to lane
            # words on device (flba_bytes_to_lanes) instead of offsets.
            from ..cpu.delta import (
                assemble_delta_byte_array,
                decode_delta_binary_packed,
                scan_delta_length_byte_array,
            )

            _def_standalone()
            prefix_lens, ppos = decode_delta_binary_packed(
                values_seg, np.int64)
            if prefix_lens.size != non_null:
                raise ValueError("DELTA_BYTE_ARRAY: prefix count mismatch")
            soffs, spos = scan_delta_length_byte_array(
                values_seg, non_null, ppos)
            suffix_lens = np.diff(soffs)
            if non_null:
                if prefix_lens[0] != 0:
                    raise ValueError(
                        "DELTA_BYTE_ARRAY: first prefix must be 0")
                if (prefix_lens < 0).any():
                    raise ValueError("DELTA_BYTE_ARRAY: negative prefix")
            total_lens = prefix_lens + suffix_lens
            if non_null > 1 and (prefix_lens[1:]
                                 > total_lens[:-1]).any():
                raise ValueError(
                    "DELTA_BYTE_ARRAY: prefix longer than previous value")
            flba_len = (node.element.type_length
                        if ptype == Type.FIXED_LEN_BYTE_ARRAY else None)
            if flba_len is not None and non_null and not (
                    total_lens == flba_len).all():
                raise ValueError(
                    "DELTA_BYTE_ARRAY: FLBA value length mismatch")
            offs = np.zeros(non_null + 1, dtype=np.int64)
            np.cumsum(total_lens, out=offs[1:])
            expanded = int(offs[-1])
            n_suffix = int(soffs[-1]) if non_null else 0
            compact = n_suffix + 8 * non_null  # suffixes + token table
            if (non_null == 0 or expanded > (1 << 30)
                    or expanded < compact):
                # host assembly only when it ships STRICTLY fewer
                # bytes than the compact wire form (wire-neutral pages
                # take the copy-graph kernel below); the empty-page and
                # bucket(expanded)-past-int32 guards (cf. plan_tokens)
                # stay host for correctness.  Assembles from the
                # ALREADY-parsed streams — no re-parse.  The per-page
                # wire numbers that justify the choice ride the event
                # gate and are pinned by tests/test_fallback_matrix.py.
                _tr = "dba-host"
                if _ev is not None:
                    _wire_ev = expanded
                    _raw_ev = expanded
                    _gate = {"expanded": expanded, "compact": compact}
                    _reason = (
                        f"front coding non-expanding: host assembly "
                        f"ships {expanded}B vs compact wire {compact}B")
                suffix_view = np.frombuffer(values_seg, np.uint8,
                                            n_suffix, spos)
                col = assemble_delta_byte_array(prefix_lens, soffs,
                                                suffix_view)
                if flba_len is not None:
                    rows = np.asarray(col.data)[: non_null * flba_len] \
                        .reshape(non_null, flba_len)
                    ops.append(
                        lambda s, p, _r=rows, _nn=non_null:
                        p["val"].append((_stage_byte_rows(_r), _nn))
                    )
                else:
                    dh = stager.add(col.data)
                    ops.append(
                        lambda s, p, _dh=dh,
                        _o=col.offsets.astype(np.int64),
                        _nb=int(col.data.size):
                        p["bytes"].append((_o, s[_dh], _nb))
                    )
            else:
                from .decode import bucket as _bucket

                _tr = "dba"
                if _ev is not None:
                    _wire_ev = compact
                    _raw_ev = expanded
                    _gate = {"expanded": expanded, "compact": compact}
                    _reason = (f"copy-token expansion: {compact}B wire "
                               f"vs {expanded}B expanded")
                out_cap = _bucket(expanded)
                T = _bucket(2 * non_null)
                te = np.full(T, out_cap, dtype=np.int32)
                ts = np.full(T, -1, dtype=np.int32)
                # copy token i: output [offs[i], offs[i]+p[i]) reads
                # from the previous value's start; literal token i:
                # the suffix bytes
                te[0 : 2 * non_null : 2] = (offs[:-1]
                                            + prefix_lens).astype(np.int32)
                te[1 : 2 * non_null : 2] = offs[1:].astype(np.int32)
                prev_start = np.zeros(non_null, dtype=np.int64)
                prev_start[1:] = offs[:-2]
                ts[0 : 2 * non_null : 2] = prev_start.astype(np.int32)
                ts[1 : 2 * non_null : 2] = (-soffs[:-1] - 1).astype(
                    np.int32)
                lits = np.frombuffer(values_seg, np.uint8, n_suffix,
                                     spos)
                th = stager.add_many([te, ts], pad=False)
                lh = stager.add(lits)
                steps = max(int(np.ceil(np.log2(max(expanded, 2)))), 1)

                def op(s, p, _th=th, _lh=lh, _cap=out_cap, _st=steps,
                       _o=offs, _nb=expanded, _nn=non_null,
                       _fl=flba_len):
                    from .decode import flba_bytes_to_lanes
                    from .snappy import expand_tokens

                    out = expand_tokens(s[_th[0]], s[_th[1]], s[_lh],
                                        _cap, _st)
                    if _fl is not None:
                        p["val"].append(
                            (flba_bytes_to_lanes(out, _nn, _fl), _nn))
                    else:
                        p["bytes"].append((_o, out, _nb))

                ops.append(op)
        elif enc == Encoding.DELTA_BINARY_PACKED and ptype in (
                Type.INT32, Type.INT64):
            _tr = "delta-bp"
            _def_standalone()
            if ptype == Type.INT32:
                build = _stage_delta_plan(
                    plan_delta_i32(values_seg), stager, need_hi=False)
                ops.append(
                    lambda s, p, _b=build, _nn=non_null:
                    p["val"].append(
                        (expand_delta_i32(_b(s))[:_nn], _nn)
                    )
                )
            else:
                build = _stage_delta_plan(
                    plan_delta_i64(values_seg), stager, need_hi=True)
                ops.append(
                    lambda s, p, _b=build, _nn=non_null:
                    p["val"].append(
                        (expand_delta_i64(_b(s))[: _nn * 2], _nn)
                    )
                )
        else:
            # CPU fallback for the remaining encodings; stage the result.
            _tr = "host"
            if _ev is not None:
                _reason = "no device kernel for this encoding"
            _def_standalone()
            if _st is not None:
                _st.pages_host_values += 1
            col = decode_values_cpu(ptype, enc, values_seg, non_null,
                                    node.element.type_length)
            # own the bytes (see the degraded branch above): the
            # decoders may return views of the recyclable arena slab
            if isinstance(col, ByteArrayColumn):
                col = ByteArrayColumn(np.array(col.offsets, copy=True),
                                      np.array(col.data, copy=True))
            elif isinstance(col, np.ndarray):
                col = np.array(col, copy=True)
            if isinstance(col, ByteArrayColumn):
                dh = stager.add(col.data)
                ops.append(
                    lambda s, p, _dh=dh, _o=col.offsets.astype(np.int32),
                    _nb=int(col.data.size):
                    p["bytes"].append((_o, s[_dh], _nb))
                )
            else:
                ops.append(
                    lambda s, p, _c=col, _nn=non_null:
                    p["val"].append((_stage_numpy_fixed(_c, ptype), _nn))
                )

        # one event per data page: the dispatch chain above resolved
        # the transport; every branch reaches this point (dictionary
        # pages `continue` before it and are not data pages).  A
        # branch that forgot its `_tr = ...` label ships as "unknown"
        # rather than a silent null — visible in transport_counts()
        # and the profile table, so the gap can't hide.
        if _ev is not None:
            _ev.page(
                column=_col_path, page=_page_i,
                page_type=("v2" if ptype_page == PageType.DATA_PAGE_V2
                           else "v1"),
                encoding=Encoding(enc).name, codec=codec.name,
                num_values=n, non_null=non_null,
                transport=_tr if _tr is not None else "unknown",
                wire_bytes=_wire_ev, raw_bytes=_raw_ev,
                gate=_gate, reason=_reason,
                plan_s=time.perf_counter() - _t_pg,
            )
        if _record is not None:
            _record.append(_rec_entry)
        _page_i += 1

    if _record is not None and _pc is not None:
        from .plancache import plan_cache_budget

        _pc.store(cache_key, _record, plan_cache_budget())

    type_length = node.element.type_length

    def finish(staged) -> DeviceColumn:
        parts = {"val": [], "bytes": [], "rep": [], "def": []}
        for op in ops:
            op(staged, parts)

        rep, _ = _merge_parts(parts["rep"])
        dl, _ = _merge_parts(parts["def"])
        if max_def and dl is not None:
            mask, positions = levels_to_validity(dl, max_def)
        else:
            mask = positions = None

        bytes_parts = parts["bytes"]
        if bytes_parts:
            if len(bytes_parts) == 1:
                offs_np, data, nbytes = bytes_parts[0]
                offsets = jnp.asarray(offs_np.astype(np.int64))
                return DeviceColumn(ptype, type_length, data, offsets,
                                    mask, positions, rep, dl, total,
                                    n_packed=len(offs_np) - 1,
                                    n_bytes=nbytes)
            # merge per-page byte columns: rebase offsets, concat data
            all_offs = [np.zeros(1, dtype=np.int64)]
            datas = []
            base_off = 0
            for offs, data, nbytes in bytes_parts:
                all_offs.append(
                    np.asarray(offs[1:], dtype=np.int64) + base_off)
                datas.append(jnp.asarray(data)[:nbytes])
                base_off += nbytes
            offsets = jnp.asarray(np.concatenate(all_offs))
            data = (jnp.concatenate(datas) if datas
                    else jnp.zeros(0, jnp.uint8))
            return DeviceColumn(ptype, type_length, data, offsets,
                                mask, positions, rep, dl, total,
                                n_packed=sum(len(o) for o in all_offs) - 1,
                                n_bytes=base_off)

        data, n_packed = _merge_parts(parts["val"], lanes=vlanes)
        return DeviceColumn(ptype, type_length, data, None, mask,
                            positions, rep, dl, total,
                            n_packed=n_packed or 0)

    return finish


def _defer_levels(ops, stager, kind, scan, host_vals, n, width,
                  max_level=None, cast=jnp.int32):
    """Register a deferred hybrid-stream expansion: scan -> device
    expand, or host-decoded values -> staged transfer.  Levels use the
    default int32 ``cast``; value streams (boolean RLE) pass
    ``cast=None`` to keep the expand's u32.  ``max_level`` enables the
    range validation of ``cpu/levels._check`` (rep levels would otherwise
    silently mis-nest on corrupt streams)."""
    if scan is not None:
        from .hybrid import count_eq_scan, plan_stream_args

        if max_level is not None:
            count_eq_scan(scan, width, max_level, validate_max=True)
        args, cnt, nbp, sg = plan_stream_args(scan, n, width)
        hs = stager.add_many(args, pad=False)

        def op(s, p, _hs=hs, _cnt=cnt, _nbp=nbp, _n=n, _w=width, _sg=sg):
            from .decode import expand_tbl

            dev = expand_tbl(
                s[_hs[0]], s[_hs[1]], _cnt, _w, _nbp, single=_sg)
            if cast is not None:
                dev = dev.astype(cast)
            p[kind].append((dev, _n))

        ops.append(op)
    elif host_vals is not None:
        hh = stager.add(np.asarray(host_vals, dtype=np.int32))
        ops.append(lambda s, p, _h=hh, _n=n: p[kind].append((s[_h], _n)))


def _merge_parts(parts, lanes: int = 1):
    """Merge [(padded device array, logical n)] -> (array, total n).

    Single-part chunks keep their padding (consumers slice lazily);
    multi-part chunks slice then concatenate.  ``lanes`` scales the
    slice for flat value buffers (n u32 words per value)."""
    if not parts:
        return None, 0
    if len(parts) == 1:
        return parts[0]
    k = lanes or 1
    arrs = [a if a.shape[0] == m * k else a[: m * k] for a, m in parts]
    return jnp.concatenate(arrs), sum(m for _, m in parts)


def stage_chunkdata(cd, node) -> DeviceColumn:
    """Stage one host-decoded :class:`~tpuparquet.io.chunk.ChunkData`
    as a :class:`DeviceColumn` — the transfer step of the
    late-materialization path: the predicate already ran on host, so
    only the SURVIVING rows' bytes cross the link.  Buffer layout
    matches the fused-kernel path exactly (flat u32 lanes / byte-array
    offsets+data), so downstream consumers (``gather_column`` et al.)
    cannot tell the difference."""
    ptype = Type(node.element.type)
    dl = np.asarray(cd.def_levels, dtype=np.int32)
    rep = np.asarray(cd.rep_levels, dtype=np.int32)
    num = dl.shape[0]
    max_def = node.max_def_level
    mask_h = pos_h = None
    if max_def:
        valid = dl == max_def
        if not valid.all():
            pidx = np.cumsum(valid, dtype=np.int64) - 1
            mask_h = valid
            pos_h = np.maximum(pidx, 0).astype(np.int32)
    vals = cd.values
    offsets = None
    n_bytes = None
    if isinstance(vals, ByteArrayColumn):
        offs = np.asarray(vals.offsets)
        n_bytes = int(offs[-1]) if offs.size else 0
        odt = np.int32 if n_bytes <= np.iinfo(np.int32).max else np.int64
        offsets = jnp.asarray(offs.astype(odt))
        data = jnp.asarray(np.asarray(vals.data, dtype=np.uint8))
        n_packed = max(offs.size - 1, 0)
    else:
        arr = np.asarray(vals)
        n_packed = arr.shape[0]
        if ptype == Type.BOOLEAN:
            flat = arr.astype(np.uint32)
        elif ptype == Type.FIXED_LEN_BYTE_ARRAY:
            flat = _stage_byte_rows_np(arr)
        elif ptype == Type.INT96:
            flat = np.ascontiguousarray(arr, dtype="<u4").reshape(-1)
        else:
            flat = np.ascontiguousarray(arr).view("<u4").reshape(-1)
        data = jnp.asarray(flat)
    return DeviceColumn(
        ptype, node.element.type_length, data, offsets,
        None if mask_h is None else jnp.asarray(mask_h),
        None if pos_h is None else jnp.asarray(pos_h),
        jnp.asarray(rep) if node.max_rep_level else None,
        jnp.asarray(dl) if max_def else None,
        num, n_packed=n_packed, n_bytes=n_bytes)


def _read_row_group_device_filtered(reader, rg_index: int, filt,
                                    verdict) -> dict[str, DeviceColumn]:
    """Late-materialized device read: filter columns decode on host,
    the predicate evaluates exactly, and only surviving rows stage to
    the device (``stage_chunkdata``).  Pruned pages are never
    decompressed; pruned row groups return schema-shaped empty
    columns.  Bit-exact vs decoding everything and post-filtering on
    device."""
    from ..filter import read_row_group_filtered

    chunks, _rows = read_row_group_filtered(reader, rg_index, filt,
                                            verdict)
    t0 = time.perf_counter()
    out = {}
    for path, cd in chunks.items():
        node = reader.schema.leaf(path)
        out[path] = stage_chunkdata(cd, node)
    jax.block_until_ready(
        [x for c in out.values() for x in c._buffers()])
    t1 = time.perf_counter()
    from ..stats import current_stats

    _cs = current_stats()
    if _cs is not None:
        _cs.transfer_s += t1 - t0
        if _cs.events is not None:
            _cs.events.span("transfer", "decode", t0, t1,
                            tid=threading.get_ident(),
                            columns=len(out))
    return out


def read_row_group_device(reader, rg_index: int, filter=None,
                          verdict=None) -> dict[str, DeviceColumn]:
    """Decode the selected columns of one row group onto the device.

    The device-path sibling of ``FileReader.read_row_group_arrays``: same
    selection semantics, device-resident results.  Each column chunk
    plans as an independent task — on multi-core hosts a SINGLE large
    row group (the common TPU-input shape) fans its columns across the
    plan pool — then all columns' plan tables and page words ship in one
    batched wave transfer (``_put_all``) and the fused page kernels
    dispatch and are drained before returning (async pile-up degrades
    the remote tunnel — see the comment in ``_finish_row_group``).  For
    multi-row-group reads prefer :func:`read_row_groups_device`, which
    additionally overlaps row group N+1's host planning with N's
    transfer.

    ``filter`` (a :mod:`tpuparquet.filter` expression, optionally with
    a precomputed ``verdict``) switches to the late-materialized
    pushdown path: filter columns decode on host first, pruned pages
    are never decompressed, and only surviving rows transfer —
    bit-exact vs decode-everything-then-post-filter."""
    from ..stats import current_stats

    _cs = current_stats()
    if _cs is not None:
        _cs.row_groups += 1
    if filter is not None:
        try:
            return _read_row_group_device_filtered(
                reader, rg_index, filter, verdict)
        except ScanError as e:
            raise e.annotate(row_group=rg_index)
    rg = reader.meta.row_groups[rg_index]
    arenas = []
    try:
        cols = reader.selected_chunks(rg)
        # remote sources: batch-prefetch the row group's chunk ranges
        # (coalesced, parallel) so the column planners below hit the
        # disk tier instead of issuing one round trip each.  No-op for
        # local/in-memory sources.
        pf = getattr(reader, "prefetch_chunks", None)
        if pf is not None:
            pf(rg)
        n_workers = min(_plan_threads(), max(len(cols), 1))
        if n_workers <= 1:
            # serial path: plan on the calling thread under the caller's
            # collector — byte-identical plans, no pool overhead.  One
            # arena serves every column (no racing planners here), so
            # decompression slabs recycle across columns like the
            # pre-column-parallel planner's did.
            a = lease_arena()
            arenas.append(a)
            planned = []
            for path, node, cm in cols:
                planned.append(
                    _plan_one_column(reader, rg_index, path, node, cm, a))
        else:
            from concurrent.futures import ThreadPoolExecutor

            degraded = _host_values_only()
            with ThreadPoolExecutor(max_workers=n_workers) as ex:
                futs = []
                for path, node, cm in cols:
                    a = lease_arena()
                    arenas.append(a)
                    futs.append(ex.submit(
                        _plan_column_task, reader, rg_index, path, node,
                        cm, a, _cs, degraded))
                planned = []
                err = None
                for f in futs:
                    try:
                        entry, ws = f.result()
                    except BaseException as e:
                        err = err if err is not None else e
                        continue
                    if _cs is not None:
                        _cs.merge_from(ws)
                    planned.append(entry)
                if err is not None:
                    raise err
        out = _finish_row_group(planned)
    except ScanError as e:
        # arenas are dropped, not recycled: in-flight transfers (or
        # abandoned plan tasks) may still read their slabs
        raise e.annotate(row_group=rg_index)
    for a in arenas:
        return_arena(a)
    return out


def read_row_group_device_resilient(reader, rg_index: int,
                                    retries: int | None = None,
                                    sleep=time.sleep,
                                    dispatch_deadline: float | None = None,
                                    filter=None, verdict=None):
    """:func:`read_row_group_device` with the device-failure policy:
    retry device dispatch with bounded exponential backoff, then
    degrade to the bit-exact CPU decode (:func:`cpu_fallback_values`)
    for this unit.  Corruption errors propagate unchanged — they are
    permanent and belong to the quarantine layer, not retry.

    ``dispatch_deadline`` (None = env ``TPQ_DISPATCH_DEADLINE_S``,
    off) bounds EACH attempt's wall: an attempt that runs past it —
    a wedged accelerator or dead tunnel that neither fails nor
    finishes — is abandoned and counted as a
    :class:`~tpuparquet.errors.DispatchDeadlineError`, which takes
    exactly the retry → CPU-fallback ladder a failing dispatch does.

    Counts ``DecodeStats.dispatch_retries`` per retry and
    ``units_degraded`` when the CPU fallback engages; the fallback is
    also recorded as an obs fault event.  The retry schedule shares
    the transient-I/O knobs (``TPQ_IO_RETRIES`` etc.).

    Counter exactness: each attempt runs under a scratch collector
    that merges into the caller's only on SUCCESS — a unit that
    retried N times still counts its pages/values/bytes exactly once
    and leaves no phantom page events from aborted attempts.  Failed
    attempts contribute only their fault-layer observability
    (``faults_injected``/``crc_mismatches``/``io_retries`` and fault
    events)."""
    from ..deadline import call_with_deadline, dispatch_deadline_default
    from ..errors import DispatchDeadlineError
    from ..stats import current_stats, merge_worker_stats, worker_stats

    if dispatch_deadline is None:
        dispatch_deadline = dispatch_deadline_default()
    # the deadline wrapper executes on a disposable worker thread, but
    # both the degraded-decode flag and jax's default device are
    # THREAD-LOCAL — the work callable re-enters them itself
    _dev = getattr(jax.config, "jax_default_device", None)

    def work(degraded: bool):
        dev_ctx = (jax.default_device(_dev) if _dev is not None
                   else contextlib.nullcontext())
        deg_ctx = cpu_fallback_values() if degraded \
            else contextlib.nullcontext()
        with dev_ctx, deg_ctx:
            return read_row_group_device(reader, rg_index,
                                         filter=filter, verdict=verdict)

    def attempt_bare(degraded):
        st = current_stats()
        if st is None:
            return work(degraded)
        with worker_stats(like=st) as ws:
            try:
                out = work(degraded)
            except BaseException:
                merge_worker_stats(st, ws, failed=True)
                raise
        merge_worker_stats(st, ws, failed=False)
        return out

    def attempt_once(degraded=False):
        # the deadline wrapper already runs the attempt under a worker
        # collector with the same merge policy; only the bare attempt
        # needs its own.  The DEGRADED attempt is never bounded: the
        # dispatch budget is sized for device-dispatch latency, and
        # the CPU fallback is the last-resort path that must be
        # allowed to finish (the unit-level deadline still bounds it
        # in a quarantining scan).
        if degraded or not dispatch_deadline:
            return attempt_bare(degraded)
        return call_with_deadline(
            lambda: work(degraded),
            dispatch_deadline, site="kernels.device.unit_dispatch",
            error=DispatchDeadlineError,
            file=getattr(reader, "name", None), row_group=rg_index)

    last = None
    delays = backoff_delays(retries)
    for attempt in range(len(delays) + 1):
        try:
            return attempt_once()
        except DeviceDispatchError as e:
            last = e
        except RuntimeError as e:
            # a real accelerator failure surfaces as a JAX/XLA
            # RuntimeError; treat it exactly like a dispatch fault
            if isinstance(e, (NotImplementedError, RecursionError)):
                raise
            last = e
        if attempt < len(delays):
            if _flightrec._active is not None:
                _flightrec.flight(
                    "dispatch_retry",
                    site="kernels.device.unit_dispatch",
                    row_group=rg_index, error=type(last).__name__)
            if _trace._active is not None:
                _trace.emit_span(
                    "dispatch_retry", time.perf_counter(), 0.0,
                    status="error", row_group=rg_index,
                    error=type(last).__name__)
            st = current_stats()
            if st is not None:
                st.dispatch_retries += 1
            sleep(delays[attempt])
    # retries exhausted: degrade this unit to the CPU oracle decode
    # (cold site — the bare emit_span, like the bare flight above)
    flight("degraded-to-host", site="kernels.device.unit_dispatch",
           row_group=rg_index, error=type(last).__name__,
           message=str(last))
    emit_span("degraded_to_host", time.perf_counter(), 0.0,
              status="error", row_group=rg_index,
              error=type(last).__name__)
    st = current_stats()
    if st is not None:
        st.units_degraded += 1
        if st.events is not None:
            st.events.fault(
                site="kernels.device.unit_dispatch",
                kind="degraded-to-host", row_group=rg_index,
                error=type(last).__name__, message=str(last))
    return attempt_once(degraded=True)


def _drop_range_caches(reader) -> None:
    """Corruption hook for remote sources: the bad bytes may have been
    SERVED from the range cache, so evict both tiers for this source —
    the resilient retry then refetches from the store, not the poison.
    No-op for local readers."""
    src = getattr(reader, "_source", None)
    if src is None:
        return
    from ..io.rangecache import invalidate_source_caches

    invalidate_source_caches(src.uri)


def _plan_one_column(reader, rg_index: int, path, node, cm,
                     arena: HostArena, degraded: bool = False):
    """Plan ONE column chunk into its own stager — the unit of work the
    column-parallel planner schedules.  Returns ``(path, finish,
    stager)``; plan wall and the plan span (with its plan-cache verdict)
    are recorded on the calling thread's collector.

    ``degraded`` re-enters :func:`cpu_fallback_values` — the flag is
    thread-local, so a pool worker must restore the submitting thread's
    degradation state itself."""
    from ..stats import current_stats

    from .plancache import plan_cache

    deg_ctx = (cpu_fallback_values() if degraded
               else contextlib.nullcontext())
    t0 = time.perf_counter()
    # causal trace: the plan span is OPENED (not emitted whole) so the
    # chunk read it triggers nests under it as a child span
    tsp = _trace.open_span("plan", column=path) \
        if _trace._active is not None else None
    stager = _Stager()
    # fingerprint only when the cache is on: computing it lazily costs
    # a footer re-read on file-backed sources, which cache-off scans
    # must never pay
    fingerprint = (getattr(reader, "plan_fingerprint", None)
                   if plan_cache() is not None else None)
    cache_key = (None if fingerprint is None
                 else (fingerprint, rg_index, path))
    cache_state = []
    try:
        with deg_ctx:
            blob, start = reader.chunk_blob(cm, path)
            finish = plan_chunk_device(
                memoryview(blob), cm, node, start, stager, arena,
                verify_crc=getattr(reader, "_verify_crc", None),
                cache_key=cache_key, cache_state=cache_state)
    except ScanError as e:
        if isinstance(e, (CorruptPageError, CorruptChunkError)):
            # the bytes no longer match the footer: cached plans for
            # this file identity are stale
            from .plancache import invalidate_fingerprint

            invalidate_fingerprint(fingerprint)
            _drop_range_caches(reader)
        _trace.close_span(tsp, status="error")
        raise e.annotate(column=path, file=getattr(reader, "name", None))
    except ValueError as e:
        # codec-layer domain errors become taxonomy errors with
        # coordinates; raw crash types propagate as the bugs they
        # are (the crash-corpus clean-failure contract)
        from .plancache import invalidate_fingerprint

        invalidate_fingerprint(fingerprint)
        _drop_range_caches(reader)
        _trace.close_span(tsp, status="error")
        raise CorruptChunkError(
            str(e), column=path,
            file=getattr(reader, "name", None)) from e
    except BaseException:
        _trace.close_span(tsp, status="error")
        raise
    _trace.close_span(tsp, cache=(cache_state[0] if cache_state
                                  else "off"))
    t1 = time.perf_counter()
    if _flightrec._active is not None:
        _flightrec.flight(
            "span:plan", site="kernels.device", column=path,
            s=round(t1 - t0, 6),
            cache=(cache_state[0] if cache_state else "off"))
    _cs = current_stats()
    if _cs is not None:
        _cs.plan_s += t1 - t0
        if _cs.events is not None:
            _cs.events.span(
                "plan", "decode", t0, t1, tid=threading.get_ident(),
                column=path,
                cache=(cache_state[0] if cache_state else "off"))
    return path, finish, stager


def _plan_column_task(reader, rg_index: int, path, node, cm,
                      arena: HostArena, like, degraded: bool,
                      tctx=None, usp=None):
    """Pool-worker wrapper around :func:`_plan_one_column`: fresh
    per-thread collector (``worker_stats(like=)`` — the coordinator
    merges after joining, the exactness discipline ``stats.py``
    documents) and the submitting thread's degradation state.
    ``tctx`` re-enters the submitting site's trace context (the
    unit's span) so this column's plan/read spans parent causally
    under their unit regardless of which pool thread ran them;
    ``usp`` is that unit's OPEN span handle — the first task to run
    stamps its execution start (``setdefault`` is GIL-atomic), so the
    unit span measures work, not submission-queue wait."""
    from ..stats import worker_stats

    if usp is not None:
        usp.setdefault("t0_exec", time.perf_counter())
    with _trace.adopt(tctx), worker_stats(like=like) as ws:
        entry = _plan_one_column(reader, rg_index, path, node, cm,
                                 arena, degraded=degraded)
    return entry, ws


def _plan_row_group(reader, rg, stager: _Stager, arena: HostArena):
    """Serial compat path (tools/exp_gap.py and friends): plan every
    selected column of one row group into ONE shared stager on the
    calling thread.  The production readers plan per-column stagers via
    :func:`_plan_one_column` instead."""
    from ..stats import current_stats

    t0 = time.perf_counter()
    planned = []
    verify_crc = getattr(reader, "_verify_crc", None)
    for path, node, cm, blob, start in reader.iter_selected_chunks(rg):
        try:
            planned.append(
                (path,
                 plan_chunk_device(memoryview(blob), cm, node, start,
                                   stager, arena, verify_crc=verify_crc))
            )
        except ScanError as e:
            raise e.annotate(column=path, file=getattr(reader, "name",
                                                       None))
        except ValueError as e:
            # codec-layer domain errors become taxonomy errors with
            # coordinates; raw crash types propagate as the bugs they
            # are (the crash-corpus clean-failure contract)
            raise CorruptChunkError(
                str(e), column=path,
                file=getattr(reader, "name", None)) from e
    _cs = current_stats()
    if _cs is not None:
        t1 = time.perf_counter()
        _cs.plan_s += t1 - t0
        if _cs.events is not None:
            _cs.events.span("plan", "decode", t0, t1,
                            tid=threading.get_ident(),
                            columns=len(planned))
    return planned


def _finish_row_group(planned):
    """Stage + dispatch one unit's column plans: ``planned`` is
    ``[(path, finish, stager)]`` from :func:`_plan_one_column`.  All
    columns' arrays ship in ONE shared wave sequence (``_put_all``, in
    column order — wave composition is identical to the old single-
    stager path and independent of plan-thread count)."""
    from ..stats import current_stats

    if not _host_values_only():
        # unit-level simulated device failures (harness sites); skipped
        # on the degraded re-plan, whose remaining device work is bare
        # buffer staging.  The hang site simulates a wedged
        # accelerator/tunnel: under a dispatch deadline it becomes a
        # DispatchDeadlineError instead of a stalled scan.
        fault_point("kernels.device.unit_dispatch")
        fault_point("kernels.device.hang")
    t0 = time.perf_counter()
    # stage hints: transfer and dispatch only emit_span AFTER
    # measuring, so the sampler needs in-flight markers scoped to the
    # same windows the spans time (doctor cross-checks the two)
    ptok = _profiler.stage_begin("transfer") \
        if _profiler._active is not None else None
    try:
        staged_lists = _put_all([stager for _, _, stager in planned])
    finally:
        if ptok is not None:
            _profiler.stage_end(ptok)
    t1 = time.perf_counter()
    ptok = _profiler.stage_begin("dispatch") \
        if _profiler._active is not None else None
    try:
        out = {path: finish(staged)
               for (path, finish, _), staged in
               zip(planned, staged_lists)}
        # Drain the dispatched kernels before returning: on the
        # remote-attached TPU, letting async work pile up degrades
        # every subsequent transfer ~2x (measured 1.16s vs 0.53s over
        # 8 row groups at 50M values) — the tunnel serializes badly
        # under a deep queue.  Compute itself is sub-ms; this costs
        # one sync, and it also fences the finish()-time transfers
        # sourced from arena slabs.  One batched block_until_ready:
        # per-buffer syncs are a round trip EACH over the tunnel
        # (~240 of them across 8 row groups x 5 columns x 6 buffers
        # cost ~0.6s — the entire e2e-vs-internals gap).
        jax.block_until_ready(
            [x for c in out.values() for x in c._buffers()])
    finally:
        if ptok is not None:
            _profiler.stage_end(ptok)
    t2 = time.perf_counter()
    if _flightrec._active is not None:
        _flightrec.flight(
            "span:stage", site="kernels.device", columns=len(out),
            transfer_s=round(t1 - t0, 6),
            dispatch_s=round(t2 - t1, 6))
    if _trace._active is not None:
        _trace.emit_span("transfer", t0, t1 - t0, columns=len(out))
        _trace.emit_span("dispatch", t1, t2 - t1, columns=len(out))
    _cs = current_stats()
    if _cs is not None:
        _cs.transfer_s += t1 - t0
        _cs.dispatch_s += t2 - t1
        if _cs.events is not None:
            import threading

            tid = threading.get_ident()
            _cs.events.span("transfer", "decode", t0, t1, tid=tid,
                            columns=len(out))
            _cs.events.span("dispatch", "decode", t1, t2, tid=tid,
                            columns=len(out))
    return out


def _plan_threads() -> int:
    """Plan-phase worker count (column-parallel planner).

    On a good link the pipeline is PLAN-bound (50M taxi: plan 1.1-2.4 s
    vs ~9 ms of transfer at PCIe rates), and the plan phase is
    GIL-releasing C/numpy whose file reads are already lock-protected
    (``FileReader._io_lock``), so planning many columns concurrently is
    the direct lever on the e2e wall.  Default: one worker per USABLE
    core (affinity/cpuset-aware — a 1-core container gets exactly one
    planner and the exact serial-plan behavior; this is also the
    oversubscription clamp).  ``TPQ_PLAN_THREADS`` is authoritative
    when set.  The writer's encode pool (``TPQ_WRITE_THREADS``)
    defaults to the same core count: a process that scans and writes
    CONCURRENTLY should split the budget explicitly (e.g.
    ``TPQ_PLAN_THREADS=N/2 TPQ_WRITE_THREADS=N/2``) — the library
    never runs both pools for the same operation, so sequential
    read-then-write workloads need no tuning.  Stats stay exact at any
    worker count: each column plan runs under a per-thread collector
    (``stats.worker_stats``) merged on the coordinating thread when its
    future is consumed.

    Under an active serve arbiter (``tpuparquet.serve``) a thread
    bound to a tenant sizes from that tenant's share of the GLOBAL
    worker budget instead — consulted per call, so adaptive
    rebalances take effect at the next unit boundary; unbound threads
    and arbiter-less processes keep the legacy behavior exactly."""
    from ..serve import arbiter as _arbiter

    share = _arbiter.plan_budget()
    if share is not None:
        return share
    _arbiter.warn_if_oversubscribed()
    v = os.environ.get("TPQ_PLAN_THREADS")
    if v is not None:
        try:
            return max(int(v), 1)
        except ValueError:
            pass  # malformed override falls back to the default
    return _usable_cpus()


def _usable_cpus() -> int:
    """CPUs this process may actually run on: honors cpuset/affinity
    restrictions that ``os.cpu_count()`` ignores (a 16-core box pinned
    to one CPU must not spin up 4 contending planners)."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def filtered_pipelined_reads(readers, units, device_for=None,
                             start: int = 0, *, filter=None,
                             verdicts=None):
    """The late-materialization sibling of :func:`pipelined_reads`:
    each unit's filtered host decode (filter columns first, pruned
    pages skipped, survivors gathered) runs as one pool task while the
    main thread stages the previous unit's survivors on its device —
    plan/transfer overlap is preserved, just at unit granularity
    (filtered decode is one fused host pass, not per-column plan
    tasks).  ``verdicts`` optionally maps ``(file, rg)`` to a
    precomputed :class:`~tpuparquet.filter.PruneVerdict` so the scan's
    unit-forming pass is not re-run per unit."""
    from concurrent.futures import ThreadPoolExecutor

    from ..filter import read_row_group_filtered
    from ..stats import current_stats, worker_stats

    order = list(range(start, len(units)))
    if not order:
        return
    _cs = current_stats()
    n_workers = _plan_threads()
    degraded = _host_values_only()

    def task(ri, rgi, tctx=None, usp=None):
        deg_ctx = (cpu_fallback_values() if degraded
                   else contextlib.nullcontext())
        t0 = time.perf_counter()
        if usp is not None:
            usp.setdefault("t0_exec", t0)
        with _trace.adopt(tctx), worker_stats(like=_cs) as ws, deg_ctx:
            tsp = _trace.open_span("plan", filtered=True) \
                if _trace._active is not None else None
            v = None if verdicts is None else verdicts.get((ri, rgi))
            try:
                chunks, _rows = read_row_group_filtered(
                    readers[ri], rgi, filter, v)
            except BaseException:
                _trace.close_span(tsp, status="error")
                raise
            _trace.close_span(tsp)
            ws.plan_s += time.perf_counter() - t0
        return chunks, ws

    ex = ThreadPoolExecutor(max_workers=n_workers)
    inflight = {}
    unit_spans = {}
    state = {"next_j": 0}

    def fill(window: int):
        while state["next_j"] < len(order) and len(inflight) < window:
            k = order[state["next_j"]]
            state["next_j"] += 1
            ri, rgi = units[k]
            usp = None
            if _trace._active is not None:
                usp = _trace.open_span("unit", push=False, unit=k,
                                       file=ri, row_group=rgi)
            unit_spans[k] = usp
            inflight[k] = ex.submit(task, ri, rgi, _trace.ctx_of(usp),
                                    usp)

    try:
        fill(n_workers + 1)
        for k in order:
            usp = unit_spans.pop(k, None)
            try:
                chunks, ws = inflight.pop(k).result()
            except BaseException as e:
                _trace.close_span(usp, status="error",
                                  error=type(e).__name__)
                raise
            if usp is not None and "t0_exec" in usp:
                usp["t0"] = usp["t0_exec"]
            if _cs is not None:
                _cs.merge_from(ws)
                _cs.row_groups += 1
            ri, _rgi = units[k]
            reader = readers[ri]
            t0 = time.perf_counter()
            dev_ctx = (jax.default_device(device_for(k))
                       if device_for is not None
                       else contextlib.nullcontext())
            ptok = _profiler.stage_begin("transfer") \
                if _profiler._active is not None else None
            try:
                with dev_ctx:
                    out = {path: stage_chunkdata(
                               cd, reader.schema.leaf(path))
                           for path, cd in chunks.items()}
                    jax.block_until_ready(
                        [x for c in out.values()
                         for x in c._buffers()])
            finally:
                if ptok is not None:
                    _profiler.stage_end(ptok)
            t1 = time.perf_counter()
            if _cs is not None:
                _cs.transfer_s += t1 - t0
            if _trace._active is not None:
                _trace.emit_span("transfer", t0, t1 - t0,
                                 parent=_trace.ctx_of(usp),
                                 columns=len(out))
            _trace.close_span(usp)
            fill(n_workers + 1)
            yield k, out
    finally:
        ex.shutdown(wait=True)
        for usp in unit_spans.values():
            _trace.close_span(usp, status="cancelled")
        unit_spans.clear()


def pipelined_reads(readers, units, device_for=None, start: int = 0):
    """Yield ``(unit_index, {path: DeviceColumn})`` for
    ``units[start:]`` (each a ``(reader_index, rg_index)`` pair),
    overlapping host planning with device transfer.

    One shared pool of ``_plan_threads()`` workers runs PER-COLUMN plan
    tasks (file reads, block decompression, run-table scans — all
    GIL-releasing C/numpy work) while the main thread transfers and
    dispatches unit N on its assigned device (``device_for(unit_index)``,
    default device when None; plans are device-independent, so the
    target only matters at transfer time).  Column granularity means
    workers steal across units: a single wide row group fans out, and a
    fast unit's idle workers pull the next unit's columns — not one
    future per row group.  The submission window is derived from
    in-flight TASKS (at least ``n_workers + 1`` column tasks and one
    whole unit ahead), and every task leases its own arena from the
    shared pool (``kernels/arena.py``) so racing planners never share a
    slab; leases recycle only after the unit's transfers drain.
    Results are identical to a serial :func:`read_row_group_device`
    loop at any thread count.  The single shared pipeline under
    ``read_row_groups_device`` and the scan drivers in ``shard/``."""
    from concurrent.futures import ThreadPoolExecutor

    from ..stats import current_stats

    order = list(range(start, len(units)))
    if not order:
        return
    _cs = current_stats()
    n_workers = _plan_threads()
    degraded = _host_values_only()  # thread-local: workers re-enter it

    ex = ThreadPoolExecutor(max_workers=n_workers)
    inflight = {}    # unit k -> [future per column, in column order]
    arenas_of = {}   # unit k -> [leased arenas]
    unit_spans = {}  # unit k -> open trace span handle (or None)
    state = {"next_j": 0, "tasks": 0}

    def submit_unit():
        k = order[state["next_j"]]
        state["next_j"] += 1
        ri, rgi = units[k]
        reader = readers[ri]
        cols = reader.selected_chunks(reader.meta.row_groups[rgi])
        # unit span: opened WITHOUT pushing the ambient context (its
        # open/close straddles generator yields) — the plan tasks and
        # the finish step re-enter it explicitly, so a unit's spans
        # connect under it even though planning overlaps other units
        usp = None
        if _trace._active is not None:
            usp = _trace.open_span("unit", push=False, unit=k,
                                   file=ri, row_group=rgi)
        unit_spans[k] = usp
        tctx = _trace.ctx_of(usp)
        futs, ars = [], []
        # single-worker pools run a unit's column tasks sequentially,
        # so one shared arena per unit keeps the old cross-column slab
        # reuse; real parallelism needs a lease per racing task
        shared = lease_arena() if n_workers == 1 and cols else None
        if shared is not None:
            ars.append(shared)
        for path, node, cm in cols:
            a = shared
            if a is None:
                a = lease_arena()
                ars.append(a)
            futs.append(ex.submit(_plan_column_task, reader, rgi, path,
                                  node, cm, a, _cs, degraded, tctx,
                                  usp))
        inflight[k] = futs
        arenas_of[k] = ars
        state["tasks"] += len(futs)

    def fill_window(min_units: int):
        while state["next_j"] < len(order) and (
                len(inflight) < min_units
                or state["tasks"] < n_workers + 1):
            submit_unit()

    try:
        fill_window(2)  # current unit + at least one planned ahead
        for k in order:
            futs = inflight.pop(k)
            state["tasks"] -= len(futs)
            usp = unit_spans.pop(k, None)
            planned = []
            err = None
            for f in futs:
                try:
                    entry, ws = f.result()
                except BaseException as e:
                    err = err if err is not None else e
                    continue
                if _cs is not None:
                    _cs.merge_from(ws)
                planned.append(entry)
            if usp is not None and "t0_exec" in usp:
                # the unit span starts when its first plan task RAN
                # (stamped by the worker; all futures joined above),
                # not when the window submitted it — queue wait
                # belongs to the scan's driver time, not the unit
                usp["t0"] = usp["t0_exec"]
            if err is not None:
                _trace.close_span(usp, status="error",
                                  error=type(err).__name__)
                raise err
            try:
                with _trace.adopt(_trace.ctx_of(usp)):
                    if device_for is not None:
                        with jax.default_device(device_for(k)):
                            out = _finish_row_group(planned)
                    else:
                        # drains; arenas free
                        out = _finish_row_group(planned)
            except BaseException as e:
                _trace.close_span(usp, status="error",
                                  error=type(e).__name__)
                raise
            _trace.close_span(usp)
            for a in arenas_of.pop(k):
                return_arena(a)
            fill_window(1)
            if _cs is not None:
                _cs.row_groups += 1
            yield k, out
    finally:
        # On error/early close just drop the leased arenas (never
        # recycle slabs that in-flight transfers might still read); the
        # workers are joined so no new borrows can race interpreter
        # shutdown.  Trimming releases the scan's slab high-water mark
        # back to the allocator (keep=2: the resilient per-unit path
        # still reuses a couple of warm arenas between scans).
        ex.shutdown(wait=True)
        # pre-submitted units the consumer never drained: their plan
        # spans were already emitted (the workers ran), so emit the
        # unit spans as cancelled rather than orphaning the children
        for usp in unit_spans.values():
            _trace.close_span(usp, status="cancelled")
        unit_spans.clear()
        trim_arena_pool(keep=2)


def read_row_groups_device(reader, rg_indices=None, filter=None,
                           out_sharding=None, gather_to=None):
    """Yield ``(rg_index, {path: DeviceColumn})`` for several row groups,
    overlapping host planning with device transfer (see
    :func:`pipelined_reads`).  Results are identical to calling
    :func:`read_row_group_device` per index.  With ``filter``, row
    groups the static verdict proves empty are skipped entirely (not
    yielded) and the rest decode late-materialized.

    ``out_sharding`` (a ``NamedSharding`` over the consumer's mesh) /
    ``gather_to`` (a device or local-device index) place the decode
    itself: row groups round-robin the TARGET's devices, so every
    decoded buffer is born on a shard that will consume it — the
    device-read face of the scan layer's consumer-aligned output
    placement (:func:`tpuparquet.shard.scan.gather_column`).  Explicit
    only — the ``TPQ_GATHER_TO`` env default is a scan-level knob and
    does not reach this surface."""
    from ..stats import current_stats

    device_for = None
    if out_sharding is not None or gather_to is not None:
        from ..shard.mesh import placement_devices, resolve_out_sharding

        target = resolve_out_sharding(None, out_sharding, gather_to,
                                      env_default=False)
        # "replicated" resolves to None: the default decode placement
        if target is not None:
            devs = placement_devices(target)
            device_for = lambda k: devs[k % len(devs)]  # noqa: E731
    if rg_indices is None:
        rg_indices = range(reader.row_group_count())
    indices = list(rg_indices)
    if filter is not None:
        from ..filter import bind_filter

        bind_filter(filter, reader.schema)
        kept, verdicts = [], {}
        st = current_stats()
        for i in indices:
            v = reader.prune_row_group(filter, i)
            if v.skip:
                if st is not None:
                    st.row_groups_pruned += 1
                    st.rows_pruned += \
                        reader.meta.row_groups[i].num_rows
                    st.bloom_hits += v.bloom_hits
                continue
            if st is not None:
                st.bloom_hits += v.bloom_hits
            verdicts[(0, i)] = v
            kept.append(i)
        for k, out in filtered_pipelined_reads(
                [reader], [(0, i) for i in kept], device_for,
                filter=filter, verdicts=verdicts):
            yield kept[k], out
        return
    for k, out in pipelined_reads([reader], [(0, i) for i in indices],
                                  device_for):
        yield indices[k], out


def decode_values_cpu(ptype, enc, data, count, type_length):
    from ..io.pages import decode_values

    return decode_values(ptype, enc, data, count, type_length)


def _stage_numpy_fixed(col, ptype: Type) -> jax.Array:
    """Host-decoded values -> flat u32 lane buffer."""
    arr = np.asarray(col)
    if arr.dtype == np.bool_:
        return jnp.asarray(arr.astype(np.uint32).reshape(-1))
    if arr.dtype.itemsize in (4, 8):
        return jnp.asarray(np.ascontiguousarray(arr).view("<u4")
                           .reshape(-1))
    if arr.ndim == 2:  # FLBA / int96 byte matrices
        return _stage_byte_rows(arr)
    raise TypeError(f"cannot stage {arr.dtype} for {ptype}")


def _scan_levels_v1(raw, n, max_level, pos, encoding=Encoding.RLE):
    """Scan a V1 def-level stream without expanding it.

    Returns (scan | None, host levels | None, end pos); expansion happens
    inside the fused page kernel (or standalone for non-fused paths)."""
    if max_level == 0:
        return None, None, pos
    width = max_level.bit_length()
    if encoding == Encoding.BIT_PACKED:
        from ..cpu import decode_levels_bitpacked

        nbytes = (n * width + 7) // 8
        vals = decode_levels_bitpacked(raw[pos : pos + nbytes], n, max_level)
        return None, vals, pos + nbytes
    import struct

    from ..cpu.hybrid import scan_hybrid

    (size,) = struct.unpack_from("<I", raw, pos)
    sc = scan_hybrid(raw[pos + 4 : pos + 4 + size], n, width)
    return sc, None, pos + 4 + size


